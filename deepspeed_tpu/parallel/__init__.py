from deepspeed_tpu.parallel.pallas_shard import (  # noqa
    current_kernel_mesh, pallas_kernel_mesh, sharded_masked_flash,
    sharded_paged_decode)
