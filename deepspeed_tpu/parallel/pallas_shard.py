"""shard_map wrap for Pallas kernels under a GSPMD mesh.

A ``pallas_call`` cannot be auto-partitioned by GSPMD: left inside a
jit with sharded operands, XLA either replicates the operands (wrong
answer for a sharded cache) or fails to partition — which is why, until
PR 11, the serving engine silently dropped the PR 8 paged-decode kernel
for the max_len-bounded gather path the moment ``inference.mesh`` was
set, losing the O(live tokens) win exactly at pod scale.

The fix is the canonical one: wrap the kernel in ``jax.shard_map`` over
the mesh's head axis, so each device runs the *identical* kernel on its
local head shard — attention is embarrassingly parallel over (kv) heads,
no collectives needed inside. This module is the one home for those
wraps:

- :func:`sharded_paged_decode` — the PR 8 decode kernel over a
  kv-head-sharded page pool (the serving engine's mesh path; the
  compiled program is pinned gather-free by ``hlo_audit.gather_ops``
  in tier-1).
- :func:`sharded_masked_flash` — the unified training kernel
  (``ops/attention/masked_flash.py``) over head-sharded q/k/v. Requires
  a head-uniform BlockMask (``mask.heads == 1`` — dense, causal, and
  every propagated SparsityConfig layout): shard_map is SPMD, so
  per-head metadata cannot differ across shards. NOTE: the in-kernel
  dropout hash is keyed on the *local* head index, so a sharded run
  draws a different (equally valid) keep-mask than an unsharded one.
- :func:`pallas_kernel_mesh` / :func:`current_kernel_mesh` — a
  trace-time context the serving engine uses to thread its mesh down to
  the models' kernel call sites without widening every forward
  signature: the engine traces its compiled programs under the context,
  ``models/gpt2.paged_decode_ctx`` consults it.

Head-axis legality mirrors the PR 7 cache sharding: the mesh axis must
divide q heads AND kv heads (each shard then owns whole GQA groups, so
group g of q head h lands on the same shard as kv head h // G).
"""

import contextlib
import functools
from typing import NamedTuple, Optional

import jax
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.parallel.mesh import axis_size

__all__ = ["sharded_paged_decode", "sharded_masked_flash",
           "pallas_kernel_mesh", "current_kernel_mesh", "KernelMesh",
           "head_shard_supported", "context_prefill_mesh",
           "current_cp_mesh"]


class KernelMesh(NamedTuple):
    mesh: Mesh
    axis: str


_ACTIVE: list = []          # stack; trace-time only
_CP_ACTIVE: list = []       # context-parallel prefill stack (ISSUE 19)


@contextlib.contextmanager
def pallas_kernel_mesh(mesh: Optional[Mesh], axis: str = "model"):
    """Trace-time context: while active, mesh-aware kernel call sites
    (``models/gpt2.paged_decode_ctx``) wrap their Pallas kernels in
    shard_map over ``(mesh, axis)``. ``mesh=None`` (or an absent/size-1
    axis) is a no-op, so callers can wrap unconditionally."""
    if mesh is None or axis_size(mesh, axis) <= 1:
        yield
        return
    _ACTIVE.append(KernelMesh(mesh, axis))
    try:
        yield
    finally:
        _ACTIVE.pop()


def current_kernel_mesh() -> Optional[KernelMesh]:
    return _ACTIVE[-1] if _ACTIVE else None


@contextlib.contextmanager
def context_prefill_mesh(mesh: Optional[Mesh], axis: str = "model"):
    """Trace-time context for CONTEXT-PARALLEL prefill (ISSUE 19):
    while active, the models' multi-query paged gather attention
    routes through ``ops.attention.ring.ring_prefill_attention`` —
    the chunk's sequence axis sharded over ``(mesh, axis)`` with K/V
    stripes rotating around the ring. A separate stack from
    :func:`pallas_kernel_mesh` because the serving engine traces its
    CP chunk program under BOTH (the decode-side kernel context stays
    on for any seq-1 call sites). ``mesh=None``/size-1 axis is a
    no-op."""
    if mesh is None or axis_size(mesh, axis) <= 1:
        yield
        return
    _CP_ACTIVE.append(KernelMesh(mesh, axis))
    try:
        yield
    finally:
        _CP_ACTIVE.pop()


def current_cp_mesh() -> Optional[KernelMesh]:
    return _CP_ACTIVE[-1] if _CP_ACTIVE else None


def head_shard_supported(n: int, *head_counts) -> bool:
    """Can a Pallas attention kernel shard over an n-way head axis for
    these head counts? Every count must divide (whole GQA groups per
    shard)."""
    return all(h % n == 0 for h in head_counts)


def sharded_paged_decode(q, kpool, vpool, block_tables, cache_position,
                         mesh: Mesh, axis: str = "model",
                         sm_scale: Optional[float] = None,
                         interpret: Optional[bool] = None,
                         k_scales=None, v_scales=None):
    """PR 8 ``paged_decode_attention`` under a GSPMD mesh: q sharded
    over heads, pools over kv heads (the engine's
    ``P(None, None, 'model')`` cache split, per layer), block tables and
    positions replicated. The int8-pool arity (``k_scales``/``v_scales``,
    PR 17) shards the fp32 scale pools over the same kv-head dim as the
    payload pools — each shard dequantizes its own head's tiles in
    VMEM, no collectives. Falls through to the plain kernel when the
    axis is absent or size 1."""
    from deepspeed_tpu.ops.attention.paged import paged_decode_attention
    n = axis_size(mesh, axis)
    kernel = functools.partial(paged_decode_attention, sm_scale=sm_scale,
                               interpret=interpret)
    quantized = k_scales is not None
    if n <= 1:
        if quantized:
            return kernel(q, kpool, vpool, block_tables, cache_position,
                          k_scales=k_scales, v_scales=v_scales)
        return kernel(q, kpool, vpool, block_tables, cache_position)
    H, KH = q.shape[1], kpool.shape[1]
    assert head_shard_supported(n, H, KH), (
        f"paged decode: mesh axis {axis!r} ({n}-way) must divide "
        f"q heads ({H}) and kv heads ({KH})")
    pool_specs = (P(None, axis), P(None, axis), P(None, axis), P(), P())
    if quantized:
        def inner(q, kpool, vpool, block_tables, cache_position, ks, vs):
            return kernel(q, kpool, vpool, block_tables, cache_position,
                          k_scales=ks, v_scales=vs)
        f = jax.shard_map(
            inner, mesh=mesh,
            in_specs=pool_specs + (P(None, axis), P(None, axis)),
            out_specs=P(None, axis), check_vma=False)
        return f(q, kpool, vpool, block_tables, cache_position,
                 k_scales, v_scales)
    f = jax.shard_map(
        kernel, mesh=mesh,
        in_specs=pool_specs,
        out_specs=P(None, axis), check_vma=False)
    return f(q, kpool, vpool, block_tables, cache_position)


def sharded_masked_flash(q, k, v, mask, key_mask=None,
                         mesh: Optional[Mesh] = None, axis: str = "model",
                         sm_scale=None, dropout_rate: float = 0.0,
                         dropout_rng=None,
                         interpret: Optional[bool] = None):
    """The unified training kernel head-sharded over ``(mesh, axis)``
    — same signature and semantics as
    :func:`~deepspeed_tpu.ops.attention.masked_flash.masked_flash_attention`
    plus the mesh. Differentiable (the custom vjp transposes through
    shard_map). Requires a head-uniform mask (``mask.heads == 1``)."""
    import jax.numpy as jnp
    import numpy as np
    from deepspeed_tpu.ops.attention.flash import (_use_pallas,
                                                   dropout_seed_from_rng)
    from deepspeed_tpu.ops.attention.masked_flash import masked_flash_call
    n = axis_size(mesh, axis) if mesh is not None else 1
    b, h, _, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(d)
    if interpret is None:
        interpret = not _use_pallas()
    if n <= 1:
        from deepspeed_tpu.ops.attention.masked_flash import \
            masked_flash_attention
        return masked_flash_attention(q, k, v, mask, key_mask=key_mask,
                                      sm_scale=sm_scale,
                                      dropout_rate=dropout_rate,
                                      dropout_rng=dropout_rng,
                                      interpret=interpret)
    assert mask.heads == 1, (
        "sharded_masked_flash needs a head-uniform BlockMask "
        f"(mask.heads == 1, got {mask.heads}): shard_map is SPMD, so "
        "per-head mask metadata cannot differ across shards")
    assert head_shard_supported(n, h, k.shape[1]), (
        f"mesh axis {axis!r} ({n}-way) must divide q heads ({h}) and "
        f"kv heads ({k.shape[1]})")
    rate = float(dropout_rate)
    if rate > 0.0:
        assert dropout_rng is not None
        seed = dropout_seed_from_rng(dropout_rng)
    else:
        seed = jnp.zeros((1, 1), jnp.int32)
    sk = k.shape[2]
    has_kpm = key_mask is not None
    kpm = jnp.zeros((b, 1), jnp.float32) if key_mask is None else \
        key_mask.reshape(b, sk).astype(jnp.float32)

    def inner(q, k, v, kpm, seed):
        return masked_flash_call(q, k, v, kpm, seed, mask,
                                 float(sm_scale), bool(interpret), rate,
                                 has_kpm)

    f = jax.shard_map(
        inner, mesh=mesh,
        in_specs=(P(None, axis), P(None, axis), P(None, axis), P(), P()),
        out_specs=P(None, axis), check_vma=False)
    return f(q, k, v, kpm, seed)
