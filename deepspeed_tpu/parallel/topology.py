"""Named-axis process topology — the rank math of N-D parallelism.

TPU-native analog of the reference's ``deepspeed/runtime/pipe/topology.py``
(ProcessTopology at topology.py:12, PipeDataParallelTopology :235,
PipeModelDataParallelTopology :246). The reference used these coordinate
lists to hand-build NCCL process groups; here the same math (a) constructs
``jax.sharding.Mesh`` objects with matching named axes and (b) still answers
host-side questions (checkpoint naming, stage adjacency, tied-weight groups).

Implementation is index arithmetic on a row-major layout rather than the
reference's itertools cartesian-product tables.
"""

from collections import namedtuple
from typing import List, Optional, Sequence


class ProcessTopology:
    """Maps ranks <-> coordinates on a named-axis cartesian grid.

    Axes are ordered major-to-minor: the LAST axis varies fastest with rank
    (row-major), matching the reference's convention where e.g. with axes
    ['x','y'] rank 1 is (x=0, y=1).
    """

    def __init__(self, axes: Sequence[str], dims: Sequence[int]):
        if len(axes) != len(dims):
            raise ValueError("axes and dims must have equal length")
        if len(set(axes)) != len(axes):
            raise ValueError(f"duplicate axis names in {axes}")
        for d in dims:
            if d < 1:
                raise ValueError(f"axis dims must be >= 1, got {dims}")
        self.axes = list(axes)
        self.dims = list(dims)
        self.ProcessCoord = namedtuple("ProcessCoord", self.axes)
        # row-major strides: stride of axis i = product of dims after i
        self._strides = []
        s = 1
        for d in reversed(self.dims):
            self._strides.append(s)
            s *= d
        self._strides.reverse()
        self._world_size = s

    def world_size(self) -> int:
        return self._world_size

    def get_dim(self, axis: str) -> int:
        if axis not in self.axes:
            return 0
        return self.dims[self.axes.index(axis)]

    def get_rank(self, **coords) -> int:
        """Rank of the process at the given full coordinate."""
        if sorted(coords.keys()) != sorted(self.axes):
            raise ValueError(
                f"get_rank() requires all axes {self.axes}, got {list(coords)}")
        rank = 0
        for ax, stride, dim in zip(self.axes, self._strides, self.dims):
            c = coords[ax]
            if not 0 <= c < dim:
                raise ValueError(f"coord {ax}={c} out of range [0,{dim})")
            rank += c * stride
        return rank

    def get_coord(self, rank: int):
        """Coordinate namedtuple of ``rank``."""
        if not 0 <= rank < self._world_size:
            raise ValueError(f"rank {rank} out of range [0,{self._world_size})")
        coords = {}
        for ax, stride, dim in zip(self.axes, self._strides, self.dims):
            coords[ax] = (rank // stride) % dim
        return self.ProcessCoord(**coords)

    def get_axis_names(self) -> List[str]:
        return list(self.axes)

    def get_rank_repr(self, rank: int, omit_axes=("data",), inner_sep="_",
                      outer_sep="-") -> str:
        """String like 'pipe_0-model_1' used in checkpoint filenames
        (reference topology.py:88: omits data axis since DP ranks share
        weights)."""
        omit_axes = list(omit_axes)
        axes = [a for a in self.axes if a not in omit_axes]
        names = []
        coord = self.get_coord(rank)
        for ax in axes:
            names.append(f"{ax}{inner_sep}{getattr(coord, ax)}")
        return outer_sep.join(names)

    def get_axis_list(self, axis: str, idx: int) -> List[int]:
        """All ranks whose coordinate along ``axis`` equals ``idx``."""
        return [r for r in range(self._world_size)
                if getattr(self.get_coord(r), axis) == idx]

    def get_axis_comm_lists(self, axis: str) -> List[List[int]]:
        """Groups of ranks that differ only along ``axis`` — exactly the
        process groups the reference built for NCCL (topology.py:131); here
        they seed host-side group logic and tests."""
        if axis not in self.axes:
            return []
        other_axes = [a for a in self.axes if a != axis]
        lists = []
        seen = set()
        for rank in range(self._world_size):
            coord = self.get_coord(rank)
            key = tuple(getattr(coord, a) for a in other_axes)
            if key in seen:
                continue
            seen.add(key)
            group = [r for r in range(self._world_size)
                     if all(getattr(self.get_coord(r), a) == k
                            for a, k in zip(other_axes, key))]
            lists.append(group)
        return lists

    def filter_match(self, **filter_kwargs) -> List[int]:
        """Ranks whose coordinates match all given axis=value filters
        (reference topology.py:171)."""
        def matches(rank):
            coord = self.get_coord(rank)
            return all(getattr(coord, ax) == v for ax, v in filter_kwargs.items())
        return [r for r in range(self._world_size) if matches(r)]

    def split_axis(self, axis: str, outer_name: str, inner_name: str,
                   inner_size: int) -> "ProcessTopology":
        """New topology with ``axis`` (size W) split into
        ``outer_name`` (W // inner_size, major) x ``inner_name``
        (inner_size, minor), preserving every rank's position.

        Because the layout is row-major, splitting an axis in place keeps
        rank <-> coordinate assignments consistent: a rank's old ``axis``
        coordinate c becomes (outer=c // inner_size, inner=c %
        inner_size). This is the host-side mirror of
        ``parallel.mesh.split_data_axis`` (hierarchical ZeRO++-style
        collectives put the bandwidth-heavy hop on the minor/inner axis,
        whose peers are rank-adjacent and therefore ICI neighbors).
        """
        if axis not in self.axes:
            raise ValueError(f"no axis {axis!r} in {self.axes}")
        W = self.dims[self.axes.index(axis)]
        if inner_size < 1 or W % inner_size != 0:
            raise ValueError(
                f"axis {axis!r} size {W} not divisible by {inner_size}")
        if outer_name in self.axes or inner_name in self.axes:
            raise ValueError(
                f"split names {outer_name!r}/{inner_name!r} collide with "
                f"existing axes {self.axes}")
        axes, dims = [], []
        for a, d in zip(self.axes, self.dims):
            if a == axis:
                axes += [outer_name, inner_name]
                dims += [W // inner_size, inner_size]
            else:
                axes.append(a)
                dims.append(d)
        return ProcessTopology(axes, dims)

    def __str__(self):
        return f"ProcessTopology(axes={self.axes}, dims={self.dims})"


class PipeDataParallelTopology(ProcessTopology):
    """2D pipe × data grid (reference topology.py:235). ZeRO-style DP shards
    within a pipeline stage."""

    def __init__(self, num_pp: int, num_dp: int):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """3D pipe × data × model hybrid grid (reference topology.py:246).

    'model' is the minor axis so tensor-parallel peers are adjacent ranks —
    on TPU these land on ICI nearest neighbors, where the per-layer
    all-reduces are cheapest (same reasoning as NVLink adjacency on GPU).
    """

    def __init__(self, num_pp: int, num_mp: int, num_dp: int):
        super().__init__(axes=["pipe", "data", "model"],
                         dims=[num_pp, num_dp, num_mp])


class ParallelGrid:
    """The MPU ("model parallel unit") facade over a topology + JAX mesh.

    Implements the mpu protocol the reference engine consumes
    (topology.py:405-455: get_{data,model,pipe,slice}_parallel_{rank,
    world_size,group}) so client code written against Megatron-style mpu
    objects ports over. "Groups" are returned as mesh axis *names* — inside
    jit, XLA collectives take axis names, not process-group handles.
    """

    def __init__(self, topology: Optional[ProcessTopology] = None,
                 process_index: Optional[int] = None):
        import jax

        if topology is None:
            topology = PipeDataParallelTopology(1, jax.device_count())
        self._topo = topology
        if process_index is not None:
            self.global_rank = process_index
        else:
            # Ranks index logical devices, not hosts: this host's rank is its
            # first local device's global id (under SPMD every host runs the
            # same program; per-device coordinates come from the mesh).
            self.global_rank = min(d.id for d in jax.local_devices())
        self.world_size = topology.world_size()

        self.data_parallel_size = max(1, topology.get_dim("data"))
        self.pipe_parallel_size = max(1, topology.get_dim("pipe"))
        self.model_parallel_size = max(1, topology.get_dim("model"))

    # -- coordinate lookups (host-side; valid when 1 process == 1 device,
    #    or per-host under multi-controller SPMD) --
    def _coord_axis(self, axis: str, default: int = 0) -> int:
        if self._topo.get_dim(axis) == 0:
            return default
        return getattr(self._topo.get_coord(self.global_rank), axis)

    def get_global_rank(self) -> int:
        return self.global_rank

    # data parallel
    def get_data_parallel_rank(self) -> int:
        return self._coord_axis("data")

    def get_data_parallel_world_size(self) -> int:
        return self.data_parallel_size

    def get_data_parallel_group(self) -> str:
        return "data"

    # model (tensor) parallel
    def get_model_parallel_rank(self) -> int:
        return self._coord_axis("model")

    def get_model_parallel_world_size(self) -> int:
        return self.model_parallel_size

    def get_model_parallel_group(self) -> str:
        return "model"

    # alias used by some clients for tensor-slicing groups
    get_slice_parallel_rank = get_model_parallel_rank
    get_slice_parallel_world_size = get_model_parallel_world_size
    get_slice_parallel_group = get_model_parallel_group

    # pipeline parallel
    def get_pipe_parallel_rank(self) -> int:
        return self._coord_axis("pipe")

    def get_pipe_parallel_world_size(self) -> int:
        return self.pipe_parallel_size

    def get_pipe_parallel_group(self) -> str:
        return "pipe"

    def get_stage_id(self) -> int:
        return self.get_pipe_parallel_rank()

    def is_first_stage(self) -> bool:
        return self.get_stage_id() == 0

    def is_last_stage(self) -> bool:
        return self.get_stage_id() == self.pipe_parallel_size - 1

    def stage_to_global(self, stage_id: int, **kwargs) -> int:
        """Global rank of the same (data, model) coordinate at another
        pipeline stage (reference topology.py:391)."""
        me = self._topo.get_coord(self.global_rank)._asdict()
        me.update(kwargs)
        me["pipe"] = stage_id
        return self._topo.get_rank(**me)

    def p2p_pairs(self) -> List[List[int]]:
        """Adjacent-stage rank pairs, incl. wraparound (reference
        topology.py:372 _build_p2p_groups); deduped, no self-pairs."""
        if self.pipe_parallel_size < 2:
            return []
        pairs = set()
        for rank in range(self.world_size):
            coord = self._topo.get_coord(rank)
            nxt = dict(coord._asdict())
            nxt["pipe"] = (coord.pipe + 1) % self.pipe_parallel_size
            other = self._topo.get_rank(**nxt)
            if other != rank:
                pairs.add(tuple(sorted((rank, other))))
        return [list(p) for p in sorted(pairs)]

    @property
    def topology(self) -> ProcessTopology:
        return self._topo
