"""Device-mesh construction — the TPU replacement for NCCL process groups.

Where the reference hand-built torch.distributed groups from topology rank
lists (topology.py:303-364), here one ``jax.sharding.Mesh`` with named axes
serves every parallel dimension; collectives inside jit take axis names.

Canonical axis names (any subset may be present, size-1 axes are legal):

- ``pipe``   : pipeline stages
- ``data``   : data parallel (ZeRO shards along this axis too)
- ``data_inter`` / ``data_intra`` : hierarchical split of the data axis
               (ZeRO++-style 2D collectives, runtime/quantized_collectives):
               ``data_intra`` is the minor of the two so intra-slice peers
               sit on ICI nearest neighbors while ``data_inter`` spans the
               slow (DCN / inter-slice) dimension. Mutually exclusive with
               a plain ``data`` axis.
- ``expert`` : expert parallel (MoE expert banks, ops/moe.py) — TPU-native
               extension; absent from the reference snapshot
- ``seq``    : sequence/context parallel (ring attention) — TPU-native
               extension; absent from the reference snapshot
- ``model``  : tensor (megatron-style) parallel; innermost so TP peers sit
               on ICI nearest neighbors
"""

import math
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.parallel.topology import ProcessTopology

CANONICAL_AXIS_ORDER = ("pipe", "data", "data_inter", "data_intra",
                        "expert", "seq", "model")

# the hierarchical split of the data axis, major (slow wire) first
DATA_SUB_AXES = ("data_inter", "data_intra")


def data_axis_names(mesh: Mesh):
    """The mesh's data-parallel axis names, major->minor: ``("data",)``,
    ``("data_inter", "data_intra")`` for a hierarchical mesh, or ``()``
    when no data axis exists."""
    if "data" in mesh.axis_names:
        return ("data",)
    present = tuple(a for a in DATA_SUB_AXES if a in mesh.axis_names)
    if present and len(present) != 2:
        raise ValueError(
            f"hierarchical data mesh needs both of {DATA_SUB_AXES}, "
            f"got axes {mesh.axis_names}")
    return present


def data_axis_size(mesh: Mesh) -> int:
    """Total data-parallel degree (product over the data axes), 1 if none."""
    size = 1
    for a in data_axis_names(mesh):
        size *= mesh.shape[a]
    return size


def split_data_axis(axes: Dict[str, int], intra: int) -> Dict[str, int]:
    """Rewrite a ``{'data': W, ...}`` axes dict into the hierarchical form
    ``{'data_inter': W // intra, 'data_intra': intra, ...}``.

    ``data_intra`` is placed minor so the intra-slice peers are ICI
    nearest neighbors — the whole point of the 2D collectives.
    """
    axes = dict(axes)
    if intra < 2:
        raise ValueError(f"hierarchical intra size must be >= 2, got {intra}")
    if "data" not in axes:
        if all(a in axes for a in DATA_SUB_AXES):
            # already split explicitly in mesh.axes — but it must AGREE
            # with the requested intra size, or the bandwidth-heavy hop
            # would silently land on a different-width axis
            if axes["data_intra"] != intra:
                raise ValueError(
                    f"mesh.axes gives data_intra={axes['data_intra']} but "
                    f"quantized_comm.hierarchical={intra}; make them "
                    "match (or drop one)")
            return axes
        raise ValueError(
            f"cannot split: no 'data' axis in {axes}")
    W = axes.pop("data")
    if W == -1 or W % intra != 0:
        raise ValueError(
            f"data axis size {W} is not divisible by hierarchical intra "
            f"size {intra} (set mesh.axes.data explicitly)")
    axes["data_inter"] = W // intra
    axes["data_intra"] = intra
    return axes


def _order_axes(axes: Dict[str, int]) -> Dict[str, int]:
    """Order axes canonically (major → minor); unknown axes go after 'data'."""
    ordered = {}
    for name in CANONICAL_AXIS_ORDER:
        if name in axes:
            ordered[name] = axes[name]
    for name, size in axes.items():
        if name not in ordered:
            ordered[name] = size
    return ordered


def resolve_axis_sizes(axes: Optional[Dict[str, int]],
                       n_devices: int) -> Dict[str, int]:
    """Concrete axis sizes for an axes dict that may carry one ``-1``
    (inferred), ordered canonically — the same resolution
    :func:`build_mesh` applies, callable BEFORE any mesh exists (the
    comm autotuner plans the hierarchy split pre-mesh)."""
    if not axes:
        return {"data": n_devices}
    axes = _order_axes(dict(axes))
    unknown = [k for k, v in axes.items() if v == -1]
    if len(unknown) > 1:
        raise ValueError(f"at most one mesh axis may be -1, got {axes}")
    if unknown:
        known = math.prod(v for v in axes.values() if v != -1)
        if n_devices % known != 0:
            raise ValueError(
                f"cannot infer axis {unknown[0]}: {n_devices} devices not "
                f"divisible by {known}")
        axes[unknown[0]] = n_devices // known
    return axes


def natural_intra_size(devices: Optional[Sequence] = None) -> int:
    """Physical intra-slice hint for the comm autotuner: devices per
    process (the host-local ICI island — cross-process hops ride the
    slow DCN wire). 0 when the topology offers no meaningful split
    (single process, uneven spread, or fewer than 2 local devices)."""
    if devices is None:
        devices = jax.devices()
    per_proc: Dict[int, int] = {}
    for d in devices:
        pi = getattr(d, "process_index", 0)
        per_proc[pi] = per_proc.get(pi, 0) + 1
    counts = set(per_proc.values())
    if len(per_proc) < 2 or len(counts) != 1:
        return 0
    local = counts.pop()
    return local if local >= 2 else 0


def build_mesh(axes: Optional[Dict[str, int]] = None,
               devices: Optional[Sequence] = None) -> Mesh:
    """Build a named-axis Mesh over the available devices.

    ``axes`` maps axis name -> size; at most one size may be -1 (inferred).
    Default: all devices on the ``data`` axis.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)

    axes = resolve_axis_sizes(axes, n)

    size = math.prod(axes.values())
    if size < n:
        # explicit axes asking for fewer devices than exist (a resolved
        # -1 always covers all of them): run on a subset — the
        # elastic-resume case (reference reloads ZeRO state under a
        # smaller dp world, stage2.py:1785-1793)
        devices = list(devices)[:size]
        n = size
    if size != n:
        raise ValueError(
            f"mesh axes {axes} require {size} devices but {n} are available")

    names = tuple(axes.keys())
    dims = tuple(axes.values())
    try:
        from jax.experimental import mesh_utils
        device_array = mesh_utils.create_device_mesh(dims, devices=devices)
    except Exception:
        # CPU/host platform: physical layout doesn't matter
        device_array = np.asarray(devices).reshape(dims)
    return Mesh(device_array, axis_names=names)


def mesh_from_topology(topo: ProcessTopology,
                       devices: Optional[Sequence] = None) -> Mesh:
    """Mesh whose named axes mirror a ProcessTopology's axes/dims."""
    return build_mesh(dict(zip(topo.axes, topo.dims)), devices=devices)


def data_sharding(mesh: Mesh, batch_axis: str = "data") -> NamedSharding:
    """Sharding for a batch: leading dim split over the data axis (and seq
    axis for the sequence dim if present is handled by callers). On a
    hierarchical mesh the leading dim splits over BOTH data sub-axes."""
    if batch_axis == "data" and batch_axis not in mesh.axis_names:
        sub = data_axis_names(mesh)
        if sub:
            return NamedSharding(mesh, PartitionSpec(sub))
    if batch_axis not in mesh.axis_names:
        return NamedSharding(mesh, PartitionSpec())
    return NamedSharding(mesh, PartitionSpec(batch_axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def axis_size(mesh: Mesh, name: str) -> int:
    """Size of a mesh axis, 1 if absent."""
    if name in mesh.axis_names:
        return mesh.shape[name]
    return 1


def single_device_mesh() -> Mesh:
    """1-device mesh with the canonical axes, for tests/single-chip runs."""
    dev = np.array(jax.devices()[:1]).reshape(1, 1, 1)
    return Mesh(dev, axis_names=("pipe", "data", "model"))
