"""Logging utilities.

TPU-native analog of the reference's ``deepspeed/utils/logging.py``
(LoggerFactory at utils/logging.py:7, log_dist at :40). On TPU we filter by
``jax.process_index()`` instead of torch.distributed rank.
"""

import logging
import os
import sys
from typing import Iterable, Optional

LOG_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


class LoggerFactory:

    @staticmethod
    def create_logger(name: str = "DeepSpeedTPU", level=logging.INFO) -> logging.Logger:
        """Create a logger with a stdout stream handler."""
        if name is None:
            raise ValueError("name for logger cannot be None")
        formatter = logging.Formatter(
            "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s")
        logger_ = logging.getLogger(name)
        logger_.setLevel(level)
        logger_.propagate = False
        if not logger_.handlers:
            ch = logging.StreamHandler(stream=sys.stdout)
            ch.setLevel(level)
            ch.setFormatter(formatter)
            logger_.addHandler(ch)
        return logger_


logger = LoggerFactory.create_logger(
    name="DeepSpeedTPU",
    level=LOG_LEVELS.get(os.environ.get("DSTPU_LOG_LEVEL", "info"), logging.INFO),
)


def _process_index() -> int:
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


# one-line which-path logging, once per hashable key (typically a
# (reason, *shape) tuple) — the engine's which-path-compiled convention
# instead of per-module _WARNED_* mutable globals whose state leaks
# across tests and configs
_ONCE_KEYS = set()


def log_once(key, msg: str, warn: bool = False) -> None:
    if key in _ONCE_KEYS:
        return
    _ONCE_KEYS.add(key)
    (logger.warning if warn else logger.info)(msg)


def reset_once_logging() -> None:
    """Test hook: forget which (reason, shape) lines were emitted."""
    _ONCE_KEYS.clear()


def log_dist(message: str, ranks: Optional[Iterable[int]] = None, level=logging.INFO) -> None:
    """Log ``message`` only on the listed process indices.

    ``ranks=None`` or ``ranks=[-1]`` logs on every process (mirrors reference
    utils/logging.py:40 semantics, with jax.process_index() standing in for
    the torch.distributed rank).
    """
    my_rank = _process_index()
    ranks = list(ranks) if ranks is not None else []
    should_log = not ranks or (-1 in ranks) or (my_rank in ranks)
    if should_log:
        logger.log(level, f"[Rank {my_rank}] {message}")
