"""Compiled-HLO collective accounting (shared by tests and bench).

The only multi-chip perf evidence a single-host rig can produce:
compile the partitioned program on a virtual CPU mesh, walk the HLO,
and pin communication volume to theory. Used by
``tests/unit/test_hlo_collectives.py`` / ``test_hlo_quantized_comm.py``
and by ``bench.py``'s hardware-free ``comm_wire_bytes_per_step`` row.

Counting rules:

- **Elements** are backend-invariant for float math comparisons (the
  CPU backend upcasts bf16 dots to f32, so float byte counts are not).
- **Bytes** ARE meaningful for quantized payloads: int8 collectives
  stay s8 in HLO on every backend (FloatNormalization touches only
  floats), which is exactly what the quantized-comm audits measure.
- all-reduce counts 2x its size (ring cost = reduce-scatter +
  all-gather); all-to-all / all-gather / reduce-scatter /
  collective-permute count 1x their output.
- async pairs count ONCE: the ``-start`` form is skipped (its tuple
  result carries operand + result, double-counting the transfer) and
  the ``-done`` form's plain result is counted.
"""

import re
from typing import List, NamedTuple, Optional, Tuple

__all__ = ["HLO_DTYPE_BYTES", "shape_elems", "shape_bytes",
           "Collective", "collect_collectives", "collect_collectives_full",
           "wire_elements", "wire_bytes_of", "send_bytes_of",
           "conditional_branch_comps", "hlo_computation_body",
           "dense_allreduce_ring_bytes", "while_body_comps",
           "cone_reaches_compute", "overlap_structure",
           "gather_ops", "max_gather_elems"]

# dtype name -> byte width; accounting by ELEMENTS uses only the names
HLO_DTYPE_BYTES = {"f64": 8, "s64": 8, "u64": 8,
                   "f32": 4, "s32": 4, "u32": 4,
                   "bf16": 2, "f16": 2, "s16": 2, "u16": 2,
                   "s8": 1, "u8": 1, "pred": 1}

_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                "collective-permute", "all-to-all")


def _shapes(shape_str):
    """[(dtype, elems)] for every array in an HLO result type (handles
    tuples)."""
    out = []
    for dt, dims in re.findall(r"(\w+)\[([\d,]*)\]", shape_str):
        if dt not in HLO_DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        out.append((dt, n))
    return out


def shape_elems(shape_str) -> int:
    """Total elements across every array in an HLO result type."""
    return sum(n for _, n in _shapes(shape_str))


def shape_bytes(shape_str) -> int:
    """Total payload bytes across every array in an HLO result type."""
    return sum(n * HLO_DTYPE_BYTES[dt] for dt, n in _shapes(shape_str))


def _group_size(line) -> Optional[int]:
    """Devices per replica group of a collective instruction, parsed from
    either the explicit ``replica_groups={{0,1},{2,3}}`` form or the
    iota ``replica_groups=[G,S]<=[...]`` form (S = group size). None if
    the attribute is absent (single-group collective)."""
    m = re.search(r"replica_groups=\[(\d+),(\d+)\]<=", line)
    if m:
        return int(m.group(2))
    m = re.search(r"replica_groups=\{\{([\d,]*)\}", line)
    if m:
        ids = [x for x in m.group(1).split(",") if x]
        return len(ids)
    return None


class Collective(NamedTuple):
    op: str            # e.g. "all-gather"
    elems: int         # result elements (transfer size, counting rules)
    bytes: int         # result payload bytes (int8-aware)
    group_size: Optional[int]  # devices per replica group
    line: str
    comp: Optional[str]        # enclosing HLO computation name


def _iter_collectives(hlo_text):
    comp = None
    comp_pat = re.compile(r"^\s*(?:ENTRY\s+)?%?([\w.\-]+)\s*\([^=]*\)\s*->")
    # the result type may be a variadic tuple whose long form carries
    # /*index=N*/ comments (which contain '='), so match lazily up to
    # the op name rather than forbidding '=' inside parens
    pat = re.compile(
        r"^\s*(?:ROOT\s+)?%?[\w.\-]+ = (\(.*?\)|\S+) "
        r"(" + "|".join(_COLLECTIVES) + r")(-start|-done)?\(",
    )
    for line in hlo_text.splitlines():
        cm = comp_pat.match(line)
        if cm and "{" in line:
            comp = cm.group(1)
        m = pat.match(line)
        if m:
            if m.group(3) == "-start":
                continue            # counted at the matching -done
            yield m, line, comp


def collect_collectives(hlo_text):
    """[(op, result_elems, line, computation)] for every collective
    instruction in a compiled (SPMD-partitioned) HLO module — the
    4-tuple shape the element-count audits consume."""
    return [(m.group(2), shape_elems(m.group(1)), line.strip(), comp)
            for m, line, comp in _iter_collectives(hlo_text)]


def collect_collectives_full(hlo_text) -> List[Collective]:
    """:class:`Collective` records with byte accounting and replica-group
    sizes — what the quantized-comm audits need (int8 payloads, and
    which mesh axis a collective ran over, identified by group size)."""
    out = []
    for m, line, comp in _iter_collectives(hlo_text):
        shape = m.group(1)
        # async -done: replica_groups live on the matching -start line
        gsz = _group_size(line)
        out.append(Collective(op=m.group(2), elems=shape_elems(shape),
                              bytes=shape_bytes(shape), group_size=gsz,
                              line=line.strip(), comp=comp))
    if any(c.group_size is None and c.line.find("-done(") >= 0
           for c in out):
        # map -done ops to their -start's replica_groups via operand name
        starts = {}
        for raw in hlo_text.splitlines():
            sm = re.match(r"^\s*(?:ROOT\s+)?%?([\w.\-]+) = (?:\(.*?\)|\S+) "
                          r"(?:" + "|".join(_COLLECTIVES) + r")-start\(",
                          raw)
            if sm:
                starts[sm.group(1)] = _group_size(raw)
        fixed = []
        for c in out:
            if c.group_size is None:
                dm = re.search(r"-done\(%?([\w.\-]+)\)", c.line)
                if dm and dm.group(1) in starts:
                    c = c._replace(group_size=starts[dm.group(1)])
            fixed.append(c)
        out = fixed
    return out


def wire_elements(colls) -> int:
    """Ring-model wire cost in elements: all-reduce = 2x its size.
    Accepts 4-tuples or :class:`Collective` records."""
    return sum(c[1] * (2 if c[0] == "all-reduce" else 1) for c in colls)


def wire_bytes_of(colls) -> int:
    """Ring-model wire cost in result-payload bytes (int8-aware);
    requires :class:`Collective` records."""
    return sum(c.bytes * (2 if c.op == "all-reduce" else 1) for c in colls)


def dense_allreduce_ring_bytes(n: int, world: int,
                               dtype_bytes: int = 2) -> int:
    """Theory baseline: per-rank bytes of a dense ring allreduce of
    ``n`` elements (reduce-scatter + all-gather legs)."""
    return 2 * (world - 1) * n * dtype_bytes // world


def send_bytes_of(colls, default_group: Optional[int] = None) -> int:
    """Per-rank SEND volume in bytes: result-payload bytes converted by
    each collective's replica-group size. An all-gather / all-to-all
    result of ``n`` bytes over a group of ``g`` means each rank sent
    (and received) ``(g-1)/g * n`` — its own chunk never crossed the
    wire; an all-reduce ring costs 2x that. This is the convention the
    host-side wire model (``quantized_collectives.wire_bytes``) reports,
    so model-vs-HLO drift checks compare like for like instead of
    carrying the W/(W-1) fudge factor around. ``default_group`` covers
    collectives with no replica_groups attribute (single whole-world
    group)."""
    total = 0.0
    for c in colls:
        g = c.group_size or default_group
        f = (g - 1) / g if g and g > 1 else 1.0
        total += c.bytes * f * (2 if c.op == "all-reduce" else 1)
    return int(round(total))


_GATHER_PAT = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+ = (\(.*?\)|\S+) gather\(")


def gather_ops(hlo_text) -> List[Tuple[int, int, str]]:
    """[(result_elems, result_bytes, result_shape)] for every ``gather``
    instruction in a compiled HLO module. The paged-serving bandwidth
    audits use this to pin WHERE decode reads come from: the
    stripe-gather decode path materializes a gather of every table
    entry's page per layer (a ``max_len``-bounded tensor), while the
    fused Pallas decode kernel's program contains no pool-sized gather
    at all — its pool reads are per-page dynamic slices."""
    out = []
    for line in hlo_text.splitlines():
        m = _GATHER_PAT.match(line)
        if m:
            shape = m.group(1)
            out.append((shape_elems(shape), shape_bytes(shape),
                        shape.strip("()")))
    return out


def max_gather_elems(hlo_text) -> int:
    """Largest single gather result (elements) in a compiled module;
    0 when the program contains no gather."""
    return max((e for e, _, _ in gather_ops(hlo_text)), default=0)


def while_body_comps(hlo_text):
    """Names of computations used as while-loop bodies (lax.scan /
    fori_loop lower to these)."""
    return {m.group(1)
            for m in re.finditer(r"\bbody=%?([\w.\-]+)", hlo_text)}


_DEF_PAT = re.compile(r"^\s*(?:ROOT\s+)?%?([\w.\-]+) = ")
# compute markers: a dot-general, a convolution, or a backend matmul
# custom-call (the CPU backend may rewrite dots to oneDNN custom-calls)
_COMPUTE_PAT = re.compile(r"\b(?:dot|convolution)\(|__onednn|\$matmul|"
                          r"custom-call.*gemm", re.IGNORECASE)
_CALLS_PAT = re.compile(r"(?:calls|to_apply|body|condition|"
                        r"true_computation|false_computation)="
                        r"%?([\w.\-]+)")


def _body_defs(hlo_text, comp_name):
    """{instr name: line} for one computation's body."""
    defs = {}
    for line in hlo_computation_body(hlo_text, comp_name):
        m = _DEF_PAT.match(line)
        if m:
            defs[m.group(1)] = line
    return defs


def _comp_has_compute(hlo_text, comp_name, _memo=None):
    """True if a computation (or anything it calls) contains a
    dot/convolution/matmul instruction."""
    if _memo is None:
        _memo = {}
    if comp_name in _memo:
        return _memo[comp_name]
    _memo[comp_name] = False          # cycle guard
    hit = False
    for line in hlo_computation_body(hlo_text, comp_name):
        if _COMPUTE_PAT.search(line):
            hit = True
            break
        for cm in _CALLS_PAT.finditer(line):
            if _comp_has_compute(hlo_text, cm.group(1), _memo):
                hit = True
                break
        if hit:
            break
    _memo[comp_name] = hit
    return hit


def _line_operands(line):
    """Names referenced after the '=' of an instruction line (operands
    plus called-computation attrs — the cone walk filters by the body's
    def map, and inspects called computations separately)."""
    eq = line.find(" = ")
    return re.findall(r"%([\w.\-]+)", line[eq + 3:] if eq >= 0 else line)


def _cone_walk(hlo_text, defs, root_names, memo):
    """BFS over the operand cone of ``root_names`` within one body's
    ``defs`` map; True when it reaches compute (directly or inside a
    called computation). ``memo`` caches per-computation compute
    lookups across walks — overlap_structure shares one across every
    collective it audits."""
    seen = set()
    frontier = []
    for r in root_names:
        frontier.extend(o for o in _line_operands(defs[r]) if o in defs)
    while frontier:
        name = frontier.pop()
        if name in seen:
            continue
        seen.add(name)
        line = defs[name]
        if _COMPUTE_PAT.search(line):
            return True
        for cm in _CALLS_PAT.finditer(line):
            if _comp_has_compute(hlo_text, cm.group(1), memo):
                return True
        frontier.extend(o for o in _line_operands(line) if o in defs)
    return False


def cone_reaches_compute(hlo_text, comp_name, root_pred):
    """Dependence audit for compute/comm overlap: does the operand cone
    of any instruction matching ``root_pred`` (a predicate on the raw
    line) inside computation ``comp_name`` reach a dot-general /
    convolution / matmul — transitively through operands, and through
    fusion/call bodies?

    A SERIAL exchange consumes gradients produced by the same
    iteration's backward, so its cone contains dot-generals. An
    OVERLAPPED (double-buffered) exchange consumes only the loop carry
    — its cone is dot-free, which is exactly the structural fact that
    lets the scheduler run it concurrently with the next micro-step's
    compute. Scheduler- and backend-independent, unlike textual
    instruction order."""
    defs = _body_defs(hlo_text, comp_name)
    roots = [name for name, line in defs.items() if root_pred(line)]
    return _cone_walk(hlo_text, defs, roots, {})


def overlap_structure(hlo_text, payload_pred=lambda line: "s8[" in line):
    """Structural overlap report of a compiled fused-step program, for
    the hardware-free ``comm_overlap_structure`` bench row and the
    tier-1 overlap audits.

    Looks at every while-loop body that contains both compute
    (dot-general/matmul) and collectives whose line matches
    ``payload_pred`` (default: int8 payloads — the quantized exchange),
    and reports::

        exchange_collectives   total matching collectives in loop bodies
        overlap_free           how many have a dot-free operand cone
                               (structurally overlappable with the
                               iteration's compute)
        overlap_fraction       overlap_free / exchange_collectives
        interleaved_fraction   fraction positioned between the first
                               and last dot-general in the printed body
                               (schedule-order view; serial ~ tail)
        flush_outside_loop     matching collectives OUTSIDE loop bodies
                               (the post-scan flush of the last window)
    """
    bodies = while_body_comps(hlo_text)
    total = free = 0
    interleaved = 0
    in_body_names = set()
    memo = {}          # shared per-computation compute cache
    for comp in bodies:
        defs = _body_defs(hlo_text, comp)
        in_body_names |= set(defs)
        lines = list(defs.items())
        coll = [(i, name) for i, (name, line) in enumerate(lines)
                if any(op + "(" in line for op in _COLLECTIVES)
                and payload_pred(line)]
        dots = [i for i, (_, line) in enumerate(lines)
                if _COMPUTE_PAT.search(line)]
        if not coll or not dots:
            continue
        total += len(coll)
        lo, hi = min(dots), max(dots)
        interleaved += sum(1 for i, _ in coll if lo < i < hi)
        for _, name in coll:
            if not _cone_walk(hlo_text, defs, [name], memo):
                free += 1
    outside = 0
    for c in collect_collectives_full(hlo_text):
        if payload_pred(c.line):
            m = _DEF_PAT.match(c.line)
            name = m.group(1) if m else None
            if name not in in_body_names:
                outside += 1
    return {
        "exchange_collectives": total,
        "overlap_free": free,
        "overlap_fraction": (free / total) if total else 0.0,
        "interleaved_fraction": (interleaved / total) if total else 0.0,
        "flush_outside_loop": outside,
    }


def conditional_branch_comps(hlo_text):
    """Names of computations used as lax.cond branches (direct bodies)."""
    names = set()
    for m in re.finditer(r"(?:true_computation|false_computation)="
                         r"%?([\w.\-]+)", hlo_text):
        names.add(m.group(1))
    for m in re.finditer(r"branch_computations=\{([^}]*)\}", hlo_text):
        for n in m.group(1).split(","):
            names.add(n.strip().lstrip("%"))
    return names


def hlo_computation_body(hlo_text, comp_name):
    """Lines of one named HLO computation's body."""
    lines = hlo_text.splitlines()
    out, inside = [], False
    pat = re.compile(r"^\s*(?:ENTRY\s+)?%?" + re.escape(comp_name) +
                     r"\s*\(")
    for line in lines:
        if not inside and pat.match(line) and "{" in line:
            inside = True
            continue
        if inside:
            if line.strip() == "}" or line.strip().startswith("}"):
                break
            out.append(line)
    return out
