"""Compiled-HLO collective accounting (shared by tests and bench).

The only multi-chip perf evidence a single-host rig can produce:
compile the partitioned program on a virtual CPU mesh, walk the HLO,
and pin communication volume to theory. Used by
``tests/unit/test_hlo_collectives.py`` / ``test_hlo_quantized_comm.py``
and by ``bench.py``'s hardware-free ``comm_wire_bytes_per_step`` row.

Counting rules:

- **Elements** are backend-invariant for float math comparisons (the
  CPU backend upcasts bf16 dots to f32, so float byte counts are not).
- **Bytes** ARE meaningful for quantized payloads: int8 collectives
  stay s8 in HLO on every backend (FloatNormalization touches only
  floats), which is exactly what the quantized-comm audits measure.
- all-reduce counts 2x its size (ring cost = reduce-scatter +
  all-gather); all-to-all / all-gather / reduce-scatter /
  collective-permute count 1x their output.
- async pairs count ONCE: the ``-start`` form is skipped (its tuple
  result carries operand + result, double-counting the transfer) and
  the ``-done`` form's plain result is counted.
"""

import re
from typing import List, NamedTuple, Optional

__all__ = ["HLO_DTYPE_BYTES", "shape_elems", "shape_bytes",
           "Collective", "collect_collectives", "collect_collectives_full",
           "wire_elements", "wire_bytes_of", "conditional_branch_comps",
           "hlo_computation_body", "dense_allreduce_ring_bytes"]

# dtype name -> byte width; accounting by ELEMENTS uses only the names
HLO_DTYPE_BYTES = {"f64": 8, "s64": 8, "u64": 8,
                   "f32": 4, "s32": 4, "u32": 4,
                   "bf16": 2, "f16": 2, "s16": 2, "u16": 2,
                   "s8": 1, "u8": 1, "pred": 1}

_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                "collective-permute", "all-to-all")


def _shapes(shape_str):
    """[(dtype, elems)] for every array in an HLO result type (handles
    tuples)."""
    out = []
    for dt, dims in re.findall(r"(\w+)\[([\d,]*)\]", shape_str):
        if dt not in HLO_DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        out.append((dt, n))
    return out


def shape_elems(shape_str) -> int:
    """Total elements across every array in an HLO result type."""
    return sum(n for _, n in _shapes(shape_str))


def shape_bytes(shape_str) -> int:
    """Total payload bytes across every array in an HLO result type."""
    return sum(n * HLO_DTYPE_BYTES[dt] for dt, n in _shapes(shape_str))


def _group_size(line) -> Optional[int]:
    """Devices per replica group of a collective instruction, parsed from
    either the explicit ``replica_groups={{0,1},{2,3}}`` form or the
    iota ``replica_groups=[G,S]<=[...]`` form (S = group size). None if
    the attribute is absent (single-group collective)."""
    m = re.search(r"replica_groups=\[(\d+),(\d+)\]<=", line)
    if m:
        return int(m.group(2))
    m = re.search(r"replica_groups=\{\{([\d,]*)\}", line)
    if m:
        ids = [x for x in m.group(1).split(",") if x]
        return len(ids)
    return None


class Collective(NamedTuple):
    op: str            # e.g. "all-gather"
    elems: int         # result elements (transfer size, counting rules)
    bytes: int         # result payload bytes (int8-aware)
    group_size: Optional[int]  # devices per replica group
    line: str
    comp: Optional[str]        # enclosing HLO computation name


def _iter_collectives(hlo_text):
    comp = None
    comp_pat = re.compile(r"^\s*(?:ENTRY\s+)?%?([\w.\-]+)\s*\([^=]*\)\s*->")
    # the result type may be a variadic tuple whose long form carries
    # /*index=N*/ comments (which contain '='), so match lazily up to
    # the op name rather than forbidding '=' inside parens
    pat = re.compile(
        r"^\s*(?:ROOT\s+)?%?[\w.\-]+ = (\(.*?\)|\S+) "
        r"(" + "|".join(_COLLECTIVES) + r")(-start|-done)?\(",
    )
    for line in hlo_text.splitlines():
        cm = comp_pat.match(line)
        if cm and "{" in line:
            comp = cm.group(1)
        m = pat.match(line)
        if m:
            if m.group(3) == "-start":
                continue            # counted at the matching -done
            yield m, line, comp


def collect_collectives(hlo_text):
    """[(op, result_elems, line, computation)] for every collective
    instruction in a compiled (SPMD-partitioned) HLO module — the
    4-tuple shape the element-count audits consume."""
    return [(m.group(2), shape_elems(m.group(1)), line.strip(), comp)
            for m, line, comp in _iter_collectives(hlo_text)]


def collect_collectives_full(hlo_text) -> List[Collective]:
    """:class:`Collective` records with byte accounting and replica-group
    sizes — what the quantized-comm audits need (int8 payloads, and
    which mesh axis a collective ran over, identified by group size)."""
    out = []
    for m, line, comp in _iter_collectives(hlo_text):
        shape = m.group(1)
        # async -done: replica_groups live on the matching -start line
        gsz = _group_size(line)
        out.append(Collective(op=m.group(2), elems=shape_elems(shape),
                              bytes=shape_bytes(shape), group_size=gsz,
                              line=line.strip(), comp=comp))
    if any(c.group_size is None and c.line.find("-done(") >= 0
           for c in out):
        # map -done ops to their -start's replica_groups via operand name
        starts = {}
        for raw in hlo_text.splitlines():
            sm = re.match(r"^\s*(?:ROOT\s+)?%?([\w.\-]+) = (?:\(.*?\)|\S+) "
                          r"(?:" + "|".join(_COLLECTIVES) + r")-start\(",
                          raw)
            if sm:
                starts[sm.group(1)] = _group_size(raw)
        fixed = []
        for c in out:
            if c.group_size is None:
                dm = re.search(r"-done\(%?([\w.\-]+)\)", c.line)
                if dm and dm.group(1) in starts:
                    c = c._replace(group_size=starts[dm.group(1)])
            fixed.append(c)
        out = fixed
    return out


def wire_elements(colls) -> int:
    """Ring-model wire cost in elements: all-reduce = 2x its size.
    Accepts 4-tuples or :class:`Collective` records."""
    return sum(c[1] * (2 if c[0] == "all-reduce" else 1) for c in colls)


def wire_bytes_of(colls) -> int:
    """Ring-model wire cost in result-payload bytes (int8-aware);
    requires :class:`Collective` records."""
    return sum(c.bytes * (2 if c.op == "all-reduce" else 1) for c in colls)


def dense_allreduce_ring_bytes(n: int, world: int,
                               dtype_bytes: int = 2) -> int:
    """Theory baseline: per-rank bytes of a dense ring allreduce of
    ``n`` elements (reduce-scatter + all-gather legs)."""
    return 2 * (world - 1) * n * dtype_bytes // world


def conditional_branch_comps(hlo_text):
    """Names of computations used as lax.cond branches (direct bodies)."""
    names = set()
    for m in re.finditer(r"(?:true_computation|false_computation)="
                         r"%?([\w.\-]+)", hlo_text):
        names.add(m.group(1))
    for m in re.finditer(r"branch_computations=\{([^}]*)\}", hlo_text):
        for n in m.group(1).split(","):
            names.add(n.strip().lstrip("%"))
    return names


def hlo_computation_body(hlo_text, comp_name):
    """Lines of one named HLO computation's body."""
    lines = hlo_text.splitlines()
    out, inside = [], False
    pat = re.compile(r"^\s*(?:ENTRY\s+)?%?" + re.escape(comp_name) +
                     r"\s*\(")
    for line in lines:
        if not inside and pat.match(line) and "{" in line:
            inside = True
            continue
        if inside:
            if line.strip() == "}" or line.strip().startswith("}"):
                break
            out.append(line)
    return out
