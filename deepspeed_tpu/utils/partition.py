"""Partitioning math shared by pipeline-module layer assignment and ZeRO.

TPU-native analog of the reference's ``deepspeed/runtime/utils.py`` partition
helpers (partition_uniform :295, partition_balanced :361 with binary-search +
linear probe _lprobe :310).
"""

import bisect
from typing import List, Sequence


def partition_uniform(num_items: int, num_parts: int) -> List[int]:
    """Split ``num_items`` into ``num_parts`` near-equal contiguous chunks.

    Returns ``num_parts+1`` boundaries; part p owns [parts[p], parts[p+1]).
    Remainder spread one-each over the leading parts (so sizes differ by at
    most 1 — an improvement over the reference's floor+tail-dump).
    """
    if num_parts <= 0:
        raise ValueError("num_parts must be positive")
    base, rem = divmod(num_items, num_parts)
    parts = [0]
    for p in range(num_parts):
        parts.append(parts[-1] + base + (1 if p < rem else 0))
    return parts


def prefix_sum_inc(weights: Sequence[float]) -> List[float]:
    """Inclusive prefix sum (reference runtime/utils.py:303)."""
    out = []
    acc = 0
    for w in weights:
        acc += w
        out.append(acc)
    return out


def partition_balanced(weights: Sequence[float], num_parts: int) -> List[int]:
    """Balanced contiguous partition of weighted items.

    Minimizes the maximum part weight (same contract as reference
    runtime/utils.py:361). Implemented as a binary search over the bottleneck
    value with a greedy feasibility check — O(n log(sum/min_gap)) instead of
    the reference's probe loop, same results on its test cases.
    """
    n = len(weights)
    if num_parts <= 0:
        raise ValueError("num_parts must be positive")
    if n == 0:
        return [0] * (num_parts + 1)

    prefix = prefix_sum_inc(weights)
    total = prefix[-1]

    def feasible(bottleneck: float) -> List[int] | None:
        """Greedy: place each boundary as far right as possible while the
        part weight stays <= bottleneck."""
        parts = [0]
        start_w = 0.0
        for _ in range(num_parts):
            # furthest index j such that prefix[j-1] - start_w <= bottleneck
            j = bisect.bisect_right(prefix, start_w + bottleneck)
            j = max(j, parts[-1])  # never move backwards
            parts.append(j)
            if j >= n:
                break
            start_w = prefix[j - 1] if j > 0 else 0.0
        while len(parts) < num_parts + 1:
            parts.append(n)
        return parts if parts[num_parts] == n else None

    lo = max((w for w in weights), default=0.0)
    hi = total
    # binary search on the bottleneck weight
    for _ in range(64):
        mid = (lo + hi) / 2
        if feasible(mid) is not None:
            hi = mid
        else:
            lo = mid
        if hi - lo <= 1e-9 * max(1.0, total):
            break
    result = feasible(hi)
    assert result is not None
    return result
