"""Platform forcing for subprocess-launched workloads.

Some environments preload an accelerator plugin at interpreter start, so
the ``JAX_PLATFORMS`` env var alone arrives too late to steer backend
selection; the working recipe (tests/conftest.py) is to set
``jax.config.update("jax_platforms", ...)`` before the first jax use.
This helper applies the same recipe from environment variables so
CLI-launched training scripts (tests/model harnesses, the launcher) can
force a platform:

- ``DSTPU_PLATFORM``      : e.g. ``cpu`` — force the jax platform
- ``DSTPU_HOST_DEVICES``  : N — with cpu, provision N host devices
                            (``--xla_force_host_platform_device_count``)

Call before any jax computation (importing jax is fine; initializing its
backend is not).
"""

import os

_CACHE_ENABLED_DIR = None


def enable_compile_cache(cache_dir, min_compile_secs=1.0) -> bool:
    """Point JAX's persistent compilation cache at ``cache_dir`` so
    re-runs (bench children, resumed jobs, repeated CLI launches) load
    compiled executables from disk instead of re-paying XLA compiles —
    which through a remote-compile tunnel can dominate wall time.

    Idempotent; returns True when the cache is active. A second call
    with a DIFFERENT dir is ignored (jax's cache dir is global) and
    returns False. ``cache_dir=None`` selects the per-user default
    (``constants.COMPILE_CACHE_DIR_DEFAULT``).
    """
    global _CACHE_ENABLED_DIR
    if cache_dir is None:
        from ..runtime.constants import COMPILE_CACHE_DIR_DEFAULT
        cache_dir = COMPILE_CACHE_DIR_DEFAULT
    cache_dir = os.path.expanduser(cache_dir)
    if _CACHE_ENABLED_DIR is not None:
        return _CACHE_ENABLED_DIR == cache_dir
    import jax
    # validate + set the threshold BEFORE the dir: if anything here
    # raises, the cache dir is still unset and the cache truly inactive
    try:
        secs = float(min_compile_secs)
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          secs)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except (OSError, AttributeError, ValueError, TypeError):
        return False   # unwritable dir / older jax / bad value: uncached
    _CACHE_ENABLED_DIR = cache_dir
    return True


def apply_platform_env() -> None:
    plat = os.environ.get("DSTPU_PLATFORM")
    if not plat:
        return
    n = os.environ.get("DSTPU_HOST_DEVICES")
    if n:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") +
            f" --xla_force_host_platform_device_count={int(n)}")
    import jax
    jax.config.update("jax_platforms", plat)
