"""Platform forcing for subprocess-launched workloads.

Some environments preload an accelerator plugin at interpreter start, so
the ``JAX_PLATFORMS`` env var alone arrives too late to steer backend
selection; the working recipe (tests/conftest.py) is to set
``jax.config.update("jax_platforms", ...)`` before the first jax use.
This helper applies the same recipe from environment variables so
CLI-launched training scripts (tests/model harnesses, the launcher) can
force a platform:

- ``DSTPU_PLATFORM``      : e.g. ``cpu`` — force the jax platform
- ``DSTPU_HOST_DEVICES``  : N — with cpu, provision N host devices
                            (``--xla_force_host_platform_device_count``)

Call before any jax computation (importing jax is fine; initializing its
backend is not).
"""

import os


def apply_platform_env() -> None:
    plat = os.environ.get("DSTPU_PLATFORM")
    if not plat:
        return
    n = os.environ.get("DSTPU_HOST_DEVICES")
    if n:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") +
            f" --xla_force_host_platform_device_count={int(n)}")
    import jax
    jax.config.update("jax_platforms", plat)
