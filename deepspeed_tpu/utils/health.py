"""Health plane: flight recorder, stall watchdog, numeric anomaly
detectors (ISSUE 15).

The repo's in-band telemetry (profiling Observer, serve tracer, fleet
metrics) explains a run *while it is healthy*; this module explains it
when it dies or wedges. At pod scale collective schedules fail as
hangs and stragglers before they fail as errors ("The Big Send-off",
PAPERS.md) — a stack that survives preemption but can't say which
phase stalled or why the loss exploded is only half-observable. Four
pieces, all host-side:

- :class:`FlightRecorder` — a bounded in-memory ring of the last N
  telemetry rows, fed by tapping the monitor's mirror writer (the same
  stream ``events.jsonl`` gets — no second emission path). Zero
  steady-state I/O; on an uncaught exception, a preemption drain, a
  watchdog trip, or an armed fault the ring is dumped *atomically* to
  ``flight.json`` (tmp + ``os.replace``) — the crash-safe black box.
- :class:`Watchdog` — a daemon thread fed :meth:`HealthPlane.heartbeat`
  at every dispatch/phase boundary (pinned :data:`HEALTH_PHASES`
  vocabulary). ``stall_timeout_s`` without a beat dumps all-thread
  stacks (``sys._current_frames``) plus the flight ring, emits a
  ``stall_detected`` event row naming the last phase, then either
  warns or exits with :data:`STALL_EXIT_CODE` (distinguishable from
  elastic's RESUMABLE_EXIT_CODE=85 and an uncaught SIGTERM's 143).
- :class:`NumericHealth` — anomaly detectors over values the engine
  already materialized host-side at its deferred-telemetry flush
  barriers (NEVER an added device sync): nonfinite-loss streaks,
  rolling-window loss-spike z-score, grad-norm explosion, loss-scale
  collapse, recompile storms. Alerts are ``health`` event rows with a
  reason from the pinned :data:`HEALTH_REASONS` vocabulary plus a
  cumulative ``Health/alerts`` scalar (monitor.TAG_HEALTH_ALERTS).
- :class:`HealthPlane` — the engine-facing facade (train, pipe,
  inference, fleet, bench all wire it); construction always succeeds
  and every method no-ops when disabled, so callers wire it
  unconditionally like the profiling Observer.

Deliberately stdlib-only (no jax import): the watchdog must be able to
dump stacks while the process is wedged *inside* a device call, and
``bench.py``'s ladder children arm it before any backend import.
Config: ``observability.health:{}`` (runtime/config.py validates it;
docs/config.md documents it). ``tools/obs_report.py --health`` renders
the postmortem.
"""

import json
import os
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Callable, Dict, Optional

from deepspeed_tpu.utils.logging import logger

__all__ = [
    "HEALTH_PHASES", "HEALTH_REASONS", "STALL_EXIT_CODE",
    "FlightRecorder", "Watchdog", "NumericHealth", "HealthPlane",
    "load_flight",
]

#: Pinned heartbeat phase vocabulary — one name per dispatch/phase
#: boundary wired through the engines (tests pin the set; an unknown
#: phase raises so a new boundary must be added HERE, where obs_report
#: and the docs can see it).
HEALTH_PHASES = (
    "train_batch",        # engine.train_batch / pipe train_batch window
    "prefill",            # inference prefill phase
    "decode",             # inference decode/verify phase
    "handoff_claim",      # disagg decode-worker handoff intake
    "chunk_prefill",      # chunked-prefill chunk dispatch (ISSUE 19)
    "checkpoint_commit",  # save snapshot/commit stages
    "fleet_step",         # FleetRouter scheduling round
    "bench_metric",       # bench.py ladder child metric body
    "rpc_call",           # router-side blocking RPC wait on a replica
)

#: Pinned numeric-anomaly reason vocabulary (``health`` event rows).
HEALTH_REASONS = (
    "nan_loss",             # nonfinite-loss streak
    "loss_spike",           # rolling-window z-score blowout
    "grad_norm_explosion",  # grad norm above the configured ceiling
    "loss_scale_collapse",  # dynamic loss scale ground into the floor
    "recompile_storm",      # steady-state recompiles in a short window
)

# Distinguished "watchdog tripped and on_stall=exit" code: 85 is the
# elastic resumable-preemption code, 143 an uncaught SIGTERM — a
# supervisor (or bench parent) can tell a diagnosed stall from both.
STALL_EXIT_CODE = 87


def _atomic_write_json(path: str, payload: dict) -> None:
    """tmp + fsync + os.replace: a crash mid-dump leaves either the
    previous flight.json or the new one, never a torn file."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=2, default=str)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_flight(path) -> Optional[dict]:
    """Salvage a flight recorder dump (the black box a dead process
    left behind): returns the parsed payload, or None when the file is
    missing/unreadable/torn. The fleet router uses this to fold a dead
    replica's last moments into ITS OWN event trail
    (``fleet_flight_salvage`` rows) — the atomic dump protocol means a
    readable file is always a complete one."""
    if not path:
        return None
    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return None
    return payload if isinstance(payload, dict) else None


def _all_thread_stacks() -> Dict[str, Any]:
    """Formatted stacks of every live thread (the wedge diagnosis)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    stacks = {}
    for ident, frame in sys._current_frames().items():
        label = f"{names.get(ident, '?')} ({ident})"
        stacks[label] = traceback.format_stack(frame)
    return stacks


class _MirrorTap:
    """Transparent tee in front of a monitor mirror (`_JsonlWriter`
    duck type): forwards every row to the inner writer unchanged AND
    copies it into the flight ring. Installing/removing the tap can
    never change what lands in events.jsonl — the zero-perturbation
    contract."""

    def __init__(self, inner, ring: "FlightRecorder"):
        self.inner = inner
        self._ring = ring

    def add_scalar(self, tag, value, step):
        self._ring.record({"tag": str(tag), "value": float(value),
                           "step": int(step)})
        if self.inner is not None:
            self.inner.add_scalar(tag, value, step)

    def add_event(self, kind, **fields):
        row = {"event": str(kind)}
        row.update(fields)
        self._ring.record(row)
        if self.inner is not None:
            self.inner.add_event(kind, **fields)

    def flush(self):
        if self.inner is not None:
            self.inner.flush()

    def close(self):
        if self.inner is not None:
            self.inner.close()


class FlightRecorder:
    """Bounded ring of the last ``ring_events`` telemetry rows, dumped
    atomically to ``flight.json`` on demand. Steady state is an
    O(1) deque append per row — no I/O, no growth."""

    def __init__(self, flight_path: str, ring_events: int = 256):
        self.flight_path = flight_path
        self.ring: deque = deque(maxlen=max(1, int(ring_events)))
        self._lock = threading.Lock()
        self._taps = []            # (monitor, tap) pairs installed
        self._prev_excepthook = None
        self.dumps = 0

    # ------------------------------------------------------------- feed
    def record(self, row: dict) -> None:
        with self._lock:
            self.ring.append(row)

    def tap(self, monitor) -> None:
        """Interpose on ``monitor.mirror`` so every mirrored scalar/
        event row is copied into the ring on its way to events.jsonl.
        Works with ``mirror=None`` too (ring-only)."""
        tap = _MirrorTap(getattr(monitor, "mirror", None), self)
        monitor.mirror = tap
        self._taps.append((monitor, tap))

    def untap(self) -> None:
        """Restore every tapped monitor's original mirror (engine
        close path — the profiling Observer's identity check on its own
        writer must see the raw mirror again)."""
        for monitor, tap in self._taps:
            if getattr(monitor, "mirror", None) is tap:
                monitor.mirror = tap.inner
        self._taps.clear()

    # ---------------------------------------------------------- dumping
    def dump(self, trigger: str, extra: Optional[dict] = None,
             stacks: bool = False) -> Optional[str]:
        """Write the black box. Returns the path, or None on failure
        (best-effort by design: the dump runs on crash paths where
        raising would mask the original error)."""
        with self._lock:
            rows = list(self.ring)
        payload = {
            "trigger": str(trigger),
            "pid": os.getpid(),
            "time_unix": time.time(),
            "ring_events": self.ring.maxlen,
            "rows": rows,
        }
        if stacks:
            payload["stacks"] = _all_thread_stacks()
        if extra:
            payload.update(extra)
        try:
            _atomic_write_json(self.flight_path, payload)
        except Exception as e:
            logger.warning(f"health: flight dump failed ({e!r})")
            return None
        self.dumps += 1
        return self.flight_path

    # ------------------------------------------- uncaught-exception hook
    def install_excepthook(self) -> None:
        """Chain onto ``sys.excepthook``: an uncaught exception dumps
        the flight ring (with the exception identity) before the
        previous hook prints the traceback."""
        if self._prev_excepthook is not None:
            return
        self._prev_excepthook = sys.excepthook

        def hook(exc_type, exc, tb):
            try:
                self.dump("exception", extra={
                    "exception": {
                        "type": getattr(exc_type, "__name__",
                                        str(exc_type)),
                        "value": str(exc),
                        "traceback": traceback.format_exception(
                            exc_type, exc, tb),
                    }}, stacks=True)
            except Exception:
                pass
            prev = self._prev_excepthook or sys.__excepthook__
            prev(exc_type, exc, tb)

        sys.excepthook = hook
        self._hook = hook

    def uninstall_excepthook(self) -> None:
        if self._prev_excepthook is None:
            return
        if sys.excepthook is getattr(self, "_hook", None):
            sys.excepthook = self._prev_excepthook
        self._prev_excepthook = None


class Watchdog:
    """Daemon thread that trips when ``stall_timeout_s`` passes without
    a heartbeat. The trip collects every thread's stack, dumps the
    flight ring, reports through ``on_trip(phase, silent_s, stacks)``,
    then either warns (and re-arms) or exits the process with
    :data:`STALL_EXIT_CODE`."""

    def __init__(self, stall_timeout_s: float, on_stall: str = "warn",
                 on_trip: Optional[Callable[..., None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.stall_timeout_s = float(stall_timeout_s)
        self.on_stall = on_stall
        self._on_trip = on_trip
        self._clock = clock
        self._last_beat = clock()
        self._last_phase: Optional[str] = None
        self._last_detail: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.trips = 0

    def start(self) -> None:
        if self._thread is not None:
            return
        self._last_beat = self._clock()
        self._thread = threading.Thread(
            target=self._run, name="dstpu-health-watchdog", daemon=True)
        self._thread.start()

    def beat(self, phase: str, detail: Optional[str] = None) -> None:
        # plain assignments: atomic under the GIL, no lock on the hot
        # path (the poll thread tolerates a torn phase/beat pair — it
        # only costs one poll interval of slack). ``detail`` names the
        # specific thing this phase is waiting on (e.g. which replica a
        # blocking rpc_call targets) so a trip can report it.
        self._last_phase = phase
        self._last_detail = detail
        self._last_beat = self._clock()

    def _run(self) -> None:
        poll = max(min(self.stall_timeout_s / 4.0, 1.0), 0.01)
        while not self._stop.wait(poll):
            silent = self._clock() - self._last_beat
            if silent < self.stall_timeout_s:
                continue
            self.trips += 1
            phase = self._last_phase or "(no heartbeat yet)"
            detail = self._last_detail
            stacks = _all_thread_stacks()
            logger.error(
                f"health: watchdog tripped — {silent:.1f}s without a "
                f"heartbeat (last phase {phase!r}"
                + (f" [{detail}]" if detail else "")
                + f", timeout {self.stall_timeout_s:.1f}s)")
            if self._on_trip is not None:
                try:
                    self._on_trip(phase=phase, silent_s=silent,
                                  stacks=stacks, detail=detail)
                except Exception as e:
                    logger.warning(f"health: on_trip failed ({e!r})")
            if self.on_stall == "exit":
                # os._exit, not sys.exit: the main thread is wedged
                # (that is WHY we tripped) and cannot unwind
                os._exit(STALL_EXIT_CODE)
            self.beat(phase, detail)   # warn mode: re-arm, don't spam

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


class NumericHealth:
    """Anomaly detectors over already-host-side telemetry values.

    Each ``observe_*`` method takes plain Python floats the engine
    materialized at its own flush barriers — calling them never forces
    a device sync. Alerts fire through ``on_alert(reason, step,
    detail)`` once per *episode* (entering the bad state), not once
    per sample, so a 10k-step NaN run emits one row, not 10k."""

    def __init__(self, cfg: Dict[str, Any],
                 on_alert: Optional[Callable[..., None]] = None):
        self.cfg = cfg
        self._on_alert = on_alert
        self.alerts_total = 0
        self.alerts_by_reason: Dict[str, int] = {}
        self._nonfinite_run = 0
        self._nan_active = False
        self._window: deque = deque(
            maxlen=max(2, int(cfg.get("spike_window", 32))))
        self._spike_active = False
        self._grad_active = False
        self._scale_active = False
        self._recompile_marks: deque = deque()   # steps of recent compiles
        self._last_recompiles: Optional[float] = None
        self._storm_active = False

    # ------------------------------------------------------------ alerts
    def _alert(self, reason: str, step: int, **detail) -> None:
        assert reason in HEALTH_REASONS, reason
        self.alerts_total += 1
        self.alerts_by_reason[reason] = \
            self.alerts_by_reason.get(reason, 0) + 1
        logger.warning(f"health: {reason} at step {step} ({detail})")
        if self._on_alert is not None:
            self._on_alert(reason=reason, step=step, detail=detail)

    # --------------------------------------------------------- detectors
    def observe_loss(self, loss: Optional[float], step: int) -> None:
        if loss is None:
            return
        loss = float(loss)
        finite = loss == loss and abs(loss) != float("inf")
        if not finite:
            self._nonfinite_run += 1
            streak = int(self.cfg.get("nonfinite_streak", 3))
            if self._nonfinite_run >= streak and not self._nan_active:
                self._nan_active = True
                self._alert("nan_loss", step,
                            streak=self._nonfinite_run)
            return
        self._nonfinite_run = 0
        self._nan_active = False
        # rolling-window z-score spike (finite values only)
        w = self._window
        if len(w) >= max(8, w.maxlen // 4):
            mean = sum(w) / len(w)
            var = sum((v - mean) ** 2 for v in w) / len(w)
            sd = var ** 0.5
            z = (loss - mean) / sd if sd > 0 else 0.0
            zmax = float(self.cfg.get("spike_zscore", 6.0))
            if z > zmax:
                if not self._spike_active:
                    self._spike_active = True
                    self._alert("loss_spike", step, z=round(z, 2),
                                loss=loss, window_mean=round(mean, 6))
            else:
                self._spike_active = False
        w.append(loss)

    def observe_grad_norm(self, norm: Optional[float], step: int) -> None:
        if norm is None:
            return
        norm = float(norm)
        ceiling = float(self.cfg.get("grad_norm_max", 1e4))
        bad = not (norm == norm) or norm > ceiling
        if bad and not self._grad_active:
            self._grad_active = True
            self._alert("grad_norm_explosion", step, grad_norm=norm,
                        ceiling=ceiling)
        elif not bad:
            self._grad_active = False

    def observe_loss_scale(self, scale: Optional[float],
                           step: int) -> None:
        if scale is None:
            return
        scale = float(scale)
        floor = float(self.cfg.get("scale_collapse_below", 2.0))
        if scale < floor:
            if not self._scale_active:
                self._scale_active = True
                self._alert("loss_scale_collapse", step,
                            loss_scale=scale, floor=floor)
        else:
            self._scale_active = False

    def observe_recompiles(self, total: Optional[float],
                           step: int) -> None:
        """Feed the *cumulative* compile counter (the Observability/
        recompiles scalar the tracker already keeps host-side)."""
        if total is None:
            return
        total = float(total)
        if self._last_recompiles is None:
            self._last_recompiles = total
            return
        fresh = int(total - self._last_recompiles)
        self._last_recompiles = total
        for _ in range(max(fresh, 0)):
            self._recompile_marks.append(step)
        window = int(self.cfg.get("recompile_storm_window", 16))
        while self._recompile_marks and \
                self._recompile_marks[0] < step - window:
            self._recompile_marks.popleft()
        count = int(self.cfg.get("recompile_storm_count", 3))
        if len(self._recompile_marks) >= count:
            if not self._storm_active:
                self._storm_active = True
                self._alert("recompile_storm", step,
                            recompiles=len(self._recompile_marks),
                            window_steps=window)
        else:
            self._storm_active = False


class HealthPlane:
    """Engine-facing facade: flight ring + watchdog + detectors behind
    one validated config dict (``observability.health``). Construction
    always succeeds; when ``enabled`` is false every method is a no-op,
    so the engines wire it unconditionally (the Observer pattern).

    ``monitor`` (optional): its mirror gets tapped for the flight ring
    and ``Health/alerts`` scalars go through ``write_scalar``.
    ``events_dir`` anchors the default ``flight.json`` location (next
    to events.jsonl); ``flight_path`` in the config overrides it.
    """

    def __init__(self, cfg: Optional[Dict[str, Any]], monitor=None,
                 rank: int = 0, component: str = "train",
                 events_dir: Optional[str] = None):
        self.cfg = dict(cfg or {})
        self.component = component
        self.enabled = bool(self.cfg.get("enabled")) and rank == 0
        self.monitor = monitor
        self.recorder: Optional[FlightRecorder] = None
        self.watchdog: Optional[Watchdog] = None
        self.detectors: Optional[NumericHealth] = None
        self._closed = False
        if not self.enabled:
            return
        flight_path = self.cfg.get("flight_path") or os.path.join(
            events_dir or "/tmp/deepspeed_tpu_obs",
            f"flight_{component}.json" if component != "train"
            else "flight.json")
        self.flight_path = flight_path
        self.recorder = FlightRecorder(
            flight_path, ring_events=int(self.cfg.get("ring_events", 256)))
        if monitor is not None:
            self.recorder.tap(monitor)
        self.recorder.install_excepthook()
        det = self.cfg.get("detectors") or {}
        if det.get("enabled", True):
            self.detectors = NumericHealth(det, on_alert=self._on_alert)
        timeout = float(self.cfg.get("stall_timeout_s", 0.0) or 0.0)
        if timeout > 0:
            self.watchdog = Watchdog(
                timeout, on_stall=str(self.cfg.get("on_stall", "warn")),
                on_trip=self._on_trip)
            self.watchdog.start()
        logger.info(
            f"health plane enabled ({component}): flight ring "
            f"{self.recorder.ring.maxlen} rows -> {flight_path}"
            + (f", watchdog {timeout:.1f}s ({self.watchdog.on_stall})"
               if self.watchdog else ", watchdog off"))

    # ------------------------------------------------------------- sinks
    def _event(self, kind: str, **fields) -> None:
        """One structured row through the (tapped) mirror: it lands in
        the flight ring AND events.jsonl in one write."""
        mirror = getattr(self.monitor, "mirror", None) \
            if self.monitor is not None else None
        if mirror is not None:
            mirror.add_event(kind, **fields)
            mirror.flush()
        elif self.recorder is not None:
            self.recorder.record({"event": kind, **fields})

    def _on_alert(self, reason: str, step: int, detail: dict) -> None:
        self._event("health", reason=reason, step=step,
                    component=self.component, **detail)
        if self.monitor is not None:
            from deepspeed_tpu.utils.monitor import TAG_HEALTH_ALERTS
            self.monitor.write_scalar(
                TAG_HEALTH_ALERTS,
                self.detectors.alerts_total if self.detectors else 0,
                step)

    def _on_trip(self, phase: str, silent_s: float, stacks: dict,
                 detail: Optional[str] = None) -> None:
        path = None
        if self.recorder is not None:
            path = self.recorder.dump(
                "watchdog", extra={"stall": {
                    "phase": phase, "detail": detail,
                    "silent_s": round(silent_s, 3),
                    "timeout_s": self.watchdog.stall_timeout_s,
                    "component": self.component,
                }, "stacks": stacks})
        self._event("stall_detected", phase=phase, detail=detail,
                    silent_s=round(silent_s, 3),
                    timeout_s=self.watchdog.stall_timeout_s,
                    component=self.component, flight=path)

    # ----------------------------------------------------------- surface
    def heartbeat(self, phase: str, detail: Optional[str] = None) -> None:
        """One liveness beat from a pinned phase boundary. Unknown
        phases raise — the vocabulary is the contract obs_report and
        the stall postmortem render, not free text. ``detail`` is free
        text naming what the phase waits on (e.g. ``"replica 2"`` for
        an ``rpc_call`` beat) — a trip reports it so a hung replica
        call names its target."""
        if phase not in HEALTH_PHASES:
            raise ValueError(
                f"health: unknown heartbeat phase {phase!r} "
                f"(pinned vocabulary: {HEALTH_PHASES})")
        if self.watchdog is not None:
            self.watchdog.beat(phase, detail)

    def observe_loss(self, loss, step: int) -> None:
        if self.detectors is not None:
            self.detectors.observe_loss(loss, step)

    def observe_grad_norm(self, norm, step: int) -> None:
        if self.detectors is not None:
            self.detectors.observe_grad_norm(norm, step)

    def observe_loss_scale(self, scale, step: int) -> None:
        if self.detectors is not None:
            self.detectors.observe_loss_scale(scale, step)

    def observe_recompiles(self, total, step: int) -> None:
        if self.detectors is not None:
            self.detectors.observe_recompiles(total, step)

    def dump(self, trigger: str, **extra) -> Optional[str]:
        """Explicit black-box dump (preemption drain, armed fault)."""
        if self.recorder is None:
            return None
        path = self.recorder.dump(trigger, extra=extra or None,
                                  stacks=True)
        self._event("flight_dump", trigger=trigger, flight=path,
                    component=self.component)
        return path

    @property
    def alerts_total(self) -> int:
        return self.detectors.alerts_total if self.detectors else 0

    def close(self) -> None:
        """Stop the watchdog, restore the mirror, drop the excepthook.
        Idempotent; the engines call it before Observer.close() so the
        Observer's mirror-identity check sees its own writer again."""
        if self._closed or not self.enabled:
            return
        self._closed = True
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.recorder is not None:
            self.recorder.uninstall_excepthook()
            self.recorder.untap()
