"""Scan-amortized device timing for op-level micro-benchmarks.

One copy of the measurement protocol shared by ``bench.py``,
``tools/autotune_blocks.py`` and ``tools/ab_coarse_sparse.py`` (it grew
up in the autotune harness; the copies had started to diverge):

- N grad evals are chained inside ONE dispatch via ``lax.scan`` with a
  tiny gradient feedback into the operands, so XLA can neither hoist
  loop-invariant work nor dedupe the iterations, and the result is a
  scalar.  A per-call timing loop instead pays the device tunnel's
  per-dispatch latency N times AND eagerly transfers every full-tensor
  gradient through it — at S=8192 that measured ~870 ms/call for a
  kernel whose device time is ~10 ms.
- A measurement window must clear an ``floor_mult x RTT`` noise floor or
  the RTT subtraction is itself noise; the scan length is rescaled until
  one does.  A combo that can never clear the floor RAISES — a noise
  reading must never be reported as a measurement (a 20 ms window
  against 66 ms RTT once "measured" 0.00 ms and poisoned the block
  table).
- Refinement windows below the floor (RTT jitter ate them) are
  discarded rather than min()'d in.

Reference analog: the GemmTest autotuner's repeated-timing loop
(csrc/includes/gemm_test.h:27) — on TPU the enemy is tunnel latency,
not cublas algo variance.
"""

import time

import numpy as np

__all__ = ["NoiseFloorError", "measure_rtt", "scan_grad_seconds"]


class NoiseFloorError(RuntimeError):
    """No measurement window cleared the RTT-noise floor.

    Distinct from kernel/compile failures on purpose: callers that fall
    back to a different kernel on ``Exception`` must NOT treat a
    measurement failure as a kernel failure (that would silently publish
    a worse-kernel row where the protocol demands an error row)."""


def measure_rtt():
    """Round-trip of a cached trivial dispatch + scalar fetch, min of 3."""
    import jax
    import jax.numpy as jnp

    zf = jax.jit(lambda: jnp.zeros(()))
    np.asarray(zf())
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        np.asarray(zf())
        ts.append(time.perf_counter() - t0)
    return min(ts)


def scan_grad_seconds(grad_fn, args, rtt, *, start_len=8, max_len=4096,
                      windows=3, floor_mult=8.0, min_floor=0.25,
                      feedback=1e-6, grow_rounds=5, beat=None):
    """Seconds per ``grad_fn(*args)`` eval, measured scan-amortized.

    ``grad_fn`` must return one gradient per positional arg (i.e.
    ``jax.grad(loss, argnums=tuple(range(len(args))))``).  Returns
    ``(seconds_per_eval, scan_length_used)``.  Raises ``NoiseFloorError``
    when no window can clear the RTT-noise floor.  ``beat`` (optional
    zero-arg callable) is invoked after every completed device fetch so
    a caller's stall watchdog can distinguish slow-but-alive remote
    compiles from a dead tunnel.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    def build(length):
        def many(*xs):
            def body(carry, _):
                gs = grad_fn(*carry)
                return tuple(x + feedback * g.astype(x.dtype)
                             for x, g in zip(carry, gs)), ()
            out, _ = lax.scan(body, tuple(xs), None, length=length)
            return jnp.sum(out[0].astype(jnp.float32))
        return jax.jit(many)

    floor = max(floor_mult * rtt, min_floor)
    n, g, w = start_len, None, None
    measured_n = start_len
    for _ in range(grow_rounds):
        measured_n = n
        g = build(n)
        np.asarray(g(*args))      # compile + settle
        if beat is not None:
            beat()
        t0 = time.perf_counter()
        np.asarray(g(*args))
        w = time.perf_counter() - t0 - rtt
        if beat is not None:
            beat()
        if w >= floor:
            break
        if n >= max_len:
            break                 # raise below: floor unreachable
        if w > 0.5 * rtt:
            # trustworthy-enough window: grow by the measured ratio
            factor = int(np.ceil(floor / w * 1.5))
        else:
            # jitter swallowed the window (w ~ 0 or negative): a ratio
            # would explode; grow geometrically instead
            factor = 8
        n = min(n * min(max(factor, 2), 64), max_len)
    if w is None or w < floor:
        raise NoiseFloorError(
            f"window {(w or 0) * 1e3:.1f} ms never cleared the "
            f"{floor * 1e3:.0f} ms RTT-noise floor at scan length "
            f"{measured_n}")
    best = w
    for _ in range(windows - 1):
        t0 = time.perf_counter()
        np.asarray(g(*args))
        w2 = time.perf_counter() - t0 - rtt
        if beat is not None:
            beat()
        if w2 >= floor:           # jitter can eat a refinement window
            best = min(best, w2)
    return best / n, n
