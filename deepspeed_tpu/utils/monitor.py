"""Training metrics monitor (tensorboard).

Reference: the engine's tensorboardX integration
(``deepspeed/runtime/engine.py:14,151-156,780-790,922-936``): rank 0 writes
``Train/Samples/train_loss``, ``Train/Samples/lr``,
``Train/Samples/loss_scale`` and per-timer scalars under
``Train/Samples/<timer>``.

TPU build: ``torch.utils.tensorboard`` (torch-cpu is in the image) when
available; otherwise a JSONL event log with the same (tag, value, step)
records so metrics are never silently dropped. Construction mirrors the
reference's ``get_summary_writer`` naming scheme
(``<base>/<job_name>_<host>`` under ``DLWS_JOB_ID``/``DLTS_JOB_ID`` when
set).
"""

import json
import math
import os
import socket
import time
from typing import Dict, Optional

from deepspeed_tpu.utils.logging import logger

__all__ = ["TensorBoardMonitor", "get_summary_writer", "Histogram"]

# serving telemetry tags (written by write_serving_metrics for the
# inference engine; x-axis = cumulative generated tokens). Canonical
# home — profiling/__init__.py re-exports them into its tag registry;
# stdlib-only tools/obs_report.py mirrors the strings (pinned together
# by tests/unit/test_inference.py).
TAG_SERVE_TTFT = "Serve/ttft_ms"                    # per admitted request
TAG_SERVE_TOKEN_LATENCY = "Serve/token_latency_ms"  # per decode dispatch
TAG_SERVE_TPS = "Serve/tokens_per_sec"              # cumulative rate
TAG_SERVE_QUEUE_DEPTH = "Serve/queue_depth"         # waiting requests
TAG_SERVE_OCCUPANCY = "Serve/batch_occupancy"       # active / total slots
TAG_SERVE_KV_PAGES = "Serve/kv_pages_in_use"        # paged pool occupancy
TAG_SERVE_TOKENS_IN_FLIGHT = "Serve/tokens_in_flight"  # live cache tokens
TAG_SERVE_PREFIX_HIT = "Serve/prefix_hit_rate"      # prompt tokens reused
TAG_SERVE_DECODE_ATTN = "Serve/decode_attn_path"    # 1 = pallas paged
#                                                     kernel, 0 = gather
# request-granular serving plane (ISSUE 9): latency decomposition +
# SLO/goodput accounting (inference/tracing.py ServeTracer)
TAG_SERVE_QUEUE_WAIT = "Serve/queue_wait_ms"        # per admitted request
TAG_SERVE_TBT = "Serve/tbt_ms"                      # per decode dispatch
#                                  (mean per-request time-between-tokens)
TAG_SERVE_SLO = "Serve/slo_attainment"              # finished-in-SLO frac
TAG_SERVE_GOODPUT = "Serve/goodput_tokens_per_s"    # within-SLO tokens/s
# disagg + speculative decoding plane (ISSUE 13): draft acceptance per
# verify dispatch and the prefill->decode handoff leg of TTFT
TAG_SERVE_SPEC_ACCEPT = "Serve/spec_accept_rate"    # accepted/proposed
#                                                     per verify dispatch
TAG_SERVE_HANDOFF = "Serve/handoff_ms"              # per claimed handoff
#                                                     (queue + transfer)
# fleet plane (ISSUE 14): the multi-replica router's shed ladder,
# aggregate queue, and live-weight-swap stamp (inference/fleet.py)
TAG_SERVE_SHED_RATE = "Serve/shed_rate"             # shed / submitted
TAG_SERVE_FLEET_QDEPTH = "Serve/fleet_queue_depth"  # sum of replica queues
TAG_SERVE_WEIGHT_VERSION = "Serve/weight_version"   # committed swap
#                                                     ordinal (0 = boot)
# process-fleet plane (ISSUE 16): live KV-page migrations between
# replicas and supervised child relaunches (inference/fleet.py)
TAG_SERVE_MIGRATIONS = "Serve/migrations"           # live requests moved
TAG_SERVE_REPLICA_RESTARTS = "Serve/replica_restarts"  # supervised
# quantized-serving plane (ISSUE 17): static pool cost per token of KV
# capacity (int8 pools land near half the bf16 figure) and the offline
# quantized-vs-fp-oracle max logit error probe (engine.
# record_quant_logit_err — the serving path never pays for the oracle)
TAG_SERVE_KV_POOL_BPT = "Serve/kv_pool_bytes_per_token"
TAG_SERVE_QUANT_LOGIT_ERR = "Serve/quant_logit_err"
# chunked-prefill plane (ISSUE 19): long prompts land as fixed-size
# chunk dispatches interleaved with decode — the dispatch counter plus
# the per-step WORST time-between-tokens (the bound chunking pins; the
# mean alone would hide a whole-prompt prefill stall)
TAG_SERVE_CHUNK_DISPATCHES = "Serve/chunk_dispatches"  # cumulative
TAG_SERVE_TBT_MAX = "Serve/tbt_max_ms"              # per decode dispatch
# elastic / async-checkpoint plane (ISSUE 10): snapshot-vs-write split
# of every save, the async writer's backlog, and how many times the
# supervisor has relaunched this run. Canonical home — profiling/
# __init__.py re-exports them; tools/obs_report.py mirrors the strings
# (pinned together by tests/unit/test_elastic.py).
TAG_CKPT_SNAPSHOT_MS = "Checkpoint/snapshot_ms"     # device->host copy
TAG_CKPT_WRITE_MS = "Checkpoint/write_ms"           # stage/commit protocol
TAG_CKPT_PENDING = "Checkpoint/pending_saves"       # async writer backlog
TAG_CKPT_RESTARTS = "Checkpoint/restarts"           # supervisor relaunches
# health plane (ISSUE 15): cumulative numeric-anomaly alert count from
# utils/health.py's detectors (nan_loss / loss_spike / ... — the pinned
# HEALTH_REASONS vocabulary rides in the per-alert "health" event rows).
# Canonical home — profiling/__init__.py re-exports it; tools/
# obs_report.py mirrors the string (pinned by tests/unit/test_health.py).
TAG_HEALTH_ALERTS = "Health/alerts"                 # cumulative alerts


class Histogram:
    """Bounded log-bucketed latency histogram (the serving-plane
    percentile sink).

    Last-value scalars can't answer "what was p99 TTFT" without keeping
    every sample; this keeps geometrically-spaced buckets instead —
    memory is bounded by the value range (``O(decades x
    bins_per_decade)`` integer counts, ~300 entries for ns..hours at
    the default resolution), so a serving daemon can record millions of
    requests without growing the host heap. Percentiles are
    approximate: relative error is one bucket width
    (``10^(1/bins_per_decade)`` — ~7.5% at the default 32/decade),
    which is telemetry-grade, not benchmark-grade. Exact ``min``,
    ``max``, ``count`` and ``sum`` ride along for free.
    """

    def __init__(self, bins_per_decade: int = 32, floor: float = 1e-3):
        self.bins_per_decade = int(bins_per_decade)
        self.floor = float(floor)       # values below land in bucket 0
        self._buckets: Dict[int, int] = {}
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def _bucket(self, v: float) -> int:
        if v <= self.floor:
            return 0
        return 1 + int(math.log10(v / self.floor) * self.bins_per_decade)

    def _bucket_value(self, b: int) -> float:
        if b == 0:
            return self.floor
        # geometric midpoint of the bucket's span
        return self.floor * 10.0 ** ((b - 0.5) / self.bins_per_decade)

    def record(self, v) -> None:
        v = float(v)
        if not math.isfinite(v):
            return
        b = self._bucket(v)
        self._buckets[b] = self._buckets.get(b, 0) + 1
        self.count += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)

    @property
    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None

    def percentile(self, q: float) -> Optional[float]:
        """Approximate q-quantile (q in [0, 1]); exact at the
        extremes (q=0 -> min, q=1 -> max)."""
        if not self.count:
            return None
        if q <= 0:
            return self.min
        if q >= 1:
            return self.max
        rank = q * (self.count - 1)
        seen = 0
        for b in sorted(self._buckets):
            seen += self._buckets[b]
            if seen > rank:
                # clamp the bucket estimate into the exact bounds
                return min(max(self._bucket_value(b), self.min), self.max)
        return self.max

    def snapshot(self) -> dict:
        """The report-facing summary (rounded; JSON-friendly)."""
        r = (lambda v: round(v, 3) if v is not None else None)
        return {"count": self.count, "mean": r(self.mean),
                "p50": r(self.percentile(0.50)),
                "p95": r(self.percentile(0.95)),
                "p99": r(self.percentile(0.99)),
                "min": r(self.min), "max": r(self.max)}


class _JsonlWriter:
    """Fallback SummaryWriter look-alike: one JSON object per scalar.

    Crash-safe by construction: the file is opened line-buffered, so
    every record hits the OS the moment it is written — a preempted run
    loses at most the line being formatted, never a buffered backlog.
    Also usable as a context manager, and the fd is reclaimed on GC
    (``__del__``) so abandoned writers don't leak descriptors.

    Schema (pinned by tests/unit/test_monitor.py; tools/obs_report.py
    relies on it): scalar rows are ``{"tag": str, "value": float,
    "step": int}``; structured rows carry ``{"event": str, ...}``.

    ``max_mb`` > 0 turns on size-based rotation: when the live file
    exceeds the limit it is atomically renamed to
    ``events.jsonl.<seq>`` (``os.replace`` — a crash mid-rollover
    leaves either the old name or the new, never a torn file) and a
    fresh ``events.jsonl`` opens, so a long serving run's event log is
    bounded per segment instead of growing without limit.
    ``tools/obs_report.py`` reads the rotated segments back in
    sequence order before the live file.
    """

    def __init__(self, log_dir: str, max_mb: float = 0.0):
        os.makedirs(log_dir, exist_ok=True)
        self.path = os.path.join(log_dir, "events.jsonl")
        self.max_bytes = int(float(max_mb or 0.0) * 2 ** 20)
        self._seq = 1 + max(
            (int(n.rsplit(".", 1)[1])
             for n in os.listdir(log_dir)
             if n.startswith("events.jsonl.")
             and n.rsplit(".", 1)[1].isdigit()), default=0)
        self._open()

    def _open(self):
        self._f = open(self.path, "a", buffering=1)
        self._bytes = self._f.tell()        # append mode: current size

    def _write_line(self, line: str):
        self._f.write(line)
        self._bytes += len(line)
        if self.max_bytes and self._bytes >= self.max_bytes:
            self._rotate()

    def _rotate(self):
        self._f.close()
        os.replace(self.path, f"{self.path}.{self._seq}")
        self._seq += 1
        self._open()

    def add_scalar(self, tag, value, step):
        if self._f is None:
            return
        self._write_line(json.dumps(
            {"tag": str(tag), "value": float(value), "step": int(step)})
            + "\n")

    def add_event(self, kind, **fields):
        """One structured (non-scalar) record, e.g. a compile event.

        Every row is stamped with ``t`` — wall-clock epoch seconds —
        unless the caller supplied one. Event rows are the only record
        the fleet merger (``obs_report --fleet``) can align across
        process boundaries, and alignment needs a shared-epoch clock
        plus the per-replica ``clock_sync`` offsets; ``time.time()`` is
        that clock. Host-side only — never a device sync."""
        if self._f is None:
            return
        row = {"event": str(kind)}
        row.update(fields)
        row.setdefault("t", round(time.time(), 6))
        self._write_line(json.dumps(row, default=str) + "\n")

    def flush(self):
        if self._f is not None:
            self._f.flush()

    def close(self):
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def _make_writer(log_dir: str):
    """torch SummaryWriter, or the JSONL fallback when unavailable."""
    try:
        from torch.utils.tensorboard import SummaryWriter
        return SummaryWriter(log_dir=log_dir)
    except Exception as e:
        logger.warning(f"tensorboard unavailable ({e}); falling back to "
                       f"JSONL event log in {log_dir}")
        return _JsonlWriter(log_dir)


def get_summary_writer(name: str = "DeepSpeedTPUJobName",
                       base: str = os.path.join(os.path.expanduser("~"),
                                                "tensorboard")):
    """(reference ``engine.py:246-254``) Build a SummaryWriter under
    ``<base>/<infra job id>/logs/<name>_<host>``."""
    if "DLWS_JOB_ID" in os.environ:
        infra_job_id = os.environ["DLWS_JOB_ID"]
    elif "DLTS_JOB_ID" in os.environ:
        infra_job_id = os.environ["DLTS_JOB_ID"]
    else:
        infra_job_id = "unknown-job-id"
    summary_writer_dir_name = os.path.join(infra_job_id, "logs")
    return _make_writer(os.path.join(base, summary_writer_dir_name,
                                     name + "_" + socket.gethostname()))


class TensorBoardMonitor:
    """Engine-facing wrapper: no-ops unless enabled and on rank 0.

    ``mirror`` (optional, set by the observability layer) receives a
    copy of every scalar — typically a :class:`_JsonlWriter` — so one
    crash-safe ``events.jsonl`` records the full run even when the
    tensorboard writer is the binary torch one (or disabled entirely).
    """

    def __init__(self, enabled: bool, output_path: Optional[str] = None,
                 job_name: Optional[str] = None, rank: int = 0):
        self.enabled = bool(enabled) and rank == 0
        self.writer = None
        self.mirror = None
        if self.enabled:
            if output_path:
                self.writer = _make_writer(os.path.join(
                    output_path, job_name or "DeepSpeedTPUJobName"))
            else:
                self.writer = get_summary_writer(
                    name=job_name or "DeepSpeedTPUJobName")

    def _writes(self) -> bool:
        return self.writer is not None or self.mirror is not None

    def write_scalar(self, tag: str, value, step: int):
        if self.writer is not None:
            self.writer.add_scalar(tag, float(value), int(step))
        if self.mirror is not None:
            self.mirror.add_scalar(tag, float(value), int(step))

    def write_train_metrics(self, *, loss=None, lr=None, loss_scale=None,
                            samples: int = 0, flush: bool = True):
        """The reference's per-step scalars (engine.py:780-790, 922-936):
        x-axis is cumulative sample count. ``flush=False`` lets the
        engine's deferred-telemetry ring write a whole window of
        records and flush once at the end."""
        if not self._writes():
            return
        if loss is not None:
            self.write_scalar("Train/Samples/train_loss", loss, samples)
        if lr is not None:
            self.write_scalar("Train/Samples/lr", lr, samples)
        if loss_scale is not None:
            self.write_scalar("Train/Samples/loss_scale", loss_scale,
                              samples)
        if flush:
            self.flush()

    def write_checkpoint_event(self, *, action: str, ok: bool = True,
                               duration_ms=None, samples: int = 0):
        """Checkpoint durability telemetry: ``save``/``load`` durations and
        ``fallback`` events (a tag skipped as uncommitted or corrupt), so
        preemption recovery is visible on the same samples x-axis as loss."""
        if not self._writes():
            return
        if duration_ms is not None:
            self.write_scalar(f"Train/Samples/checkpoint_{action}_ms",
                              duration_ms, samples)
        self.write_scalar(f"Train/Samples/checkpoint_{action}_ok",
                          1.0 if ok else 0.0, samples)
        self.flush()

    def write_elastic_metrics(self, *, snapshot_ms=None, write_ms=None,
                              pending_saves=None, restarts=None,
                              samples: int = 0, flush: bool = True):
        """Elastic-resilience telemetry (ISSUE 10): the snapshot-vs-write
        decomposition of each save (the snapshot is the only part the
        step loop waits for under ``checkpoint.async_save``), the async
        writer's backlog, and the supervisor restart count of this
        incarnation — all on the samples x-axis, so a preemption storm
        is visible right next to the loss curve. ``write_ms`` rows may
        be emitted from the background writer thread (one line-buffered
        write; safe under the GIL)."""
        if not self._writes():
            return
        if snapshot_ms is not None:
            self.write_scalar(TAG_CKPT_SNAPSHOT_MS, snapshot_ms, samples)
        if write_ms is not None:
            self.write_scalar(TAG_CKPT_WRITE_MS, write_ms, samples)
        if pending_saves is not None:
            self.write_scalar(TAG_CKPT_PENDING, pending_saves, samples)
        if restarts is not None:
            self.write_scalar(TAG_CKPT_RESTARTS, restarts, samples)
        if flush:
            self.flush()

    def write_comm_metrics(self, *, bytes_per_step=None,
                           compression_ratio=None, samples: int = 0,
                           mode: Optional[str] = None):
        """Per-step data-parallel communication telemetry (TPU-native
        extension): modeled wire bytes per rank per optimizer step and
        the compression ratio vs a dense fp32 ring allreduce — so a
        quantized_comm config change shows up on the same samples x-axis
        as loss/throughput. ``mode`` tags WHICH exchange produced the
        bytes (e.g. ``"hierarchical-twohop+overlap"``; the comm
        autotuner's choice): strings can't ride the scalar stream, so a
        ``comm_mode`` event row lands in the mirror log whenever the
        mode changes — obs_report shows it per run."""
        if not self._writes():
            return
        if bytes_per_step is not None:
            self.write_scalar("Train/Samples/comm_bytes_per_step",
                              bytes_per_step, samples)
        if compression_ratio is not None:
            self.write_scalar("Train/Samples/comm_compression_ratio",
                              compression_ratio, samples)
        if mode is not None and \
                mode != getattr(self, "_last_comm_mode", None):
            self._last_comm_mode = mode
            if self.mirror is not None:
                self.mirror.add_event("comm_mode", mode=str(mode),
                                      step=int(samples))
        # like every other write_* method: without the flush, comm
        # telemetry buffered in the writer is lost on crash/preemption
        self.flush()

    def write_serving_metrics(self, *, ttft_ms=None, token_latency_ms=None,
                              tokens_per_sec=None, queue_depth=None,
                              batch_occupancy=None, kv_pages_in_use=None,
                              tokens_in_flight=None, prefix_hit_rate=None,
                              decode_attn_path=None, queue_wait_ms=None,
                              tbt_ms=None, slo_attainment=None,
                              goodput_tokens_per_s=None,
                              spec_accept_rate=None, handoff_ms=None,
                              shed_rate=None, fleet_queue_depth=None,
                              weight_version=None, migrations=None,
                              replica_restarts=None,
                              kv_pool_bytes_per_token=None,
                              quant_logit_err=None,
                              chunk_dispatches=None, tbt_max_ms=None,
                              tokens: int = 0, flush: bool = True):
        """Serving telemetry (inference engine; TPU-native extension —
        the reference snapshot is training-only): time-to-first-token
        per admitted request, per-decode-step token latency, cumulative
        tokens/s, request-queue depth and decode-slot occupancy, plus
        the paged-cache view (pool pages in use, live cache tokens in
        flight, prefix-cache hit rate over prompt tokens, and WHICH
        decode attention ran — 1.0 = fused Pallas paged kernel, 0.0 =
        the gather fallback, so a silent fallback is visible in run
        reports; the engine also logs a ``decode_attn_path`` event row
        with the reason, mirroring the comm autotuner's
        which-exchange-compiled telemetry). The request-granular plane
        (inference/tracing.py) adds the latency decomposition and SLO
        view: queue wait per admitted request, mean per-request
        time-between-tokens per decode dispatch, the fraction of
        finished requests that met the configured SLO, and the
        within-SLO token rate — so throughput and *goodput* are
        distinct numbers. The x-axis is cumulative generated tokens
        (the serving analog of the training samples axis). Tags are
        pinned by tests/unit/test_inference.py and rendered by
        tools/obs_report.py's serving section."""
        if not self._writes():
            return
        if ttft_ms is not None:
            self.write_scalar(TAG_SERVE_TTFT, ttft_ms, tokens)
        if token_latency_ms is not None:
            self.write_scalar(TAG_SERVE_TOKEN_LATENCY, token_latency_ms,
                              tokens)
        if tokens_per_sec is not None:
            self.write_scalar(TAG_SERVE_TPS, tokens_per_sec, tokens)
        if queue_depth is not None:
            self.write_scalar(TAG_SERVE_QUEUE_DEPTH, queue_depth, tokens)
        if batch_occupancy is not None:
            self.write_scalar(TAG_SERVE_OCCUPANCY, batch_occupancy,
                              tokens)
        if kv_pages_in_use is not None:
            self.write_scalar(TAG_SERVE_KV_PAGES, kv_pages_in_use, tokens)
        if tokens_in_flight is not None:
            self.write_scalar(TAG_SERVE_TOKENS_IN_FLIGHT,
                              tokens_in_flight, tokens)
        if prefix_hit_rate is not None:
            self.write_scalar(TAG_SERVE_PREFIX_HIT, prefix_hit_rate,
                              tokens)
        if decode_attn_path is not None:
            self.write_scalar(TAG_SERVE_DECODE_ATTN, decode_attn_path,
                              tokens)
        if queue_wait_ms is not None:
            self.write_scalar(TAG_SERVE_QUEUE_WAIT, queue_wait_ms, tokens)
        if tbt_ms is not None:
            self.write_scalar(TAG_SERVE_TBT, tbt_ms, tokens)
        if tbt_max_ms is not None:
            self.write_scalar(TAG_SERVE_TBT_MAX, tbt_max_ms, tokens)
        if chunk_dispatches is not None:
            self.write_scalar(TAG_SERVE_CHUNK_DISPATCHES,
                              chunk_dispatches, tokens)
        if slo_attainment is not None:
            self.write_scalar(TAG_SERVE_SLO, slo_attainment, tokens)
        if goodput_tokens_per_s is not None:
            self.write_scalar(TAG_SERVE_GOODPUT, goodput_tokens_per_s,
                              tokens)
        if spec_accept_rate is not None:
            self.write_scalar(TAG_SERVE_SPEC_ACCEPT, spec_accept_rate,
                              tokens)
        if handoff_ms is not None:
            self.write_scalar(TAG_SERVE_HANDOFF, handoff_ms, tokens)
        if shed_rate is not None:
            self.write_scalar(TAG_SERVE_SHED_RATE, shed_rate, tokens)
        if fleet_queue_depth is not None:
            self.write_scalar(TAG_SERVE_FLEET_QDEPTH, fleet_queue_depth,
                              tokens)
        if weight_version is not None:
            self.write_scalar(TAG_SERVE_WEIGHT_VERSION, weight_version,
                              tokens)
        if migrations is not None:
            self.write_scalar(TAG_SERVE_MIGRATIONS, migrations, tokens)
        if replica_restarts is not None:
            self.write_scalar(TAG_SERVE_REPLICA_RESTARTS,
                              replica_restarts, tokens)
        if kv_pool_bytes_per_token is not None:
            self.write_scalar(TAG_SERVE_KV_POOL_BPT,
                              kv_pool_bytes_per_token, tokens)
        if quant_logit_err is not None:
            self.write_scalar(TAG_SERVE_QUANT_LOGIT_ERR,
                              quant_logit_err, tokens)
        if flush:
            self.flush()

    def write_timer_values(self, timer_values: dict, samples: int = 0):
        """Per-timer milliseconds (engine.py:950-974 pattern)."""
        if not self._writes():
            return
        for name, ms in timer_values.items():
            self.write_scalar(f"Train/Samples/{name}", ms, samples)
        # same contract as every other write_* method: without the
        # flush, timer telemetry buffered in the writer is lost on
        # crash/preemption
        self.flush()

    def flush(self):
        if self.writer is not None:
            self.writer.flush()
        if self.mirror is not None:
            self.mirror.flush()

    def close(self):
        if self.writer is not None:
            self.writer.close()
            self.writer = None
        self.mirror = None  # owned by the observability layer, not closed
