"""Wall-clock and throughput timers.

TPU-native analog of the reference's ``deepspeed/utils/timer.py``:
- SynchronizedWallClockTimer (timer.py:20) used cuda.synchronize(); here we
  block on JAX async dispatch with ``jax.block_until_ready`` hooks or plain
  ``jax.effects_barrier()`` when no array is at hand.
- ThroughputTimer (timer.py:100) reports samples/sec.
"""

import time
from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import log_dist


def _device_sync():
    """Drain the async dispatch queue so wall-clock timings are honest."""
    try:
        import jax
        jax.effects_barrier()
    except Exception:
        pass


class Timer_:
    """One named timer (reference timer.py:23)."""

    def __init__(self, name: str, synchronize: bool = True):
        self.name_ = name
        self.elapsed_ = 0.0
        self.started_ = False
        self.start_time = 0.0
        self.synchronize = synchronize

    def start(self):
        assert not self.started_, f"timer {self.name_} has already been started"
        if self.synchronize:
            _device_sync()
        self.start_time = time.perf_counter()
        self.started_ = True

    def stop(self, reset: bool = False):
        assert self.started_, f"timer {self.name_} is not started"
        if self.synchronize:
            _device_sync()
        if reset:
            self.elapsed_ = time.perf_counter() - self.start_time
        else:
            self.elapsed_ += time.perf_counter() - self.start_time
        self.started_ = False

    def reset(self):
        self.elapsed_ = 0.0
        self.started_ = False

    def elapsed(self, reset: bool = True) -> float:
        started = self.started_
        if started:
            self.stop()
        elapsed_ = self.elapsed_
        if reset:
            self.reset()
        if started:
            self.start()
        return elapsed_


class SynchronizedWallClockTimer:
    """Group of named timers (reference timer.py:20)."""

    def __init__(self, synchronize: bool = True):
        self.timers: Dict[str, Timer_] = {}
        self.synchronize = synchronize

    def __call__(self, name: str) -> Timer_:
        if name not in self.timers:
            self.timers[name] = Timer_(name, synchronize=self.synchronize)
        return self.timers[name]

    @staticmethod
    def memory_stats() -> Optional[dict]:
        """Structured device-memory sample: ``{"bytes_in_use",
        "peak_bytes_in_use", "source"}`` (``source: "host"`` = RSS
        fallback on backends without allocator stats), or None when
        nothing is readable. The observability layer writes these as
        per-step scalars (profiling/memory.py owns the sampling)."""
        try:
            from deepspeed_tpu.profiling.memory import memory_snapshot
            return memory_snapshot()
        except Exception:
            return None

    @staticmethod
    def memory_usage() -> str:
        stats = SynchronizedWallClockTimer.memory_stats()
        if stats is None:
            return "mem stats unavailable"
        in_use = stats["bytes_in_use"] / (1024**3)
        peak = stats["peak_bytes_in_use"] / (1024**3)
        src = "" if stats["source"] == "device" else f" ({stats['source']})"
        return f"mem in_use={in_use:.2f} GB peak={peak:.2f} GB{src}"

    def log(self, names: List[str], normalizer: float = 1.0, reset: bool = True,
            ranks: Optional[List[int]] = None, memory_breakdown: bool = False):
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            if name in self.timers:
                elapsed_time = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                string += f" | {name}: {elapsed_time:.2f}"
        if memory_breakdown:
            string += " | " + self.memory_usage()
        log_dist(string, ranks=ranks or [0])


class ThroughputTimer:
    """Samples/sec reporting (reference timer.py:100)."""

    def __init__(self, batch_size: int, num_workers: int = 1, start_step: int = 2,
                 steps_per_output: int = 50, monitor_memory: bool = False, logging_fn=None):
        self.start_time = 0.0
        self.end_time = 0.0
        self.started = False
        self.batch_size = max(1, batch_size)
        self.num_workers = num_workers
        self.start_step = start_step
        self.epoch_count = 0
        self.local_step_count = 0
        self.total_step_count = 0
        self.total_elapsed_time = 0.0
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or log_dist

    def update_epoch_count(self):
        self.epoch_count += 1
        self.local_step_count = 0

    def start(self):
        self.started = True
        if self.total_step_count >= self.start_step:
            # NO device sync here: a per-step barrier serializes the async
            # dispatch pipeline (ruinous over a network-tunneled device).
            # We sync only at reporting boundaries, which makes the
            # *cumulative* time — and therefore avg samples/sec — honest.
            self.start_time = time.perf_counter()

    def stop(self, report_speed: bool = True):
        if not self.started:
            return
        self.started = False
        self.total_step_count += 1
        self.local_step_count += 1
        if self.total_step_count > self.start_step:
            will_report = (report_speed and
                           self.local_step_count % self.steps_per_output == 0)
            if will_report:
                _device_sync()
            self.end_time = time.perf_counter()
            duration = self.end_time - self.start_time
            self.total_elapsed_time += duration
            if will_report:
                self.logging(
                    f"epoch={self.epoch_count}/step={self.local_step_count}: "
                    f"{self.avg_samples_per_sec():.2f} samples/sec, "
                    f"batch_time={duration * 1000.0:.2f} ms")

    def avg_samples_per_sec(self) -> float:
        if self.total_step_count > self.start_step and self.total_elapsed_time > 0:
            samples = self.batch_size * self.num_workers
            avg_time_per_step = self.total_elapsed_time / (self.total_step_count - self.start_step)
            return samples / avg_time_per_step
        return float("-1")
