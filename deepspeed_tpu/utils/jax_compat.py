"""JAX version compatibility shims.

The framework targets the current ``jax.shard_map`` API (top-level
export, ``check_vma`` kwarg). Older jax releases (< 0.5) ship the same
machinery as ``jax.experimental.shard_map.shard_map`` with the kwarg
spelled ``check_rep``. Rather than sprinkling try/except at every call
site, :func:`install` publishes one adapter as ``jax.shard_map`` when
the top-level name is missing, so the rest of the codebase (and user
scripts written against it) can use the modern spelling everywhere.

Idempotent and a no-op on jax versions that already export
``jax.shard_map``.
"""

import jax

__all__ = ["install"]


def _make_adapter(legacy_shard_map):
    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, check_rep=None, axis_names=None, **kw):
        check = True
        if check_vma is not None:
            check = check_vma
        if check_rep is not None:
            check = check_rep
        if axis_names is not None:
            # modern API: axis_names = the MANUAL axes; legacy spells the
            # complement as auto= (axes left to the partitioner)
            auto = frozenset(mesh.axis_names) - frozenset(axis_names)
            if auto:
                kw.setdefault("auto", auto)
        return legacy_shard_map(f, mesh=mesh, in_specs=in_specs,
                                out_specs=out_specs, check_rep=check, **kw)
    shard_map.__doc__ = legacy_shard_map.__doc__
    return shard_map


def _axis_size(axis_name):
    """``jax.lax.axis_size`` backport: static size of a bound mesh axis
    (or product over a tuple of axes) inside shard_map/pmap."""
    if isinstance(axis_name, (tuple, list)):
        n = 1
        for a in axis_name:
            n *= _axis_size(a)
        return n
    frame = jax.core.axis_frame(axis_name)
    return int(getattr(frame, "size", frame))


def install():
    """Publish ``jax.shard_map`` / ``jax.lax.axis_size`` on jax versions
    that predate them."""
    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as legacy
        jax.shard_map = _make_adapter(legacy)
    if not hasattr(jax.lax, "axis_size"):
        jax.lax.axis_size = _axis_size
    try:
        import jax.experimental.pallas.tpu as pltpu
        if not hasattr(pltpu, "HBM"):
            # newer pallas spells HBM-resident refs pltpu.HBM; older
            # releases only have the ANY memory space (same placement)
            pltpu.HBM = pltpu.ANY
    except ImportError:       # pallas not present on this backend
        pass
