"""One serving replica as a child process (ISSUE 16).

``python -m deepspeed_tpu.inference.replica_worker --port P --spec F``
builds ONE :class:`~.engine.InferenceEngine` from the JSON spec at
``F``, connects back to the router's loopback listener on ``P``,
announces readiness (pid, program count, migration capability), and
serves the :mod:`~.rpc` method surface until told to shut down. This
is the process-boundary shim the DeepSpeed launcher shape implies: the
engine, its compiled programs, its flight recorder, and its watchdog
all live in an isolated failure domain — a crash (or the watchdog's
``os._exit(87)``) takes down one replica, not the fleet.

Spec grammar (everything the child needs, nothing the parent keeps)::

    {"family": "gpt2",
     "model_config": {...GPT2Config kwargs...},
     "init_seed": 3,                  # deterministic param init, OR
     "checkpoint_dir": "...", "tag": "...",   # load a committed tag
     "inference": {...inference config...},
     "observability": {...},          # health.enabled gives the child
     "dtype": "float32",              #   its own flight_serve.json
     "warm_migration": true}

Death protocol: a preemption (SIGTERM via the installed
:class:`~deepspeed_tpu.runtime.elastic.PreemptionGuard`, or an
env-armed ``serve.replica_kill`` injection — fired only while a
request is mid-decode, so tests die at the worst moment) is answered
with a *deathbed frame*: every in-flight request's live KV pages are
exported through the warmup-compiled migration program and shipped in
the reply (``{"dying": true, "exports": [...]}`` + slab payload), the
flight recorder dumps, and the process exits
``RESUMABLE_EXIT_CODE`` (85) so the supervisor knows this death is
restart-eligible. The router imports the exports into survivors —
decode resumes at the same ``cache_position``, bitwise-identical, no
re-prefill. Genuine handler failures stay alive (an ``ok: false``
reply); only an uncaught crash in the serve loop exits nonzero.
"""

import argparse
import json
import os
import sys
import time
from dataclasses import asdict
from typing import Any, Dict, Tuple

from deepspeed_tpu.inference import rpc
from deepspeed_tpu.inference.rpc import (request_from_wire,
                                         request_to_wire)
from deepspeed_tpu.runtime import fault
from deepspeed_tpu.runtime.elastic import (RESUMABLE_EXIT_CODE,
                                           Preempted, PreemptionGuard)
from deepspeed_tpu.utils.logging import logger

__all__ = ["main", "ReplicaWorker", "request_from_wire",
           "request_to_wire"]


class _Death(Exception):
    """Internal: the worker must die gracefully (deathbed frame)."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class ReplicaWorker:
    """The dispatch table around one engine. Method surface mirrors the
    engine's host API; every reply carries a ``state`` snapshot so the
    router's routing/drain decisions never need extra round trips."""

    def __init__(self, engine, guard: PreemptionGuard):
        self.engine = engine
        self.guard = guard
        self.exit_code = 0
        self._handlers = {
            "submit": self._h_submit, "cancel": self._h_cancel,
            "step": self._h_step, "state": self._h_state,
            "export_request": self._h_export,
            "import_request": self._h_import,
            "swap_params": self._h_swap,
            "set_speculation": self._h_spec,
            "clock_ping": self._h_clock_ping,
            "shutdown": self._h_shutdown,
        }

    # ------------------------------------------------------------ state
    def state(self) -> Dict[str, Any]:
        eng = self.engine
        sched = eng.scheduler
        active = [(s, sched.slots[s]) for s in sched.active_slots()]
        alloc = getattr(sched, "allocator", None)
        q = getattr(eng, "_handoff_q", None)
        return {
            "pid": os.getpid(),
            "queue_depth": sched.queue_depth,
            "queued_uids": [r.uid for r in sched.queue],
            "active_uids": [s.request.uid for _, s in active],
            "mid_decode_uids": [s.request.uid for _, s in active
                                if s.pending_tok is not None],
            "occupancy": sched.occupancy,
            "total_tokens": sched.total_tokens,
            "pages_in_use": (alloc.pages_in_use
                             if alloc is not None else None),
            "idle": sched.idle() and (q is None or len(q) == 0),
            "weight_version": eng.weight_version,
            "weight_ordinal": eng.weight_ordinal,
            "steady_state_recompiles": eng.steady_state_recompiles,
            "can_migrate": getattr(eng, "can_migrate", False),
            # cumulative device dispatches (CompileTracker) — the
            # fleet_trace_overhead bench's dispatch_delta pin reads
            # this through the router proxy; a host int, never a sync
            "dispatches": getattr(getattr(eng, "compile_tracker", None),
                                  "total_dispatches", None),
        }

    def hello(self) -> Dict[str, Any]:
        health = getattr(self.engine, "health", None)
        return {"pid": os.getpid(),
                "flight_path": getattr(health, "flight_path", None),
                "events_dir": self.engine.config.get("events_dir"),
                "state": self.state()}

    # --------------------------------------------------------- handlers
    def _h_submit(self, params, payload):
        uid = self.engine.submit(request_from_wire(params["request"]))
        return {"uid": uid, "state": self.state()}, b""

    def _h_cancel(self, params, payload):
        fin = self.engine.cancel(int(params["uid"]),
                                 reason=params.get("reason", "evicted"))
        return {"fin": None if fin is None else asdict(fin),
                "state": self.state()}, b""

    def _h_step(self, params, payload):
        sched = self.engine.scheduler
        if any(sched.slots[s].pending_tok is not None
               for s in sched.active_slots()):
            # the kill test's hook: armed via DSTPU_FAULT_ARM, this
            # fires only while a request is mid-decode — death at the
            # worst moment, generated tokens and live pages at stake
            fault.fire("serve.replica_kill", pid=os.getpid())
        if self.guard.preempted:
            raise _Death(self.guard.reason or "preempted")
        fins = self.engine.step()
        return {"fins": [asdict(f) for f in fins],
                "state": self.state()}, b""

    def _h_state(self, params, payload):
        return {"state": self.state()}, b""

    def _h_export(self, params, payload):
        rec = self.engine.export_request(int(params["uid"]))
        if rec is None:
            return {"header": None, "state": self.state()}, b""
        head, slab = rpc.migration_to_wire(rec)
        return {"header": head, "state": self.state()}, slab

    def _h_import(self, params, payload):
        rec = rpc.migration_from_wire(params["header"], payload)
        sid = self.engine.import_request(rec)
        return {"slot": sid, "state": self.state()}, b""

    def _h_swap(self, params, payload):
        version = self.engine.swap_params(
            params["load_dir"], tag=params.get("tag"),
            verify_integrity=bool(params.get("verify_integrity", True)))
        return {"weight_version": version, "state": self.state()}, b""

    def _h_spec(self, params, payload):
        changed = self.engine.set_speculation(bool(params["on"]))
        return {"changed": changed, "state": self.state()}, b""

    def _h_clock_ping(self, params, payload):
        # clock-alignment probe (ISSUE 18): reply with this process's
        # wall clock and NOTHING else — no state snapshot, so the reply
        # is as small (and the midpoint estimate as tight) as the
        # channel allows. The router brackets the call with its own
        # t0/t1 and estimates offset = t_child - (t0 + t1) / 2 with
        # uncertainty (t1 - t0) / 2.
        return {"t_child": time.time()}, b""

    def _h_shutdown(self, params, payload):
        raise rpc.ServerExit(result={"bye": True,
                                     "state": self.state()})

    # --------------------------------------------------------- dispatch
    def dispatch(self, method: str, params: Dict[str, Any],
                 payload: bytes) -> Tuple[Any, bytes]:
        handler = self._handlers.get(method)
        if handler is None:
            raise KeyError(f"unknown rpc method {method!r}")
        try:
            return handler(params, payload)
        except (fault.InjectedCrash, Preempted, _Death) as e:
            raise self._deathbed(getattr(e, "reason", None)
                                 or f"{type(e).__name__}: {e}")

    def _deathbed(self, reason: str) -> rpc.ServerExit:
        """Export every in-flight request's live pages, dump the flight
        recorder, and hand the serve loop a reply-then-exit frame."""
        eng = self.engine
        sched = eng.scheduler
        uids = [sched.slots[s].request.uid for s in sched.active_slots()]
        exports = []
        for uid in uids:
            try:
                rec = eng.export_request(uid)
            except Exception as e:  # noqa: BLE001 — salvage the rest
                logger.warning(f"replica worker: deathbed export of "
                               f"uid {uid} failed ({e!r})")
                continue
            if rec is not None:
                exports.append(rec)
        headers, slabs = [], []
        for rec in exports:
            h, p = rpc.migration_to_wire(rec)
            headers.append(h)
            slabs.append(p)
        health = getattr(eng, "health", None)
        if health is not None and getattr(health, "enabled", False):
            health.dump("replica_death", reason=reason,
                        exports=len(exports))
        logger.warning(
            f"replica worker {os.getpid()}: dying ({reason}); "
            f"{len(exports)} in-flight requests exported for "
            f"migration")
        self.exit_code = RESUMABLE_EXIT_CODE
        return rpc.ServerExit(
            result={"dying": True, "reason": reason,
                    "exit_code": RESUMABLE_EXIT_CODE,
                    "exports": headers,
                    "queued": [request_to_wire(r)
                               for r in sched.queue]},
            payload=b"".join(slabs))


def _build_engine(spec: Dict[str, Any]):
    """Heavy half, deliberately after the socket connect: jax import +
    model build + warmup happen while the router already holds the
    accepted connection and simply waits for the ready frame."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.models.gpt2 import GPT2Config, init_gpt2_params

    family = spec.get("family", "gpt2")
    if family != "gpt2":
        raise ValueError(f"replica_worker: unsupported model family "
                         f"{family!r}")
    mcfg = GPT2Config(**spec["model_config"])
    dtype = jnp.dtype(spec.get("dtype", "bfloat16"))
    if spec.get("checkpoint_dir"):
        engine = InferenceEngine.from_checkpoint(
            spec["checkpoint_dir"], mcfg, tag=spec.get("tag"),
            inference_config=spec.get("inference"), dtype=dtype,
            observability_config=spec.get("observability"))
    else:
        params = init_gpt2_params(
            mcfg, jax.random.PRNGKey(int(spec.get("init_seed", 0))))
        engine = InferenceEngine(
            mcfg, params, spec.get("inference"), dtype=dtype,
            observability_config=spec.get("observability"))
    engine.warmup()
    if spec.get("warm_migration", True) and engine.paged:
        engine.warm_migration()
    return engine


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="replica_worker")
    ap.add_argument("--port", type=int, required=True,
                    help="router loopback port to connect back to")
    ap.add_argument("--spec", required=True,
                    help="path to the replica spec JSON")
    ap.add_argument("--connect_timeout_s", type=float, default=60.0)
    args = ap.parse_args(argv)
    with open(args.spec) as f:
        spec = json.load(f)
    # connect FIRST (cheap) so the router's accept() returns while the
    # expensive engine build runs; the ready frame closes the gap
    sock = rpc.connect_local(args.port,
                             timeout_s=args.connect_timeout_s)
    sock.settimeout(None)
    # env-armed faults (DSTPU_FAULT_ARM) — the kill tests arm
    # serve.replica_kill in exactly one child's environment
    fault.arm_from_env()
    guard = PreemptionGuard()
    guard.install()
    try:
        engine = _build_engine(spec)
    except Exception as e:  # noqa: BLE001 — tell the router, then die
        rpc.send_frame(sock, {"ok": False, "error": {
            "kind": "remote",
            "message": f"engine build failed: {type(e).__name__}: {e}"}})
        raise
    worker = ReplicaWorker(engine, guard)
    rpc.send_frame(sock, {"ok": True, "result": worker.hello()})
    rpc.RpcServer(sock).serve(worker.dispatch)
    try:
        engine.close()
    except Exception as e:  # noqa: BLE001 — exit code already decided
        logger.warning(f"replica worker: close failed ({e!r})")
    guard.uninstall()
    return worker.exit_code


if __name__ == "__main__":
    sys.exit(main())
