"""The inference serving engine.

The reference snapshot (DeepSpeed v0.3.0) is training-only; this is the
serving half the ROADMAP's "heavy traffic" north star needs, built
TPU-first:

- **Fixed program set, fixed shapes.** A jit-compiled *prefill*
  runs the padded prompt batch through the model's cached forward
  (``models/*`` ``kv_cache=`` mode — the SAME blocks as training) and
  writes the prompt K/V into the cache; a jit-compiled single-token
  *decode* advances every slot one position. Both carry the
  preallocated cache as a **donated** argument — steady state allocates
  nothing.
- **Paged KV cache (default).** The cache is a pool of fixed
  ``(kv_heads, page_size, head_dim)`` pages addressed through
  static-shape per-slot block tables (``inference/kv_cache.py``); HBM
  occupancy is bounded by the tokens reserved in flight, not
  ``slots x max_len``, and page-aligned shared prompt prefixes
  hash-dedup so a fleet of requests on one system prompt prefills it
  once. Page allocation is host-side (scheduler) — the compiled
  programs never see it. ``paged_kv.enabled: false`` restores the dense
  slot x max_len cache (the PR-5 layout, kept as the parity/bench
  baseline).
- **Fused paged-decode attention (default).** The decode step computes
  attention *directly against the page pool* through the Pallas
  paged-attention kernel (``ops/attention/paged.py``): block tables in
  SMEM drive per-sequence page walks, only each row's live pages are
  streamed (double-buffered DMA), so per-step decode reads are O(live
  tokens) instead of the ``max_len``-bounded stripe the gather path
  materializes. ``paged_kv.attn_kernel: "gather"`` pins the stripe
  path (the numerics oracle); unsupported geometries fall back to it
  automatically with a one-line log and a ``Serve/decode_attn_path``
  telemetry tag. The decode dispatch additionally clamps its block
  tables to the batch's live-page bucket
  (``paged_kv.decode_page_buckets``), so even the gather fallback
  stops paying full ``max_len`` bandwidth.
- **Bucketed shapes.** Prompts pad to configured ``prompt_buckets`` and
  prefill batches to ``batch_buckets`` (inference/buckets.py), so
  steady-state serving dispatches exactly
  ``len(batch_buckets) x len(prompt_buckets)`` prefill programs + 1
  decode program — all compiled by :meth:`InferenceEngine.warmup` and
  pinned by the engine's CompileTracker (``steady_state_recompiles``
  must stay 0; tier-1 asserted).
- **Serving mesh.** With ``inference.mesh.axes`` set (e.g.
  ``{"model": 4}``) the programs jit with GSPMD NamedShardings over a
  ``parallel/mesh.py`` mesh: params carry the families' Megatron
  column/row PartitionSpecs, the KV cache/pool shards over its kv_heads
  dim — tensor-parallel prefill/decode over ICI. The Pallas paged-decode
  kernel runs shard_mapped over the mesh's model axis
  (``parallel/pallas_shard.py``) — sharded serving keeps the O(live
  tokens) read; the compiled sharded decode program is pinned
  gather-free in tier-1.
  :meth:`from_checkpoint` reshards committed train-mesh params onto the
  serving mesh on load (portable array redistribution: the checkpoint
  is logically indexed, ``load_params_only`` materializes straight into
  the serving shardings).
- **Continuous batching.** The host-side :class:`~.scheduler.Scheduler`
  admits queued requests into freed decode slots every step and evicts
  finished sequences (EOS / max_tokens) — iteration-level scheduling
  with bounded-lookahead admission (a head that doesn't fit the free
  pages can't stall the queue), per-request sampling state.
- **Checkpoint -> serving bridge.** :meth:`from_checkpoint` loads a
  committed PR-1 checkpoint's ``model_states`` group only
  (``runtime/checkpoint.load_params_only``), optionally shipping the
  weights through the qwZ int8 block format
  (``runtime/quantized_collectives``).
- **Serving telemetry.** TTFT, per-token latency, tokens/s, queue
  depth, slot occupancy — plus paged-cache occupancy (pages in use,
  tokens in flight, prefix hit rate) — stream through the PR-3 monitor
  into ``events.jsonl`` (``Serve/*`` tags), rendered by
  ``tools/obs_report.py``'s serving section.
- **Request-granular observability.** Every request carries a stamped
  lifecycle trail (submit -> defer/admit -> prefill -> first token ->
  sampled decode windows -> finish/evict) with a queue-wait / prefill /
  time-between-tokens latency decomposition, SLO attainment + goodput
  accounting against ``observability.serve.slo``, per-request Chrome
  trace lanes, and live pool introspection via :meth:`debug_state` —
  all host-side and sync-free (``inference/tracing.py``), so the
  compiled program set and the zero-recompile contract are untouched
  with tracing on. ``tools/obs_report.py --serve`` renders the SLO
  report; the ``serve_trace_overhead`` bench row pins the no-overhead
  claim.
"""

import os
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.inference.buckets import (chunk_warmup_plan, pad_prompts,
                                             pick_bucket, warmup_plan)
from deepspeed_tpu.inference.disagg import (DispatchTrace, HandoffQueue,
                                            HandoffRecord, HandoffStats,
                                            MigrationRecord,
                                            price_handoff)
from deepspeed_tpu.inference.draft import make_drafter
from deepspeed_tpu.inference.kv_cache import (PageAllocator, cache_spec_for,
                                              init_kv_cache,
                                              init_paged_kv_cache,
                                              kv_cache_bytes, paged_kv_bytes,
                                              paged_spec_for, pages_for)
from deepspeed_tpu.inference.scheduler import (FinishedRequest, Request,
                                               Scheduler)
from deepspeed_tpu.inference.tracing import ServeTracer
from deepspeed_tpu.models.gpt2 import (GPT2Config, gpt2_forward,
                                       gpt2_param_specs, init_gpt2_params)
from deepspeed_tpu.models.llama import (LlamaConfig, init_llama_params,
                                        llama_forward, llama_param_specs)
from deepspeed_tpu.ops.attention.flash import NEG_INF
from deepspeed_tpu.parallel.mesh import axis_size, build_mesh
from deepspeed_tpu.profiling.recompile import CompileTracker
from deepspeed_tpu.profiling.spans import ChromeTraceRecorder, trace_span
from deepspeed_tpu.runtime.quantized_params import (QuantizedParam,
                                                    dequantize_param_tree,
                                                    is_quantized_tree,
                                                    quantize_param_tree,
                                                    quantized_tree_bytes)
from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.utils.monitor import TensorBoardMonitor, _JsonlWriter

__all__ = ["InferenceEngine"]

_FAMILIES = {
    GPT2Config: ("gpt2", gpt2_forward, init_gpt2_params,
                 gpt2_param_specs),
    LlamaConfig: ("llama", llama_forward, init_llama_params,
                  llama_param_specs),
}


def _family_of(model_config):
    for cls, entry in _FAMILIES.items():
        if isinstance(model_config, cls):
            return entry
    raise TypeError(
        f"unsupported model config {type(model_config).__name__}; "
        f"serving supports {[c.__name__ for c in _FAMILIES]}")


def _normalize_inference_config(inference_config) -> Dict[str, Any]:
    from deepspeed_tpu.runtime.config import get_inference_config
    return get_inference_config(
        {"inference": dict(inference_config or {})})


def _resolve_committed_tag(ckptlib, load_dir: str, tag: Optional[str],
                           verify_integrity: bool) -> str:
    """The one committed-tag pre-flight all serving loads share
    (``from_checkpoint``, ``swap_params``, and — via
    ``tools/verify_checkpoint.py --serve-ready`` — the supervisor that
    pushes swaps): newest committed tag wins when ``tag`` is None,
    corrupt/uncommitted/model-states-less tags are skipped with a
    warning, and a tag that survives is loadable by definition."""
    candidates = [tag] if tag is not None else \
        ckptlib.candidate_tags(load_dir)
    for t in candidates:
        d = os.path.join(load_dir, t)
        ok, problems = ckptlib.verify_checkpoint_dir(
            d, check_crc=verify_integrity)
        if ok and ckptlib.state_groups(d)["model_states"]:
            return d
        logger.warning(f"serving checkpoint pre-flight: skipping {d}: "
                       f"{problems or 'no model_states group'}")
    raise FileNotFoundError(
        f"no loadable committed checkpoint with model_states "
        f"under {load_dir} (tag={tag!r})")


def _serving_mesh(cfg, mesh=None):
    """The serving mesh from ``inference.mesh.axes`` (or an injected
    one); None for single-device serving."""
    if mesh is not None:
        return mesh
    axes = dict(cfg["mesh"]["axes"])
    return build_mesh(axes) if axes else None


def _leaf_sharding(mesh, spec, shape) -> NamedSharding:
    """A leaf's serving NamedSharding: the family's TP spec, with any
    dim the mesh axis doesn't divide falling back to replication (the
    zero_shardings discipline — small/indivisible leaves are cheap to
    replicate; device_put requires exact divisibility)."""
    dims = list(spec) + [None] * (len(shape) - len(spec))
    fixed = []
    for d, ax in zip(shape, dims):
        if ax is None:
            fixed.append(None)
            continue
        n = 1
        for a in (ax if isinstance(ax, tuple) else (ax,)):
            n *= axis_size(mesh, a)
        fixed.append(ax if n > 0 and d % n == 0 else None)
    return NamedSharding(mesh, P(*fixed))


def _param_shardings(mesh, specs_fn, model_config, template):
    """Per-leaf serving shardings for a params pytree (``template``:
    real arrays or ``jax.eval_shape`` structs — only shapes are read).
    Quantized (int8-resident) leaves mirror the template's
    :class:`~deepspeed_tpu.runtime.quantized_params.QuantizedParam`
    structure: ``q`` keeps the weight's original rank (blockwise along
    the last axis), so the family's TP spec applies to it unchanged;
    the scale tree takes the same spec through the divisibility
    fallback (its trailing blocks dim is usually too small to split and
    replicates)."""
    def one(leaf, s):
        if isinstance(leaf, QuantizedParam):
            return QuantizedParam(
                _leaf_sharding(mesh, s, leaf.q.shape),
                _leaf_sharding(mesh, s, leaf.scale.shape),
                leaf.orig_dtype, leaf.block)
        return _leaf_sharding(mesh, s, leaf.shape)
    return jax.tree_util.tree_map(
        one, template, specs_fn(model_config),
        is_leaf=lambda l: isinstance(l, QuantizedParam))


def qwz_distribute_params(params, block: int = 256,
                          resident: str = "bf16"):
    """Ship params through the qwZ int8 block wire format (ZeRO++
    quantized weight gather): every floating matmul/embedding leaf
    crosses as int8 blocks + fp32 scales — ~4x less weight traffic when
    fanning one committed checkpoint out to many replicas. The WIRE
    format and the RESIDENT format are decoupled (PR 17): both paths
    quantize through ``runtime/quantized_params.quantize_param_tree``;
    ``resident`` picks what the replica keeps.

    - ``"bf16"`` (historical behavior): dequantize eagerly back to the
      original dtype. NOTE the cost this hides: eager dequant
      re-materializes the FULL original-dtype HBM footprint on the
      replica — the 4x saving is wire-only, resident weight HBM is
      unchanged.
    - ``"int8"``: keep the int8 blocks + scales live (a tree of
      ``QuantizedParam`` leaves). The compiled prefill/decode programs
      dequantize per block at each weight use (``models/*`` ``_wd``),
      so resident weight HBM drops ~2x and the wire saving survives on
      the replica.

    1-D leaves (biases, norms) stay dense either way — their bytes are
    noise and the historical all-leaf quantization bought nothing but
    extra rounding error on them."""
    qtree = quantize_param_tree(params, block)
    if resident == "int8":
        return qtree
    if resident != "bf16":
        raise ValueError(
            f"qwz_distribute_params resident must be 'bf16' or 'int8', "
            f"got {resident!r}")
    return dequantize_param_tree(qtree)


class InferenceEngine:
    """Paged (or dense) bucketed prefill/decode serving over a
    continuous-batching scheduler, optionally sharded over a serving
    mesh. See the module docstring for the architecture;
    ``docs/inference.md`` for usage."""

    def __init__(self, model_config, params, inference_config=None,
                 dtype=jnp.bfloat16, monitor: Optional[Any] = None,
                 mesh: Optional[Any] = None, observability_config=None,
                 draft_fn=None):
        self.model_config = model_config
        (self.family, self._forward, _,
         self._param_specs_fn) = _family_of(model_config)
        self.dtype = dtype
        cfg = _normalize_inference_config(inference_config)
        self.config = cfg
        from deepspeed_tpu.runtime.config import get_observability_config
        self.obs_config = get_observability_config(
            {"observability": dict(observability_config or {})})

        self.num_slots = cfg["max_batch_size"]
        self._rows = self.num_slots + 1          # +1 scratch row
        self._scratch = self.num_slots
        max_len = min(cfg["max_seq_len"],
                      model_config.max_position_embeddings)
        if max_len < cfg["max_seq_len"]:
            logger.info(f"inference: max_seq_len clamped to the model's "
                        f"max_position_embeddings ({max_len})")
        if max(cfg["prompt_buckets"]) > max_len:
            raise ValueError(
                f"inference.prompt_buckets max "
                f"({max(cfg['prompt_buckets'])}) exceeds the effective "
                f"max_seq_len ({max_len})")
        self.max_len = max_len
        self._vocab = model_config.vocab_size
        self._top_k = min(cfg["top_k"], self._vocab)

        # ------------------------------------------ chunked prefill
        # a long prompt becomes k fixed-size chunk dispatches that
        # interleave with the decode cadence: chunk state is just
        # cache_position advancing over pages the request already
        # owns, and the chunk program IS the prefill program at ids
        # shape (batch_bucket, chunk_tokens). Prompts longer than the
        # largest prompt bucket can only be served this way.
        ck = cfg["chunked_prefill"]
        self.chunked = bool(ck["enabled"])
        self._chunk_tokens = min(int(ck["chunk_tokens"]), max_len) \
            if self.chunked else 0
        self._cp_threshold = int(ck["cp_threshold_tokens"]) \
            if self.chunked else 0
        self._cp_shards = 1           # >1 = context-parallel chunks
        self._cp_reason = "chunked prefill off" if not self.chunked \
            else "cp_threshold_tokens unset"
        self._chunk_cp = None
        self._chunk_dispatches = 0

        # ---------------------------------------------- serving mesh
        self.mesh = _serving_mesh(cfg, mesh)

        # ------------------------------------- int8-resident weights
        # quantize_weights: False | "bf16" (wire-only) | "int8" (keep
        # qwZ blocks + scales as the LIVE tree; compiled programs
        # dequant per block at each matmul — models/* ``_wd``)
        qw = cfg["quantize_weights"]
        self.weights_resident = "int8" if qw == "int8" else (
            "bf16" if qw else "off")
        self._weight_block = int(cfg["quantize_block"])
        if qw == "int8":
            # no-op when from_checkpoint already shipped a quantized
            # tree (quantize_param_tree passes quantized leaves through)
            params = quantize_param_tree(params, self._weight_block)
            if self.mesh is None:
                # host round-trip: pin the quantized tree to the dense
                # constructor's UNcommitted placement, so swap_params'
                # requantize lands on identical program keys
                params = jax.tree_util.tree_map(
                    lambda x: jnp.asarray(np.asarray(x)), params)

        self._param_shardings = None
        self._cache_sharding = None
        if self.mesh is not None:
            tp = axis_size(self.mesh, "model")
            kv_heads = getattr(model_config, "kv_heads", None) or \
                model_config.num_heads
            if model_config.num_heads % tp or kv_heads % tp:
                raise ValueError(
                    f"inference.mesh model axis ({tp}) must divide "
                    f"num_heads ({model_config.num_heads}) and kv_heads "
                    f"({kv_heads})")
            self._param_shardings = _param_shardings(
                self.mesh, self._param_specs_fn, model_config, params)
            # dense cache and paged pool alike carry kv_heads at dim 2
            self._cache_sharding = NamedSharding(
                self.mesh, P(None, None, "model"))
            self.params = jax.tree_util.tree_map(
                lambda x, s: jax.device_put(jnp.asarray(x), s),
                params, self._param_shardings)
        else:
            self.params = jax.tree_util.tree_map(jnp.asarray, params)

        # -------------------- disaggregation + speculative decoding
        sd = cfg["spec_decode"]
        dg = cfg["disagg"]
        self.spec = bool(sd["enabled"])
        self._spec_k = int(sd["k"]) if self.spec else 0
        self._verify_widths = ()
        self._drafter = None
        if self.spec:
            # one compiled verify program per width; default = a single
            # seq-(k+1) program (config validation keeps widths >= 2 —
            # width 1 IS the plain decode program)
            widths = tuple(int(w) for w in sd["verify_widths"]) or \
                (self._spec_k + 1,)
            self._verify_widths = tuple(sorted(set(widths)))
            self._drafter = make_drafter(sd, draft_fn)
        self.disagg = bool(dg["enabled"])
        self._decode_mesh_axes = (dict(dg["decode_mesh"]["axes"])
                                  if self.disagg else {})
        sep = dg["separate_pools"]
        if sep is None:
            # a distinct decode mesh forces distinct pools (pages must
            # physically move); same-mesh disagg defaults to the
            # zero-copy shared-pool handoff
            sep = bool(self._decode_mesh_axes)
        self._separate_pools = bool(self.disagg and sep)
        # decode-side placement: identical to the prefill side unless
        # disagg.decode_mesh carves the decode workers their own mesh
        self._mesh_decode = self.mesh
        self._param_shardings_decode = self._param_shardings
        self._cache_sharding_decode = self._cache_sharding
        self.params_decode = self.params
        if self._decode_mesh_axes:
            self._mesh_decode = build_mesh(self._decode_mesh_axes)
            tp = axis_size(self._mesh_decode, "model")
            kv_heads = getattr(model_config, "kv_heads", None) or \
                model_config.num_heads
            if model_config.num_heads % tp or kv_heads % tp:
                raise ValueError(
                    f"inference.disagg.decode_mesh model axis ({tp}) "
                    f"must divide num_heads ({model_config.num_heads}) "
                    f"and kv_heads ({kv_heads})")
            self._param_shardings_decode = _param_shardings(
                self._mesh_decode, self._param_specs_fn, model_config,
                self.params)
            self._cache_sharding_decode = NamedSharding(
                self._mesh_decode, P(None, None, "model"))
            # the decode workers' own weight copy (the priced reshard
            # moves only KV pages per request — weights ship once)
            self.params_decode = jax.tree_util.tree_map(
                lambda x, s: jax.device_put(x, s),
                self.params, self._param_shardings_decode)
        self._handoff_q = HandoffQueue() if self.disagg else None
        self._handoff_stats = HandoffStats() if self.disagg else None
        # chunked engines keep the trace too: the TBT bound is the pure
        # ordering pin "at most one chunk dispatch per step, after every
        # decode of that step" (bench chunked_prefill_tbt checks it)
        self._dispatch_trace = DispatchTrace() \
            if (self.disagg or self.chunked) else None
        self._link = None
        if self._separate_pools:
            from deepspeed_tpu.runtime.comm_autotune import LinkModel
            self._link = LinkModel()

        # telemetry: monitor (PR-3 pattern) + crash-safe events.jsonl
        # (size-rotated when observability.events_max_mb is set)
        serve_obs = self.obs_config["serve"]
        self.monitor = monitor if monitor is not None else \
            TensorBoardMonitor(enabled=False)
        self._log = None
        if cfg["events_dir"]:
            self._log = _JsonlWriter(cfg["events_dir"],
                                     max_mb=serve_obs["events_max_mb"])
            if getattr(self.monitor, "mirror", None) is None:
                self.monitor.mirror = self._log
        # per-request Chrome-trace lanes + engine phase spans land in
        # one recorder when a chrome_trace_path is configured
        self._recorder = None
        self._chrome_path = self.obs_config["chrome_trace_path"] or None
        if self._chrome_path:
            self._recorder = ChromeTraceRecorder()
        # the request-granular serving plane: lifecycle trail, latency
        # decomposition histograms, SLO/goodput split — pure host code
        # (inference/tracing.py), wired through the scheduler's hooks
        self._tracer = ServeTracer(serve_obs, writer=self._log,
                                   recorder=self._recorder)
        self.compile_tracker = CompileTracker(
            step_provider=lambda: self._steps, warn_after=0,
            on_event=self._on_compile_event)
        # postmortem health plane (utils/health.py): flight ring over
        # the mirror, stall watchdog fed per-phase beats (prefill/
        # decode/handoff_claim) — serve-side black box, host-only
        from deepspeed_tpu.utils.health import HealthPlane
        self.health = HealthPlane(
            self.obs_config.get("health"), monitor=self.monitor,
            rank=0, component="serve",
            events_dir=cfg["events_dir"] or None)
        self._steps = 0
        self._warm_compiles: Optional[int] = None
        self._serve_secs = 0.0
        # offline fp-oracle probe result (record_quant_logit_err):
        # serving can't afford an fp oracle per dispatch, so the error
        # rides telemetry only when a test/bench measures it
        self.quant_logit_err: Optional[float] = None
        self._state_event_every = 64       # serve_state cadence (steps)
        self._key_cache: Dict[int, np.ndarray] = {}

        # ------------------------------------------------- KV cache
        pk = cfg["paged_kv"]
        self.paged = bool(pk["enabled"])
        allocator = None
        self._decode_attn_path = None          # "pallas" | "gather" (paged)
        self._decode_attn_reason = None
        self._decode_page_buckets = ()
        admit_allocator = None
        self.paged_spec_prefill = None
        self._cache_prefill = None
        self._page_bytes = 0
        if self.paged:
            ps = pk["page_size"]
            # auto pool: the dense-equivalent worst case (+ null page) —
            # same capacity, but shared/short requests no longer charge
            # max_len each
            num_pages = pk["num_pages"] or (
                self.num_slots * pages_for(max_len, ps) + 1)
            # pool payload dtype: the engine dtype unless paged_kv.
            # kv_dtype overrides it ("int8" = quantized pool — the
            # cache tree grows per-token-row fp32 scale pools and the
            # decode kernel dequantizes tiles in VMEM)
            kv_dtype = {"bf16": jnp.bfloat16, "int8": jnp.int8}.get(
                pk["kv_dtype"], dtype)
            self.paged_spec = paged_spec_for(
                model_config, num_pages, ps, max_len, dtype=kv_dtype,
                kv_quant_block=pk["kv_quant_block"])
            self.cache_spec = None
            self._cache = init_paged_kv_cache(self.paged_spec)
            allocator = PageAllocator(num_pages, ps,
                                      prefix_cache=pk["prefix_cache"])
            cache_bytes = paged_kv_bytes(self.paged_spec)
            self._page_bytes = cache_bytes // num_pages
            # static pool cost per token of capacity — the
            # Serve/kv_pool_bytes_per_token gauge (int8 pools land
            # near half the bf16 figure; scales are the remainder)
            self._kv_bpt = cache_bytes / float(num_pages * ps)
            if self._separate_pools:
                # the prefill workers' own pool: prompts only (decode
                # lifetime is reserved from the main pool at handoff
                # claim), sized for num_slots worst-case prompts unless
                # pinned by disagg.prefill_pages. The prefix cache
                # lives HERE — sharing is a prefill-side concern and
                # ends at the handoff (the migrated copy is private)
                # chunked prefill holds WHOLE long prompts on the
                # prefill side until the final chunk hands off, so the
                # pool (and the handoff slab width) is sized by max_len
                # rather than the largest prompt bucket
                max_prompt = max_len if self.chunked \
                    else max(cfg["prompt_buckets"])
                ppages = dg["prefill_pages"] or (
                    self.num_slots * pages_for(max_prompt, ps) + 1)
                self.paged_spec_prefill = paged_spec_for(
                    model_config, ppages, ps, max_prompt, dtype=kv_dtype,
                    kv_quant_block=pk["kv_quant_block"])
                self._cache_prefill = init_paged_kv_cache(
                    self.paged_spec_prefill)
                admit_allocator = PageAllocator(
                    ppages, ps, prefix_cache=pk["prefix_cache"])
                cache_bytes += paged_kv_bytes(self.paged_spec_prefill)
            self._resolve_decode_attn(pk)
        else:
            self.paged_spec = None
            self.cache_spec = cache_spec_for(model_config, self._rows,
                                             max_len, dtype=dtype)
            self._cache = init_kv_cache(self.cache_spec)
            cache_bytes = kv_cache_bytes(self.cache_spec)
            self._kv_bpt = cache_bytes / float(self._rows * max_len)
        # pages_per_seq of the pool the PREFILL program scatters into
        self._prefill_pps = (self.paged_spec_prefill.pages_per_seq
                             if self._separate_pools else
                             self.paged_spec.pages_per_seq) \
            if self.paged else 0
        # the width of one handoff migration (pad-0 rows land in the
        # null page): every live prompt page fits, shape stays static
        self._handoff_width = (self.paged_spec_prefill.pages_per_seq
                               if self._separate_pools else 0)
        # cross-REPLICA live migration programs (ISSUE 16) — compiled
        # on demand by warm_migration(), against the MAIN pool
        self._mig_export = None
        self._mig_import = None
        self._mig_width = 0
        if self._cache_sharding_decode is not None:
            self._cache = tuple(
                jax.device_put(c, self._cache_sharding_decode)
                for c in self._cache)
        if self._cache_prefill is not None and \
                self._cache_sharding is not None:
            self._cache_prefill = tuple(
                jax.device_put(c, self._cache_sharding)
                for c in self._cache_prefill)
        self.scheduler = Scheduler(self.num_slots, cfg["prompt_buckets"],
                                   cfg["batch_buckets"], max_len,
                                   allocator=allocator,
                                   lookahead=cfg["admit_lookahead"],
                                   tracer=self._tracer,
                                   admit_allocator=admit_allocator,
                                   drafter=self._drafter,
                                   spec_k=self._spec_k,
                                   chunk_tokens=self._chunk_tokens)
        # serving-weights version stamp: "initial" for constructor
        # params; from_checkpoint / swap_params overwrite it with the
        # checkpoint tag. The ordinal counts committed swaps (the
        # Serve/weight_version scalar — tags are strings, scalars
        # aren't).
        self._weight_version = "initial"
        self._weight_ordinal = 0
        self.scheduler.weight_version = self._weight_version

        if self.paged:
            self._prefill = self._wrap_program(
                self._prefill_paged_impl, 8, "prefill")
            self._decode = self._wrap_program(
                self._decode_paged_impl, 7, "decode",
                mesh=self._mesh_decode,
                param_shardings=self._param_shardings_decode,
                cache_sharding=self._cache_sharding_decode)
            self._verify = None
            if self.spec:
                self._verify = self._wrap_program(
                    self._verify_paged_impl, 7, "verify",
                    mesh=self._mesh_decode,
                    param_shardings=self._param_shardings_decode,
                    cache_sharding=self._cache_sharding_decode)
            if self._separate_pools:
                self._wrap_handoff_programs()
            if self.chunked:
                self._resolve_context_parallel()
            geom = (f"paged KV cache: {self.paged_spec.num_pages} pages "
                    f"x {self.paged_spec.page_size} tokens "
                    f"({cache_bytes / 2**20:.1f} MiB, "
                    f"{jnp.dtype(self.paged_spec.dtype).name}"
                    f"{' + fp32 scales' if self.paged_spec.quantized else ''}"
                    f"), prefix cache "
                    f"{'on' if pk['prefix_cache'] else 'off'}, "
                    f"decode attn {self._decode_attn_path}")
            # the which-decode-attention-compiled line (PR 6's
            # which-exchange pattern): a silent fallback to the
            # stripe-gather path must be visible in logs + run reports
            logger.info(
                f"inference decode attention: {self._decode_attn_path} "
                f"({self._decode_attn_reason}; page walk widths "
                f"{list(self._decode_page_buckets)})")
            if self._log is not None:
                self._log.add_event(
                    "decode_attn_path", path=self._decode_attn_path,
                    reason=self._decode_attn_reason,
                    requested=pk["attn_kernel"],
                    decode_page_buckets=list(self._decode_page_buckets))
        else:
            self._prefill = self._wrap_program(
                self._prefill_impl, 7, "prefill")
            self._decode = self._wrap_program(
                self._decode_impl, 6, "decode")
            geom = (f"dense KV cache "
                    f"{cache_bytes / 2**20:.1f} MiB")
        mesh_note = (f", mesh {dict(self.mesh.shape)}"
                     if self.mesh is not None else "")
        if self.spec:
            mesh_note += (f", spec_decode k={self._spec_k} "
                          f"verify_widths={list(self._verify_widths)} "
                          f"({type(self._drafter).__name__})")
        if self.disagg:
            pool_note = "separate pools" if self._separate_pools \
                else "shared pool"
            if self._decode_mesh_axes:
                pool_note += f", decode mesh {self._decode_mesh_axes}"
            mesh_note += f", disagg ({pool_note})"
        logger.info(
            f"inference engine: {self.family}, {self.num_slots} slots, "
            f"max_len {max_len}, prompt buckets {cfg['prompt_buckets']}, "
            f"batch buckets {cfg['batch_buckets']}, {geom}{mesh_note}")

    def _resolve_decode_attn(self, pk):
        """Pick the paged decode attention path once, at init (the
        compiled program set is fixed, so the choice is too):
        ``attn_kernel: "pallas"`` runs the fused paged-attention Pallas
        kernel (``ops/attention/paged.py`` — O(live tokens) pool reads)
        wherever it can compile, with the stripe-gather path as the
        automatic fallback; ``"gather"`` pins the fallback. Also
        resolves the decode table-width buckets: the decode dispatch
        clamps its block tables to the smallest bucket covering the
        batch's live pages, so the gather fallback's bandwidth scales
        with tokens in flight too (one compiled decode program per
        width; default = a single full-width program, preserving the
        PR 5/7 warmup program count)."""
        from deepspeed_tpu.ops.attention.paged import \
            paged_decode_supported
        requested = pk["attn_kernel"]
        if requested != "pallas":
            self._decode_attn_path = "gather"
            self._decode_attn_reason = "configured"
        else:
            ok, why = paged_decode_supported(
                self.paged_spec.page_size, self.paged_spec.head_dim,
                dtype=self.paged_spec.dtype)
            if ok and self.mesh is not None:
                # a pallas_call can't be auto-partitioned by GSPMD —
                # the kernel runs shard_mapped over the mesh's model
                # axis instead (parallel/pallas_shard), each device
                # walking its local kv-head shard of the pool: sharded
                # serving KEEPS the O(live tokens) read. Geometry is
                # always legal here: __init__'s cache-sharding check
                # already rejected any model axis that does not divide
                # num_heads AND kv_heads (whole GQA groups per shard).
                from deepspeed_tpu.parallel.mesh import axis_size
                from deepspeed_tpu.parallel.pallas_shard import \
                    head_shard_supported
                n = axis_size(self.mesh, "model")
                assert head_shard_supported(
                    n, self.model_config.num_heads,
                    self.paged_spec.kv_heads), (n, "unreachable: init "
                                                "validates divisibility")
                self._decode_attn_path = "pallas"
                self._decode_attn_reason = (
                    f"shard_map over mesh axis 'model' ({n}-way); {why}")
            elif ok:
                self._decode_attn_path = "pallas"
                self._decode_attn_reason = why
            else:
                self._decode_attn_path = "gather"
                self._decode_attn_reason = f"pallas unsupported: {why}"
        pps = self.paged_spec.pages_per_seq
        widths = [int(b) for b in pk["decode_page_buckets"] if b < pps]
        self._decode_page_buckets = tuple(widths) + (pps,)

    def _resolve_context_parallel(self):
        """Decide once, at init, whether chunk dispatches for prompts
        past ``cp_threshold_tokens`` run context-parallel: the chunk's
        sequence axis ring-sharded over the serving mesh's model axis
        (``ops/attention/ring.ring_prefill_attention`` — forward-only
        online-softmax merge, K/V stripes rotating via ppermute). Any
        ineligibility falls back to single-shard chunks with the reason
        logged — the fallback matrix in docs/inference.md. The CP chunk
        program is a SECOND compiled program (``chunk_cp``) so
        sub-threshold chunks keep the plain prefill program and the
        compiled set stays fixed."""
        if self._cp_threshold <= 0:
            return
        stripe = self._prefill_pps * self.paged_spec.page_size
        if self.mesh is None:
            self._cp_reason = "no serving mesh (inference.mesh unset)"
        else:
            n = axis_size(self.mesh, "model")
            if n <= 1:
                self._cp_reason = "mesh model axis is size 1"
            elif self._chunk_tokens % n:
                self._cp_reason = (
                    f"chunk_tokens ({self._chunk_tokens}) not divisible "
                    f"by mesh model axis ({n})")
            elif stripe % n:
                self._cp_reason = (
                    f"kv stripe ({stripe} tokens) not divisible by "
                    f"mesh model axis ({n})")
            else:
                self._cp_shards = n
                self._cp_reason = (
                    f"ring prefill over mesh axis 'model' ({n}-way)")
                self._chunk_cp = self._wrap_program(
                    self._chunk_cp_impl, 8, "chunk_cp")
        logger.info(
            f"inference context-parallel prefill: "
            f"{'on' if self._cp_shards > 1 else 'off'} "
            f"({self._cp_reason}; threshold {self._cp_threshold} tokens)")
        if self._log is not None:
            self._log.add_event(
                "chunked_prefill_path", chunk_tokens=self._chunk_tokens,
                cp_shards=self._cp_shards, cp_reason=self._cp_reason,
                cp_threshold_tokens=self._cp_threshold)

    def _wrap_program(self, fn, nargs: int, name: str, mesh="__self__",
                      param_shardings=None, cache_sharding=None):
        """jit + CompileTracker wrap; with a serving mesh, pin GSPMD
        NamedShardings (params on their TP specs, cache on the kv_heads
        split, host arrays replicated) so every dispatch hits the same
        partitioned program. The mesh also rides a trace-time context
        (``parallel/pallas_shard.pallas_kernel_mesh``) so the models'
        Pallas kernel call sites shard_map over it instead of tripping
        GSPMD. Disaggregated serving wraps the decode-side programs
        against the DECODE mesh/shardings — pass them explicitly; the
        defaults are the prefill side's."""
        if mesh == "__self__":
            mesh = self.mesh
            param_shardings = self._param_shardings
            cache_sharding = self._cache_sharding
        if mesh is None:
            jitted = jax.jit(fn, donate_argnums=(1,))
        else:
            from deepspeed_tpu.parallel.pallas_shard import \
                pallas_kernel_mesh

            def fn_under_mesh(*args, _fn=fn, _mesh=mesh):
                with pallas_kernel_mesh(_mesh, "model"):
                    return _fn(*args)

            repl = NamedSharding(mesh, P())
            # one sharding per cache leaf: the (kc, vc) pair, or the
            # quantized 4-tuple (kc, vc, kscale, vscale) — scale pools
            # carry kv_heads at dim 2 exactly like the payload pools
            cache_sh = tuple(cache_sharding for _ in self._cache)
            in_sh = (param_shardings, cache_sh) + \
                (repl,) * (nargs - 2)
            jitted = jax.jit(fn_under_mesh, donate_argnums=(1,),
                             in_shardings=in_sh,
                             out_shardings=(repl, cache_sh))
        return self.compile_tracker.wrap(jitted, name)

    def _wrap_handoff_programs(self):
        """The two cross-pool page-migration programs (separate-pools
        disaggregation only): ``handoff_export`` gathers the live
        prompt pages out of the prefill pool (no donation — the pool
        keeps serving other slots), ``handoff_import`` scatters the
        slab into the decode pool (pool donated: migration allocates
        nothing steady-state). Fixed index width
        (``self._handoff_width``, pad index 0) keeps both in the
        warmup-compiled program set. Between them the slab crosses
        meshes by ``device_put`` when ``disagg.decode_mesh`` differs —
        the priced hop."""
        nleaf = len(self._cache)
        if self.mesh is None:
            ex = jax.jit(self._export_pages_impl)
        else:
            cs = self._cache_sharding
            slab_sh = NamedSharding(self.mesh, P(None, None, "model"))
            repl = NamedSharding(self.mesh, P())
            ex = jax.jit(self._export_pages_impl,
                         in_shardings=((cs,) * nleaf, repl),
                         out_shardings=(slab_sh,) * nleaf)
        self._export = self.compile_tracker.wrap(ex, "handoff_export")
        self._slab_sharding_decode = None
        if self._mesh_decode is None:
            im = jax.jit(self._import_pages_impl, donate_argnums=(0,))
        else:
            cs = self._cache_sharding_decode
            slab_sh = NamedSharding(self._mesh_decode,
                                    P(None, None, "model"))
            self._slab_sharding_decode = slab_sh
            repl = NamedSharding(self._mesh_decode, P())
            im = jax.jit(self._import_pages_impl, donate_argnums=(0,),
                         in_shardings=((cs,) * nleaf,
                                       (slab_sh,) * nleaf, repl),
                         out_shardings=(cs,) * nleaf)
        self._import = self.compile_tracker.wrap(im, "handoff_import")

    # -------------------------------------------------- compiled programs
    def _sample_tokens(self, logits, keys, temps):
        """Per-request sampling: greedy rows (temp <= 0) take argmax;
        the rest sample ``categorical(logits / temp)`` under the
        engine-global top-k filter with each row's own PRNG key."""
        logits = logits.astype(jnp.float32)
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
        if self._top_k > 0:
            kth = jax.lax.top_k(scaled, self._top_k)[0][:, -1][:, None]
            scaled = jnp.where(scaled < kth, NEG_INF, scaled)
        sampled = jax.vmap(jax.random.categorical)(keys, scaled)
        return jnp.where(temps > 0, sampled.astype(jnp.int32), greedy)

    def _prefill_impl(self, params, cache, ids, lengths, slots, keys,
                      temps):
        """One bucketed DENSE prefill: run the padded prompt batch
        through the model's cached forward against a fresh
        (bucket-batch-sized) cache, scatter its rows into the persistent
        slot cache at ``slots`` (pad rows target the scratch row), and
        sample each row's FIRST token from its last true prompt
        position."""
        kc, vc = cache
        Bb = ids.shape[0]
        spec = self.cache_spec
        tmp = (jnp.zeros((spec.num_layers, Bb, spec.kv_heads,
                          spec.max_len, spec.head_dim), spec.dtype),
               jnp.zeros((spec.num_layers, Bb, spec.kv_heads,
                          spec.max_len, spec.head_dim), spec.dtype))
        logits, (nkc, nvc) = self._forward(
            params, self.model_config, ids, dtype=self.dtype,
            kv_cache=tmp,
            cache_position=jnp.zeros((Bb,), jnp.int32))
        kc = kc.at[:, slots].set(nkc)
        vc = vc.at[:, slots].set(nvc)
        last = logits[jnp.arange(Bb), lengths - 1]          # (Bb, V)
        first_keys = jax.vmap(jax.random.fold_in)(keys, lengths)
        first = self._sample_tokens(last, first_keys, temps)
        return first, (kc, vc)

    def _decode_impl(self, params, cache, toks, positions, keys, temps):
        """One DENSE decode step over the FULL slot table: write each
        slot's pending token at its own position, sample the next.
        Inactive rows compute garbage that the host discards — uniform
        shapes are what keep this a single compiled program."""
        logits, cache = self._forward(
            params, self.model_config, toks[:, None], dtype=self.dtype,
            kv_cache=cache, cache_position=positions)
        step_keys = jax.vmap(jax.random.fold_in)(keys, positions + 1)
        nxt = self._sample_tokens(logits[:, 0], step_keys, temps)
        return nxt, cache

    def _prefill_paged_impl(self, params, cache, ids, lengths, positions,
                            tables, keys, temps):
        """One bucketed PAGED prefill: run each row's un-prefixed prompt
        suffix (``ids``, true lengths ``lengths``) through the cached
        forward starting at its ``positions`` offset (= tokens covered
        by shared prefix pages), scattering K/V straight into the page
        pool via ``tables`` — no per-bucket temp cache, no row copy; pad
        rows carry all-null tables so their garbage lands in the null
        page. Samples each row's FIRST token from its last true prompt
        position (absolute position ``positions + lengths`` — the same
        key schedule as the dense path)."""
        Bb = ids.shape[0]
        logits, cache = self._forward(
            params, self.model_config, ids, dtype=self.dtype,
            kv_cache=cache, cache_position=positions,
            block_tables=tables,
            paged_attn_kernel=self._decode_attn_path)
        last = logits[jnp.arange(Bb), lengths - 1]          # (Bb, V)
        first_keys = jax.vmap(jax.random.fold_in)(keys,
                                                  positions + lengths)
        first = self._sample_tokens(last, first_keys, temps)
        return first, cache

    def _chunk_cp_impl(self, params, cache, ids, lengths, positions,
                       tables, keys, temps):
        """The context-parallel chunk program: the SAME paged prefill
        body traced under the ``context_prefill_mesh`` context, so the
        models' q_len>1 gather attention routes through
        ``ring_prefill_attention`` — queries sequence-sharded over the
        mesh's model axis, K/V stripes rotating via ppermute, partials
        merged with the exact online-softmax combine. Everything else
        (scatter into the pool, final-position sampling, the key
        schedule) is byte-identical to :meth:`_prefill_paged_impl`."""
        from deepspeed_tpu.parallel.pallas_shard import \
            context_prefill_mesh
        with context_prefill_mesh(self.mesh, "model"):
            return self._prefill_paged_impl(params, cache, ids, lengths,
                                            positions, tables, keys,
                                            temps)

    def _decode_paged_impl(self, params, cache, toks, positions, tables,
                           keys, temps):
        """One PAGED decode step over the full slot table: each slot's
        pending token scatters into its block table's page at its own
        position; attention then runs straight off the pool — the
        fused Pallas paged kernel walks only each row's live pages
        (``_decode_attn_path == "pallas"``), or the gather fallback
        assembles the table-width stripe. The table WIDTH is the
        dispatch's live-page bucket (one compiled program per width),
        so even the fallback's reads scale with tokens in flight.
        Inactive rows carry all-null tables — garbage in, garbage
        discarded."""
        logits, cache = self._forward(
            params, self.model_config, toks[:, None], dtype=self.dtype,
            kv_cache=cache, cache_position=positions,
            block_tables=tables,
            paged_attn_kernel=self._decode_attn_path)
        step_keys = jax.vmap(jax.random.fold_in)(keys, positions + 1)
        nxt = self._sample_tokens(logits[:, 0], step_keys, temps)
        return nxt, cache

    def _verify_paged_impl(self, params, cache, toks, positions, tables,
                           keys, temps):
        """One speculative VERIFY dispatch: ``toks[i] = [pending,
        d_1..d_{v-1}]`` — each row's pending token plus its draft
        proposals (zero-padded) — runs as a seq-``v`` pass through the
        SAME paged cached forward as decode, writing all ``v``
        positions and producing ``v`` next-token samples per row:
        ``out[i, j]`` is what sequential decode would have sampled
        after position ``positions[i] + j`` (per-position keys continue
        the exact ``fold_in(key, position + 1)`` chain, so acceptance
        is bitwise-faithful for greedy AND sampled rows). The host
        accepts the longest prefix of drafts matching ``out`` and rolls
        the rest back by pure position bookkeeping: rejected positions'
        K/V sit beyond the causal cache mask and are overwritten by
        later contiguous writes before any query can attend them — no
        cache edit, no extra dispatch. Tables ride at FULL width (one
        program per verify width, not per width x page bucket)."""
        B, V = toks.shape
        logits, cache = self._forward(
            params, self.model_config, toks, dtype=self.dtype,
            kv_cache=cache, cache_position=positions,
            block_tables=tables,
            paged_attn_kernel=self._decode_attn_path)
        offs = positions[:, None] + 1 + \
            jnp.arange(V, dtype=jnp.int32)[None, :]
        vkeys = jax.vmap(lambda k, o: jax.vmap(
            lambda oo: jax.random.fold_in(k, oo))(o))(keys, offs)
        out = self._sample_tokens(logits.reshape(B * V, -1),
                                  vkeys.reshape(B * V, 2),
                                  jnp.repeat(temps, V))
        return out.reshape(B, V), cache

    def _export_pages_impl(self, cache, idx):
        """Gather ``idx``'s rows (live prompt pages) out of the prefill
        pool into a contiguous slab — the unit that crosses the
        prefill->decode link. No donation: the pool keeps serving.
        Leaf-generic over the cache tree: a quantized pool's fp32 scale
        pools ride the same gather, so migrated pages stay int8 on the
        wire (the scale slab is the small side-channel)."""
        return tuple(c[:, idx] for c in cache)

    def _import_pages_impl(self, cache, slab, idx):
        """Scatter a handoff slab into the decode pool at ``idx``
        (pad index 0 rows land in the null page — garbage by design).
        The pool is donated: steady-state migration allocates
        nothing. Leaf-generic like the export."""
        return tuple(c.at[:, idx].set(s) for c, s in zip(cache, slab))

    # ----------------------------------------------------------- serving
    # seeds are caller-supplied, so the memo must be bounded: a serving
    # daemon taking per-request random seeds would otherwise grow it one
    # entry per distinct seed, forever
    _KEY_CACHE_CAP = 4096

    def _key_for(self, seed: int) -> np.ndarray:
        key = self._key_cache.get(seed)
        if key is None:
            if len(self._key_cache) >= self._KEY_CACHE_CAP:
                self._key_cache.clear()
            key = np.asarray(jax.random.PRNGKey(seed))
            self._key_cache[seed] = key
        return key

    def submit(self, request: Request) -> int:
        """Queue one request; returns its uid (serving order is FIFO
        with bounded-lookahead admission)."""
        return self.scheduler.submit(request)

    def cancel(self, uid: int, reason: str = "evicted"
               ) -> Optional[FinishedRequest]:
        """Evict ``uid`` (queued or in flight): pages free immediately,
        a ``serve_evict`` event lands in the trail, and the returned
        FinishedRequest carries ``ttft_ms=None`` — never 0.0 — when the
        request was evicted before its first token. None for unknown/
        finished uids. Call between :meth:`step` calls, not inside
        one."""
        # disagg: a prefill-complete request can be waiting in the
        # handoff queue — pop its record NOW, before the slot eviction
        # below. Left queued it would sit as a phantom entry (depth
        # stays wrong, `dropped` never counted), and if this eviction
        # makes the scheduler idle, the serving loop exits with the
        # stale record still holding the queue — no later claim drain
        # ever voids it. The slot's page reservation itself is released
        # by ``scheduler.evict`` (``_release`` frees from whichever
        # pool owns the slot's pages).
        if self._handoff_q is not None:
            rec = self._handoff_q.pop(uid)
            if rec is not None:
                self._handoff_q.dropped(rec)
        return self.scheduler.evict(uid, reason=reason)

    # ------------------------------------------- live KV migration (16)
    def export_request(self, uid: int):
        """Export one in-flight request's complete portable state — a
        :class:`~.disagg.MigrationRecord` with its live pages gathered
        into a host slab — and evict it locally (reason "migrate", a
        bookkeeping row the router drops, never the client's answer).
        None when the request isn't portable from here: unknown uid,
        migration not warmed, no token sampled yet (mid-prefill — the
        queue path redistributes those), or pages still in the prefill
        pool (separate-pools disagg, pre-claim). Call between
        :meth:`step` calls."""
        if self._mig_export is None:
            return None
        sched = self.scheduler
        for sid in sched.active_slots():
            slot = sched.slots[sid]
            if slot.request.uid != uid:
                continue
            if slot.pending_tok is None:
                return None
            if self._separate_pools and slot.pool == "admit":
                return None
            spec = self.paged_spec
            live = min(pages_for(slot.position, spec.page_size),
                       len(slot.pages))
            idx = np.zeros((self._mig_width,), np.int32)
            idx[:live] = slot.pages[:live]
            slabs = self._mig_export(self._cache, jnp.asarray(idx))
            # trim to the live pages on the host — the wire carries
            # content, never the reservation. Quantized pools export
            # four slabs (payload + fp32 scales); migrated pages stay
            # int8 on the wire.
            slabs = tuple(np.asarray(s[:, :live]) for s in slabs)
            kslab, vslab = slabs[0], slabs[1]
            kscale_slab = slabs[2] if len(slabs) == 4 else None
            vscale_slab = slabs[3] if len(slabs) == 4 else None
            req = slot.request
            now = sched._clock()
            rec = MigrationRecord(
                uid=uid, prompt=list(req.prompt),
                max_new_tokens=req.max_new_tokens,
                temperature=req.temperature, seed=req.seed,
                eos_id=req.eos_id,
                priority=getattr(req, "priority", 0),
                position=slot.position, pending_tok=slot.pending_tok,
                tokens=list(slot.tokens), live_pages=live,
                page_bytes=self._page_bytes, ttft_ms=slot.ttft_ms,
                queue_wait_ms=slot.queue_wait_ms,
                elapsed_ms=(now - slot.t_submit) * 1e3,
                draft_proposed=slot.draft_proposed,
                draft_accepted=slot.draft_accepted,
                weight_version=self._weight_version,
                trace_id=getattr(req, "trace_id", None),
                hop=getattr(req, "hop", 0),
                kslab=kslab, vslab=vslab,
                kscale_slab=kscale_slab, vscale_slab=vscale_slab)
            # lineage row BEFORE the eviction below pops the trace —
            # the destination's serve_migrate_in shares the trace id
            self._tracer.on_migrate_out(uid, position=rec.position,
                                        pages=rec.live_pages,
                                        nbytes=rec.nbytes)
            sched.evict(uid, reason="migrate")
            return rec
        return None

    def import_request(self, rec) -> Optional[int]:
        """Resume a migrated request here: allocate its full-lifetime
        page reservation, scatter the shipped slab at the same logical
        positions (warmup-compiled ``migrate_import`` — zero
        recompiles), and install the slot at the same
        ``cache_position``. Decode continues bitwise-identically
        because sampling keys derive from (seed, position) only. None
        — with nothing leaked — when this replica can't take it (no
        free slot, pool exhausted, or geometry/dtype mismatch with the
        source: a mismatched slab would mint a new program signature)."""
        if self._mig_import is None:
            return None
        spec = self.paged_spec
        want = (spec.num_layers, rec.live_pages, spec.kv_heads,
                spec.page_size, spec.head_dim)
        if (rec.kslab is None or tuple(rec.kslab.shape) != want
                or tuple(rec.vslab.shape) != want
                or np.dtype(rec.kslab.dtype) != np.dtype(spec.dtype)
                or rec.live_pages > self._mig_width):
            return None
        slabs_in = [rec.kslab, rec.vslab]
        if spec.quantized:
            # a quantized pool needs the scale slabs too — an fp-pool
            # record (or a geometry-mismatched scale slab) bounces with
            # nothing leaked, same as a payload dtype mismatch
            swant = (spec.num_layers, rec.live_pages, spec.kv_heads,
                     spec.page_size, spec.scale_blocks)
            ks = getattr(rec, "kscale_slab", None)
            vs = getattr(rec, "vscale_slab", None)
            if (ks is None or vs is None
                    or tuple(ks.shape) != swant
                    or tuple(vs.shape) != swant):
                return None
            slabs_in += [ks, vs]
        elif getattr(rec, "kscale_slab", None) is not None:
            return None    # int8-pool record into an fp pool
        sched = self.scheduler
        if not sched.free_slots():
            return None
        need = pages_for(len(rec.prompt) + rec.max_new_tokens,
                         spec.page_size)
        pages = sched.allocator.alloc(max(need, rec.live_pages))
        if pages is None:
            return None
        width = self._mig_width
        idx = np.zeros((width,), np.int32)
        idx[:rec.live_pages] = pages[:rec.live_pages]
        wide = []
        for s, leaf in zip(slabs_in, self._cache):
            w = np.zeros((spec.num_layers, width) + tuple(leaf.shape[2:]),
                         np.dtype(leaf.dtype))
            w[:, :rec.live_pages] = s
            wide.append(jnp.asarray(w))
        # pad rows scatter zeros into the null page — garbage by design
        self._cache = self._mig_import(self._cache, tuple(wide),
                                       jnp.asarray(idx))
        req = Request(prompt=list(rec.prompt),
                      max_new_tokens=rec.max_new_tokens,
                      temperature=rec.temperature, seed=rec.seed,
                      eos_id=rec.eos_id, priority=rec.priority,
                      uid=rec.uid,
                      trace_id=getattr(rec, "trace_id", None),
                      hop=int(getattr(rec, "hop", 0)) + 1)
        sid = sched.install_slot(
            req, position=rec.position, pending_tok=rec.pending_tok,
            tokens=rec.tokens, pages=pages, ttft_ms=rec.ttft_ms,
            queue_wait_ms=rec.queue_wait_ms, elapsed_ms=rec.elapsed_ms,
            draft_proposed=rec.draft_proposed,
            draft_accepted=rec.draft_accepted, pool="main")
        if sid is None:
            sched.allocator.free(pages)
            return None
        # destination half of the lineage pair: resumes the ORIGINAL
        # trace id (hop bumped), so later decode-window/finish rows on
        # this replica stitch to the source's serve_migrate_out
        self._tracer.on_migrate_in(
            rec.uid, trace_id=req.trace_id, hop=req.hop,
            position=rec.position, pages=rec.live_pages,
            nbytes=rec.nbytes, queue_wait_ms=rec.queue_wait_ms,
            ttft_ms=rec.ttft_ms, elapsed_ms=rec.elapsed_ms,
            tokens=len(rec.tokens))
        if self._log is not None:
            self._log.add_event("serve_resume", uid=rec.uid, slot=sid,
                                position=rec.position,
                                live_pages=rec.live_pages)
        return sid

    # ------------------------------------------------- live weight swap
    @property
    def weight_version(self) -> str:
        """The checkpoint tag currently serving ("initial" for
        constructor-supplied params) — stamped onto every
        FinishedRequest."""
        return self._weight_version

    @property
    def weight_ordinal(self) -> int:
        """Committed swap count (the ``Serve/weight_version`` scalar:
        0 = the weights the engine started with)."""
        return self._weight_ordinal

    def swap_params(self, load_dir: str, tag: Optional[str] = None,
                    verify_integrity: bool = True) -> str:
        """Push a newly committed checkpoint tag into the RUNNING
        engine — the live half of the train->serve loop.

        Loads the tag's ``model_states`` group through
        ``load_params_only`` with the engine's live params as the
        template, so every new leaf materializes with the OLD leaf's
        dtype and sharding: the compiled program set keys on
        aval+sharding, both are unchanged, and steady-state serving
        continues with zero recompiles. The swap is atomic-or-rollback:
        nothing is assigned until the whole tree has loaded, so any
        failure (bad tag, I/O error, injected ``serve.swap_load``
        fault) leaves the engine serving the old weights untouched.

        Call between :meth:`step` calls (same contract as
        :meth:`cancel`); in-flight requests switch weights at their
        next dispatch — their KV prefix stays valid (same model
        geometry), which is the standard live-upgrade semantic.
        Returns the new version stamp (the tag name)."""
        from deepspeed_tpu.runtime import checkpoint as ckptlib
        from deepspeed_tpu.runtime import fault
        t0 = time.perf_counter()
        try:
            chosen = _resolve_committed_tag(ckptlib, load_dir, tag,
                                            verify_integrity)
            version = os.path.basename(chosen)
            fault.fire("serve.swap_load", path=chosen, version=version)
            if is_quantized_tree(self.params):
                # int8-resident replica: the checkpoint holds fp
                # weights, so the live tree can't be the load template.
                # Load fp against a dense eval_shape template (resharded
                # onto the fp TP specs), then REQUANTIZE into the exact
                # resident layout — same avals, same shardings, same
                # committedness as the constructor's tree, so the warm
                # program set keys hit: zero recompiles.
                _, _, init_fn, specs_fn = _family_of(self.model_config)
                template = jax.eval_shape(
                    lambda k: init_fn(self.model_config, k),
                    jax.random.PRNGKey(0))
                fp_sh = None
                if self.mesh is not None:
                    fp_sh = _param_shardings(
                        self.mesh, specs_fn, self.model_config, template)
                new_params = ckptlib.load_params_only(chosen, template,
                                                      fp_sh)
                new_params = quantize_param_tree(new_params,
                                                 self._weight_block)
                if self.mesh is not None:
                    new_params = jax.tree_util.tree_map(
                        lambda x, s: jax.device_put(x, s),
                        new_params, self._param_shardings)
            else:
                new_params = ckptlib.load_params_only(
                    chosen, self.params, self._param_shardings)
        except BaseException as e:
            if self._log is not None:
                self._log.add_event(
                    "fleet_swap", ok=False, tag=tag,
                    load_dir=str(load_dir),
                    error=str(e) or type(e).__name__,
                    weight_version=self._weight_version,
                    weight_ordinal=self._weight_ordinal)
            logger.warning(
                f"swap_params: load failed ({e!r}); still serving "
                f"weight_version={self._weight_version}")
            raise
        if self.mesh is None:
            # single-device serving: construction built params with
            # ``jnp.asarray`` (UNcommitted); the loader returns
            # committed arrays, and jit specializes on committedness —
            # the host round-trip restores the constructor's placement
            # so the warm program set keys hit (zero recompiles)
            new_params = jax.tree_util.tree_map(
                lambda x: jnp.asarray(np.asarray(x)), new_params)
        # commit — from here on every dispatch sees the new weights
        alias = self.params_decode is self.params
        self.params = new_params
        if alias:
            self.params_decode = new_params
        else:
            # disagg decode mesh: re-ship the decode workers' copy onto
            # their own shardings (weights move once per swap, exactly
            # like construction)
            self.params_decode = jax.tree_util.tree_map(
                lambda x, s: jax.device_put(x, s),
                new_params, self._param_shardings_decode)
        self._weight_version = version
        self._weight_ordinal += 1
        self.scheduler.weight_version = version
        wall_ms = (time.perf_counter() - t0) * 1e3
        if self._log is not None:
            self._log.add_event(
                "fleet_swap", ok=True, checkpoint=chosen,
                weight_version=version,
                weight_ordinal=self._weight_ordinal,
                wall_ms=round(wall_ms, 3))
        self.monitor.write_serving_metrics(
            weight_version=self._weight_ordinal,
            tokens=self.scheduler.total_tokens)
        logger.info(f"swap_params: now serving {version} "
                    f"(ordinal {self._weight_ordinal}, "
                    f"{wall_ms:.1f} ms, zero recompiles by construction)")
        return version

    def record_quant_logit_err(self, err: float) -> None:
        """Record an offline quantized-vs-fp-oracle max-logit-error
        probe (tests/bench compute it against a
        :func:`~deepspeed_tpu.runtime.quantized_params.dequantize_param_tree`
        oracle — the serving path itself never pays for one). The next
        decode telemetry write carries it as ``Serve/quant_logit_err``
        and ``debug_state`` mirrors it for ``obs_report --serve``."""
        self.quant_logit_err = float(err)

    def set_speculation(self, on: bool) -> bool:
        """Degrade rung of the fleet shed ladder: toggle speculative
        decoding without touching the compiled program set (the plain
        one-token decode program is part of the warmed set, so turning
        drafting off never recompiles). Returns False — and does
        nothing — on an engine built without spec_decode."""
        if not self.spec:
            return False
        self.scheduler.spec_k = self._spec_k if on else 0
        return True

    def debug_state(self) -> Dict[str, Any]:
        """Live introspection snapshot — pure host reads, zero device
        syncs, safe to call mid-serving from a debug endpoint: page
        pool occupancy/fragmentation + prefix-cache accounting, the
        slot table, queue depth by prompt bucket, per-program dispatch
        counts, and the tracer's SLO/latency histograms. Rendered by
        ``tools/obs_report.py --serve`` from the periodic
        ``serve_state`` event rows."""
        sched = self.scheduler
        slots = []
        for sid in sched.active_slots():
            s = sched.slots[sid]
            slots.append({"slot": sid, "uid": s.request.uid,
                          "position": s.position,
                          "generated": len(s.tokens),
                          "prefix_tokens": s.prefix_len,
                          "pages": len(s.pages)})
        ct = self.compile_tracker
        programs = {n: {"dispatches": d, "compiles": ct.counts.get(n, 0)}
                    for n, d in sorted(ct.dispatch_counts.items())}
        pool = None
        if self.paged and sched.allocator is not None:
            pool = sched.allocator.debug_state()
            used_tokens = pool["pages_in_use"] * pool["page_size"]
            # internal fragmentation: reserved pool capacity not yet
            # holding a live token (padding tails + reserved-but-
            # unreached decode pages)
            pool["tokens_in_flight"] = sched.tokens_in_flight
            pool["internal_fragmentation"] = round(
                1.0 - sched.tokens_in_flight / used_tokens, 4) \
                if used_tokens else 0.0
            pool["decode_attn_path"] = self._decode_attn_path
        wq, wd = quantized_tree_bytes(self.params)
        quant = {
            "weights_resident": self.weights_resident,
            "weight_bytes": wq,
            "weight_bytes_dense": wd,
            "kv_dtype": (jnp.dtype(self.paged_spec.dtype).name
                         if self.paged else
                         jnp.dtype(self.cache_spec.dtype).name),
            "kv_quant_block": (self.paged_spec.quant_block
                               if self.paged else 0),
            "kv_pool_bytes_per_token": round(self._kv_bpt, 3),
            "quant_logit_err": self.quant_logit_err,
        }
        state = {
            "family": self.family,
            "steps": self._steps,
            "quantization": quant,
            "queue_depth": sched.queue_depth,
            "queue_by_bucket": sched.queue_by_bucket(),
            "occupancy": round(sched.occupancy, 4),
            "slots": slots,
            "programs": programs,
            "steady_state_recompiles": self.steady_state_recompiles,
            "page_pool": pool,
            "slo": self._tracer.snapshot(),
            "weight_version": self._weight_version,
            "weight_ordinal": self._weight_ordinal,
        }
        if self.spec:
            state["spec_decode"] = {
                "k": self._spec_k,
                "verify_widths": list(self._verify_widths),
                "drafter": type(self._drafter).__name__,
            }
        if self.disagg:
            dff = self._dispatch_trace.decode_first_fraction()
            dg = {"separate_pools": self._separate_pools,
                  "queue": self._handoff_q.debug_state(),
                  "handoff": self._handoff_stats.snapshot(),
                  "decode_first_fraction": (round(dff, 4)
                                            if dff is not None else None)}
            if self._separate_pools:
                dg["prefill_pool"] = sched.admit_allocator.debug_state()
            state["disagg"] = dg
        if self.chunked:
            state["chunked_prefill"] = {
                "chunk_tokens": self._chunk_tokens,
                "dispatches": self._chunk_dispatches,
                "chunking_slots": len(sched.chunking_slots()),
                "cp_shards": self._cp_shards,
                "cp_threshold_tokens": self._cp_threshold,
                "cp_reason": self._cp_reason,
            }
        return state

    def _run_prefill(self, batch) -> np.ndarray:
        keys = np.zeros((batch.batch_bucket, 2), np.uint32)
        temps = np.zeros((batch.batch_bucket,), np.float32)
        for i, req in enumerate(batch.requests):
            keys[i] = self._key_for(req.seed)
            temps[i] = req.temperature
        with trace_span("serve/prefill", recorder=self._recorder,
                        batch=batch.batch_bucket,
                        prompt=batch.prompt_bucket):
            if self.paged:
                suffixes = [r.prompt[pl:] for r, pl in
                            zip(batch.requests, batch.prefix_lens)]
                ids, lengths = pad_prompts(suffixes, batch.prompt_bucket,
                                           batch.batch_bucket)
                positions = np.zeros((batch.batch_bucket,), np.int32)
                tables = np.zeros(
                    (batch.batch_bucket, self._prefill_pps), np.int32)
                for i, (pl, pages) in enumerate(
                        zip(batch.prefix_lens, batch.page_tables)):
                    positions[i] = pl
                    tables[i, :len(pages)] = pages
                if self._separate_pools:
                    first, self._cache_prefill = self._prefill(
                        self.params, self._cache_prefill,
                        jnp.asarray(ids), jnp.asarray(lengths),
                        jnp.asarray(positions), jnp.asarray(tables),
                        jnp.asarray(keys), jnp.asarray(temps))
                else:
                    first, self._cache = self._prefill(
                        self.params, self._cache, jnp.asarray(ids),
                        jnp.asarray(lengths), jnp.asarray(positions),
                        jnp.asarray(tables), jnp.asarray(keys),
                        jnp.asarray(temps))
            else:
                ids, lengths = pad_prompts(
                    [r.prompt for r in batch.requests],
                    batch.prompt_bucket, batch.batch_bucket)
                slots = np.full((batch.batch_bucket,), self._scratch,
                                np.int32)
                slots[:len(batch.slot_ids)] = batch.slot_ids
                first, self._cache = self._prefill(
                    self.params, self._cache, jnp.asarray(ids),
                    jnp.asarray(lengths), jnp.asarray(slots),
                    jnp.asarray(keys), jnp.asarray(temps))
            return np.asarray(first)

    def _drain_request_metrics(self):
        """Per-admitted-request scalar writes (TTFT / queue wait)
        pulled off the scheduler's drain queues."""
        sched = self.scheduler
        for ttft in sched.drain_ttfts():
            self.monitor.write_serving_metrics(
                ttft_ms=ttft, tokens=sched.total_tokens, flush=False)
        for qwait in sched.drain_queue_waits():
            self.monitor.write_serving_metrics(
                queue_wait_ms=qwait, tokens=sched.total_tokens,
                flush=False)

    def _prefill_phase(self, finished: List[FinishedRequest]) -> None:
        """Admission + bucketed prefill dispatches (the prefill worker
        loop). Non-disagg: each first token releases to its request
        immediately. Disagg: it parks in the handoff queue instead —
        the DECODE phase claims it, so TTFT honestly includes the
        handoff wait."""
        sched = self.scheduler
        self.health.heartbeat("prefill")
        t0 = time.perf_counter()
        for batch in sched.admit():
            t_p = time.perf_counter()
            first = self._run_prefill(batch)
            prefill_ms = (time.perf_counter() - t_p) * 1e3
            if self._dispatch_trace is not None:
                self._dispatch_trace.record(self._steps, "prefill")
            for sid, req in zip(batch.slot_ids, batch.requests):
                self._tracer.on_prefill(
                    req.uid, sid, prefill_ms, batch.prompt_bucket,
                    batch.batch_bucket, len(batch.requests))
            if self.disagg:
                now = time.perf_counter()
                ps = self.paged_spec.page_size
                for i, (sid, req) in enumerate(zip(batch.slot_ids,
                                                   batch.requests)):
                    self._handoff_q.push(HandoffRecord(
                        uid=req.uid, slot=sid,
                        first_token=int(first[i]),
                        live_pages=pages_for(len(req.prompt), ps),
                        prompt_tokens=len(req.prompt), t_ready=now))
            else:
                finished.extend(sched.record_tokens(
                    {sid: int(first[i])
                     for i, sid in enumerate(batch.slot_ids)}))
            self._drain_request_metrics()
        self._serve_secs += time.perf_counter() - t0

    def _chunk_phase(self, finished: List[FinishedRequest]) -> None:
        """At most ONE chunk dispatch per engine step — the pinned TBT
        bound: a decode dispatch never waits behind more than one
        ``chunk_tokens``-sized prefill slice, however long the prompt.
        The dispatch reuses the prefill program at ids shape
        (batch_bucket, chunk_tokens) — ``positions`` is each slot's
        absolute prefilled offset, ``tables`` its full page list, K/V
        scatter straight into the pool. Intermediate chunks' sampled
        tokens are discarded on the host; the FINAL chunk samples from
        ``fold_in(key, positions + lengths)`` = the whole-prompt key,
        so the first token is bitwise the one whole-prompt prefill
        would have produced. Past ``cp_threshold_tokens`` (and with an
        eligible mesh) the dispatch runs the context-parallel chunk
        program instead."""
        if not self.chunked:
            return
        sched = self.scheduler
        cand = sched.chunk_batch(cap=max(self.config["batch_buckets"]))
        if not cand:
            return
        self.health.heartbeat("chunk_prefill")
        t0 = time.perf_counter()
        use_cp = False
        if self._cp_shards > 1:
            # one program per dispatch: the head's eligibility class
            # picks it, rows of the other class wait for a later step
            def _cp(sid):
                return (len(sched.slots[sid].request.prompt)
                        >= self._cp_threshold)
            use_cp = _cp(cand[0])
            cand = [sid for sid in cand if _cp(sid) == use_cp]
        bb = pick_bucket(len(cand), self.config["batch_buckets"])
        ct = self._chunk_tokens
        ids = np.zeros((bb, ct), np.int32)
        lengths = np.ones((bb,), np.int32)
        positions = np.zeros((bb,), np.int32)
        tables = np.zeros((bb, self._prefill_pps), np.int32)
        keys = np.zeros((bb, 2), np.uint32)
        temps = np.zeros((bb,), np.float32)
        spans = []
        for i, sid in enumerate(cand):
            slot = sched.slots[sid]
            req = slot.request
            start, n = sched.chunk_span(sid)
            spans.append((sid, req, start, n,
                          (start - slot.prefix_len) // ct))
            ids[i, :n] = req.prompt[start:start + n]
            lengths[i] = n
            positions[i] = start
            tables[i, :len(slot.pages)] = slot.pages
            keys[i] = self._key_for(req.seed)
            temps[i] = req.temperature
        prog = self._chunk_cp if use_cp else self._prefill
        t_c = time.perf_counter()
        with trace_span("serve/chunk", recorder=self._recorder,
                        batch=bb, chunk=ct,
                        cp_shards=self._cp_shards if use_cp else 1):
            if self._separate_pools:
                first, self._cache_prefill = prog(
                    self.params, self._cache_prefill, jnp.asarray(ids),
                    jnp.asarray(lengths), jnp.asarray(positions),
                    jnp.asarray(tables), jnp.asarray(keys),
                    jnp.asarray(temps))
            else:
                first, self._cache = prog(
                    self.params, self._cache, jnp.asarray(ids),
                    jnp.asarray(lengths), jnp.asarray(positions),
                    jnp.asarray(tables), jnp.asarray(keys),
                    jnp.asarray(temps))
            # host sync: final chunks release their first token
            first = np.asarray(first)
        wall_ms = (time.perf_counter() - t_c) * 1e3
        if self._dispatch_trace is not None:
            self._dispatch_trace.record(self._steps, "chunk")
        self._chunk_dispatches += 1
        shards = self._cp_shards if use_cp else 1
        now = time.perf_counter()
        released: Dict[int, int] = {}
        for i, (sid, req, start, n, k) in enumerate(spans):
            self._tracer.on_prefill_chunk(req.uid, sid, k, n, wall_ms,
                                          cp_shards=shards)
            if not sched.record_chunk(sid, n):
                continue                    # mid-prompt, keep chunking
            if self.disagg:
                ps = self.paged_spec.page_size
                self._handoff_q.push(HandoffRecord(
                    uid=req.uid, slot=sid, first_token=int(first[i]),
                    live_pages=pages_for(len(req.prompt), ps),
                    prompt_tokens=len(req.prompt), t_ready=now))
            else:
                released[sid] = int(first[i])
        if released:
            finished.extend(sched.record_tokens(released))
        self.monitor.write_serving_metrics(
            chunk_dispatches=self._chunk_dispatches,
            tokens=sched.total_tokens, flush=False)
        self._drain_request_metrics()
        self._serve_secs += time.perf_counter() - t0

    def _claim_phase(self, finished: List[FinishedRequest]) -> None:
        """Disagg decode-worker intake: claim completed prefills off
        the handoff queue, transferring page OWNERSHIP to the decode
        loop — a zero-copy host bookkeeping move on a shared pool, or
        an export -> link -> import migration of only the live prompt
        pages (never the full reservation) across separate pools /
        meshes, priced by the LinkModel next to the measured wall
        time. A claim the decode pool can't fund yet bounces back
        (requeue + "handoff" defer): decode-side memory pressure
        backpressures the handoff, never the prefill loop. Each claim
        releases the request's first token."""
        sched = self.scheduler
        q = self._handoff_q
        tracer = self._tracer
        self.health.heartbeat("handoff_claim")
        t0 = time.perf_counter()
        for rec in q.drain():
            slot = sched.slots[rec.slot]
            if slot is None or slot.request.uid != rec.uid:
                q.dropped(rec)     # evicted while the handoff waited
                continue
            transfer_ms = 0.0
            priced = 0.0
            pages = nbytes = 0
            mode = "shared_pool"
            if self._separate_pools:
                req = slot.request
                need = pages_for(len(req.prompt) + req.max_new_tokens,
                                 self.paged_spec.page_size)
                new_pages = sched.allocator.alloc(need)
                if new_pages is None:
                    q.requeue(rec)
                    tracer.on_defer(rec.uid, "handoff")
                    continue
                cross = self._mesh_decode is not self.mesh
                mode = "migrate_mesh" if cross else "migrate"
                t_m = time.perf_counter()
                src = np.zeros((self._handoff_width,), np.int32)
                dst = np.zeros((self._handoff_width,), np.int32)
                live = slot.pages[:rec.live_pages]
                src[:len(live)] = live
                dst[:len(live)] = new_pages[:len(live)]
                slab = self._export(self._cache_prefill,
                                    jnp.asarray(src))
                if cross and self._slab_sharding_decode is not None:
                    slab = tuple(
                        jax.device_put(s, self._slab_sharding_decode)
                        for s in slab)
                self._cache = self._import(self._cache, slab,
                                           jnp.asarray(dst))
                # one host sync per CLAIM (once per request, never per
                # dispatch): the measured wall time must cover the
                # device copy it reports
                jax.block_until_ready(self._cache[0])
                transfer_ms = (time.perf_counter() - t_m) * 1e3
                pages = len(live)
                nbytes = pages * self._page_bytes
                priced = price_handoff(
                    pages, self._page_bytes, self._link,
                    axis="inter" if cross else "intra")
                sched.adopt_pages(rec.slot, new_pages)
                if self._dispatch_trace is not None:
                    self._dispatch_trace.record(self._steps, "handoff")
            queue_ms = q.claimed(rec)
            tracer.on_handoff(rec.uid, queue_ms, transfer_ms, pages,
                              nbytes, mode, priced)
            self._handoff_stats.record(queue_ms, transfer_ms, pages,
                                       nbytes)
            self.monitor.write_serving_metrics(
                handoff_ms=queue_ms + transfer_ms,
                tokens=sched.total_tokens, flush=False)
            finished.extend(sched.record_tokens(
                {rec.slot: rec.first_token}))
            self._drain_request_metrics()
        self._serve_secs += time.perf_counter() - t0

    def _decode_phase(self, finished: List[FinishedRequest]) -> bool:
        """Advance every in-flight sequence: a plain one-token decode
        dispatch, or — with speculation and live draft proposals — ONE
        seq-``v`` verify dispatch that emits ``accepted + 1`` tokens
        per row. Returns whether anything dispatched."""
        sched = self.scheduler
        self.health.heartbeat("decode")
        sids, toks, poss, temps, seeds = sched.decode_state()
        if not sids:
            return False
        t0 = time.perf_counter()
        occupancy = len(sids) / self.num_slots
        toks_a = np.zeros((self._rows,), np.int32)
        poss_a = np.zeros((self._rows,), np.int32)
        temps_a = np.zeros((self._rows,), np.float32)
        keys_a = np.zeros((self._rows, 2), np.uint32)
        for sid, tok, pos, temp, seed in zip(sids, toks, poss, temps,
                                             seeds):
            toks_a[sid] = tok
            poss_a[sid] = pos
            temps_a[sid] = temp
            keys_a[sid] = self._key_for(seed)
        props: Dict[int, List[int]] = {}
        if self.spec and self.paged:
            props = sched.draft_proposals(
                cap=max(self._verify_widths) - 1)
        spec_kw = {}
        runs: Dict[int, List[int]] = {}
        draft_stats = None
        t_d = time.perf_counter()
        if props:
            dmax = max(len(p) for p in props.values())
            v = pick_bucket(dmax + 1, self._verify_widths)
            vt = np.zeros((self._rows, v), np.int32)
            vt[:, 0] = toks_a
            for sid, p in props.items():
                vt[sid, 1:1 + len(p)] = p
            # verify tables ride at FULL width: one compiled program
            # per verify width, not per width x page bucket
            tables = sched.block_table_rows(
                self._rows, self.paged_spec.pages_per_seq)
            with trace_span("serve/verify", recorder=self._recorder,
                            active=len(sids), width=v):
                out, self._cache = self._verify(
                    self.params_decode, self._cache, jnp.asarray(vt),
                    jnp.asarray(poss_a), jnp.asarray(tables),
                    jnp.asarray(keys_a), jnp.asarray(temps_a))
                # host sync: the scheduler needs the token values
                out = np.asarray(out)
            if self._dispatch_trace is not None:
                self._dispatch_trace.record(self._steps, "verify")
            draft_stats = {}
            proposed_total = accepted_total = 0
            for sid in sids:
                p = props.get(sid)
                if not p:
                    # rode the verify program with zero drafts — a
                    # draft stall, traced once per request
                    runs[sid] = [int(out[sid, 0])]
                    tracer_uid = sched.slots[sid].request.uid
                    self._tracer.on_defer(tracer_uid, "draft_stall")
                    continue
                m = 0
                while m < len(p) and p[m] == int(out[sid, m]):
                    m += 1
                runs[sid] = [int(t) for t in out[sid, :m + 1]]
                draft_stats[sid] = (len(p), m)
                self._tracer.on_spec(
                    sched.slots[sid].request.uid, len(p), m)
                proposed_total += len(p)
                accepted_total += m
            if proposed_total:
                spec_kw["spec_accept_rate"] = (accepted_total
                                               / proposed_total)
        else:
            with trace_span("serve/decode", recorder=self._recorder,
                            active=len(sids)):
                if self.paged:
                    # clamp the dispatch's table width to the batch's
                    # live-page bucket: reads (kernel walk or gather
                    # stripe) scale with tokens in flight, and every
                    # width was compiled at warmup
                    width = pick_bucket(
                        min(sched.max_live_pages(),
                            self.paged_spec.pages_per_seq),
                        self._decode_page_buckets)
                    tables = sched.block_table_rows(self._rows, width)
                    nxt, self._cache = self._decode(
                        self.params_decode, self._cache,
                        jnp.asarray(toks_a), jnp.asarray(poss_a),
                        jnp.asarray(tables), jnp.asarray(keys_a),
                        jnp.asarray(temps_a))
                else:
                    nxt, self._cache = self._decode(
                        self.params_decode, self._cache,
                        jnp.asarray(toks_a), jnp.asarray(poss_a),
                        jnp.asarray(keys_a), jnp.asarray(temps_a))
                # host sync: the scheduler needs the token values
                nxt = np.asarray(nxt)
            if self._dispatch_trace is not None:
                self._dispatch_trace.record(self._steps, "decode")
            runs = {sid: [int(nxt[sid])] for sid in sids}
            if self.spec:
                # speculation on, drafter had nothing anywhere: the
                # whole dispatch fell back to plain decode
                for sid in sids:
                    self._tracer.on_defer(
                        sched.slots[sid].request.uid, "draft_stall")
        tok_ms = (time.perf_counter() - t_d) * 1e3
        finished.extend(sched.record_token_runs(runs, draft_stats))
        self._serve_secs += time.perf_counter() - t0
        tps = (sched.total_tokens / self._serve_secs
               if self._serve_secs > 0 else 0.0)
        paged_kw = {}
        if self.paged:
            alloc = sched.allocator
            hit_alloc = sched.admit_allocator
            seen = (hit_alloc.prefix_hit_tokens
                    + hit_alloc.prefix_miss_tokens)
            paged_kw = dict(
                kv_pages_in_use=alloc.pages_in_use,
                tokens_in_flight=sched.tokens_in_flight,
                prefix_hit_rate=(hit_alloc.prefix_hit_tokens / seen
                                 if seen else 0.0),
                decode_attn_path=(
                    1.0 if self._decode_attn_path == "pallas"
                    else 0.0),
                kv_pool_bytes_per_token=self._kv_bpt)
        if self.quant_logit_err is not None:
            paged_kw["quant_logit_err"] = self.quant_logit_err
        tracer = self._tracer
        slo_kw = {}
        if tracer.enabled:
            tbts = tracer.drain_step_tbts()
            if tbts:
                slo_kw["tbt_ms"] = sum(tbts) / len(tbts)
                slo_kw["tbt_max_ms"] = max(tbts)
            att = tracer.slo_attainment
            if att is not None:
                slo_kw["slo_attainment"] = att
                slo_kw["goodput_tokens_per_s"] = (
                    tracer.good_tokens / self._serve_secs
                    if self._serve_secs > 0 else 0.0)
        self.monitor.write_serving_metrics(
            token_latency_ms=tok_ms, tokens_per_sec=tps,
            queue_depth=sched.queue_depth, batch_occupancy=occupancy,
            tokens=sched.total_tokens, flush=False, **paged_kw,
            **slo_kw, **spec_kw)
        return True

    def step(self) -> List[FinishedRequest]:
        """One serving iteration. Default: admit waiting requests into
        free slots (bucketed prefill, first token released), then
        advance every in-flight sequence one decode (or speculative
        verify) dispatch. Disaggregated (``inference.disagg``): the
        DECODE phase runs FIRST — handoff claims, then the decode/
        verify dispatch — and the prefill phase runs after it, so no
        decode dispatch ever waits behind a prefill dispatch
        (structural; pinned by the dispatch trace). Chunked prefill
        (``inference.chunked_prefill``) makes every step decode-first
        and slips AT MOST ONE chunk dispatch between the decode and
        admission phases: claim? -> decode -> chunk -> prefill.
        Returns requests that finished this iteration."""
        finished: List[FinishedRequest] = []
        finished.extend(self.scheduler.drain_rejects())
        if self.disagg:
            self._claim_phase(finished)
            self._decode_phase(finished)
            self._chunk_phase(finished)
            self._prefill_phase(finished)
        elif self.chunked:
            # decode-first for chunked engines: the in-flight decodes
            # advance, then at most one chunk slice, then admission —
            # the interleave guarantee that bounds TBT-max
            self._decode_phase(finished)
            self._chunk_phase(finished)
            self._prefill_phase(finished)
        else:
            self._prefill_phase(finished)
            self._decode_phase(finished)

        # serve_finish / serve_evict rows are emitted by the tracer as
        # the scheduler retires each request (sync-free host appends)
        self.monitor.flush()
        self._steps += 1
        if self._log is not None and self._state_event_every and \
                self._steps % self._state_event_every == 0:
            self._log.add_event("serve_state", step=self._steps,
                                **self.debug_state())
        return finished

    def run(self) -> List[FinishedRequest]:
        """Serve until queue and slots drain; returns everything that
        finished."""
        out: List[FinishedRequest] = list(self.scheduler.drain_rejects())
        while not self.scheduler.idle():
            out.extend(self.step())
        out.extend(self.scheduler.drain_rejects())
        return out

    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: Optional[int] = None,
                 temperature: Optional[float] = None,
                 seeds: Optional[Sequence[int]] = None,
                 eos_id: Optional[int] = "__cfg__") -> List[List[int]]:
        """Batch convenience API over submit/run: serve ``prompts`` and
        return the full sequences (prompt + generated tokens) in
        submission order. Per-request knobs default to the
        ``inference:{}`` config."""
        cfg = self.config
        if eos_id == "__cfg__":
            eos_id = cfg["eos_token_id"]
        reqs = [Request(
            prompt=p,
            max_new_tokens=(max_new_tokens if max_new_tokens is not None
                            else cfg["max_new_tokens"]),
            temperature=(temperature if temperature is not None
                         else cfg["temperature"]),
            seed=(seeds[i] if seeds is not None else i),
            eos_id=eos_id) for i, p in enumerate(prompts)]
        uids = [self.submit(r) for r in reqs]
        finished = {f.uid: f for f in self.run()}
        return [finished[u].prompt + finished[u].tokens for u in uids]

    # ----------------------------------------------------------- warmup
    def warmup(self):
        """Compile the steady-state program set: one prefill per
        (batch bucket, prompt bucket) pair + one decode program per
        decode table-width bucket (exactly ONE at the default
        full-width ``decode_page_buckets: []`` — the PR 5/7 program
        count), all against scratch state (the dense scratch row / the
        paged null page — the live cache stays untouched where it
        matters; must run while no requests are in flight). After
        this, :attr:`steady_state_recompiles` staying 0 is the serving
        latency contract."""
        assert self.scheduler.idle(), "warmup with requests in flight"
        for bb, sb in warmup_plan(self.config["batch_buckets"],
                                  self.config["prompt_buckets"]):
            ids = np.zeros((bb, sb), np.int32)
            lengths = np.ones((bb,), np.int32)
            keys = np.zeros((bb, 2), np.uint32)
            temps = np.zeros((bb,), np.float32)
            if self.paged:
                ztab = jnp.zeros((bb, self._prefill_pps), jnp.int32)
                if self._separate_pools:
                    first, self._cache_prefill = self._prefill(
                        self.params, self._cache_prefill,
                        jnp.asarray(ids), jnp.asarray(lengths),
                        jnp.zeros((bb,), jnp.int32), ztab,
                        jnp.asarray(keys), jnp.asarray(temps))
                else:
                    first, self._cache = self._prefill(
                        self.params, self._cache, jnp.asarray(ids),
                        jnp.asarray(lengths),
                        jnp.zeros((bb,), jnp.int32), ztab,
                        jnp.asarray(keys), jnp.asarray(temps))
            else:
                slots = np.full((bb,), self._scratch, np.int32)
                first, self._cache = self._prefill(
                    self.params, self._cache, jnp.asarray(ids),
                    jnp.asarray(lengths), jnp.asarray(slots),
                    jnp.asarray(keys), jnp.asarray(temps))
        if self.paged and self.chunked:
            # one chunk shape per batch bucket (single chunk bucket x
            # batch buckets — the ladder collapse), plus the CP chunk
            # program when context parallelism resolved on
            progs = [self._prefill] + (
                [self._chunk_cp] if self._chunk_cp is not None else [])
            plan = chunk_warmup_plan(self.config["batch_buckets"],
                                     self._chunk_tokens)
            for prog in progs:
                for bb, ct in plan:
                    ids = np.zeros((bb, ct), np.int32)
                    ztab = jnp.zeros((bb, self._prefill_pps), jnp.int32)
                    cache = self._cache_prefill if self._separate_pools \
                        else self._cache
                    first, cache = prog(
                        self.params, cache, jnp.asarray(ids),
                        jnp.ones((bb,), jnp.int32),
                        jnp.zeros((bb,), jnp.int32), ztab,
                        jnp.zeros((bb, 2), jnp.uint32),
                        jnp.zeros((bb,), jnp.float32))
                    if self._separate_pools:
                        self._cache_prefill = cache
                    else:
                        self._cache = cache
        if self.paged:
            for w in self._decode_page_buckets:
                nxt, self._cache = self._decode(
                    self.params_decode, self._cache,
                    jnp.zeros((self._rows,), jnp.int32),
                    jnp.zeros((self._rows,), jnp.int32),
                    jnp.zeros((self._rows, w), jnp.int32),
                    jnp.zeros((self._rows, 2), jnp.uint32),
                    jnp.zeros((self._rows,), jnp.float32))
            if self.spec:
                # one verify program per width — tables always ride at
                # full pps, so widths x 1 (not widths x page buckets)
                for v in self._verify_widths:
                    nxt2, self._cache = self._verify(
                        self.params_decode, self._cache,
                        jnp.zeros((self._rows, v), jnp.int32),
                        jnp.zeros((self._rows,), jnp.int32),
                        jnp.zeros(
                            (self._rows, self.paged_spec.pages_per_seq),
                            jnp.int32),
                        jnp.zeros((self._rows, 2), jnp.uint32),
                        jnp.zeros((self._rows,), jnp.float32))
                    nxt = nxt2[:, 0]
            if self._separate_pools:
                # warm both handoff programs against the null page so
                # the first real claim doesn't compile on the clock
                idx = jnp.zeros((self._handoff_width,), jnp.int32)
                slab = self._export(self._cache_prefill, idx)
                if self._slab_sharding_decode is not None:
                    slab = tuple(
                        jax.device_put(s, self._slab_sharding_decode)
                        for s in slab)
                self._cache = self._import(self._cache, slab, idx)
        else:
            nxt, self._cache = self._decode(
                self.params_decode, self._cache,
                jnp.zeros((self._rows,), jnp.int32),
                jnp.zeros((self._rows,), jnp.int32),
                jnp.zeros((self._rows, 2), jnp.uint32),
                jnp.zeros((self._rows,), jnp.float32))
        jax.block_until_ready(nxt)
        self._warm_compiles = self.compile_tracker.total_compiles
        if self._log is not None:
            self._log.add_event("serve_warmup",
                                programs=self._warm_compiles,
                                batch_buckets=self.config["batch_buckets"],
                                prompt_buckets=self.config["prompt_buckets"],
                                paged=self.paged,
                                verify_widths=list(self._verify_widths),
                                disagg=self.disagg,
                                chunk_tokens=self._chunk_tokens,
                                cp_shards=self._cp_shards)
        return self._warm_compiles

    @property
    def can_migrate(self) -> bool:
        """True once :meth:`warm_migration` compiled the live-migration
        pair — the router's capability probe (duck-typed: proxies
        forward the worker's hello)."""
        return self._mig_export is not None

    def warm_migration(self) -> int:
        """Compile + warm the cross-REPLICA live-migration programs
        (ISSUE 16): ``migrate_export`` gathers an in-flight request's
        live pages out of the MAIN pool into a contiguous slab (no
        donation — the pool keeps serving), ``migrate_import`` scatters
        a shipped slab into this replica's pool (donated: migration
        allocates nothing steady-state). Same jit pair as the PR 13
        cross-pool handoff, but against the decode pool and at the full
        block-table width (``pages_per_seq`` — any in-flight request
        fits, shape stays static). Call AFTER :meth:`warmup`; the
        recompile baseline is re-anchored so
        :attr:`steady_state_recompiles` == 0 remains the contract with
        migration armed. Returns the number of programs compiled."""
        if not self.paged:
            raise RuntimeError(
                "live migration requires the paged KV pool "
                "(inference.paged.enabled)")
        assert self._warm_compiles is not None, \
            "warm_migration() before warmup()"
        if self._mig_export is not None:
            return 0
        self._mig_width = self.paged_spec.pages_per_seq
        mesh = self._mesh_decode
        nleaf = len(self._cache)
        if mesh is None:
            ex = jax.jit(self._export_pages_impl)
            im = jax.jit(self._import_pages_impl, donate_argnums=(0,))
        else:
            cs = self._cache_sharding_decode
            slab_sh = NamedSharding(mesh, P(None, None, "model"))
            repl = NamedSharding(mesh, P())
            ex = jax.jit(self._export_pages_impl,
                         in_shardings=((cs,) * nleaf, repl),
                         out_shardings=(slab_sh,) * nleaf)
            im = jax.jit(self._import_pages_impl, donate_argnums=(0,),
                         in_shardings=((cs,) * nleaf,
                                       (slab_sh,) * nleaf, repl),
                         out_shardings=(cs,) * nleaf)
        self._mig_export = self.compile_tracker.wrap(ex,
                                                     "migrate_export")
        self._mig_import = self.compile_tracker.wrap(im,
                                                     "migrate_import")
        before = self.compile_tracker.total_compiles
        # warm both against the null page so the first real migration
        # (mid-drain, latency-critical) doesn't compile on the clock
        idx = jnp.zeros((self._mig_width,), jnp.int32)
        slab = self._mig_export(self._cache, idx)
        self._cache = self._mig_import(self._cache, slab, idx)
        jax.block_until_ready(self._cache[0])
        compiled = self.compile_tracker.total_compiles - before
        self._warm_compiles = self.compile_tracker.total_compiles
        if self._log is not None:
            self._log.add_event("serve_warm_migration",
                                programs=compiled,
                                width=self._mig_width)
        return compiled

    @property
    def steady_state_recompiles(self) -> int:
        """Compiles since :meth:`warmup` — the zero-recompile serving
        contract (0 until a shape outside the bucket table sneaks in).
        -1 before warmup ran."""
        if self._warm_compiles is None:
            return -1
        return self.compile_tracker.total_compiles - self._warm_compiles

    # ----------------------------------------- checkpoint -> serving
    @classmethod
    def from_checkpoint(cls, load_dir: str, model_config,
                        tag: Optional[str] = None, inference_config=None,
                        dtype=jnp.bfloat16, monitor: Optional[Any] = None,
                        quantize_weights: Optional[bool] = None,
                        verify_integrity: bool = True,
                        observability_config=None, draft_fn=None):
        """Build a serving engine from a committed training checkpoint.

        Loads the ``model_states`` group ONLY (params-only mode —
        optimizer moments and loss scale never touch the serving
        replica). With ``tag=None`` the newest committed-and-verified
        tag wins, skipping corrupt/uncommitted ones (the PR-1 fallback
        discipline). With ``inference.mesh.axes`` configured the params
        are RESHARDED onto the serving mesh as they load — the
        checkpoint's shards are logically indexed, so a tag written by
        any train mesh restores onto any serving mesh
        (``load_params_only`` materializes straight into the serving
        NamedShardings). ``quantize_weights`` (default: the
        ``inference.quantize_weights`` config; ``True`` is an alias for
        ``"bf16"``) ships the weights through the qwZ int8 block wire
        format (:func:`qwz_distribute_params`); ``"int8"`` additionally
        keeps them int8-RESIDENT — the engine's compiled programs
        dequantize per block at each matmul, halving weight HBM."""
        from deepspeed_tpu.runtime import checkpoint as ckptlib
        cfg = _normalize_inference_config(inference_config)
        chosen = _resolve_committed_tag(ckptlib, load_dir, tag,
                                        verify_integrity)
        _, _, init_fn, specs_fn = _family_of(model_config)
        template = jax.eval_shape(
            lambda k: init_fn(model_config, k), jax.random.PRNGKey(0))
        mesh = _serving_mesh(cfg)
        shardings = None
        if mesh is not None:
            shardings = _param_shardings(mesh, specs_fn, model_config,
                                         template)
            logger.info(f"from_checkpoint: resharding params onto the "
                        f"serving mesh {dict(mesh.shape)}")
        params = ckptlib.load_params_only(chosen, template, shardings)
        if quantize_weights is None:
            quantize_weights = cfg["quantize_weights"]
        elif quantize_weights is True:
            quantize_weights = "bf16"
        if quantize_weights:
            params = qwz_distribute_params(params, cfg["quantize_block"],
                                           resident=quantize_weights)
            # keep the engine's view consistent with what actually
            # shipped (an explicit kwarg overrides the config)
            cfg = dict(cfg)
            cfg["quantize_weights"] = quantize_weights
            logger.info(f"from_checkpoint: params distributed via qwZ "
                        f"int8 (block {cfg['quantize_block']}, "
                        f"resident {quantize_weights})")
        engine = cls(model_config, params, cfg, dtype=dtype,
                     monitor=monitor, mesh=mesh,
                     observability_config=observability_config,
                     draft_fn=draft_fn)
        engine._weight_version = os.path.basename(chosen)
        engine.scheduler.weight_version = engine._weight_version
        if engine._log is not None:
            engine._log.add_event(
                "serve_load", checkpoint=chosen,
                quantize_weights=quantize_weights or False)
        logger.info(f"inference engine loaded params from {chosen}")
        return engine

    # ------------------------------------------------------------- misc
    def _on_compile_event(self, ev):
        if self._log is not None:
            self._log.add_event("compile", fn=ev.fn_name, count=ev.count,
                                wall_ms=round(ev.wall_ms, 3), step=ev.step)

    def close(self):
        # health first: untapping restores the raw mirror so the
        # identity check below still clears our own writer
        self.health.close()
        if self._log is not None:
            # seal the run with a final pool/SLO snapshot — obs_report
            # renders the LAST serve_state row as the pool view
            self._log.add_event("serve_state", step=self._steps,
                                **self.debug_state())
        if self._chrome_path and self._recorder is not None:
            try:
                self._recorder.dump(self._chrome_path)
            except Exception:
                pass
        if getattr(self.monitor, "mirror", None) is self._log:
            self.monitor.mirror = None
        if self._log is not None:
            self._log.close()
            self._log = None
        self._tracer.writer = None
