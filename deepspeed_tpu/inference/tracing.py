"""Request-granular serving observability (the serving-plane tracer).

The aggregate ``Serve/*`` scalars answer "how fast is the engine";
they cannot answer "why was THIS request slow" — queue wait? prefill
bucket padding? page starvation behind an oversized head? That is the
question a production serving system must answer per request, so every
:class:`~.scheduler.Request` gets a stamped lifecycle trail written
into the crash-safe ``events.jsonl``:

    serve_submit -> [serve_defer (reason: pages | bucket | lookahead)]*
                 -> [serve_prefix_hit] -> serve_admit -> serve_prefill
                 -> serve_first_token -> [serve_decode_window]*
                 -> serve_finish | serve_evict

plus a latency decomposition per request (queue_wait / prefill /
time-between-tokens), bounded-histogram percentiles (p50/p95/p99 via
:class:`~deepspeed_tpu.utils.monitor.Histogram` — memory stays bounded
over millions of requests), and SLO/goodput accounting: a request is
*within SLO* when its TTFT and mean TBT beat the configured
``observability.serve.slo`` thresholds, ``slo_attainment`` is the
fraction of finished requests within SLO, and *goodput* counts only
their tokens — so raw throughput and user-visible goodput are distinct
numbers in every run report.

Everything here is pure host code and sync-free by construction:
stamps are host wall-clock (``time.perf_counter``), events are
line-buffered file appends, and nothing imports jax — the compiled
program set, the warmup dispatch count, and the zero-per-dispatch-sync
contract are untouched with tracing on (pinned source-level by the
jax-free test in tests/unit/test_inference.py and end-to-end by the
``serve_trace_overhead`` bench row).

Chrome-trace request lanes: with a recorder attached (the engine wires
``profiling/spans.py``'s :class:`ChromeTraceRecorder` when
``observability.chrome_trace_path`` is set), each finished request
emits its queue_wait / prefill / decode phases onto its own lane
(``tid`` = request uid), so Perfetto shows per-request timelines next
to the engine's prefill/decode phase spans.
"""

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set

from deepspeed_tpu.utils.monitor import Histogram

__all__ = ["ServeTracer", "DEFER_REASONS"]

#: the pinned defer vocabulary (docs/observability.md event schema):
#: "pages"      - page reservation failed (pool starvation)
#: "bucket"     - ride-along skipped: prompt bucket != the head's
#: "lookahead"  - outside the bounded admission window this round
DEFER_REASONS = ("pages", "bucket", "lookahead")


@dataclass
class _ReqTrace:
    """Host-side per-request stamps (tracer clock)."""
    uid: int
    prompt_tokens: int = 0
    max_new_tokens: int = 0
    t_submit: float = 0.0
    t_admit: Optional[float] = None
    t_first: Optional[float] = None
    t_last: Optional[float] = None
    slot: Optional[int] = None
    queue_wait_ms: Optional[float] = None     # scheduler-clock values
    ttft_ms: Optional[float] = None
    n_tokens: int = 0
    tbt_sum: float = 0.0
    tbt_max: float = 0.0
    # decode-window sampling state (intervals tracked separately: the
    # first window spans stride-1 TBT intervals, later ones stride)
    window_t0: Optional[float] = None
    window_tokens: int = 0
    window_intervals: int = 0
    deferred: Set[str] = field(default_factory=set)


class ServeTracer:
    """Lifecycle tracing + SLO/goodput accounting for the serving
    engine.

    ``cfg`` is the parsed ``observability.serve`` section
    (``{"enabled", "slo": {"ttft_ms", "tbt_ms"}, "sample_rate"}``);
    ``writer`` a ``_JsonlWriter``-shaped sink (or None — accounting
    still runs for :meth:`snapshot`/``engine.debug_state()``);
    ``recorder`` an optional Chrome-trace recorder with an
    ``add_lane`` method. When ``enabled`` is False every hook is a
    no-op except :meth:`on_finish`, which still emits the legacy
    ``serve_finish``/``serve_evict`` row (the pre-tracing schema, with
    ``ttft_ms`` null for requests evicted before their first token).

    The scheduler owns the request-ms values it computes with its own
    (injectable) clock — queue wait, TTFT, total latency ride in
    through the hook arguments; the tracer's own clock covers only
    what the scheduler doesn't measure: time-between-tokens and the
    Chrome lane spans.
    """

    #: defaults when constructed without a parsed config section
    DEFAULT_SLO_TTFT_MS = 2000.0
    DEFAULT_SLO_TBT_MS = 200.0
    DEFAULT_SAMPLE_RATE = 0.0625          # one window row per 16 tokens

    def __init__(self, cfg: Optional[Dict[str, Any]] = None,
                 writer=None, recorder=None, clock=time.perf_counter):
        cfg = cfg or {}
        slo = cfg.get("slo") or {}
        self.enabled = bool(cfg.get("enabled", True))
        self.slo_ttft_ms = float(slo.get("ttft_ms",
                                         self.DEFAULT_SLO_TTFT_MS))
        self.slo_tbt_ms = float(slo.get("tbt_ms", self.DEFAULT_SLO_TBT_MS))
        rate = float(cfg.get("sample_rate", self.DEFAULT_SAMPLE_RATE))
        # deterministic stride, not RNG: a window row every 1/rate
        # tokens per request (0 disables window sampling)
        self.window_tokens = int(round(1.0 / rate)) if rate > 0 else 0
        self.writer = writer
        self.recorder = recorder
        self._clock = clock
        self._req: Dict[int, _ReqTrace] = {}
        self.hist = {"queue_wait_ms": Histogram(), "ttft_ms": Histogram(),
                     "prefill_ms": Histogram(), "tbt_ms": Histogram()}
        # SLO / goodput accounting
        self.finished = 0
        self.finished_in_slo = 0
        self.evicted = 0
        self.good_tokens = 0
        self.finished_tokens = 0
        self._step_tbts: List[float] = []

    # ------------------------------------------------------------- sinks
    def _event(self, kind: str, **fields) -> None:
        if self.writer is not None:
            self.writer.add_event(kind, **fields)

    @staticmethod
    def _r(v: Optional[float]) -> Optional[float]:
        return round(v, 3) if v is not None else None

    # ------------------------------------------------------------- hooks
    def on_submit(self, uid: int, prompt_tokens: int,
                  max_new_tokens: int) -> None:
        if not self.enabled:
            return
        self._req[uid] = _ReqTrace(uid=uid, prompt_tokens=prompt_tokens,
                                   max_new_tokens=max_new_tokens,
                                   t_submit=self._clock())
        self._event("serve_submit", uid=uid, prompt_tokens=prompt_tokens,
                    max_new_tokens=max_new_tokens)

    def on_defer(self, uid: int, reason: str) -> None:
        """One admission pass skipped ``uid`` for ``reason``. Deduped
        per (uid, reason) — admission rescans its window every engine
        step, and an event per rescan would swamp the log with copies
        of the same fact."""
        if not self.enabled:
            return
        tr = self._req.get(uid)
        if tr is None or reason in tr.deferred:
            return
        tr.deferred.add(reason)
        self._event("serve_defer", uid=uid, reason=str(reason))

    def on_prefix_hit(self, uid: int, tokens: int, pages: int) -> None:
        if not self.enabled:
            return
        self._event("serve_prefix_hit", uid=uid, tokens=int(tokens),
                    pages=int(pages))

    def on_admit(self, uid: int, slot: int, queue_wait_ms: float,
                 prefix_tokens: int, prompt_bucket: int,
                 batch_bucket: int) -> None:
        if not self.enabled:
            return
        tr = self._req.get(uid)
        if tr is None:       # submitted before the tracer existed
            tr = self._req[uid] = _ReqTrace(uid=uid,
                                            t_submit=self._clock())
        tr.t_admit = self._clock()
        tr.slot = slot
        tr.queue_wait_ms = queue_wait_ms
        tr.deferred.clear()
        self.hist["queue_wait_ms"].record(queue_wait_ms)
        self._event("serve_admit", uid=uid, slot=int(slot),
                    queue_wait_ms=self._r(queue_wait_ms),
                    prefix_tokens=int(prefix_tokens),
                    prompt_bucket=int(prompt_bucket),
                    batch_bucket=int(batch_bucket))

    def on_prefill(self, uid: int, slot: int, wall_ms: float,
                   prompt_bucket: int, batch_bucket: int,
                   rows: int) -> None:
        """The engine ran ``uid``'s prefill dispatch (``rows`` real
        requests shared the padded (batch_bucket, prompt_bucket)
        program — the wall time is the batch's, amortized context for
        this request's trail)."""
        if not self.enabled:
            return
        self._event("serve_prefill", uid=uid, slot=int(slot),
                    wall_ms=self._r(wall_ms),
                    prompt_bucket=int(prompt_bucket),
                    batch_bucket=int(batch_bucket), rows=int(rows))

    def on_first_token(self, uid: int, ttft_ms: float) -> None:
        if not self.enabled:
            return
        tr = self._req.get(uid)
        if tr is None:
            return
        now = self._clock()
        tr.t_first = tr.t_last = now
        tr.ttft_ms = ttft_ms
        tr.n_tokens = 1
        tr.window_t0 = now
        tr.window_tokens = 1
        tr.window_intervals = 0
        prefill_ms = (ttft_ms - tr.queue_wait_ms
                      if tr.queue_wait_ms is not None else None)
        self.hist["ttft_ms"].record(ttft_ms)
        if prefill_ms is not None:
            self.hist["prefill_ms"].record(max(prefill_ms, 0.0))
        self._event("serve_first_token", uid=uid, ttft_ms=self._r(ttft_ms),
                    prefill_ms=self._r(prefill_ms))

    def on_token(self, uid: int) -> None:
        """One decode token for ``uid``: a time-between-tokens sample,
        plus the sampled ``serve_decode_window`` row at window
        boundaries."""
        if not self.enabled:
            return
        tr = self._req.get(uid)
        if tr is None or tr.t_last is None:
            return
        now = self._clock()
        tbt = (now - tr.t_last) * 1e3
        tr.t_last = now
        tr.n_tokens += 1
        tr.tbt_sum += tbt
        tr.tbt_max = max(tr.tbt_max, tbt)
        self.hist["tbt_ms"].record(tbt)
        self._step_tbts.append(tbt)
        tr.window_tokens += 1
        tr.window_intervals += 1
        if self.window_tokens and tr.window_tokens >= self.window_tokens:
            window_ms = (now - tr.window_t0) * 1e3
            self._event(
                "serve_decode_window", uid=uid, tokens=tr.window_tokens,
                end_token=tr.n_tokens,
                window_ms=self._r(window_ms),
                tbt_ms=self._r(window_ms / max(tr.window_intervals, 1)))
            tr.window_t0 = now
            tr.window_tokens = 0
            tr.window_intervals = 0

    def on_finish(self, fin, evicted: bool = False) -> None:
        """Terminal hook — ``fin`` is the scheduler's
        :class:`FinishedRequest`. Emits ``serve_finish`` (or
        ``serve_evict``), classifies the request against the SLO, and
        draws the Chrome lane spans. ``ttft_ms`` is ``null`` (never
        0.0) for requests evicted before their first token."""
        kind = "serve_evict" if evicted else "serve_finish"
        tr = self._req.pop(fin.uid, None) if self.enabled else None
        if tr is None:
            # tracing off (or unknown uid): the legacy row, ttft
            # honest-null for no-first-token evictions
            self._event(kind, uid=fin.uid, reason=fin.finish_reason,
                        new_tokens=len(fin.tokens),
                        ttft_ms=self._r(fin.ttft_ms),
                        latency_ms=self._r(fin.latency_ms))
            if self.enabled:
                self._account(fin, evicted, tbt_mean=None)
            return
        tbt_mean = (tr.tbt_sum / (tr.n_tokens - 1)
                    if tr.n_tokens > 1 else None)
        prefill_ms = (fin.ttft_ms - tr.queue_wait_ms
                      if fin.ttft_ms is not None
                      and tr.queue_wait_ms is not None else None)
        slo_ok = self._account(fin, evicted, tbt_mean)
        self._event(kind, uid=fin.uid, reason=fin.finish_reason,
                    new_tokens=len(fin.tokens),
                    ttft_ms=self._r(fin.ttft_ms),
                    latency_ms=self._r(fin.latency_ms),
                    queue_wait_ms=self._r(tr.queue_wait_ms),
                    prefill_ms=self._r(prefill_ms),
                    tbt_ms=self._r(tbt_mean),
                    tbt_ms_max=self._r(tr.tbt_max if tr.n_tokens > 1
                                       else None),
                    slo_ok=slo_ok)
        self._lanes(tr)

    def _account(self, fin, evicted: bool,
                 tbt_mean: Optional[float]) -> bool:
        """SLO classification + goodput counters. An evicted request —
        or one whose first token never came — is by definition outside
        SLO."""
        self.finished += 1
        self.finished_tokens += len(fin.tokens)
        if evicted:
            self.evicted += 1
        slo_ok = (not evicted and fin.ttft_ms is not None
                  and fin.ttft_ms <= self.slo_ttft_ms
                  and (tbt_mean is None or tbt_mean <= self.slo_tbt_ms))
        if slo_ok:
            self.finished_in_slo += 1
            self.good_tokens += len(fin.tokens)
        return slo_ok

    def _lanes(self, tr: _ReqTrace) -> None:
        """Per-request Chrome-trace lane: queue_wait / prefill / decode
        phase spans on lane ``tid = uid`` (drawn at finish so each
        request costs a constant three events)."""
        if self.recorder is None or not hasattr(self.recorder, "add_lane"):
            return
        now = self._clock()
        lane = f"req {tr.uid}"
        if tr.t_admit is not None:
            self.recorder.add_lane(tr.uid, lane, "queue_wait",
                                   tr.t_submit, tr.t_admit)
            if tr.t_first is not None:
                self.recorder.add_lane(tr.uid, lane, "prefill",
                                       tr.t_admit, tr.t_first)
                self.recorder.add_lane(tr.uid, lane, "decode",
                                       tr.t_first, now,
                                       tokens=tr.n_tokens)
            else:
                self.recorder.add_lane(tr.uid, lane, "prefill",
                                       tr.t_admit, now)
        else:
            self.recorder.add_lane(tr.uid, lane, "queue_wait",
                                   tr.t_submit, now)

    # ------------------------------------------------------------ scalars
    def drain_step_tbts(self) -> List[float]:
        """TBT samples since the last drain (the engine writes their
        mean as one ``Serve/tbt_ms`` scalar per decode dispatch)."""
        out = self._step_tbts
        self._step_tbts = []
        return out

    @property
    def slo_attainment(self) -> Optional[float]:
        if not self.finished:
            return None
        return self.finished_in_slo / self.finished

    # ----------------------------------------------------------- reports
    def snapshot(self) -> Dict[str, Any]:
        """The SLO/latency block of ``engine.debug_state()`` and the
        periodic ``serve_state`` event: bounded-histogram percentiles +
        attainment/goodput counters (all host-side)."""
        att = self.slo_attainment
        return {
            "enabled": self.enabled,
            "slo": {"ttft_ms": self.slo_ttft_ms,
                    "tbt_ms": self.slo_tbt_ms},
            "finished": self.finished,
            "evicted": self.evicted,
            "in_slo": self.finished_in_slo,
            "attainment": round(att, 4) if att is not None else None,
            "good_tokens": self.good_tokens,
            "finished_tokens": self.finished_tokens,
            "in_flight": len(self._req),
            "latency": {k: h.snapshot() for k, h in self.hist.items()},
        }
