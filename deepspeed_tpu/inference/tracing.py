"""Request-granular serving observability (the serving-plane tracer).

The aggregate ``Serve/*`` scalars answer "how fast is the engine";
they cannot answer "why was THIS request slow" — queue wait? prefill
bucket padding? page starvation behind an oversized head? That is the
question a production serving system must answer per request, so every
:class:`~.scheduler.Request` gets a stamped lifecycle trail written
into the crash-safe ``events.jsonl``:

    serve_submit -> [serve_defer (reason: pages | bucket | lookahead
                                        | handoff | draft_stall)]*
                 -> [serve_prefix_hit] -> serve_admit -> serve_prefill
                 -> [serve_handoff] -> serve_first_token
                 -> [serve_decode_window | serve_spec_window]*
                 -> serve_finish | serve_evict

The fleet router (ISSUE 14, inference/fleet.py) adds fleet-plane rows
in the same trail — ``fleet_shed`` (a request rejected or degraded by
the SLO shed ladder, reason from :data:`SHED_REASONS`), ``fleet_drain``
(a replica stopped admitting and its queue was redistributed; the
rerouted requests' scheduler-side evictions ride ``serve_evict`` with
reason "drain"), ``fleet_swap`` (a live weight push, tag + ok/rollback),
and periodic ``fleet_state`` snapshots.

Disaggregated serving (ISSUE 13) adds the ``serve_handoff`` row — the
prefill->decode page-ownership transfer, with queue wait, measured
transfer wall time, and the LinkModel-priced wire cost side by side —
and splits TTFT into queue_wait / prefill / handoff / first_decode
legs on the ``serve_first_token`` row. Speculative decoding adds
sampled ``serve_spec_window`` rows (proposed vs accepted draft tokens
per window) plus per-request draft counters on the finish row.
Goodput stays honest by construction: only verified-and-KEPT tokens
ever reach ``on_token``/``on_finish`` (the scheduler never records a
rolled-back draft), so ``Serve/goodput_tokens_per_s`` cannot be
inflated by speculation.

plus a latency decomposition per request (queue_wait / prefill /
time-between-tokens), bounded-histogram percentiles (p50/p95/p99 via
:class:`~deepspeed_tpu.utils.monitor.Histogram` — memory stays bounded
over millions of requests), and SLO/goodput accounting: a request is
*within SLO* when its TTFT and mean TBT beat the configured
``observability.serve.slo`` thresholds, ``slo_attainment`` is the
fraction of finished requests within SLO, and *goodput* counts only
their tokens — so raw throughput and user-visible goodput are distinct
numbers in every run report.

Everything here is pure host code and sync-free by construction:
stamps are host wall-clock (``time.perf_counter``), events are
line-buffered file appends, and nothing imports jax — the compiled
program set, the warmup dispatch count, and the zero-per-dispatch-sync
contract are untouched with tracing on (pinned source-level by the
jax-free test in tests/unit/test_inference.py and end-to-end by the
``serve_trace_overhead`` bench row).

Chrome-trace request lanes: with a recorder attached (the engine wires
``profiling/spans.py``'s :class:`ChromeTraceRecorder` when
``observability.chrome_trace_path`` is set), each finished request
emits its queue_wait / prefill / decode phases onto its own lane
(``tid`` = request uid), so Perfetto shows per-request timelines next
to the engine's prefill/decode phase spans.
"""

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set

from deepspeed_tpu.utils.monitor import Histogram

__all__ = ["ServeTracer", "DEFER_REASONS", "SHED_REASONS"]

#: the pinned defer vocabulary (docs/observability.md event schema):
#: "pages"       - page reservation failed (pool starvation)
#: "bucket"      - ride-along skipped: prompt bucket != the head's
#: "lookahead"   - outside the bounded admission window this round
#: "handoff"     - disagg: decode-pool claim bounced, handoff requeued
#: "draft_stall" - speculation: drafter proposed nothing this dispatch
#:                 (the slot rode the verify program with 0 drafts)
DEFER_REASONS = ("pages", "bucket", "lookahead", "handoff", "draft_stall")

#: the pinned fleet shed/degrade vocabulary (``fleet_shed`` rows and
#: drain-path ``serve_evict`` rows — docs/serving-fleet.md):
#: "shed_slo"        - rejected: fleet p95 TTFT breached the budget and
#:                     the request's priority tier is below the floor
#: "shed_capacity"   - rejected: no live replica can ever serve it
#:                     (fleet draining/retired, not a transient defer)
#: "degrade_max_new" - admitted, but max_new_tokens capped by the shed
#:                     ladder's degrade rung
#: "degrade_spec_off"- fleet-wide: speculation switched off under
#:                     sustained SLO breach (plain decode programs are
#:                     already warm — zero recompiles)
#: "drain"           - requeued off a draining replica and resubmitted
#:                     to a survivor (the client still gets exactly one
#:                     response; the drain-side eviction row is
#:                     bookkeeping, not an answer)
#: "reject_too_long" - rejected at submit: the prompt exceeds what the
#:                     engine's geometry can EVER serve (over the
#:                     largest prompt bucket with chunked prefill off,
#:                     or prompt + max_new over max_len / the page
#:                     pool). A graceful FinishedRequest, never a
#:                     crash or silent truncation.
SHED_REASONS = ("shed_slo", "shed_capacity", "degrade_max_new",
                "degrade_spec_off", "drain", "reject_too_long")


@dataclass
class _ReqTrace:
    """Host-side per-request stamps (tracer clock)."""
    uid: int
    prompt_tokens: int = 0
    max_new_tokens: int = 0
    # distributed-trace context (ISSUE 18): stamped by the fleet router
    # before dispatch and carried on every row this request emits, so
    # ``obs_report --fleet`` can stitch one timeline across process
    # boundaries. ``hop`` counts boundary crossings (0 = the replica
    # the request was first dispatched to; each migration import
    # increments it).
    trace_id: Optional[str] = None
    hop: int = 0
    t_submit: float = 0.0
    t_admit: Optional[float] = None
    t_first: Optional[float] = None
    t_last: Optional[float] = None
    slot: Optional[int] = None
    queue_wait_ms: Optional[float] = None     # scheduler-clock values
    ttft_ms: Optional[float] = None
    n_tokens: int = 0
    tbt_sum: float = 0.0
    tbt_max: float = 0.0
    # decode-window sampling state (intervals tracked separately: the
    # first window spans stride-1 TBT intervals, later ones stride)
    window_t0: Optional[float] = None
    window_tokens: int = 0
    window_intervals: int = 0
    deferred: Set[str] = field(default_factory=set)
    # disagg: prefill->decode handoff leg of TTFT (queue + transfer)
    handoff_ms: Optional[float] = None
    # speculation: per-request draft accounting + window sampling
    spec_proposed: int = 0
    spec_accepted: int = 0
    spec_dispatches: int = 0
    spec_window_proposed: int = 0
    spec_window_accepted: int = 0
    spec_window_dispatches: int = 0
    # chunked prefill: chunk dispatches this request's prompt rode and
    # their summed wall time (the trail's per-chunk rows carry the
    # running ``cum_ms`` so TTFT decomposes into queue + k*chunk)
    chunks: int = 0
    chunk_ms: float = 0.0


class ServeTracer:
    """Lifecycle tracing + SLO/goodput accounting for the serving
    engine.

    ``cfg`` is the parsed ``observability.serve`` section
    (``{"enabled", "slo": {"ttft_ms", "tbt_ms"}, "sample_rate"}``);
    ``writer`` a ``_JsonlWriter``-shaped sink (or None — accounting
    still runs for :meth:`snapshot`/``engine.debug_state()``);
    ``recorder`` an optional Chrome-trace recorder with an
    ``add_lane`` method. When ``enabled`` is False every hook is a
    no-op except :meth:`on_finish`, which still emits the legacy
    ``serve_finish``/``serve_evict`` row (the pre-tracing schema, with
    ``ttft_ms`` null for requests evicted before their first token).

    The scheduler owns the request-ms values it computes with its own
    (injectable) clock — queue wait, TTFT, total latency ride in
    through the hook arguments; the tracer's own clock covers only
    what the scheduler doesn't measure: time-between-tokens and the
    Chrome lane spans.
    """

    #: defaults when constructed without a parsed config section
    DEFAULT_SLO_TTFT_MS = 2000.0
    DEFAULT_SLO_TBT_MS = 200.0
    DEFAULT_SAMPLE_RATE = 0.0625          # one window row per 16 tokens

    #: every ``serve_*`` event kind this tracer can emit — the schema
    #: contract tests walk (each kind must appear in the pinned
    #: TRAIL_SCHEMA and have an obs_report handler, so a new trail row
    #: cannot silently fall out of the report)
    EVENT_KINDS = (
        "serve_submit", "serve_defer", "serve_prefix_hit",
        "serve_admit", "serve_prefill", "serve_prefill_chunk",
        "serve_handoff",
        "serve_spec_window", "serve_first_token", "serve_decode_window",
        "serve_finish", "serve_evict",
        "serve_migrate_out", "serve_migrate_in",
    )

    def __init__(self, cfg: Optional[Dict[str, Any]] = None,
                 writer=None, recorder=None, clock=time.perf_counter):
        cfg = cfg or {}
        slo = cfg.get("slo") or {}
        self.enabled = bool(cfg.get("enabled", True))
        # fleet identity: which replica's log this is. Stamped on every
        # event row (``replica_id``) so the offline fleet merger can
        # attribute rows without trusting directory names. None for a
        # standalone engine — the field is simply omitted.
        rid = cfg.get("replica_id")
        self.replica_id = int(rid) if rid is not None else None
        self.slo_ttft_ms = float(slo.get("ttft_ms",
                                         self.DEFAULT_SLO_TTFT_MS))
        self.slo_tbt_ms = float(slo.get("tbt_ms", self.DEFAULT_SLO_TBT_MS))
        rate = float(cfg.get("sample_rate", self.DEFAULT_SAMPLE_RATE))
        # deterministic stride, not RNG: a window row every 1/rate
        # tokens per request (0 disables window sampling)
        self.window_tokens = int(round(1.0 / rate)) if rate > 0 else 0
        self.writer = writer
        self.recorder = recorder
        self._clock = clock
        self._req: Dict[int, _ReqTrace] = {}
        self.hist = {"queue_wait_ms": Histogram(), "ttft_ms": Histogram(),
                     "prefill_ms": Histogram(), "tbt_ms": Histogram(),
                     "handoff_ms": Histogram(),
                     "spec_accept_rate": Histogram(),
                     "chunk_ms": Histogram(),
                     "chunks_per_request": Histogram()}
        # SLO / goodput accounting
        self.finished = 0
        self.finished_in_slo = 0
        self.evicted = 0
        self.good_tokens = 0
        self.finished_tokens = 0
        self._step_tbts: List[float] = []
        # global speculation / disagg counters (engine scalar writes +
        # debug_state; per-request detail rides the event rows)
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_dispatches = 0
        self.handoffs = 0
        # chunked prefill: chunk-row dispatches across all requests
        # (one request contributes ceil(suffix / chunk_tokens) rows)
        self.chunk_rows = 0
        self.chunked_requests = 0

    # ------------------------------------------------------------- sinks
    def _event(self, kind: str, **fields) -> None:
        if self.writer is not None:
            if self.replica_id is not None:
                fields.setdefault("replica_id", self.replica_id)
            self.writer.add_event(kind, **fields)

    def _ctx(self, uid: int) -> Dict[str, Any]:
        """Trace-context fields for ``uid``'s rows ({} when the request
        was never stamped — single-engine serving stays schema-stable)."""
        tr = self._req.get(uid)
        if tr is None or tr.trace_id is None:
            return {}
        return {"trace_id": tr.trace_id, "hop": tr.hop}

    @staticmethod
    def _r(v: Optional[float]) -> Optional[float]:
        return round(v, 3) if v is not None else None

    # ------------------------------------------------------------- hooks
    def on_submit(self, uid: int, prompt_tokens: int,
                  max_new_tokens: int,
                  trace_id: Optional[str] = None, hop: int = 0) -> None:
        if not self.enabled:
            return
        self._req[uid] = _ReqTrace(uid=uid, prompt_tokens=prompt_tokens,
                                   max_new_tokens=max_new_tokens,
                                   t_submit=self._clock(),
                                   trace_id=trace_id, hop=int(hop))
        self._event("serve_submit", uid=uid, prompt_tokens=prompt_tokens,
                    max_new_tokens=max_new_tokens, **self._ctx(uid))

    def on_defer(self, uid: int, reason: str) -> None:
        """One admission pass skipped ``uid`` for ``reason``. Deduped
        per (uid, reason) — admission rescans its window every engine
        step, and an event per rescan would swamp the log with copies
        of the same fact."""
        if not self.enabled:
            return
        tr = self._req.get(uid)
        if tr is None or reason in tr.deferred:
            return
        tr.deferred.add(reason)
        self._event("serve_defer", uid=uid, reason=str(reason),
                    **self._ctx(uid))

    def on_prefix_hit(self, uid: int, tokens: int, pages: int) -> None:
        if not self.enabled:
            return
        self._event("serve_prefix_hit", uid=uid, tokens=int(tokens),
                    pages=int(pages), **self._ctx(uid))

    def on_admit(self, uid: int, slot: int, queue_wait_ms: float,
                 prefix_tokens: int, prompt_bucket: int,
                 batch_bucket: int) -> None:
        if not self.enabled:
            return
        tr = self._req.get(uid)
        if tr is None:       # submitted before the tracer existed
            tr = self._req[uid] = _ReqTrace(uid=uid,
                                            t_submit=self._clock())
        tr.t_admit = self._clock()
        tr.slot = slot
        tr.queue_wait_ms = queue_wait_ms
        tr.deferred.clear()
        self.hist["queue_wait_ms"].record(queue_wait_ms)
        self._event("serve_admit", uid=uid, slot=int(slot),
                    queue_wait_ms=self._r(queue_wait_ms),
                    prefix_tokens=int(prefix_tokens),
                    prompt_bucket=int(prompt_bucket),
                    batch_bucket=int(batch_bucket), **self._ctx(uid))

    def on_prefill(self, uid: int, slot: int, wall_ms: float,
                   prompt_bucket: int, batch_bucket: int,
                   rows: int) -> None:
        """The engine ran ``uid``'s prefill dispatch (``rows`` real
        requests shared the padded (batch_bucket, prompt_bucket)
        program — the wall time is the batch's, amortized context for
        this request's trail)."""
        if not self.enabled:
            return
        self._event("serve_prefill", uid=uid, slot=int(slot),
                    wall_ms=self._r(wall_ms),
                    prompt_bucket=int(prompt_bucket),
                    batch_bucket=int(batch_bucket), rows=int(rows),
                    **self._ctx(uid))

    def on_prefill_chunk(self, uid: int, slot: int, index: int,
                         tokens: int, wall_ms: float,
                         cp_shards: int = 1) -> None:
        """One chunk of ``uid``'s chunked prefill landed: ``index`` is
        the 0-based chunk ordinal, ``tokens`` the real (unpadded)
        tokens it scattered, ``wall_ms`` the dispatch wall time
        (amortized over the rows sharing it), ``cum_ms`` the running
        sum — so the trail shows TTFT decomposing into
        ``queue + k*chunk`` per request. ``cp_shards > 1`` marks a
        context-parallel chunk (the sequence axis ran sharded over the
        serving mesh)."""
        if not self.enabled:
            return
        self.chunk_rows += 1
        self.hist["chunk_ms"].record(wall_ms)
        tr = self._req.get(uid)
        cum = None
        if tr is not None:
            if tr.chunks == 0:
                self.chunked_requests += 1
            tr.chunks += 1
            tr.chunk_ms += wall_ms
            cum = tr.chunk_ms
        self._event("serve_prefill_chunk", uid=uid, slot=int(slot),
                    chunk=int(index), tokens=int(tokens),
                    wall_ms=self._r(wall_ms), cum_ms=self._r(cum),
                    cp_shards=int(cp_shards), **self._ctx(uid))

    def on_handoff(self, uid: int, queue_ms: float, transfer_ms: float,
                   pages: int, bytes_moved: int, mode: str,
                   priced_ms: Optional[float] = None) -> None:
        """Disagg only: ``uid``'s prefill->decode page handoff was
        claimed. ``queue_ms`` is the wait in the handoff queue,
        ``transfer_ms`` the measured page-migration wall time (0 for a
        shared-pool bookkeeping move), ``priced_ms`` the LinkModel's
        prediction for the same bytes — measured and modeled ride the
        row side by side. Called BEFORE the claim releases the first
        token, so :meth:`on_first_token` can subtract the handoff leg
        out of prefill time."""
        if not self.enabled:
            return
        tr = self._req.get(uid)
        total = max(queue_ms, 0.0) + max(transfer_ms, 0.0)
        if tr is not None:
            tr.handoff_ms = total
        self.handoffs += 1
        self.hist["handoff_ms"].record(total)
        self._event("serve_handoff", uid=uid, mode=str(mode),
                    queue_ms=self._r(queue_ms),
                    transfer_ms=self._r(transfer_ms),
                    handoff_ms=self._r(total),
                    priced_ms=self._r(priced_ms),
                    pages=int(pages), bytes_moved=int(bytes_moved),
                    **self._ctx(uid))

    def on_spec(self, uid: int, proposed: int, accepted: int) -> None:
        """One verify dispatch's draft outcome for ``uid``: ``proposed``
        draft tokens went in, ``accepted`` survived verification (the
        scheduler only ever records the kept ones — this hook is pure
        accounting, it does not touch token state). Emits a sampled
        ``serve_spec_window`` row on the decode-window stride."""
        if not self.enabled or proposed <= 0:
            return
        self.spec_proposed += proposed
        self.spec_accepted += accepted
        self.spec_dispatches += 1
        self.hist["spec_accept_rate"].record(accepted / proposed)
        tr = self._req.get(uid)
        if tr is None:
            return
        tr.spec_proposed += proposed
        tr.spec_accepted += accepted
        tr.spec_dispatches += 1
        tr.spec_window_proposed += proposed
        tr.spec_window_accepted += accepted
        tr.spec_window_dispatches += 1
        if (self.window_tokens
                and tr.spec_window_proposed >= self.window_tokens):
            self._event(
                "serve_spec_window", uid=uid,
                proposed=tr.spec_window_proposed,
                accepted=tr.spec_window_accepted,
                dispatches=tr.spec_window_dispatches,
                accept_rate=self._r(tr.spec_window_accepted
                                    / tr.spec_window_proposed),
                **self._ctx(uid))
            tr.spec_window_proposed = 0
            tr.spec_window_accepted = 0
            tr.spec_window_dispatches = 0

    def on_first_token(self, uid: int, ttft_ms: float) -> None:
        if not self.enabled:
            return
        tr = self._req.get(uid)
        if tr is None:
            return
        now = self._clock()
        tr.t_first = tr.t_last = now
        tr.ttft_ms = ttft_ms
        tr.n_tokens = 1
        tr.window_t0 = now
        tr.window_tokens = 1
        tr.window_intervals = 0
        # TTFT decomposition: queue_wait + prefill (+ handoff under
        # disagg; the handoff leg is 0/absent otherwise, so the legacy
        # two-way split is the same number)
        prefill_ms = (ttft_ms - tr.queue_wait_ms - (tr.handoff_ms or 0.0)
                      if tr.queue_wait_ms is not None else None)
        self.hist["ttft_ms"].record(ttft_ms)
        if prefill_ms is not None:
            self.hist["prefill_ms"].record(max(prefill_ms, 0.0))
        self._event("serve_first_token", uid=uid, ttft_ms=self._r(ttft_ms),
                    prefill_ms=self._r(prefill_ms),
                    handoff_ms=self._r(tr.handoff_ms), **self._ctx(uid))

    def on_token(self, uid: int) -> None:
        """One decode token for ``uid``: a time-between-tokens sample,
        plus the sampled ``serve_decode_window`` row at window
        boundaries."""
        if not self.enabled:
            return
        tr = self._req.get(uid)
        if tr is None or tr.t_last is None:
            return
        now = self._clock()
        tbt = (now - tr.t_last) * 1e3
        tr.t_last = now
        tr.n_tokens += 1
        tr.tbt_sum += tbt
        tr.tbt_max = max(tr.tbt_max, tbt)
        self.hist["tbt_ms"].record(tbt)
        self._step_tbts.append(tbt)
        tr.window_tokens += 1
        tr.window_intervals += 1
        if self.window_tokens and tr.window_tokens >= self.window_tokens:
            window_ms = (now - tr.window_t0) * 1e3
            self._event(
                "serve_decode_window", uid=uid, tokens=tr.window_tokens,
                end_token=tr.n_tokens,
                window_ms=self._r(window_ms),
                tbt_ms=self._r(window_ms / max(tr.window_intervals, 1)),
                **self._ctx(uid))
            tr.window_t0 = now
            tr.window_tokens = 0
            tr.window_intervals = 0

    def on_finish(self, fin, evicted: bool = False) -> None:
        """Terminal hook — ``fin`` is the scheduler's
        :class:`FinishedRequest`. Emits ``serve_finish`` (or
        ``serve_evict``), classifies the request against the SLO, and
        draws the Chrome lane spans. ``ttft_ms`` is ``null`` (never
        0.0) for requests evicted before their first token."""
        kind = "serve_evict" if evicted else "serve_finish"
        tr = self._req.pop(fin.uid, None) if self.enabled else None
        if tr is None:
            # tracing off (or unknown uid): the legacy row, ttft
            # honest-null for no-first-token evictions
            self._event(kind, uid=fin.uid, reason=fin.finish_reason,
                        new_tokens=len(fin.tokens),
                        ttft_ms=self._r(fin.ttft_ms),
                        latency_ms=self._r(fin.latency_ms))
            if self.enabled:
                self._account(fin, evicted, tbt_mean=None)
            return
        tbt_mean = (tr.tbt_sum / (tr.n_tokens - 1)
                    if tr.n_tokens > 1 else None)
        prefill_ms = (fin.ttft_ms - tr.queue_wait_ms
                      - (tr.handoff_ms or 0.0)
                      if fin.ttft_ms is not None
                      and tr.queue_wait_ms is not None else None)
        slo_ok = self._account(fin, evicted, tbt_mean)
        if tr.chunks:
            self.hist["chunks_per_request"].record(float(tr.chunks))
        ctx = ({"trace_id": tr.trace_id, "hop": tr.hop}
               if tr.trace_id is not None else {})
        self._event(kind, uid=fin.uid, reason=fin.finish_reason,
                    new_tokens=len(fin.tokens),
                    ttft_ms=self._r(fin.ttft_ms),
                    latency_ms=self._r(fin.latency_ms),
                    queue_wait_ms=self._r(tr.queue_wait_ms),
                    prefill_ms=self._r(prefill_ms),
                    handoff_ms=self._r(tr.handoff_ms),
                    tbt_ms=self._r(tbt_mean),
                    tbt_ms_max=self._r(tr.tbt_max if tr.n_tokens > 1
                                       else None),
                    slo_ok=slo_ok,
                    draft_proposed=tr.spec_proposed,
                    draft_accepted=tr.spec_accepted,
                    chunks=tr.chunks, **ctx)
        self._lanes(tr)

    # ----------------------------------------------- migration lineage
    def on_migrate_out(self, uid: int, *, position: int, pages: int,
                       nbytes: int, reason: str = "migrate") -> None:
        """The engine exported ``uid``'s live state for migration (the
        source half of the lineage pair). Emitted BEFORE the local
        "migrate" eviction, so the row still carries the request's
        trace context; the destination's ``serve_migrate_in`` shares
        the trace id, stitching the timeline across replica death."""
        if not self.enabled:
            return
        self._event("serve_migrate_out", uid=uid, position=int(position),
                    pages=int(pages), nbytes=int(nbytes),
                    reason=str(reason), **self._ctx(uid))

    def on_migrate_in(self, uid: int, *, trace_id: Optional[str],
                      hop: int, position: int, pages: int, nbytes: int,
                      queue_wait_ms: Optional[float] = None,
                      ttft_ms: Optional[float] = None,
                      elapsed_ms: float = 0.0, tokens: int = 0) -> None:
        """The engine resumed a migrated request here (the destination
        half). Installs a resumed trace so every later row —
        decode windows, the finish row — carries the ORIGINAL trace id
        with the hop ordinal bumped; the carried elapsed/queue/ttft
        durations keep the finish row's latency decomposition summing
        exactly across the hop (clocks ship as durations, never
        absolute times — disagg.MigrationRecord doctrine)."""
        if not self.enabled:
            return
        now = self._clock()
        tr = self._req[uid] = _ReqTrace(
            uid=uid, trace_id=trace_id, hop=int(hop),
            t_submit=now - max(float(elapsed_ms), 0.0) / 1e3,
            queue_wait_ms=queue_wait_ms, ttft_ms=ttft_ms,
            n_tokens=int(tokens))
        if ttft_ms is not None:
            # first token already happened on the source replica —
            # resume TBT/window sampling from the import instant
            tr.t_first = tr.t_last = now
            tr.window_t0 = now
        self._event("serve_migrate_in", uid=uid, position=int(position),
                    pages=int(pages), nbytes=int(nbytes),
                    resumed_tokens=int(tokens), **self._ctx(uid))

    def _account(self, fin, evicted: bool,
                 tbt_mean: Optional[float]) -> bool:
        """SLO classification + goodput counters. An evicted request —
        or one whose first token never came — is by definition outside
        SLO."""
        self.finished += 1
        self.finished_tokens += len(fin.tokens)
        if evicted:
            self.evicted += 1
        slo_ok = (not evicted and fin.ttft_ms is not None
                  and fin.ttft_ms <= self.slo_ttft_ms
                  and (tbt_mean is None or tbt_mean <= self.slo_tbt_ms))
        if slo_ok:
            self.finished_in_slo += 1
            self.good_tokens += len(fin.tokens)
        return slo_ok

    def _lanes(self, tr: _ReqTrace) -> None:
        """Per-request Chrome-trace lane: queue_wait / prefill / decode
        phase spans on lane ``tid = uid`` (drawn at finish so each
        request costs a constant three events)."""
        if self.recorder is None or not hasattr(self.recorder, "add_lane"):
            return
        now = self._clock()
        lane = f"req {tr.uid}"
        if tr.t_admit is not None:
            self.recorder.add_lane(tr.uid, lane, "queue_wait",
                                   tr.t_submit, tr.t_admit)
            if tr.t_first is not None:
                self.recorder.add_lane(tr.uid, lane, "prefill",
                                       tr.t_admit, tr.t_first)
                self.recorder.add_lane(tr.uid, lane, "decode",
                                       tr.t_first, now,
                                       tokens=tr.n_tokens)
            else:
                self.recorder.add_lane(tr.uid, lane, "prefill",
                                       tr.t_admit, now)
        else:
            self.recorder.add_lane(tr.uid, lane, "queue_wait",
                                   tr.t_submit, now)

    # ------------------------------------------------------------ scalars
    def drain_step_tbts(self) -> List[float]:
        """TBT samples since the last drain (the engine writes their
        mean as one ``Serve/tbt_ms`` scalar per decode dispatch)."""
        out = self._step_tbts
        self._step_tbts = []
        return out

    @property
    def slo_attainment(self) -> Optional[float]:
        if not self.finished:
            return None
        return self.finished_in_slo / self.finished

    @property
    def spec_accept_rate(self) -> Optional[float]:
        """Lifetime accepted/proposed draft ratio (None before the
        first verify dispatch with live drafts)."""
        if not self.spec_proposed:
            return None
        return self.spec_accepted / self.spec_proposed

    # ----------------------------------------------------------- reports
    def snapshot(self) -> Dict[str, Any]:
        """The SLO/latency block of ``engine.debug_state()`` and the
        periodic ``serve_state`` event: bounded-histogram percentiles +
        attainment/goodput counters (all host-side)."""
        att = self.slo_attainment
        return {
            "enabled": self.enabled,
            "slo": {"ttft_ms": self.slo_ttft_ms,
                    "tbt_ms": self.slo_tbt_ms},
            "finished": self.finished,
            "evicted": self.evicted,
            "in_slo": self.finished_in_slo,
            "attainment": round(att, 4) if att is not None else None,
            "good_tokens": self.good_tokens,
            "finished_tokens": self.finished_tokens,
            "in_flight": len(self._req),
            "spec": {"proposed": self.spec_proposed,
                     "accepted": self.spec_accepted,
                     "dispatches": self.spec_dispatches,
                     "accept_rate": (round(self.spec_accept_rate, 4)
                                     if self.spec_accept_rate is not None
                                     else None)},
            "handoffs": self.handoffs,
            "chunked_prefill": {"chunk_rows": self.chunk_rows,
                                "requests": self.chunked_requests},
            "latency": {k: h.snapshot() for k, h in self.hist.items()},
        }
