"""The serving fleet: a multi-replica router over in-process engines.

One engine is one failure domain: a single SIGTERM, a hot queue, or a
weight push takes the whole service down. :class:`FleetRouter` fronts
N :class:`~.engine.InferenceEngine` replicas (built by the caller —
this module never constructs device state) and keeps the service
answering, correctly and within SLO, while individual replicas are
preempted, overloaded, or being upgraded. Three legs:

**Routing + SLO shedding.** Requests route ``least_loaded`` (queue
depth + active slots) or ``prefix_affinity`` (the replica whose prefix
cache already covers the most prompt tokens, ties broken least-loaded)
off each engine's host-side introspection — no device syncs. Admission
is SLO-aware: when the fleet's p95 TTFT (the PR 9 goodput histograms)
breaches ``slo_shed.ttft_budget_ms``, the shed ladder engages —
*goodput, not throughput, is the objective*:

    rung 1 (p95 > budget)          reject requests below the
                                   ``shed_below_priority`` tier
                                   ("shed_slo" — a synthesized
                                   zero-token response, never a drop)
    rung 2 (p95 > budget x factor) additionally cap admitted requests'
                                   max_new_tokens ("degrade_max_new")
                                   and switch speculation off fleet-wide
                                   ("degrade_spec_off" — the plain
                                   decode program is already warm, so
                                   the ladder never recompiles)

Every shed decision lands in the serve trail (``fleet_shed`` rows)
with a reason from the pinned :data:`~.tracing.SHED_REASONS`
vocabulary.

**Replica drain.** Each replica carries a
:class:`~deepspeed_tpu.runtime.elastic.PreemptionGuard`; a SIGTERM (or
software ``request_preemption``, or :meth:`FleetRouter.drain`) flips it
and the router reacts at the next step: the replica stops receiving
work, its queued (not-yet-admitted) requests are cancelled with reason
"drain" and resubmitted — same ``Request`` objects, same uids, same
per-request seeds — to surviving replicas, where the prefix cache
re-prefills them; in-flight requests finish where they are. Greedy
outputs are bitwise unchanged because sampling is per-request-seeded
and batch-composition-independent. When its last slot empties the
replica retires (``fleet_drain`` rows bracket the episode).

**Live weight swap.** :meth:`swap_weights` pushes a committed
checkpoint tag into every running replica between dispatches via
``engine.swap_params`` (``load_params_only`` into the existing serving
shardings — zero recompiles, fixed program set, atomic-or-rollback per
replica). Every ``FinishedRequest`` carries the ``weight_version``
that produced it.

This module is jax-free (pinned source-level next to scheduler/
paging/disagg by tests/unit/test_inference.py): it orchestrates
engines purely through their host-side surface, so routing policy is
unit-testable in microseconds and cannot perturb any compiled program.
"""

import time
from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional, Sequence

from deepspeed_tpu.inference.scheduler import FinishedRequest, Request
from deepspeed_tpu.inference.tracing import SHED_REASONS  # noqa: F401
from deepspeed_tpu.runtime import fault
from deepspeed_tpu.runtime.elastic import PreemptionGuard
from deepspeed_tpu.utils.logging import logger

__all__ = ["FleetRouter", "ReplicaHandle"]

#: replica lifecycle (one-way): live -> draining -> retired
LIVE, DRAINING, RETIRED = "live", "draining", "retired"


def _normalize_fleet_config(fleet_config) -> Dict[str, Any]:
    """Run a raw ``inference.fleet`` section through the real config
    parser (defaults + DeepSpeedConfigError validation — one grammar,
    no router-private dialect). ``runtime/config.py`` is jax-free."""
    from deepspeed_tpu.runtime.config import get_inference_config
    return get_inference_config(
        {"inference": {"fleet": dict(fleet_config or {})}})["fleet"]


@dataclass
class ReplicaHandle:
    """The router's per-replica bookkeeping around one engine."""
    idx: int
    engine: Any
    guard: PreemptionGuard
    status: str = LIVE
    drain_reason: Optional[str] = None
    dispatch_faults: int = 0     # serve.dispatch injections survived
    routed: int = 0              # requests this replica received

    # ------------------------------------------------- host-side reads
    def load(self) -> int:
        """Routing load metric: waiting + in-flight requests."""
        sched = self.engine.scheduler
        return sched.queue_depth + len(sched.active_slots())

    def prefix_tokens(self, prompt: Sequence[int]) -> int:
        """Prompt tokens this replica's prefix cache already holds."""
        alloc = getattr(self.engine.scheduler, "admit_allocator", None)
        if alloc is None or not hasattr(alloc, "match_prefix"):
            return 0
        _pages, tokens = alloc.match_prefix(list(prompt))
        return int(tokens)

    def handoff_depth(self) -> int:
        q = getattr(self.engine, "_handoff_q", None)
        return len(q) if q is not None else 0

    def idle(self) -> bool:
        return self.engine.scheduler.idle() and self.handoff_depth() == 0

    def snapshot(self) -> Dict[str, Any]:
        """One row of the ``fleet_state`` event / ``debug_state()``."""
        sched = self.engine.scheduler
        alloc = getattr(sched, "allocator", None)
        return {
            "replica": self.idx,
            "status": self.status,
            "queue_depth": sched.queue_depth,
            "active_slots": len(sched.active_slots()),
            "occupancy": round(sched.occupancy, 4),
            "pages_in_use": (alloc.pages_in_use if alloc is not None
                             else None),
            "weight_version": getattr(self.engine, "weight_version",
                                      None),
            "weight_ordinal": getattr(self.engine, "weight_ordinal", 0),
            "steady_state_recompiles": getattr(
                self.engine, "steady_state_recompiles", None),
            "routed": self.routed,
            "dispatch_faults": self.dispatch_faults,
            "drain_reason": self.drain_reason,
        }


class FleetRouter:
    """Route requests across N in-process engine replicas; shed by
    SLO, drain through preemptions, swap weights live.

    ``engines`` are already-warmed :class:`~.engine.InferenceEngine`
    instances (duck-typed: anything with the engine's host surface —
    ``submit/step/cancel/scheduler/swap_params/set_speculation``).
    ``fleet_config`` is a raw ``inference.fleet`` dict (normalized and
    validated through ``runtime/config.py``). Telemetry reuses the
    first engine's monitor and events.jsonl writer unless overridden —
    the fleet trail interleaves with the per-request serve trail, one
    timeline per run.

    Drive it like an engine: ``submit`` then ``run`` (or ``step`` in a
    serving loop). ``run`` returns exactly one :class:`FinishedRequest`
    per submitted uid — shed requests get a synthesized zero-token
    response (``finish_reason`` from the pinned shed vocabulary), never
    a dropped uid.
    """

    #: fleet_state event / scalar cadence (router steps)
    _STATE_EVERY = 16

    def __init__(self, engines: Sequence[Any], fleet_config=None,
                 monitor=None, writer=None,
                 install_signal_handlers: bool = False,
                 clock=time.perf_counter):
        if not engines:
            raise ValueError("FleetRouter needs at least one engine")
        self.cfg = _normalize_fleet_config(fleet_config)
        self._clock = clock
        self.replicas = [ReplicaHandle(i, e, PreemptionGuard())
                         for i, e in enumerate(engines)]
        if install_signal_handlers:
            # chain-installed: a real SIGTERM reaches the last guard —
            # ONE replica drains, the fleet keeps serving (the process-
            # level analog of a preempted pod). Software triggers
            # (drain()/request_preemption) don't need handlers.
            for r in self.replicas:
                r.guard.install()
        self.monitor = monitor if monitor is not None else \
            getattr(engines[0], "monitor", None)
        self._log = writer if writer is not None else \
            getattr(engines[0], "_log", None)
        # env-armed serve-plane faults (DSTPU_FAULT_ARM) — latched
        # no-op when another component already armed this process
        fault.arm_from_env()
        # health plane: the router beats the FIRST replica's watchdog
        # once per scheduling round (duck-typed like monitor/_log — a
        # fleet of stubs without one simply has no fleet heartbeat)
        self.health = getattr(engines[0], "health", None)
        self._steps = 0
        self._pending: List[FinishedRequest] = []
        # ladder + ledger
        self.total_submitted = 0
        self.total_shed = 0
        self.shed_by_reason: Dict[str, int] = {}
        self.shed_by_priority: Dict[int, int] = {}
        self.total_degraded = 0
        self.total_redistributed = 0
        self.total_reroutes = 0
        self._spec_degraded = False
        sh = self.cfg["slo_shed"]
        self._budget_ms = sh["ttft_budget_ms"]
        if self._budget_ms is None:
            # fall back to the serve SLO the tracers already enforce
            tr = getattr(engines[0], "_tracer", None)
            self._budget_ms = float(getattr(tr, "slo_ttft_ms", 2000.0))
        logger.info(
            f"fleet router: {len(self.replicas)} replicas, "
            f"routing={self.cfg['routing']}, slo_shed="
            f"{'on' if sh['enabled'] else 'off'} "
            f"(p95 TTFT budget {self._budget_ms:.0f} ms)")

    # ---------------------------------------------------------- events
    def _event(self, kind: str, **fields) -> None:
        if self._log is not None:
            self._log.add_event(kind, **fields)

    # ------------------------------------------------------ shed ladder
    def _ttft_stats(self):
        """Aggregate (samples, worst p95) over serving replicas — the
        goodput histograms the tracers already keep."""
        count, p95 = 0, None
        for r in self.replicas:
            if r.status == RETIRED:
                continue
            tr = getattr(r.engine, "_tracer", None)
            if tr is None:
                continue
            h = tr.hist.get("ttft_ms")
            if h is None or not h.count:
                continue
            count += h.count
            v = h.percentile(0.95)
            if v is not None:
                p95 = v if p95 is None else max(p95, v)
        return count, p95

    def shed_level(self) -> int:
        """0 = healthy, 1 = shed rung (reject low tiers), 2 = degrade
        rung (cap max_new + speculation off)."""
        sh = self.cfg["slo_shed"]
        if not sh["enabled"]:
            return 0
        count, p95 = self._ttft_stats()
        if p95 is None or count < sh["min_samples"]:
            return 0
        if p95 > self._budget_ms * sh["degrade_factor"]:
            return 2
        if p95 > self._budget_ms:
            return 1
        return 0

    def _shed(self, req: Request, reason: str,
              **extra) -> FinishedRequest:
        """Synthesize the rejection response: the client gets exactly
        one FinishedRequest per uid — a shed is a (zero-token) answer,
        never a dropped request."""
        prio = getattr(req, "priority", 0)
        self.total_shed += 1
        self.shed_by_reason[reason] = \
            self.shed_by_reason.get(reason, 0) + 1
        self.shed_by_priority[prio] = \
            self.shed_by_priority.get(prio, 0) + 1
        self._event("fleet_shed", uid=req.uid, reason=reason,
                    priority=prio, **extra)
        fin = FinishedRequest(uid=req.uid, prompt=list(req.prompt),
                              tokens=[], finish_reason=reason,
                              ttft_ms=None, latency_ms=0.0)
        self._pending.append(fin)
        return fin

    def _apply_spec_degrade(self, level: int) -> None:
        want = level >= 2
        if want == self._spec_degraded:
            return
        changed = 0
        for r in self.replicas:
            if r.status == RETIRED:
                continue
            if getattr(r.engine, "set_speculation",
                       lambda on: False)(not want):
                changed += 1
        self._spec_degraded = want
        if changed:
            self._event("fleet_shed", reason="degrade_spec_off",
                        enabled=want, replicas=changed)

    # ---------------------------------------------------------- routing
    def _ranked(self, req: Optional[Request]) -> List[ReplicaHandle]:
        """Live replicas, best dispatch target first."""
        live = [r for r in self.replicas if r.status == LIVE]
        if self.cfg["routing"] == "prefix_affinity" and req is not None:
            return sorted(live, key=lambda r: (-r.prefix_tokens(
                req.prompt), r.load(), r.idx))
        return sorted(live, key=lambda r: (r.load(), r.idx))

    def _dispatch(self, req: Request) -> Optional[ReplicaHandle]:
        """Hand ``req`` to the best live replica; a transient
        ``serve.dispatch`` fault reroutes to the next-best instead of
        dropping. None = no replica accepted (caller sheds)."""
        for r in self._ranked(req):
            try:
                fault.fire("serve.dispatch", replica=r.idx, uid=req.uid)
                r.engine.submit(req)
            except (fault.InjectedCrash, OSError) as e:
                r.dispatch_faults += 1
                self.total_reroutes += 1
                logger.warning(f"fleet dispatch fault on replica "
                               f"{r.idx} (uid {req.uid}): {e!r}; "
                               f"rerouting")
                continue
            r.routed += 1
            return r
        return None

    # ----------------------------------------------------------- submit
    def submit(self, request: Request) -> int:
        """Admit (or shed) one request; returns its uid either way —
        the response arrives through :meth:`step`/:meth:`run`."""
        self.total_submitted += 1
        prio = getattr(request, "priority", 0)
        level = self.shed_level()
        self._apply_spec_degrade(level)
        sh = self.cfg["slo_shed"]
        if level >= 1 and prio < sh["shed_below_priority"]:
            _count, p95 = self._ttft_stats()
            self._shed(request, "shed_slo", p95_ttft_ms=p95,
                       budget_ms=self._budget_ms, level=level)
            return request.uid
        if level >= 2 and sh["degrade_max_new"] > 0 and \
                request.max_new_tokens > sh["degrade_max_new"]:
            # replace() preserves uid/seed — only the budget shrinks
            request = replace(request,
                              max_new_tokens=sh["degrade_max_new"])
            self.total_degraded += 1
            self._event("fleet_shed", uid=request.uid,
                        reason="degrade_max_new", priority=prio,
                        max_new_tokens=request.max_new_tokens)
        if self._dispatch(request) is None:
            self._shed(request, "shed_capacity",
                       live=[r.idx for r in self.replicas
                             if r.status == LIVE])
        return request.uid

    # ------------------------------------------------------------ drain
    def drain(self, replica: int, reason: str = "manual") -> None:
        """Software-preempt one replica (the SIGTERM-equivalent). The
        actual drain runs at the next :meth:`step`."""
        self.replicas[replica].guard.trigger(reason)

    def _begin_drain(self, r: ReplicaHandle) -> None:
        r.status = DRAINING
        r.drain_reason = r.guard.reason or "preempted"
        survivors = [s for s in self.replicas if s.status == LIVE]
        queued = list(r.engine.scheduler.queue)
        in_flight = len(r.engine.scheduler.active_slots())
        self._event("fleet_drain", phase="begin", replica=r.idx,
                    reason=r.drain_reason, queued=len(queued),
                    in_flight=in_flight,
                    survivors=[s.idx for s in survivors])
        logger.info(
            f"fleet drain: replica {r.idx} ({r.drain_reason}) — "
            f"{in_flight} in flight finish here, {len(queued)} queued "
            f"redistribute over {len(survivors)} survivors")
        if not survivors or not queued:
            # nobody to redistribute to (the replica simply finishes
            # everything it holds), or nothing waiting
            return
        for req in queued:
            # the cancel's serve_evict row (reason "drain") is drain
            # bookkeeping, not the client's answer — _collect drops it;
            # the SAME Request object (uid, seed, budget) goes to a
            # survivor, whose prefix cache re-prefills it
            r.engine.cancel(req.uid, reason="drain")
            self.total_redistributed += 1
            if self._dispatch(req) is None:
                self._shed(req, "shed_capacity", drained_from=r.idx)

    # ------------------------------------------------------------- step
    def _collect(self, fins: List[FinishedRequest]
                 ) -> List[FinishedRequest]:
        return [f for f in fins if f.finish_reason != "drain"]

    def step(self) -> List[FinishedRequest]:
        """One fleet scheduling round: react to preemptions, advance
        every serving replica one engine step, retire empty drains.
        Returns the requests that finished (shed responses included)."""
        out: List[FinishedRequest] = []
        out.extend(self._pending)
        self._pending = []
        if self.health is not None:
            self.health.heartbeat("fleet_step")
        for r in self.replicas:
            if r.status == RETIRED:
                continue
            try:
                # the per-replica preemption probe: a raised injection
                # preempts THIS replica (the env grammar's targeted
                # form); the "preempt" action instead flags installed
                # guards, exactly like a real SIGTERM
                fault.fire("serve.replica_preempt", replica=r.idx)
            except (fault.InjectedCrash, OSError) as e:
                r.guard.trigger(f"fault:{type(e).__name__}")
            if r.status == LIVE and r.guard.preempted:
                self._begin_drain(r)
        for r in self.replicas:
            if r.status == RETIRED:
                continue
            if not r.idle():
                out.extend(self._collect(r.engine.step()))
            if r.status == DRAINING and r.idle():
                r.status = RETIRED
                self._event("fleet_drain", phase="complete",
                            replica=r.idx, reason=r.drain_reason)
                logger.info(f"fleet drain: replica {r.idx} retired")
        self._apply_spec_degrade(self.shed_level())
        self._steps += 1
        if self._steps % self._STATE_EVERY == 0:
            self._write_telemetry()
        return out

    def idle(self) -> bool:
        return not self._pending and all(
            r.status == RETIRED or r.idle() for r in self.replicas)

    def run(self) -> List[FinishedRequest]:
        """Serve until every admitted request has answered (the fleet
        analog of ``engine.run``; responses in completion order)."""
        out: List[FinishedRequest] = []
        while not self.idle():
            out.extend(self.step())
        out.extend(self._pending)
        self._pending = []
        self._write_telemetry()
        return out

    # ------------------------------------------------ live weight swap
    def swap_weights(self, load_dir: str, tag: Optional[str] = None
                     ) -> Dict[int, Optional[str]]:
        """Push a committed checkpoint tag into every serving replica
        between dispatches. Per replica atomic-or-rollback: a failed
        load (bad tag, I/O flake, injected ``serve.swap_load``) leaves
        THAT replica serving its old weights and still live — the
        result maps replica -> new version (None = rolled back)."""
        verify = self.cfg["swap"]["verify_integrity"]
        results: Dict[int, Optional[str]] = {}
        for r in self.replicas:
            if r.status == RETIRED:
                continue
            try:
                results[r.idx] = r.engine.swap_params(
                    load_dir, tag=tag, verify_integrity=verify)
            except Exception as e:
                results[r.idx] = None
                logger.warning(
                    f"fleet swap: replica {r.idx} rolled back "
                    f"({e!r}); still serving "
                    f"{getattr(r.engine, 'weight_version', '?')}")
        self._event("fleet_swap_push", load_dir=str(load_dir), tag=tag,
                    versions={str(k): v for k, v in results.items()},
                    rolled_back=[k for k, v in results.items()
                                 if v is None])
        return results

    # -------------------------------------------------------- telemetry
    @property
    def shed_rate(self) -> float:
        return (self.total_shed / self.total_submitted
                if self.total_submitted else 0.0)

    def fleet_queue_depth(self) -> int:
        return sum(r.engine.scheduler.queue_depth for r in self.replicas
                   if r.status != RETIRED)

    def debug_state(self) -> Dict[str, Any]:
        """Host-only fleet introspection (mirrors the periodic
        ``fleet_state`` event row obs_report renders)."""
        count, p95 = self._ttft_stats()
        return {
            "routing": self.cfg["routing"],
            "steps": self._steps,
            "replicas": [r.snapshot() for r in self.replicas],
            "fleet_queue_depth": self.fleet_queue_depth(),
            "submitted": self.total_submitted,
            "shed": {"total": self.total_shed,
                     "rate": round(self.shed_rate, 4),
                     "by_reason": dict(self.shed_by_reason),
                     "by_priority": {str(k): v for k, v in
                                     self.shed_by_priority.items()},
                     "degraded": self.total_degraded,
                     "spec_degraded": self._spec_degraded,
                     "level": self.shed_level()},
            "slo": {"p95_ttft_ms": p95, "samples": count,
                    "budget_ms": self._budget_ms},
            "redistributed": self.total_redistributed,
            "reroutes": self.total_reroutes,
        }

    def _write_telemetry(self) -> None:
        self._event("fleet_state", step=self._steps,
                    **self.debug_state())
        if self.monitor is None or not hasattr(
                self.monitor, "write_serving_metrics"):
            return
        tokens = sum(r.engine.scheduler.total_tokens
                     for r in self.replicas)
        self.monitor.write_serving_metrics(
            shed_rate=self.shed_rate,
            fleet_queue_depth=self.fleet_queue_depth(),
            tokens=tokens)

    # ---------------------------------------------------------- cleanup
    def close(self) -> None:
        """Uninstall guards and close every engine (final ``fleet_state``
        first, so the run report sees the fleet's last shape)."""
        self._write_telemetry()
        for r in self.replicas:
            r.guard.uninstall()
            close = getattr(r.engine, "close", None)
            if close is not None:
                close()
        self._log = None
