"""The serving fleet: a multi-replica router over in-process engines.

One engine is one failure domain: a single SIGTERM, a hot queue, or a
weight push takes the whole service down. :class:`FleetRouter` fronts
N :class:`~.engine.InferenceEngine` replicas (built by the caller —
this module never constructs device state) and keeps the service
answering, correctly and within SLO, while individual replicas are
preempted, overloaded, or being upgraded. Three legs:

**Routing + SLO shedding.** Requests route ``least_loaded`` (queue
depth + active slots) or ``prefix_affinity`` (the replica whose prefix
cache already covers the most prompt tokens, ties broken least-loaded)
off each engine's host-side introspection — no device syncs. Admission
is SLO-aware: when the fleet's p95 TTFT (the PR 9 goodput histograms)
breaches ``slo_shed.ttft_budget_ms``, the shed ladder engages —
*goodput, not throughput, is the objective*:

    rung 1 (p95 > budget)          reject requests below the
                                   ``shed_below_priority`` tier
                                   ("shed_slo" — a synthesized
                                   zero-token response, never a drop)
    rung 2 (p95 > budget x factor) additionally cap admitted requests'
                                   max_new_tokens ("degrade_max_new")
                                   and switch speculation off fleet-wide
                                   ("degrade_spec_off" — the plain
                                   decode program is already warm, so
                                   the ladder never recompiles)

Every shed decision lands in the serve trail (``fleet_shed`` rows)
with a reason from the pinned :data:`~.tracing.SHED_REASONS`
vocabulary.

**Replica drain.** Each replica carries a
:class:`~deepspeed_tpu.runtime.elastic.PreemptionGuard`; a SIGTERM (or
software ``request_preemption``, or :meth:`FleetRouter.drain`) flips it
and the router reacts at the next step: the replica stops receiving
work, its queued (not-yet-admitted) requests are cancelled with reason
"drain" and resubmitted — same ``Request`` objects, same uids, same
per-request seeds — to surviving replicas, where the prefix cache
re-prefills them; in-flight requests finish where they are. Greedy
outputs are bitwise unchanged because sampling is per-request-seeded
and batch-composition-independent. When its last slot empties the
replica retires (``fleet_drain`` rows bracket the episode).

**Live weight swap.** :meth:`swap_weights` pushes a committed
checkpoint tag into every running replica between dispatches via
``engine.swap_params`` (``load_params_only`` into the existing serving
shardings — zero recompiles, fixed program set, atomic-or-rollback per
replica). Every ``FinishedRequest`` carries the ``weight_version``
that produced it.

**Process mode (ISSUE 16).** The same router can front replicas that
live in CHILD PROCESSES: :class:`ReplicaProcess` is the duck-typed
engine proxy over the :mod:`~.rpc` channel to one
``replica_worker`` child, so routing/shed/drain/swap semantics carry
over unchanged — plus the three robustness legs only a process
boundary buys: (1) **live KV migration** — a draining or dying
replica exports each in-flight request's live pages (the PR 13
warmup-compiled export/import pair, retargeted at the main pool),
ships them through the RPC channel (LinkModel-priced,
``serve_migration`` trail row), and the importing replica resumes
decode at the same ``cache_position`` — bitwise-preserving, no
re-prefill; (2) **supervised replica lifecycle** — a dead child's
exit code routes through the launcher's restart policy
(``launcher/runner.restart_eligible``: 85/87 relaunch with backoff,
anything else gives up), its queued requests redistribute (same uids,
same seeds), and its ``flight_serve.json`` black box is salvaged into
the router's own event trail (``fleet_flight_salvage``); (3)
**goodput-driven autoscale** — sustained rung-1 shedding spawns a
replica, sustained idleness drains one via migration (hysteresis +
cooldown, never below ``min_replicas``, never a dropped request).

This module is jax-free (pinned source-level next to scheduler/
paging/disagg/rpc by tests/unit/test_inference.py): it orchestrates
engines purely through their host-side surface, so routing policy is
unit-testable in microseconds and cannot perturb any compiled program.
"""

import itertools
import json
import os
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field, replace
from typing import (Any, Callable, Dict, List, Optional, Sequence,
                    Tuple)

from deepspeed_tpu.inference import rpc
from deepspeed_tpu.inference.disagg import price_handoff
from deepspeed_tpu.inference.rpc import ReplicaDeadError, RpcError
from deepspeed_tpu.inference.scheduler import FinishedRequest, Request
from deepspeed_tpu.inference.tracing import SHED_REASONS  # noqa: F401
from deepspeed_tpu.runtime import fault
from deepspeed_tpu.runtime.elastic import PreemptionGuard
from deepspeed_tpu.utils.health import load_flight
from deepspeed_tpu.utils.logging import logger

__all__ = ["FleetRouter", "ReplicaHandle", "ReplicaProcess",
           "launch_replica_processes"]

#: replica lifecycle (one-way): live -> draining -> retired
LIVE, DRAINING, RETIRED = "live", "draining", "retired"


def _normalize_fleet_config(fleet_config) -> Dict[str, Any]:
    """Run a raw ``inference.fleet`` section through the real config
    parser (defaults + DeepSpeedConfigError validation — one grammar,
    no router-private dialect). ``runtime/config.py`` is jax-free."""
    from deepspeed_tpu.runtime.config import get_inference_config
    return get_inference_config(
        {"inference": {"fleet": dict(fleet_config or {})}})["fleet"]


@dataclass
class ReplicaHandle:
    """The router's per-replica bookkeeping around one engine."""
    idx: int
    engine: Any
    guard: PreemptionGuard
    status: str = LIVE
    drain_reason: Optional[str] = None
    dispatch_faults: int = 0     # serve.dispatch injections survived
    routed: int = 0              # requests this replica received
    # process-mode lifecycle + migration ledger (ISSUE 16)
    restarts: int = 0            # supervised relaunches so far
    last_exit_code: Optional[int] = None
    migrations_in: int = 0       # live requests imported here
    migrations_out: int = 0      # live requests exported away
    migration_bytes: int = 0     # slab bytes shipped out
    migration_priced_ms: float = 0.0   # LinkModel-priced wire cost

    # ------------------------------------------------- host-side reads
    def load(self) -> int:
        """Routing load metric: waiting + in-flight requests."""
        sched = self.engine.scheduler
        return sched.queue_depth + len(sched.active_slots())

    def prefix_tokens(self, prompt: Sequence[int]) -> int:
        """Prompt tokens this replica's prefix cache already holds."""
        alloc = getattr(self.engine.scheduler, "admit_allocator", None)
        if alloc is None or not hasattr(alloc, "match_prefix"):
            return 0
        _pages, tokens = alloc.match_prefix(list(prompt))
        return int(tokens)

    def handoff_depth(self) -> int:
        q = getattr(self.engine, "_handoff_q", None)
        return len(q) if q is not None else 0

    def idle(self) -> bool:
        return self.engine.scheduler.idle() and self.handoff_depth() == 0

    def active_uids(self) -> List[int]:
        """In-flight request uids (the migration candidates on drain).
        Process proxies keep a synced list; in-process engines read
        the live slots."""
        sched = self.engine.scheduler
        uids = getattr(sched, "active_uids", None)
        if uids is not None:
            return list(uids() if callable(uids) else uids)
        return [sched.slots[s].request.uid
                for s in sched.active_slots()]

    def process_snapshot(self) -> Dict[str, Any]:
        """One ``fleet_replica_state`` row: per-replica process health
        + migration ledger (obs_report's fleet process table)."""
        return {
            "replica": self.idx,
            "status": self.status,
            "pid": getattr(self.engine, "pid", None) or os.getpid(),
            "restarts": self.restarts,
            "last_exit_code": self.last_exit_code,
            "migrations_in": self.migrations_in,
            "migrations_out": self.migrations_out,
            "migration_bytes": self.migration_bytes,
            "migration_priced_ms": round(self.migration_priced_ms, 4),
        }

    def snapshot(self) -> Dict[str, Any]:
        """One row of the ``fleet_state`` event / ``debug_state()``."""
        sched = self.engine.scheduler
        alloc = getattr(sched, "allocator", None)
        return {
            "replica": self.idx,
            "status": self.status,
            "queue_depth": sched.queue_depth,
            "active_slots": len(sched.active_slots()),
            "occupancy": round(sched.occupancy, 4),
            "pages_in_use": (alloc.pages_in_use if alloc is not None
                             else None),
            "weight_version": getattr(self.engine, "weight_version",
                                      None),
            "weight_ordinal": getattr(self.engine, "weight_ordinal", 0),
            "steady_state_recompiles": getattr(
                self.engine, "steady_state_recompiles", None),
            "routed": self.routed,
            "dispatch_faults": self.dispatch_faults,
            "drain_reason": self.drain_reason,
        }


class _ProcScheduler:
    """Router-side mirror of a child replica's scheduler surface,
    refreshed from the ``state`` snapshot every RPC reply carries.
    Exposes exactly what the router reads for routing/drain decisions
    (``queue``/``queue_depth``/``active_slots()``/``occupancy``/
    ``total_tokens``/``idle()``/``allocator.pages_in_use``) with ZERO
    extra round trips — state piggybacks on calls already in flight."""

    class _Alloc:
        def __init__(self):
            self.pages_in_use: Optional[int] = None

    def __init__(self):
        self.queue: List[Request] = []
        self.active_uids: List[int] = []
        self.mid_decode_uids: List[int] = []
        self.occupancy = 0.0
        self.total_tokens = 0
        self._idle = True
        self.allocator = _ProcScheduler._Alloc()

    @property
    def queue_depth(self) -> int:
        return len(self.queue)

    def active_slots(self) -> List[int]:
        # across the process boundary uids stand in for slot ids; the
        # router only counts these or maps them back to uids
        return list(self.active_uids)

    def idle(self) -> bool:
        return self._idle and not self.queue


class ReplicaProcess:
    """Duck-typed engine proxy over one RPC channel to a
    ``replica_worker`` child. Presents the engine host surface the
    router drives (``submit/step/cancel/scheduler/swap_params/
    set_speculation/export_request/import_request/weight_version``) so
    :class:`FleetRouter`'s routing/shed/drain/swap semantics are
    IDENTICAL for in-process and child-process replicas — plus the
    lifecycle only a process boundary buys: :meth:`poll_exit` (the
    child's exit code feeds the launcher restart policy),
    :meth:`relaunch` (supervised restart into a fresh child), and
    deathbed handling (a ``dying`` reply surfaces as
    :class:`~.rpc.ReplicaDeadError` carrying migration exports).

    ``spec`` is the replica_worker spec grammar (model_config,
    init_seed or checkpoint_dir, inference, observability, dtype).
    Requests submitted here are kept router-side too (``_requests``)
    so a death can redistribute them — same objects, same uids, same
    seeds."""

    def __init__(self, spec: Dict[str, Any], *, name: str = "replica",
                 rpc_timeout_s: float = 120.0, rpc_retries: int = 2,
                 rpc_backoff_s: float = 0.05,
                 ready_timeout_s: float = 300.0,
                 env: Optional[Dict[str, str]] = None,
                 python: Optional[str] = None,
                 log_path: Optional[str] = None):
        self.spec = dict(spec)
        self.name = name
        self._timeout_s = float(rpc_timeout_s)
        self._retries = int(rpc_retries)
        self._backoff_s = float(rpc_backoff_s)
        self._ready_timeout_s = float(ready_timeout_s)
        self._env = dict(env or {})
        self._python = python or sys.executable
        self._log_path = log_path
        self.scheduler = _ProcScheduler()
        #: router-side copies of everything the child holds (queued +
        #: in-flight), keyed by uid — the redistribution source on death
        self._requests: Dict[int, Request] = {}
        self.pid: Optional[int] = None
        self.flight_path: Optional[str] = None
        self.weight_version: Optional[str] = "initial"
        self.weight_ordinal = 0
        self.steady_state_recompiles = -1
        self.total_dispatches: Optional[int] = None
        self._can_migrate = False
        self._proc: Optional[subprocess.Popen] = None
        self._client: Optional[rpc.RpcClient] = None
        self._srv = None
        self._spec_path: Optional[str] = None
        self._log_file = None
        self._dead = True

    # -------------------------------------------------------- lifecycle
    def start(self) -> None:
        """Spawn the child (cheap — the expensive engine build runs in
        the child while the parent does other work; pair with
        :meth:`wait_ready`, possibly after starting siblings)."""
        srv, port = rpc.listen_local()
        self._srv = srv
        fd, path = tempfile.mkstemp(prefix=f"replica_{self.name}_",
                                    suffix=".json")
        with os.fdopen(fd, "w") as f:
            json.dump(self.spec, f)
        self._spec_path = path
        if self._log_path:
            self._log_file = open(self._log_path, "ab")
            out = self._log_file
        else:
            out = subprocess.DEVNULL
        self._proc = subprocess.Popen(
            [self._python, "-m",
             "deepspeed_tpu.inference.replica_worker",
             "--port", str(port), "--spec", path,
             "--connect_timeout_s", str(self._ready_timeout_s)],
            env={**os.environ, **self._env},
            stdout=out, stderr=subprocess.STDOUT)

    def wait_ready(self) -> None:
        """Block until the child's ready frame (or its build failure).
        Raises :class:`~.rpc.ReplicaDeadError` if it never connects."""
        srv, self._srv = self._srv, None
        if srv is None:
            raise RuntimeError(f"replica {self.name}: start() first")
        srv.settimeout(self._ready_timeout_s)
        try:
            conn, _addr = srv.accept()
        except OSError as e:
            raise ReplicaDeadError(
                f"replica {self.name}: child never connected "
                f"({e!r})") from e
        finally:
            srv.close()
        conn.settimeout(self._ready_timeout_s)
        ready, _payload = rpc.recv_frame(conn)
        if not ready.get("ok"):
            err = (ready.get("error") or {}).get("message", "?")
            self.poll_exit()
            raise ReplicaDeadError(
                f"replica {self.name}: engine build failed: {err}")
        hello = ready["result"]
        self.pid = hello.get("pid")
        self.flight_path = hello.get("flight_path")
        self._client = rpc.RpcClient(
            conn, timeout_s=self._timeout_s, retries=self._retries,
            backoff_s=self._backoff_s, name=self.name)
        self._dead = False
        self._sync(hello.get("state") or {})
        logger.info(f"replica {self.name}: child pid {self.pid} ready "
                    f"(flight={self.flight_path})")

    def relaunch(self) -> None:
        """Supervised restart: fresh child, fresh engine, empty state.
        The caller (router) re-dispatches whatever the dead child held."""
        if self._proc is not None and self._proc.poll() is None:
            raise RuntimeError(
                f"replica {self.name}: relaunch while child alive")
        if self._client is not None:
            self._client.close()
            self._client = None
        self.scheduler = _ProcScheduler()
        self._requests = {}
        self.weight_version = "initial"
        self.weight_ordinal = 0
        self.steady_state_recompiles = -1
        self.total_dispatches = None
        self._can_migrate = False
        self.start()
        self.wait_ready()

    def poll_exit(self, timeout_s: float = 10.0) -> Optional[int]:
        """Reap the child; returns its exit code (None if still up)."""
        if self._proc is None:
            return None
        try:
            return self._proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            return None

    def close(self) -> None:
        if self._client is not None and not self._dead:
            try:
                self._client.call("shutdown", timeout_s=30.0)
            except RpcError:
                pass
            self._dead = True
        if self._client is not None:
            self._client.close()
            self._client = None
        if self._proc is not None:
            try:
                self._proc.wait(timeout=30.0)
            except subprocess.TimeoutExpired:
                self._proc.kill()
                self._proc.wait(timeout=10.0)
        if self._log_file is not None:
            self._log_file.close()
            self._log_file = None
        if self._spec_path:
            try:
                os.unlink(self._spec_path)
            except OSError:
                pass
            self._spec_path = None

    # ------------------------------------------------------- rpc plumbing
    def _sync(self, state: Dict[str, Any]) -> None:
        sched = self.scheduler
        sched.active_uids = list(state.get("active_uids") or [])
        sched.mid_decode_uids = list(state.get("mid_decode_uids") or [])
        sched.occupancy = float(state.get("occupancy") or 0.0)
        sched.total_tokens = int(state.get("total_tokens") or 0)
        sched._idle = bool(state.get("idle", True))
        sched.allocator.pages_in_use = state.get("pages_in_use")
        sched.queue = [self._requests[u]
                       for u in (state.get("queued_uids") or [])
                       if u in self._requests]
        self.weight_version = state.get("weight_version",
                                        self.weight_version)
        self.weight_ordinal = state.get("weight_ordinal",
                                        self.weight_ordinal)
        self.steady_state_recompiles = state.get(
            "steady_state_recompiles", self.steady_state_recompiles)
        if state.get("dispatches") is not None:
            self.total_dispatches = int(state["dispatches"])
        self._can_migrate = bool(state.get("can_migrate", False))

    def _call(self, method: str, params: Optional[Dict] = None,
              payload: bytes = b"",
              timeout_s: Optional[float] = None) -> Tuple[Any, bytes]:
        if self._dead or self._client is None:
            raise ReplicaDeadError(
                f"replica {self.name}: channel already dead",
                method=method)
        try:
            res, out = self._client.call(method, params, payload,
                                         timeout_s=timeout_s)
        except ReplicaDeadError:
            self._dead = True
            raise
        if isinstance(res, dict) and res.get("dying"):
            # the deathbed frame: last reply on this channel, carrying
            # every in-flight request's live pages + the queued backlog
            self._dead = True
            exports = rpc.decode_migrations(res.get("exports") or [],
                                            out)
            for rec in exports:
                # exported requests answer through migration (or its
                # resubmit fallback), NOT through orphans() — exactly
                # one FinishedRequest per uid
                self._requests.pop(rec.uid, None)
            err = ReplicaDeadError(
                f"replica {self.name}: died during {method} "
                f"({res.get('reason')})", method=method,
                exports=exports, reason=res.get("reason"))
            raise err
        if isinstance(res, dict) and "state" in res:
            self._sync(res["state"])
        return res, out

    # ---------------------------------------------- engine host surface
    def submit(self, request: Request) -> int:
        self._requests[request.uid] = request
        try:
            self._call("submit",
                       {"request": rpc.request_to_wire(request)})
        except RpcError:
            self._requests.pop(request.uid, None)
            raise
        return request.uid

    def cancel(self, uid: int,
               reason: str = "evicted") -> Optional[FinishedRequest]:
        res, _ = self._call("cancel", {"uid": uid, "reason": reason})
        self._requests.pop(uid, None)
        fin = res.get("fin")
        return None if fin is None else FinishedRequest(**fin)

    def step(self) -> List[FinishedRequest]:
        res, _ = self._call("step")
        fins = [FinishedRequest(**d) for d in res.get("fins") or []]
        for f in fins:
            self._requests.pop(f.uid, None)
        return fins

    def export_request(self, uid: int):
        res, payload = self._call("export_request", {"uid": uid})
        head = res.get("header")
        if head is None:
            return None
        self._requests.pop(uid, None)
        return rpc.migration_from_wire(head, payload)

    def import_request(self, rec) -> Optional[int]:
        head, payload = rpc.migration_to_wire(rec)
        res, _ = self._call("import_request", {"header": head},
                            payload=payload)
        sid = res.get("slot")
        if sid is not None:
            # track the resumed request router-side like any other
            self._requests[rec.uid] = rpc.request_from_wire({
                "prompt": rec.prompt,
                "max_new_tokens": rec.max_new_tokens,
                "temperature": rec.temperature, "seed": rec.seed,
                "eos_id": rec.eos_id, "priority": rec.priority,
                "uid": rec.uid})
        return sid

    def swap_params(self, load_dir, tag=None,
                    verify_integrity: bool = True) -> str:
        res, _ = self._call("swap_params",
                            {"load_dir": str(load_dir), "tag": tag,
                             "verify_integrity": verify_integrity})
        return res["weight_version"]

    def set_speculation(self, on: bool) -> bool:
        try:
            res, _ = self._call("set_speculation", {"on": bool(on)})
        except RpcError:
            return False
        return bool(res.get("changed"))

    def clock_ping(self, samples: int = 3) -> Dict[str, float]:
        """Estimate the child's wall-clock offset against this process
        (midpoint method): the child replies with its ``time.time()``;
        we bracket the call with our own ``t0``/``t1`` and take
        ``offset = t_child - (t0 + t1) / 2``, true to within
        ``uncertainty = (t1 - t0) / 2`` (the reply can have landed
        anywhere inside the round trip). Of ``samples`` exchanges the
        minimum-RTT one wins — it carries the tightest bound. The
        router records the result as a ``clock_sync`` event row so
        offline log merging (``obs_report --fleet``) can align replica
        timelines without trusting any single wall clock."""
        best: Optional[Tuple[float, float]] = None
        for _ in range(max(1, int(samples))):
            t0 = time.time()
            res, _ = self._call("clock_ping", {})
            t1 = time.time()
            rtt = t1 - t0
            offset = float(res["t_child"]) - (t0 + t1) / 2.0
            if best is None or rtt < best[1]:
                best = (offset, rtt)
        return {"offset_s": best[0], "uncertainty_s": best[1] / 2.0,
                "rtt_s": best[1]}

    @property
    def can_migrate(self) -> bool:
        return self._can_migrate and not self._dead

    def orphans(self) -> List[Request]:
        """Requests the dead child still owed answers for (queued +
        any in-flight the deathbed could not export) — the router
        redistributes these with the same uids and seeds."""
        return list(self._requests.values())


def launch_replica_processes(spec: Dict[str, Any], count: int, *,
                             fleet_config: Optional[Dict] = None,
                             env_by_replica: Optional[
                                 Dict[int, Dict[str, str]]] = None,
                             spec_by_replica: Optional[
                                 Dict[int, Dict[str, Any]]] = None,
                             python: Optional[str] = None,
                             log_dir: Optional[str] = None
                             ) -> List[ReplicaProcess]:
    """Spawn ``count`` replica children in parallel (all ``start()``
    first, so their engine builds overlap, then ``wait_ready()`` each)
    and return the proxies — ready to hand to :class:`FleetRouter`.
    ``env_by_replica`` injects per-child env vars (the kill tests arm
    ``DSTPU_FAULT_ARM`` in exactly one child this way);
    ``spec_by_replica`` shallow-merges per-child spec overrides (e.g.
    a distinct ``observability.health.flight_path`` per child, so the
    black boxes don't clobber each other)."""
    pm = _normalize_fleet_config(fleet_config)["process_mode"]
    reps = []
    for i in range(count):
        merged = {**spec, **(spec_by_replica or {}).get(i, {})}
        # stamp the fleet identity into the child's serve-tracer config
        # (unless the caller already picked one): every event row the
        # child writes carries ``replica_id``, so the offline fleet
        # merger attributes rows without trusting directory names
        obs = dict(merged.get("observability") or {})
        srv = dict(obs.get("serve") or {})
        srv.setdefault("replica_id", i)
        obs["serve"] = srv
        merged["observability"] = obs
        reps.append(ReplicaProcess(
            merged, name=f"r{i}",
            rpc_timeout_s=pm["rpc_timeout_s"],
            rpc_retries=pm["rpc_retries"],
            rpc_backoff_s=pm["rpc_backoff_s"],
            ready_timeout_s=pm["ready_timeout_s"],
            env=(env_by_replica or {}).get(i),
            python=python,
            log_path=(os.path.join(log_dir, f"replica_{i}.log")
                      if log_dir else None)))
    try:
        for r in reps:
            r.start()
        for r in reps:
            r.wait_ready()
    except BaseException:
        for r in reps:
            try:
                r.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        raise
    return reps


class FleetRouter:
    """Route requests across N in-process engine replicas; shed by
    SLO, drain through preemptions, swap weights live.

    ``engines`` are already-warmed :class:`~.engine.InferenceEngine`
    instances (duck-typed: anything with the engine's host surface —
    ``submit/step/cancel/scheduler/swap_params/set_speculation``).
    ``fleet_config`` is a raw ``inference.fleet`` dict (normalized and
    validated through ``runtime/config.py``). Telemetry reuses the
    first engine's monitor and events.jsonl writer unless overridden —
    the fleet trail interleaves with the per-request serve trail, one
    timeline per run.

    Drive it like an engine: ``submit`` then ``run`` (or ``step`` in a
    serving loop). ``run`` returns exactly one :class:`FinishedRequest`
    per submitted uid — shed requests get a synthesized zero-token
    response (``finish_reason`` from the pinned shed vocabulary), never
    a dropped uid.
    """

    #: fleet_state event / scalar cadence (router steps)
    _STATE_EVERY = 16
    #: periodic clock re-sync cadence (router steps) — cheap (one
    #: tiny RPC per replica) but offsets drift slowly, so sparse
    _CLOCK_SYNC_EVERY = 256

    def __init__(self, engines: Sequence[Any], fleet_config=None,
                 monitor=None, writer=None,
                 install_signal_handlers: bool = False,
                 clock=time.perf_counter,
                 replica_factory: Optional[Callable[[int], Any]] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 health=None):
        if not engines:
            raise ValueError("FleetRouter needs at least one engine")
        self.cfg = _normalize_fleet_config(fleet_config)
        self._clock = clock
        self._sleep = sleep
        # autoscale's spawn hook: (replica_idx) -> engine-like. For a
        # process fleet this respawns a ReplicaProcess; in-process
        # tests hand in a lambda.
        self._factory = replica_factory
        self.replicas = [ReplicaHandle(i, e, PreemptionGuard())
                         for i, e in enumerate(engines)]
        if install_signal_handlers:
            # chain-installed: a real SIGTERM reaches the last guard —
            # ONE replica drains, the fleet keeps serving (the process-
            # level analog of a preempted pod). Software triggers
            # (drain()/request_preemption) don't need handlers.
            for r in self.replicas:
                r.guard.install()
        self.monitor = monitor if monitor is not None else \
            getattr(engines[0], "monitor", None)
        self._log = writer if writer is not None else \
            getattr(engines[0], "_log", None)
        # env-armed serve-plane faults (DSTPU_FAULT_ARM) — latched
        # no-op when another component already armed this process
        fault.arm_from_env()
        # health plane: the router beats the FIRST replica's watchdog
        # once per scheduling round (duck-typed like monitor/_log — a
        # fleet of stubs without one simply has no fleet heartbeat).
        # Process replicas have no in-process .health, so a process-
        # mode router passes its OWN HealthPlane via the kwarg — the
        # rpc_call beats then name which replica a hung wait was on.
        self.health = health if health is not None else \
            getattr(engines[0], "health", None)
        # distributed tracing: the router mints every trace id (one
        # per client request, monotonic — no RNG, no wall clock in the
        # id itself, so traced runs stay bitwise-reproducible)
        self._trace_seq = itertools.count()
        self._trace_prefix = f"f{os.getpid():x}"
        self._steps = 0
        self._pending: List[FinishedRequest] = []
        # ladder + ledger
        self.total_submitted = 0
        self.total_shed = 0
        self.shed_by_reason: Dict[str, int] = {}
        self.shed_by_priority: Dict[int, int] = {}
        self.total_degraded = 0
        self.total_redistributed = 0
        self.total_reroutes = 0
        # process-mode robustness ledger (ISSUE 16)
        self.total_migrated = 0          # live requests moved alive
        self.migration_bytes = 0         # slab bytes shipped
        self.migration_priced_ms = 0.0   # LinkModel-priced wire time
        self.total_restarts = 0          # supervised relaunches
        self.total_salvaged = 0          # dead-child flight recorders
        # autoscale hysteresis state
        self._shed_streak = 0
        self._idle_streak = 0
        self._as_cooldown = 0
        self._mig_link = None            # lazy LinkModel (pricing)
        self._spec_degraded = False
        sh = self.cfg["slo_shed"]
        self._budget_ms = sh["ttft_budget_ms"]
        if self._budget_ms is None:
            # fall back to the serve SLO the tracers already enforce
            tr = getattr(engines[0], "_tracer", None)
            self._budget_ms = float(getattr(tr, "slo_ttft_ms", 2000.0))
        logger.info(
            f"fleet router: {len(self.replicas)} replicas, "
            f"routing={self.cfg['routing']}, slo_shed="
            f"{'on' if sh['enabled'] else 'off'} "
            f"(p95 TTFT budget {self._budget_ms:.0f} ms)")
        # initial clock alignment (process replicas only — in-process
        # engines share our clock, offset is definitionally zero)
        self._sync_clocks()

    # ---------------------------------------------------------- events
    def _event(self, kind: str, **fields) -> None:
        if self._log is not None:
            self._log.add_event(kind, **fields)

    def _beat_rpc(self, r: "ReplicaHandle") -> None:
        """Heartbeat the ``rpc_call`` phase before a blocking wait on a
        process replica, naming WHICH replica — a watchdog trip during
        a hung RPC then reads ``rpc_call (replica 2)``, not a generic
        fleet stall. In-process engines don't block on a wire, so the
        beat is skipped (phase attribution stays precise)."""
        if self.health is not None and \
                hasattr(r.engine, "poll_exit"):
            self.health.heartbeat("rpc_call",
                                  detail=f"replica {r.idx}")

    # ------------------------------------------------- clock alignment
    def _sync_clocks(self) -> None:
        """Estimate every process replica's wall-clock offset (midpoint
        method: ``offset = t_child - (t0 + t1)/2``, uncertainty =
        half the best RTT) and record a ``clock_sync`` trail row per
        replica. The offline fleet merger (obs_report --fleet) uses the
        latest row per replica to place that replica's event rows on
        the router's timeline; the uncertainty bounds how much apparent
        reordering is attributable to clock skew vs. a real anomaly."""
        for r in self.replicas:
            if r.status != LIVE:
                continue
            ping = getattr(r.engine, "clock_ping", None)
            if ping is None:
                continue
            self._beat_rpc(r)
            try:
                est = ping()
            except (RpcError, OSError, ReplicaDeadError) as e:
                logger.warning(f"fleet clock sync: replica {r.idx} "
                               f"ping failed ({e!r}); skipping")
                continue
            self._event("clock_sync", replica=r.idx,
                        offset_ms=round(est["offset_s"] * 1e3, 4),
                        uncertainty_ms=round(
                            est["uncertainty_s"] * 1e3, 4),
                        rtt_ms=round(est["rtt_s"] * 1e3, 4))

    # ------------------------------------------------------ shed ladder
    def _ttft_stats(self):
        """Aggregate (samples, worst p95) over serving replicas — the
        goodput histograms the tracers already keep."""
        count, p95 = 0, None
        for r in self.replicas:
            if r.status == RETIRED:
                continue
            tr = getattr(r.engine, "_tracer", None)
            if tr is None:
                continue
            h = tr.hist.get("ttft_ms")
            if h is None or not h.count:
                continue
            count += h.count
            v = h.percentile(0.95)
            if v is not None:
                p95 = v if p95 is None else max(p95, v)
        return count, p95

    def shed_level(self) -> int:
        """0 = healthy, 1 = shed rung (reject low tiers), 2 = degrade
        rung (cap max_new + speculation off)."""
        sh = self.cfg["slo_shed"]
        if not sh["enabled"]:
            return 0
        count, p95 = self._ttft_stats()
        if p95 is None or count < sh["min_samples"]:
            return 0
        if p95 > self._budget_ms * sh["degrade_factor"]:
            return 2
        if p95 > self._budget_ms:
            return 1
        return 0

    def _shed(self, req: Request, reason: str,
              **extra) -> FinishedRequest:
        """Synthesize the rejection response: the client gets exactly
        one FinishedRequest per uid — a shed is a (zero-token) answer,
        never a dropped request."""
        prio = getattr(req, "priority", 0)
        self.total_shed += 1
        self.shed_by_reason[reason] = \
            self.shed_by_reason.get(reason, 0) + 1
        self.shed_by_priority[prio] = \
            self.shed_by_priority.get(prio, 0) + 1
        self._event("fleet_shed", uid=req.uid, reason=reason,
                    priority=prio, **extra)
        fin = FinishedRequest(uid=req.uid, prompt=list(req.prompt),
                              tokens=[], finish_reason=reason,
                              ttft_ms=None, latency_ms=0.0)
        self._pending.append(fin)
        return fin

    def _apply_spec_degrade(self, level: int) -> None:
        want = level >= 2
        if want == self._spec_degraded:
            return
        changed = 0
        for r in self.replicas:
            if r.status == RETIRED:
                continue
            if getattr(r.engine, "set_speculation",
                       lambda on: False)(not want):
                changed += 1
        self._spec_degraded = want
        if changed:
            self._event("fleet_shed", reason="degrade_spec_off",
                        enabled=want, replicas=changed)

    # ---------------------------------------------------------- routing
    def _ranked(self, req: Optional[Request]) -> List[ReplicaHandle]:
        """Live replicas, best dispatch target first."""
        live = [r for r in self.replicas if r.status == LIVE]
        if self.cfg["routing"] == "prefix_affinity" and req is not None:
            return sorted(live, key=lambda r: (-r.prefix_tokens(
                req.prompt), r.load(), r.idx))
        return sorted(live, key=lambda r: (r.load(), r.idx))

    def _dispatch(self, req: Request) -> Optional[ReplicaHandle]:
        """Hand ``req`` to the best live replica; a transient
        ``serve.dispatch`` fault reroutes to the next-best instead of
        dropping. None = no replica accepted (caller sheds)."""
        t0 = self._clock()
        for r in self._ranked(req):
            try:
                fault.fire("serve.dispatch", replica=r.idx, uid=req.uid)
                self._beat_rpc(r)
                r.engine.submit(req)
            except ReplicaDeadError as e:
                # a process replica died under us: run the full death
                # protocol (salvage/migrate/redistribute/relaunch) now,
                # then keep looking for a home for THIS request
                self._on_replica_death(r, e)
                self.total_reroutes += 1
                continue
            except (fault.InjectedCrash, OSError, RpcError) as e:
                r.dispatch_faults += 1
                self.total_reroutes += 1
                logger.warning(f"fleet dispatch fault on replica "
                               f"{r.idx} (uid {req.uid}): {e!r}; "
                               f"rerouting")
                continue
            r.routed += 1
            # the trace spine: every placement writes one row tying
            # (trace_id, hop) to a replica, with the router-side route
            # cost. The fleet merger anchors each request's timeline
            # here — rpc_wire = replica's serve_submit.t (aligned)
            # minus this row's t.
            self._event("fleet_dispatch", uid=req.uid,
                        trace_id=getattr(req, "trace_id", None),
                        hop=getattr(req, "hop", 0), replica=r.idx,
                        route_ms=round((self._clock() - t0) * 1e3, 4))
            return r
        return None

    # ----------------------------------------------------------- submit
    def submit(self, request: Request) -> int:
        """Admit (or shed) one request; returns its uid either way —
        the response arrives through :meth:`step`/:meth:`run`."""
        self.total_submitted += 1
        # mint the trace context at the fleet's front door: one id per
        # client request, hop 0. Already-stamped requests (a caller
        # propagating an upstream trace) keep their id.
        if getattr(request, "trace_id", None) is None:
            request.trace_id = \
                f"{self._trace_prefix}-{next(self._trace_seq):06x}"
            request.hop = 0
        prio = getattr(request, "priority", 0)
        level = self.shed_level()
        self._apply_spec_degrade(level)
        sh = self.cfg["slo_shed"]
        if level >= 1 and prio < sh["shed_below_priority"]:
            _count, p95 = self._ttft_stats()
            self._shed(request, "shed_slo", p95_ttft_ms=p95,
                       budget_ms=self._budget_ms, level=level)
            return request.uid
        if level >= 2 and sh["degrade_max_new"] > 0 and \
                request.max_new_tokens > sh["degrade_max_new"]:
            # replace() preserves uid/seed — only the budget shrinks
            request = replace(request,
                              max_new_tokens=sh["degrade_max_new"])
            self.total_degraded += 1
            self._event("fleet_shed", uid=request.uid,
                        reason="degrade_max_new", priority=prio,
                        max_new_tokens=request.max_new_tokens)
        if self._dispatch(request) is None:
            self._shed(request, "shed_capacity",
                       live=[r.idx for r in self.replicas
                             if r.status == LIVE])
        return request.uid

    # ------------------------------------------------------------ drain
    def drain(self, replica: int, reason: str = "manual") -> None:
        """Software-preempt one replica (the SIGTERM-equivalent). The
        actual drain runs at the next :meth:`step`. Idempotent: a
        second drain of an already-draining (or retired) replica is a
        no-op — the episode must not restart, requests must not be
        redistributed twice."""
        r = self.replicas[replica]
        if r.status != LIVE:
            logger.info(f"fleet drain: replica {replica} already "
                        f"{r.status}; ignoring duplicate drain")
            return
        r.guard.trigger(reason)

    def _begin_drain(self, r: ReplicaHandle) -> None:
        if r.status != LIVE:
            return  # idempotency backstop (double trigger in one step)
        r.status = DRAINING
        r.drain_reason = r.guard.reason or "preempted"
        survivors = [s for s in self.replicas if s.status == LIVE]
        queued = list(r.engine.scheduler.queue)
        in_flight = len(r.engine.scheduler.active_slots())
        self._event("fleet_drain", phase="begin", replica=r.idx,
                    reason=r.drain_reason, queued=len(queued),
                    in_flight=in_flight,
                    survivors=[s.idx for s in survivors])
        logger.info(
            f"fleet drain: replica {r.idx} ({r.drain_reason}) — "
            f"{in_flight} in flight finish here, {len(queued)} queued "
            f"redistribute over {len(survivors)} survivors")
        if survivors and queued:
            for req in queued:
                # the cancel's serve_evict row (reason "drain") is
                # drain bookkeeping, not the client's answer —
                # _collect drops it; the SAME Request object (uid,
                # seed, budget) goes to a survivor, whose prefix cache
                # re-prefills it
                r.engine.cancel(req.uid, reason="drain")
                self.total_redistributed += 1
                if self._dispatch(req) is None:
                    self._shed(req, "shed_capacity",
                               drained_from=r.idx)
        if survivors:
            # in-flight requests: ship their live KV pages to a
            # survivor so decode resumes at the same cache_position —
            # no re-prefill, bitwise-identical outputs. Falls back to
            # finish-in-place when either side can't migrate.
            self._migrate_active(r)

    # -------------------------------------------------- live migration
    def _price_migration(self, rec) -> float:
        """LinkModel-priced wire cost of one migration (the disagg
        handoff price model, inter-host axis)."""
        try:
            if self._mig_link is None:
                from deepspeed_tpu.runtime.comm_autotune import \
                    LinkModel
                self._mig_link = LinkModel()
            return price_handoff(rec.live_pages, rec.page_bytes,
                                 self._mig_link, axis="inter")
        except Exception:  # noqa: BLE001 — pricing is advisory
            return 0.0

    def _place_migration(self, rec, source: ReplicaHandle) -> bool:
        """Import one exported request into the best live replica that
        can. True = resumed somewhere (``serve_migration`` trail row);
        False = the caller falls back to a full resubmit."""
        for t in self._ranked(None):
            if t is source or not getattr(t.engine, "can_migrate",
                                          False):
                continue
            t0 = self._clock()
            try:
                self._beat_rpc(t)
                sid = t.engine.import_request(rec)
            except (RpcError, OSError) as e:
                logger.warning(f"fleet migration: import of uid "
                               f"{rec.uid} into replica {t.idx} "
                               f"failed ({e!r})")
                continue
            if sid is None:
                continue  # target full or geometry mismatch; try next
            transfer_ms = (self._clock() - t0) * 1e3
            priced_ms = self._price_migration(rec)
            self.total_migrated += 1
            self.migration_bytes += rec.nbytes
            self.migration_priced_ms += priced_ms
            source.migrations_out += 1
            source.migration_bytes += rec.nbytes
            source.migration_priced_ms += priced_ms
            t.migrations_in += 1
            t.routed += 1
            self._event("serve_migration", uid=rec.uid,
                        trace_id=getattr(rec, "trace_id", None),
                        hop=getattr(rec, "hop", 0),
                        src=source.idx, dst=t.idx,
                        pages=rec.live_pages, nbytes=rec.nbytes,
                        position=rec.position,
                        transfer_ms=round(transfer_ms, 3),
                        priced_ms=round(priced_ms, 4))
            logger.info(
                f"fleet migration: uid {rec.uid} "
                f"{source.idx} -> {t.idx} ({rec.live_pages} pages, "
                f"{rec.nbytes} B, resumes at position {rec.position})")
            return True
        return False

    def _resubmit_record(self, rec, source: ReplicaHandle) -> None:
        """Migration fallback: rebuild the original Request (same uid,
        same seed — deterministic sampling gives the same answer, just
        re-decoded from a fresh prefill) and dispatch it."""
        req = Request(prompt=list(rec.prompt),
                      max_new_tokens=rec.max_new_tokens,
                      temperature=rec.temperature, seed=rec.seed,
                      eos_id=rec.eos_id, priority=rec.priority,
                      uid=rec.uid,
                      # resubmit is still a hop of the SAME trace —
                      # lineage survives even the fallback path
                      trace_id=getattr(rec, "trace_id", None),
                      hop=int(getattr(rec, "hop", 0)) + 1)
        self.total_redistributed += 1
        if self._dispatch(req) is None:
            self._shed(req, "shed_capacity", drained_from=source.idx)

    def _migrate_active(self, r: ReplicaHandle) -> None:
        """Move every in-flight request off ``r`` alive. Requires both
        sides warmed for migration (``engine.warm_migration``);
        otherwise in-flight work finishes where it is (in-process
        drain keeps its PR 14 finish-in-place semantics)."""
        if not getattr(r.engine, "can_migrate", False):
            return
        for uid in r.active_uids():
            try:
                self._beat_rpc(r)
                rec = r.engine.export_request(uid)
            except (RpcError, OSError) as e:
                logger.warning(f"fleet migration: export of uid {uid} "
                               f"from replica {r.idx} failed ({e!r})")
                continue
            if rec is None:
                continue  # not exportable (no pending token yet)
            if not self._place_migration(rec, r):
                self._resubmit_record(rec, r)

    # ------------------------------------------------------------- step
    def _collect(self, fins: List[FinishedRequest]
                 ) -> List[FinishedRequest]:
        # "drain"/"migrate" evictions are router bookkeeping (the
        # request answers elsewhere), not the client's response
        return [f for f in fins
                if f.finish_reason not in ("drain", "migrate")]

    def step(self) -> List[FinishedRequest]:
        """One fleet scheduling round: react to preemptions, advance
        every serving replica one engine step, retire empty drains.
        Returns the requests that finished (shed responses included)."""
        out: List[FinishedRequest] = []
        out.extend(self._pending)
        self._pending = []
        if self.health is not None:
            self.health.heartbeat("fleet_step")
        for r in self.replicas:
            if r.status == RETIRED:
                continue
            try:
                # the per-replica preemption probe: a raised injection
                # preempts THIS replica (the env grammar's targeted
                # form); the "preempt" action instead flags installed
                # guards, exactly like a real SIGTERM
                fault.fire("serve.replica_preempt", replica=r.idx)
            except (fault.InjectedCrash, OSError) as e:
                r.guard.trigger(f"fault:{type(e).__name__}")
            if r.status == LIVE and r.guard.preempted:
                self._begin_drain(r)
        for r in self.replicas:
            if r.status == RETIRED:
                continue
            if not r.idle():
                try:
                    self._beat_rpc(r)
                    out.extend(self._collect(r.engine.step()))
                except ReplicaDeadError as e:
                    self._on_replica_death(r, e)
                    continue
            if r.status == DRAINING and r.idle():
                r.status = RETIRED
                self._event("fleet_drain", phase="complete",
                            replica=r.idx, reason=r.drain_reason)
                logger.info(f"fleet drain: replica {r.idx} retired")
        self._apply_spec_degrade(self.shed_level())
        self._autoscale_tick()
        self._steps += 1
        if self._steps % self._STATE_EVERY == 0:
            self._write_telemetry()
        if self._steps % self._CLOCK_SYNC_EVERY == 0:
            self._sync_clocks()
        return out

    # ------------------------------------------------ death supervision
    def _on_replica_death(self, r: ReplicaHandle,
                          err: ReplicaDeadError) -> None:
        """A replica's channel died mid-step. In order: mark it gone,
        salvage its flight recorder, resume its exported in-flight
        requests on survivors (live pages, bitwise-preserving),
        redistribute everything else it owed (same uids/seeds), then
        maybe relaunch it under the launcher's restart policy."""
        r.status = RETIRED
        reason = getattr(err, "reason", None) or str(err)
        r.drain_reason = f"died:{reason}"
        poll = getattr(r.engine, "poll_exit", None)
        code = poll() if poll is not None else None
        r.last_exit_code = code
        exports = list(getattr(err, "exports", None) or [])
        self._event("fleet_replica_death", replica=r.idx,
                    reason=reason, exit_code=code,
                    exports=len(exports))
        logger.warning(
            f"fleet: replica {r.idx} died ({reason}, exit={code}); "
            f"{len(exports)} in-flight exports to place")
        # 1) the black box: the dead child's flight_serve.json becomes
        #    a row in OUR trail — the postmortem survives the process
        flight_path = getattr(r.engine, "flight_path", None)
        flight = load_flight(flight_path) if flight_path else None
        if flight is not None:
            self.total_salvaged += 1
            self._event(
                "fleet_flight_salvage", replica=r.idx,
                flight=str(flight_path),
                trigger=flight.get("trigger"),
                dead_pid=flight.get("pid"),
                dead_reason=flight.get("reason"),
                rows=len(flight.get("rows") or []))
            logger.info(f"fleet: salvaged flight recorder of replica "
                        f"{r.idx} ({flight_path})")
        # 2) deathbed exports: resume each on a survivor at the same
        #    cache_position; full resubmit only if no one can import
        for rec in exports:
            if not self._place_migration(rec, r):
                self._resubmit_record(rec, r)
        # 3) everything else the dead child owed (queued backlog +
        #    in-flight it could not export): redistribute
        orphans = getattr(r.engine, "orphans", None)
        for req in (orphans() if orphans is not None else []):
            self.total_redistributed += 1
            if self._dispatch(req) is None:
                self._shed(req, "shed_capacity", drained_from=r.idx)
        # 4) supervised relaunch — the launcher's restart policy
        #    decides (85/87 restart-eligible, anything else gives up)
        self._maybe_relaunch(r, code)

    def _maybe_relaunch(self, r: ReplicaHandle,
                        code: Optional[int]) -> None:
        relaunch = getattr(r.engine, "relaunch", None)
        if relaunch is None:
            return
        from deepspeed_tpu.launcher.runner import restart_eligible
        pm = self.cfg["process_mode"]
        if not restart_eligible(code):
            self._event("fleet_replica_restart", replica=r.idx,
                        decision="give_up", exit_code=code)
            logger.warning(f"fleet: replica {r.idx} exit code {code} "
                           f"not restart-eligible; staying retired")
            return
        if r.restarts >= pm["max_restarts"]:
            self._event("fleet_replica_restart", replica=r.idx,
                        decision="exhausted", exit_code=code,
                        restarts=r.restarts)
            logger.warning(f"fleet: replica {r.idx} restart budget "
                           f"exhausted ({r.restarts})")
            return
        delay = pm["restart_backoff_s"] * (2 ** r.restarts)
        if delay > 0:
            self._sleep(delay)
        try:
            relaunch()
        except Exception as e:  # noqa: BLE001 — a failed relaunch retires
            self._event("fleet_replica_restart", replica=r.idx,
                        decision="failed", exit_code=code,
                        error=f"{type(e).__name__}: {e}")
            logger.warning(
                f"fleet: replica {r.idx} relaunch failed ({e!r})")
            return
        r.restarts += 1
        r.status = LIVE
        r.drain_reason = None
        r.guard = PreemptionGuard()
        self.total_restarts += 1
        self._event("fleet_replica_restart", replica=r.idx,
                    decision="restarted", exit_code=code,
                    restarts=r.restarts, backoff_s=delay,
                    pid=getattr(r.engine, "pid", None))
        logger.info(f"fleet: replica {r.idx} relaunched "
                    f"(restart {r.restarts}, backoff {delay:g}s)")
        # the fresh child is a fresh clock — re-estimate its offset so
        # post-restart rows still align on the merged timeline
        self._sync_clocks()

    # -------------------------------------------------------- autoscale
    def _autoscale_tick(self) -> None:
        """Goodput-driven fleet sizing, evaluated once per router step:
        sustained rung-1+ shedding spawns a replica (needs the
        ``replica_factory`` hook), sustained idleness drains the
        least-loaded one via live migration. Hysteresis (patience
        streaks) + cooldown keep it from flapping; never below
        ``min_replicas``, never above ``max_replicas``, never a
        dropped request."""
        asc = self.cfg["autoscale"]
        if not asc["enabled"]:
            return
        if self._as_cooldown > 0:
            self._as_cooldown -= 1
            return
        live = [r for r in self.replicas if r.status == LIVE]
        busy = self.fleet_queue_depth() > 0 or any(
            len(r.engine.scheduler.active_slots()) > 0 for r in live)
        if self.shed_level() >= 1:
            self._shed_streak += 1
        else:
            self._shed_streak = 0
        self._idle_streak = 0 if busy else self._idle_streak + 1
        if (self._shed_streak >= asc["scale_up_patience"]
                and len(live) < asc["max_replicas"]
                and self._factory is not None):
            idx = len(self.replicas)
            try:
                engine = self._factory(idx)
            except Exception as e:  # noqa: BLE001 — spawn can flake
                logger.warning(f"fleet autoscale: spawn failed ({e!r})")
                self._shed_streak = 0
                return
            self.replicas.append(
                ReplicaHandle(idx, engine, PreemptionGuard()))
            self._event("fleet_autoscale", action="up", replica=idx,
                        live=len(live) + 1,
                        shed_streak=self._shed_streak)
            logger.info(f"fleet autoscale: spawned replica {idx} "
                        f"(shed streak {self._shed_streak})")
            self._shed_streak = 0
            self._as_cooldown = asc["cooldown_steps"]
            return
        if (self._idle_streak >= asc["scale_down_patience"]
                and len(live) > asc["min_replicas"]):
            # least-loaded; ties retire the newest replica first
            victim = min(live, key=lambda r: (r.load(), -r.idx))
            self._event("fleet_autoscale", action="down",
                        replica=victim.idx, live=len(live) - 1,
                        idle_streak=self._idle_streak)
            logger.info(f"fleet autoscale: draining replica "
                        f"{victim.idx} (idle streak "
                        f"{self._idle_streak})")
            victim.guard.trigger("autoscale")
            self._idle_streak = 0
            self._as_cooldown = asc["cooldown_steps"]

    def idle(self) -> bool:
        return not self._pending and all(
            r.status == RETIRED or r.idle() for r in self.replicas)

    def run(self) -> List[FinishedRequest]:
        """Serve until every admitted request has answered (the fleet
        analog of ``engine.run``; responses in completion order)."""
        out: List[FinishedRequest] = []
        while not self.idle():
            out.extend(self.step())
        out.extend(self._pending)
        self._pending = []
        self._write_telemetry()
        return out

    # ------------------------------------------------ live weight swap
    def swap_weights(self, load_dir: str, tag: Optional[str] = None
                     ) -> Dict[int, Optional[str]]:
        """Push a committed checkpoint tag into every serving replica
        between dispatches. Per replica atomic-or-rollback: a failed
        load (bad tag, I/O flake, injected ``serve.swap_load``) leaves
        THAT replica serving its old weights and still live — the
        result maps replica -> new version (None = rolled back)."""
        verify = self.cfg["swap"]["verify_integrity"]
        results: Dict[int, Optional[str]] = {}
        for r in self.replicas:
            if r.status == RETIRED:
                continue
            try:
                results[r.idx] = r.engine.swap_params(
                    load_dir, tag=tag, verify_integrity=verify)
            except Exception as e:
                results[r.idx] = None
                logger.warning(
                    f"fleet swap: replica {r.idx} rolled back "
                    f"({e!r}); still serving "
                    f"{getattr(r.engine, 'weight_version', '?')}")
        self._event("fleet_swap_push", load_dir=str(load_dir), tag=tag,
                    versions={str(k): v for k, v in results.items()},
                    rolled_back=[k for k, v in results.items()
                                 if v is None])
        return results

    # -------------------------------------------------------- telemetry
    @property
    def shed_rate(self) -> float:
        return (self.total_shed / self.total_submitted
                if self.total_submitted else 0.0)

    def fleet_queue_depth(self) -> int:
        return sum(r.engine.scheduler.queue_depth for r in self.replicas
                   if r.status != RETIRED)

    def debug_state(self) -> Dict[str, Any]:
        """Host-only fleet introspection (mirrors the periodic
        ``fleet_state`` event row obs_report renders)."""
        count, p95 = self._ttft_stats()
        return {
            "routing": self.cfg["routing"],
            "steps": self._steps,
            "replicas": [r.snapshot() for r in self.replicas],
            "fleet_queue_depth": self.fleet_queue_depth(),
            "submitted": self.total_submitted,
            "shed": {"total": self.total_shed,
                     "rate": round(self.shed_rate, 4),
                     "by_reason": dict(self.shed_by_reason),
                     "by_priority": {str(k): v for k, v in
                                     self.shed_by_priority.items()},
                     "degraded": self.total_degraded,
                     "spec_degraded": self._spec_degraded,
                     "level": self.shed_level()},
            "slo": {"p95_ttft_ms": p95, "samples": count,
                    "budget_ms": self._budget_ms},
            "redistributed": self.total_redistributed,
            "reroutes": self.total_reroutes,
            "migrations": {"total": self.total_migrated,
                           "bytes": self.migration_bytes,
                           "priced_ms": round(self.migration_priced_ms,
                                              4)},
            "restarts": self.total_restarts,
            "salvaged_flights": self.total_salvaged,
        }

    def _write_telemetry(self) -> None:
        self._event("fleet_state", step=self._steps,
                    **self.debug_state())
        for r in self.replicas:
            # one per-replica process-health row (pid, restarts, exit
            # code, migration ledger) — obs_report's fleet table
            self._event("fleet_replica_state", step=self._steps,
                        **r.process_snapshot())
        if self.monitor is None or not hasattr(
                self.monitor, "write_serving_metrics"):
            return
        tokens = sum(r.engine.scheduler.total_tokens
                     for r in self.replicas)
        self.monitor.write_serving_metrics(
            shed_rate=self.shed_rate,
            fleet_queue_depth=self.fleet_queue_depth(),
            migrations=self.total_migrated,
            replica_restarts=self.total_restarts,
            tokens=tokens)

    # ---------------------------------------------------------- cleanup
    def close(self) -> None:
        """Uninstall guards and close every engine (final ``fleet_state``
        first, so the run report sees the fleet's last shape)."""
        self._write_telemetry()
        for r in self.replicas:
            r.guard.uninstall()
            close = getattr(r.engine, "close", None)
            if close is not None:
                close()
        self._log = None
