"""Inference serving engine (TPU-native extension — the reference
DeepSpeed v0.3.0 snapshot is training-only).

Bucketed jit-compiled prefill/decode over a preallocated, donated KV
cache; continuous-batching scheduler; checkpoint -> serving bridge
(params-only load, optional qwZ int8 weight distribution); serving
telemetry through the observability event log. See
``deepspeed_tpu/inference/engine.py`` and ``docs/inference.md``.
"""

from deepspeed_tpu.inference.buckets import (pad_prompts, pick_bucket,
                                             validate_buckets, warmup_plan)
from deepspeed_tpu.inference.engine import (InferenceEngine,
                                            qwz_distribute_params)
from deepspeed_tpu.inference.kv_cache import (KVCacheSpec, cache_spec_for,
                                              init_kv_cache,
                                              kv_cache_bytes)
from deepspeed_tpu.inference.scheduler import (FinishedRequest,
                                               PrefillBatch, Request,
                                               Scheduler)

__all__ = [
    "InferenceEngine", "Request", "FinishedRequest", "PrefillBatch",
    "Scheduler", "KVCacheSpec", "cache_spec_for", "init_kv_cache",
    "kv_cache_bytes", "pick_bucket", "pad_prompts", "validate_buckets",
    "warmup_plan", "qwz_distribute_params",
]
