"""Inference serving engine (TPU-native extension — the reference
DeepSpeed v0.3.0 snapshot is training-only).

Bucketed jit-compiled prefill/decode over a preallocated, donated KV
cache — paged (block tables + refcounted prefix caching) by default,
dense slot x max_len behind ``paged_kv.enabled: false`` — optionally
GSPMD-sharded over a serving mesh; continuous-batching scheduler with
bounded-lookahead admission; checkpoint -> serving bridge (params-only
load with serving-mesh resharding, optional qwZ int8 weight
distribution); serving telemetry through the observability event log.
See ``deepspeed_tpu/inference/engine.py`` and ``docs/inference.md``.
"""

from deepspeed_tpu.inference.buckets import (pad_prompts, pick_bucket,
                                             validate_buckets, warmup_plan)
from deepspeed_tpu.inference.disagg import (DispatchTrace, HandoffQueue,
                                            HandoffRecord,
                                            MigrationRecord,
                                            price_handoff)
from deepspeed_tpu.inference.draft import (CallableDrafter, NGramDrafter,
                                           make_drafter)
from deepspeed_tpu.inference.engine import (InferenceEngine,
                                            qwz_distribute_params)
from deepspeed_tpu.inference.fleet import (FleetRouter, ReplicaHandle,
                                           ReplicaProcess,
                                           launch_replica_processes)
from deepspeed_tpu.inference.kv_cache import (KVCacheSpec, PageAllocator,
                                              PagedKVSpec, cache_spec_for,
                                              init_kv_cache,
                                              init_paged_kv_cache,
                                              kv_cache_bytes, paged_kv_bytes,
                                              paged_spec_for, pages_for)
from deepspeed_tpu.inference.scheduler import (FinishedRequest,
                                               PrefillBatch, Request,
                                               Scheduler)
from deepspeed_tpu.inference.tracing import ServeTracer

__all__ = [
    "InferenceEngine", "Request", "FinishedRequest", "PrefillBatch",
    "Scheduler", "ServeTracer", "KVCacheSpec", "cache_spec_for",
    "init_kv_cache", "kv_cache_bytes", "PagedKVSpec", "PageAllocator",
    "paged_spec_for", "init_paged_kv_cache", "paged_kv_bytes",
    "pages_for", "pick_bucket", "pad_prompts", "validate_buckets",
    "warmup_plan", "qwz_distribute_params", "NGramDrafter",
    "CallableDrafter", "make_drafter", "HandoffQueue", "HandoffRecord",
    "DispatchTrace", "price_handoff", "FleetRouter", "ReplicaHandle",
    "ReplicaProcess", "launch_replica_processes", "MigrationRecord",
]
