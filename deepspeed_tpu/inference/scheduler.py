"""Continuous-batching scheduler for the serving engine.

Static-batch serving wastes slots: a batch of 8 runs at the speed of
its longest request while 7 finished rows decode garbage. Continuous
batching (Orca-style iteration-level scheduling) instead treats the
decode batch as SLOTS: every engine step, finished sequences (EOS /
max_tokens) are evicted and waiting requests are admitted into the
freed slots via a bucketed prefill — occupancy stays high under
heterogeneous request lengths.

This module is the pure host-side half: the admission queue (FIFO with
a bounded lookahead window so one request that doesn't fit the free
pages cannot stall everything behind it), slot table, bucket grouping
for admission, per-request sampling state (temperature + PRNG seed —
deterministic per request, independent of what else shares the batch),
and completion bookkeeping (TTFT, per-request token counts).

With a :class:`~deepspeed_tpu.inference.paging.PageAllocator` the
scheduler also owns PAGE management (the jit programs only ever see the
static-shape block tables it produces): admission reserves
``ceil((prompt + max_new_tokens) / page_size)`` pages up front (no
mid-flight eviction needed), prefix-cache hits replace the leading
page-aligned prompt pages with shared refcounted ones (the engine then
prefills only the suffix), and eviction returns pages to the pool.

The jit-facing half (padded arrays, paged scatter/gather) lives in
``inference/engine.py``; nothing here imports jax, so scheduler policy
is unit-testable in microseconds.
"""

import itertools
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from deepspeed_tpu.inference.buckets import pick_bucket
from deepspeed_tpu.inference.paging import PageAllocator, pages_for

__all__ = ["Request", "FinishedRequest", "PrefillBatch", "Scheduler"]

_uid_counter = itertools.count()


@dataclass
class Request:
    """One generation request. ``seed`` drives the per-request PRNG key
    (sampling is deterministic per request regardless of batch
    composition); ``temperature <= 0`` decodes greedily."""
    prompt: Sequence[int]
    max_new_tokens: int = 16
    temperature: float = 0.0
    seed: int = 0
    eos_id: Optional[int] = None
    # admission tier for the fleet router's SLO shed ladder (higher =
    # more important; 0 is the first tier rejected under load). The
    # scheduler itself stays FIFO — priority is routing policy, not
    # slot policy (inference/fleet.py).
    priority: int = 0
    uid: int = field(default_factory=lambda: next(_uid_counter))
    # distributed-trace context (inference/fleet.py stamps these at the
    # router): one trace id follows the request across every process
    # boundary — RPC dispatch, live KV migration, resubmit — and the
    # hop ordinal counts boundary crossings. None/0 when the request
    # never leaves one engine; the tracer simply omits the fields.
    trace_id: Optional[str] = None
    hop: int = 0

    def __post_init__(self):
        self.prompt = [int(t) for t in np.asarray(self.prompt).reshape(-1)]
        if not self.prompt:
            raise ValueError("empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")


@dataclass
class FinishedRequest:
    """A completed request plus its serving telemetry. ``ttft_ms`` is
    None — never 0.0 — for a request evicted before its first token;
    ``queue_wait_ms`` is the submit -> admit wait (None when evicted
    straight out of the queue), the first leg of the per-request
    latency decomposition (queue_wait / prefill / TBT —
    inference/tracing.py)."""
    uid: int
    prompt: List[int]
    tokens: List[int]            # generated tokens (EOS included if hit)
    finish_reason: str           # "eos" | "length" | "evicted"
    ttft_ms: Optional[float]
    latency_ms: float            # submit -> finish wall time
    queue_wait_ms: Optional[float] = None
    # per-request decode rate (kept tokens / total latency; None when
    # no token or no measurable latency) and the speculative-decoding
    # ledger: every PROPOSED draft token the verify dispatches saw for
    # this request vs how many were ACCEPTED (kept). ``tokens`` only
    # ever contains verified-and-kept tokens — rolled-back drafts are
    # never recorded, so goodput accounting stays honest by
    # construction (inference/tracing.py).
    tokens_per_s: Optional[float] = None
    draft_proposed: int = 0
    draft_accepted: int = 0
    # which serving weights produced ``tokens`` — the engine stamps its
    # current checkpoint tag (or "initial") so a live weight swap is
    # attributable per response (inference/fleet.py swap protocol)
    weight_version: Optional[str] = None


@dataclass
class PrefillBatch:
    """One bucketed prefill the engine must run: ``requests[i]`` lands
    in serving slot ``slot_ids[i]``; the engine pads to
    (batch_bucket, prompt_bucket) and routes pad rows to scratch (dense)
    or the null page (paged). Paged engines additionally read
    ``prefix_lens[i]`` (tokens already covered by shared prefix pages —
    the engine prefills only ``prompt[prefix_lens[i]:]``) and
    ``page_tables[i]`` (the slot's full page list, shared prefix pages
    first)."""
    slot_ids: List[int]
    requests: List[Request]
    batch_bucket: int
    prompt_bucket: int
    prefix_lens: List[int] = field(default_factory=list)
    page_tables: List[List[int]] = field(default_factory=list)


@dataclass
class _Slot:
    request: Request
    position: int                # tokens currently in this row's cache
    pending_tok: Optional[int]   # sampled, not yet written to cache
    tokens: List[int]
    t_submit: float
    ttft_ms: Optional[float] = None
    pages: List[int] = field(default_factory=list)   # paged mode only
    prefix_len: int = 0          # tokens reused from the prefix cache
    queue_wait_ms: float = 0.0   # submit -> admit (latency decomposition)
    # which allocator owns ``pages``: admission reserves from the admit
    # allocator ("admit" — the prefill pool under disaggregated
    # separate-pools serving, else the main pool); a claimed handoff
    # re-homes the slot onto the main pool via ``adopt_pages``
    pool: str = "admit"
    draft_proposed: int = 0      # speculative-decoding ledger
    draft_accepted: int = 0
    # chunked prefill (long prompts): absolute prompt tokens already
    # scattered into this slot's pages — the ONLY extra state a chunk
    # needs (the next chunk is just the prefill program at
    # ``positions = chunk_pos``). None = not chunked / prefill done.
    # While an int, ``pending_tok`` stays None, which already keeps the
    # slot out of decode dispatches and nulls its block-table rows.
    chunk_pos: Optional[int] = None


class Scheduler:
    """Continuous-batching scheduler over ``num_slots`` decode slots.

    The engine drives it: ``submit`` -> ``admit`` (bucketed prefill
    batches for free slots) -> ``record_tokens`` (one sampled token per
    active slot; evicts finished sequences and frees their slots and
    pages). ``clock`` is injectable for deterministic tests.

    ``allocator`` (paged mode) makes admission page-aware; ``lookahead``
    bounds how many queued requests past the head are scanned for one
    that fits when the head doesn't (head-of-line fix; 0 = strict FIFO).

    ``tracer`` (optional, an ``inference/tracing.py`` ServeTracer or
    anything with its hook surface) receives the request lifecycle:
    submit, defer (with reason), prefix hit, admit, first token,
    per-token, finish/evict. Hooks are pure host calls — scheduling
    stays jax-free with tracing on.
    """

    def __init__(self, num_slots: int, prompt_buckets: Sequence[int],
                 batch_buckets: Sequence[int], max_len: int,
                 clock=time.monotonic,
                 allocator: Optional[PageAllocator] = None,
                 lookahead: int = 0, tracer=None,
                 admit_allocator: Optional[PageAllocator] = None,
                 drafter=None, spec_k: int = 0,
                 chunk_tokens: int = 0):
        if num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        if lookahead < 0:
            raise ValueError("lookahead must be >= 0")
        self.num_slots = int(num_slots)
        self.prompt_buckets = tuple(int(b) for b in prompt_buckets)
        self.batch_buckets = tuple(int(b) for b in batch_buckets)
        self.max_len = int(max_len)
        self._clock = clock
        self.allocator = allocator
        # disaggregated separate-pools mode: admission reserves PROMPT
        # pages from its own (prefill) pool; the decode-lifetime
        # reservation moves to handoff claim (``adopt_pages``). Default
        # — one pool — keeps the whole-lifetime up-front reservation.
        self.admit_allocator = (admit_allocator if admit_allocator
                                is not None else allocator)
        self._separate_pools = (self.admit_allocator is not None and
                                self.admit_allocator is not allocator)
        # speculative decoding: a host-side drafter (inference/draft.py
        # surface: ``propose(history, k) -> tokens``) proposing up to
        # ``spec_k`` tokens per slot per decode dispatch
        self.drafter = drafter
        self.spec_k = int(spec_k)
        # chunked prefill: a prompt whose (post-prefix) suffix exceeds
        # the largest prompt bucket is admitted as a sequence of
        # ``chunk_tokens``-sized prefill chunks instead of one bucketed
        # dispatch (0 = off — over-bucket prompts are rejected at
        # submit with reason "reject_too_long").
        self.chunk_tokens = int(chunk_tokens)
        self.lookahead = int(lookahead)
        self.tracer = tracer
        self.queue: List[Request] = []
        self.slots: List[Optional[_Slot]] = [None] * self.num_slots
        self._submit_time: Dict[int, float] = {}
        self.finished: List[FinishedRequest] = []
        # graceful submit-time rejections awaiting the engine's next
        # ``step``/``run`` drain (they are already in ``finished`` too)
        self._rejects: List[FinishedRequest] = []
        self._new_ttfts: List[float] = []
        self._new_queue_waits: List[float] = []
        # cumulative counters (serving telemetry)
        self.total_admitted = 0
        self.total_tokens = 0
        self.peak_tokens_in_flight = 0
        # stamped onto every FinishedRequest; the engine sets it at
        # construction / from_checkpoint / swap_params so a live weight
        # swap is attributable per response
        self.weight_version: Optional[str] = None

    # ------------------------------------------------------------ state
    def free_slots(self) -> List[int]:
        return [i for i, s in enumerate(self.slots) if s is None]

    def active_slots(self) -> List[int]:
        return [i for i, s in enumerate(self.slots) if s is not None]

    @property
    def queue_depth(self) -> int:
        return len(self.queue)

    @property
    def occupancy(self) -> float:
        return 1.0 - len(self.free_slots()) / self.num_slots

    @property
    def tokens_in_flight(self) -> int:
        """Live cache tokens across active slots — what the pool
        actually holds. Shared prefix pages are deduplicated via the
        allocator's refcounts (only prefix sharing raises a refcount
        above 1); dense slots never share."""
        n = sum(s.position for s in self.slots if s is not None)
        # prefix sharing lives in the admission-side allocator (the
        # prefill pool under separate-pools disaggregation)
        if self.admit_allocator is not None:
            n -= self.admit_allocator.shared_duplicate_tokens
        return n

    def idle(self) -> bool:
        return not self.queue and not self.active_slots()

    # ----------------------------------------------------------- submit
    def _reject_too_long(self, request: Request) -> int:
        """Graceful submit-time rejection of a request no bucket/cache
        geometry could ever serve: the caller gets a normal
        :class:`FinishedRequest` with the pinned reason
        ``"reject_too_long"`` (tokens empty, ``ttft_ms`` None) on the
        next ``step``/``run`` drain — never a crash, never a silent
        truncation. The trail records submit -> evict like any other
        terminal outcome."""
        if self.tracer is not None:
            self.tracer.on_submit(request.uid, len(request.prompt),
                                  request.max_new_tokens,
                                  trace_id=getattr(request, "trace_id",
                                                   None),
                                  hop=getattr(request, "hop", 0))
        fin = FinishedRequest(
            uid=request.uid, prompt=list(request.prompt), tokens=[],
            finish_reason="reject_too_long", ttft_ms=None,
            latency_ms=0.0, queue_wait_ms=None,
            weight_version=self.weight_version)
        self.finished.append(fin)
        self._rejects.append(fin)
        if self.tracer is not None:
            self.tracer.on_finish(fin, evicted=True)
        return request.uid

    def submit(self, request: Request) -> int:
        """Queue a request; returns its uid. What no bucket/cache
        geometry could ever serve is rejected up front with a graceful
        ``"reject_too_long"`` :class:`FinishedRequest` (drained by the
        engine's next step) — a queued request never dies later of a
        shape it arrived with. With chunked prefill on
        (``chunk_tokens > 0``) the prompt-bucket ceiling does not apply:
        any prompt fitting ``max_len`` and the page pool serves."""
        plen = len(request.prompt)
        if self.chunk_tokens <= 0 and plen > max(self.prompt_buckets):
            return self._reject_too_long(request)
        if plen + request.max_new_tokens > self.max_len:
            return self._reject_too_long(request)
        if self.allocator is not None:
            total = pages_for(plen + request.max_new_tokens,
                              self.allocator.page_size)
            if total > self.allocator.num_pages - 1:
                return self._reject_too_long(request)
        if self._separate_pools:
            ppages = pages_for(plen, self.admit_allocator.page_size)
            if ppages > self.admit_allocator.num_pages - 1:
                return self._reject_too_long(request)
        self._submit_time[request.uid] = self._clock()
        self.queue.append(request)
        if self.tracer is not None:
            self.tracer.on_submit(request.uid, plen,
                                  request.max_new_tokens,
                                  trace_id=getattr(request, "trace_id",
                                                   None),
                                  hop=getattr(request, "hop", 0))
        return request.uid

    def drain_rejects(self) -> List[FinishedRequest]:
        """Submit-time rejections since the last drain — the engine
        returns them from its next ``step`` so ``run``/``generate``
        callers see rejected requests as ordinary finished results."""
        out = self._rejects
        self._rejects = []
        return out

    def queue_by_bucket(self) -> Dict[int, int]:
        """Waiting requests per prompt bucket (live-pool introspection;
        buckets are of the FULL prompt — admission may land a shorter
        suffix bucket after a prefix hit)."""
        out: Dict[int, int] = {}
        top = max(self.prompt_buckets)
        for req in self.queue:
            # over-bucket prompts (queueable only with chunked prefill
            # on) count under the largest bucket — they have no ladder
            # rung of their own
            b = pick_bucket(min(len(req.prompt), top),
                            self.prompt_buckets)
            out[b] = out.get(b, 0) + 1
        return out

    # ------------------------------------------------------------ admit
    def _match_prefix(self, req: Request) -> Tuple[List[int], int]:
        """Cached prefix pages reusable by ``req`` — capped one token
        short of the full prompt: the last prompt token must run through
        prefill to produce the first-token logits."""
        if self.admit_allocator is None:
            return [], 0
        shared, reused = self.admit_allocator.match_prefix(req.prompt)
        ps = self.admit_allocator.page_size
        cap = (len(req.prompt) - 1) // ps
        shared = shared[:cap]
        return shared, len(shared) * ps

    def _try_reserve(self, req: Request,
                     match: Optional[Tuple[List[int], int]] = None
                     ) -> Optional[Tuple[List[int], int]]:
        """Commit page reservations for ``req``: incref its shared
        prefix pages and allocate the rest (whole lifetime —
        ``ceil((prompt + max_new) / page_size)``), or None (nothing
        taken) when the pool can't supply them. ``match`` reuses a
        just-computed ``_match_prefix`` result (admission's bucket
        pre-check) instead of re-hashing the prompt."""
        alloc = self.admit_allocator
        if alloc is None:
            return [], 0
        shared, reused = match if match is not None else \
            self._match_prefix(req)
        # separate-pools disaggregation: prefill only ever writes the
        # PROMPT's K/V, so admission reserves just that — the decode
        # lifetime (prompt + max_new) is reserved from the main pool
        # when the handoff is claimed (adopt_pages)
        tokens = len(req.prompt) if self._separate_pools else \
            len(req.prompt) + req.max_new_tokens
        total = pages_for(tokens, alloc.page_size)
        fresh = alloc.alloc(total - len(shared))
        if fresh is None:
            return None
        alloc.incref(shared)
        alloc.prefix_hit_tokens += reused
        alloc.prefix_miss_tokens += len(req.prompt) - reused
        if reused:
            alloc.prefix_hit_requests += 1
            if self.tracer is not None:
                self.tracer.on_prefix_hit(req.uid, reused, len(shared))
        pages = shared + fresh
        # publish this prompt's full pages for later (or same-batch)
        # requests sharing the prefix — content is determined by the
        # prompt alone, and every reader's gather runs after this
        # request's prefill scatter (same or later dispatch)
        alloc.register_prefix(req.prompt, pages)
        return pages, reused

    def _release(self, slot: _Slot):
        alloc = self.admit_allocator if slot.pool == "admit" else \
            self.allocator
        if alloc is not None and slot.pages:
            alloc.free(slot.pages)
            slot.pages = []

    def adopt_pages(self, sid: int, pages: List[int]) -> None:
        """Re-home slot ``sid`` onto the MAIN (decode) pool: its
        admission-side pages (the prefill pool's, under separate-pools
        disaggregation) free immediately and ``pages`` — already
        allocated from ``self.allocator`` by the engine's handoff
        claim, content already migrated — become the slot's block
        table."""
        slot = self.slots[sid]
        if slot is None:
            raise KeyError(f"slot {sid} is not active")
        self._release(slot)
        slot.pages = list(pages)
        slot.pool = "main"

    def install_slot(self, request: Request, *, position: int,
                     pending_tok: int, tokens: List[int],
                     pages: List[int], ttft_ms: Optional[float] = None,
                     queue_wait_ms: float = 0.0,
                     elapsed_ms: float = 0.0,
                     draft_proposed: int = 0, draft_accepted: int = 0,
                     pool: str = "main") -> Optional[int]:
        """Install an ALREADY-RUNNING request into a free slot — the
        destination half of live KV migration (ISSUE 16). ``pages``
        are already allocated (owner named by ``pool``) and already
        hold the migrated cache content; ``position``/``pending_tok``
        resume decode exactly where the source replica stopped — no
        re-prefill, bitwise-identical continuation (sampling keys are
        (seed, position)-derived). Cross-process clocks share no
        epoch, so the source ships *elapsed* durations and
        ``t_submit`` is back-dated against the local clock — latency
        accounting stays continuous across the hop. No tracer hooks
        fire (the request's serve trace lives on the source replica;
        the router's ``serve_migration`` row stitches the timelines).
        Returns the slot id, or None when no slot is free (the caller
        still owns ``pages`` and falls back)."""
        free = self.free_slots()
        if not free:
            return None
        sid = free[0]
        self.slots[sid] = _Slot(
            request=request, position=int(position),
            pending_tok=int(pending_tok), tokens=list(tokens),
            t_submit=self._clock() - float(elapsed_ms) / 1e3,
            ttft_ms=ttft_ms, pages=list(pages),
            queue_wait_ms=float(queue_wait_ms), pool=pool,
            draft_proposed=int(draft_proposed),
            draft_accepted=int(draft_accepted))
        self.total_admitted += 1
        self.peak_tokens_in_flight = max(self.peak_tokens_in_flight,
                                         self.tokens_in_flight)
        return sid

    def admit(self) -> List[PrefillBatch]:
        """Assign waiting requests to free slots, grouped into bucketed
        prefill batches.

        FIFO with same-bucket batching and bounded lookahead: the HEAD
        is the first request in the ``lookahead + 1``-deep window whose
        pages fit the pool (strict FIFO head when everything fits, or in
        dense mode); it fixes the prompt bucket (of its un-prefixed
        SUFFIX, in paged mode). Later queued requests sharing that
        bucket — and fitting the remaining pages — ride along (up to the
        largest batch bucket / free slots). Repeats until slots, pages,
        or queue run out. A too-big head therefore delays, but never
        blocks, everything behind it. The window bounds how far FIFO
        order is violated per admission, NOT the head's wait: under a
        sustained stream of small requests an oversized head can wait
        indefinitely (no aging/reservation yet) — set ``lookahead=0``
        for strict FIFO when that matters more than utilization.
        """
        batches: List[PrefillBatch] = []
        free = self.free_slots()
        tracer = self.tracer
        while free and self.queue:
            # head selection within the lookahead window
            head_idx = None
            head_res = None
            for i, req in enumerate(
                    self.queue[:self.lookahead + 1]):
                res = self._try_reserve(req)
                if res is not None:
                    head_idx, head_res = i, res
                    break
                if tracer is not None:
                    tracer.on_defer(req.uid, "pages")
            if head_idx is None:
                # nothing in the window fits; whatever sits just past
                # it wasn't even scanned — that's a lookahead defer,
                # not a page defer (the tracer dedupes repeats)
                if tracer is not None and \
                        len(self.queue) > self.lookahead + 1:
                    tracer.on_defer(
                        self.queue[self.lookahead + 1].uid, "lookahead")
                break
            head = self.queue[head_idx]
            if (self.chunk_tokens > 0 and
                    len(head.prompt) - head_res[1]
                    > max(self.prompt_buckets)):
                # chunked admission: the long prompt bypasses the
                # prompt-bucket ladder — it takes ONE slot now and the
                # engine prefills it ``chunk_tokens`` at a time,
                # interleaved with decode steps (at most one chunk
                # dispatch per step, so in-flight decodes never wait
                # behind the whole prompt). Pages were already reserved
                # whole-lifetime by ``_try_reserve``; chunk state is
                # just ``chunk_pos`` advancing over them.
                self.queue.pop(head_idx)
                sid = free.pop(0)
                now = self._clock()
                t_sub = self._submit_time.pop(head.uid, now)
                qwait = (now - t_sub) * 1e3
                pages, reused = head_res
                self.slots[sid] = _Slot(
                    request=head, position=reused, pending_tok=None,
                    tokens=[], t_submit=t_sub, pages=pages,
                    prefix_len=reused, queue_wait_ms=qwait,
                    chunk_pos=reused)
                self._new_queue_waits.append(qwait)
                if tracer is not None:
                    tracer.on_admit(head.uid, sid, qwait, reused,
                                    self.chunk_tokens, 1)
                self.total_admitted += 1
                continue
            head_bucket = pick_bucket(len(head.prompt) - head_res[1],
                                      self.prompt_buckets)
            cap = min(len(free), max(self.batch_buckets))
            take: List[Request] = [head]
            reserved: List[Tuple[List[int], int]] = [head_res]
            for req in self.queue[head_idx + 1:]:
                if len(take) >= cap:
                    break
                match = self._match_prefix(req)
                if (self.chunk_tokens > 0 and
                        len(req.prompt) - match[1]
                        > max(self.prompt_buckets)):
                    continue    # chunked: only ever admitted as a head
                if pick_bucket(len(req.prompt) - match[1],
                               self.prompt_buckets) != head_bucket:
                    if tracer is not None:
                        tracer.on_defer(req.uid, "bucket")
                    continue
                res = self._try_reserve(req, match)
                if res is None:
                    if tracer is not None:
                        tracer.on_defer(req.uid, "pages")
                    continue
                take.append(req)
                reserved.append(res)
            for req in take:
                self.queue.remove(req)
            batch_bucket = pick_bucket(len(take), self.batch_buckets)
            slot_ids = [free.pop(0) for _ in take]
            now = self._clock()
            for sid, req, (pages, reused) in zip(slot_ids, take, reserved):
                t_sub = self._submit_time.pop(req.uid, now)
                qwait = (now - t_sub) * 1e3
                self.slots[sid] = _Slot(
                    request=req, position=len(req.prompt),
                    pending_tok=None, tokens=[],
                    t_submit=t_sub,
                    pages=pages, prefix_len=reused,
                    queue_wait_ms=qwait)
                self._new_queue_waits.append(qwait)
                if tracer is not None:
                    tracer.on_admit(req.uid, sid, qwait, reused,
                                    head_bucket, batch_bucket)
            self.total_admitted += len(take)
            batches.append(PrefillBatch(
                slot_ids=slot_ids, requests=take,
                batch_bucket=batch_bucket, prompt_bucket=head_bucket,
                prefix_lens=[r for _, r in reserved],
                page_tables=[p for p, _ in reserved]))
        self.peak_tokens_in_flight = max(self.peak_tokens_in_flight,
                                         self.tokens_in_flight)
        return batches

    # ----------------------------------------------------- token stream
    def record_tokens(self, tokens: Dict[int, int]
                      ) -> List[FinishedRequest]:
        """Record one sampled token per slot (``{slot_id: token}``) —
        from a prefill's first token or a decode step — advancing each
        slot's pending/position bookkeeping. Finished sequences (EOS or
        max_new_tokens) are evicted; their slots (and pages) free
        immediately for the next ``admit``. Returns the newly finished
        requests."""
        return self.record_token_runs(
            {sid: [tok] for sid, tok in tokens.items()})

    def record_token_runs(self, runs: Dict[int, Sequence[int]],
                          draft_stats: Optional[
                              Dict[int, Tuple[int, int]]] = None
                          ) -> List[FinishedRequest]:
        """Record a RUN of kept tokens per slot — one token from a
        plain decode/prefill dispatch, or ``m + 1`` from a speculative
        verify dispatch that accepted ``m`` draft tokens (the accepted
        drafts plus the dispatch's fresh bonus sample). Every token in
        a run advances position by one: each was written to the cache
        by the dispatch that produced it, except the LAST, which
        becomes the new pending token — exactly the single-token
        invariant, iterated. A mid-run EOS (or max_new) finishes the
        request and DISCARDS the run's remainder: tokens past a stop
        are never emitted, counted, or written back.

        ``draft_stats`` (``{slot_id: (proposed, accepted)}``) settles
        the speculative ledger for the dispatch that produced the runs
        — rejected (rolled-back) drafts thus exist only in these
        counters, never in ``total_tokens``/goodput."""
        now = self._clock()
        tracer = self.tracer
        done: List[FinishedRequest] = []
        for sid, run in runs.items():
            slot = self.slots[sid]
            if slot is None:
                raise KeyError(f"slot {sid} is not active")
            req = slot.request
            if draft_stats is not None and sid in draft_stats:
                proposed, accepted = draft_stats[sid]
                slot.draft_proposed += int(proposed)
                slot.draft_accepted += int(accepted)
                if tracer is not None and proposed:
                    tracer.on_spec(req.uid, int(proposed), int(accepted))
            fin = None
            for tok in run:
                tok = int(tok)
                if slot.pending_tok is not None:
                    # the previous sample was written to the cache by
                    # the dispatch that produced this one
                    slot.position += 1
                if slot.ttft_ms is None:
                    slot.ttft_ms = (now - slot.t_submit) * 1e3
                    self._new_ttfts.append(slot.ttft_ms)
                    if tracer is not None:
                        tracer.on_first_token(req.uid, slot.ttft_ms)
                elif tracer is not None:
                    tracer.on_token(req.uid)
                slot.tokens.append(tok)
                slot.pending_tok = tok
                self.total_tokens += 1
                hit_eos = req.eos_id is not None and tok == req.eos_id
                if hit_eos or len(slot.tokens) >= req.max_new_tokens:
                    # ttft_ms can only be None here for a request whose
                    # first token never arrived — impossible on this
                    # path (a token was just recorded) but the
                    # FinishedRequest contract allows it (eviction
                    # produces it), so downstream consumers must treat
                    # None as "no first token", never as 0.0
                    latency_ms = (now - slot.t_submit) * 1e3
                    fin = FinishedRequest(
                        uid=req.uid, prompt=list(req.prompt),
                        tokens=list(slot.tokens),
                        finish_reason="eos" if hit_eos else "length",
                        ttft_ms=slot.ttft_ms,
                        latency_ms=latency_ms,
                        queue_wait_ms=slot.queue_wait_ms,
                        tokens_per_s=(len(slot.tokens) * 1e3 /
                                      latency_ms if latency_ms > 0
                                      else None),
                        draft_proposed=slot.draft_proposed,
                        draft_accepted=slot.draft_accepted,
                        weight_version=self.weight_version)
                    break
            if fin is not None:
                done.append(fin)
                self._release(slot)
                self.slots[sid] = None
                if tracer is not None:
                    tracer.on_finish(fin)
        self.finished.extend(done)
        self.peak_tokens_in_flight = max(self.peak_tokens_in_flight,
                                         self.tokens_in_flight)
        return done

    # ------------------------------------------------- chunked prefill
    def chunk_batch(self, cap: int) -> List[int]:
        """Slot ids with chunked prefill still in flight (oldest slot
        first), up to ``cap`` — the engine batches them into ONE chunk
        dispatch per step, so the per-step prefill work is bounded by
        ``cap * chunk_tokens`` regardless of prompt length."""
        out: List[int] = []
        for sid in self.active_slots():
            slot = self.slots[sid]
            if slot.chunk_pos is None:
                continue
            out.append(sid)
            if len(out) >= cap:
                break
        return out

    def chunk_span(self, sid: int) -> Tuple[int, int]:
        """(start, length) of slot ``sid``'s next prefill chunk in
        absolute prompt positions — the last chunk is simply shorter
        (the program pads it; ``lengths`` carries the true size)."""
        slot = self.slots[sid]
        if slot is None or slot.chunk_pos is None:
            raise KeyError(f"slot {sid} has no chunked prefill in flight")
        start = slot.chunk_pos
        return start, min(self.chunk_tokens,
                          len(slot.request.prompt) - start)

    def record_chunk(self, sid: int, ntokens: int) -> bool:
        """One prefill chunk of ``ntokens`` landed in slot ``sid``'s
        cache. Returns True when the prompt is now fully prefilled —
        the slot leaves chunk state with ``position == len(prompt)``
        and ``pending_tok`` still None: byte-identical to a freshly
        whole-prompt-prefilled slot, so the caller records the final
        chunk's first token (or pushes the disagg handoff) through the
        exact same paths."""
        slot = self.slots[sid]
        if slot is None or slot.chunk_pos is None:
            raise KeyError(f"slot {sid} has no chunked prefill in flight")
        slot.chunk_pos += int(ntokens)
        slot.position = slot.chunk_pos
        if slot.chunk_pos >= len(slot.request.prompt):
            slot.position = len(slot.request.prompt)
            slot.chunk_pos = None
            return True
        return False

    def chunking_slots(self) -> List[int]:
        """All slot ids currently mid-chunked-prefill (introspection /
        idle accounting)."""
        return [sid for sid in self.active_slots()
                if self.slots[sid].chunk_pos is not None]

    def draft_proposals(self, cap: Optional[int] = None
                        ) -> Dict[int, List[int]]:
        """Host-side speculation for the next decode dispatch: for
        every slot mid-decode, ask the drafter for up to
        ``min(spec_k, cap, tokens left before max_new)`` continuation
        tokens of the slot's full history (prompt + kept tokens — the
        pending token is history too: it is what the verify dispatch
        writes first). Slots the drafter has nothing for are simply
        absent — they ride the verify dispatch as plain one-token
        decode rows (a draft stall, not an error)."""
        out: Dict[int, List[int]] = {}
        if self.drafter is None or self.spec_k < 1:
            return out
        for sid in self.active_slots():
            slot = self.slots[sid]
            if slot.pending_tok is None:
                continue
            # the run a verify dispatch may emit is (accepted + 1)
            # tokens; cap proposals so even full acceptance cannot
            # overshoot max_new_tokens
            k_row = min(self.spec_k,
                        slot.request.max_new_tokens
                        - len(slot.tokens) - 1)
            if cap is not None:
                k_row = min(k_row, cap)
            if k_row < 1:
                continue
            history = list(slot.request.prompt) + slot.tokens
            props = [int(t) for t in
                     self.drafter.propose(history, k_row)][:k_row]
            if props:
                out[sid] = props
        return out

    def drain_ttfts(self) -> List[float]:
        """TTFTs recorded since the last drain (telemetry pull — the
        engine writes one ``Serve/ttft_ms`` scalar per admitted
        request)."""
        out = self._new_ttfts
        self._new_ttfts = []
        return out

    def drain_queue_waits(self) -> List[float]:
        """Queue waits (submit -> admit ms) recorded since the last
        drain — one ``Serve/queue_wait_ms`` scalar per admitted
        request, the first leg of the latency decomposition."""
        out = self._new_queue_waits
        self._new_queue_waits = []
        return out

    # ---------------------------------------------------------- eviction
    def evict(self, uid: int, reason: str = "evicted"
              ) -> Optional[FinishedRequest]:
        """Force ``uid`` out of the system — from the waiting queue or
        from its live slot (pages freed, slot reusable next admit).
        Returns the FinishedRequest (``ttft_ms`` None — NOT 0.0 — when
        no first token was ever produced), or None for an unknown/
        already-finished uid. Must not be called between building a
        decode batch and recording its tokens (the engine's ``step`` is
        atomic in that respect)."""
        now = self._clock()
        for i, req in enumerate(self.queue):
            if req.uid == uid:
                self.queue.pop(i)
                t_sub = self._submit_time.pop(uid, now)
                fin = FinishedRequest(
                    uid=uid, prompt=list(req.prompt), tokens=[],
                    finish_reason=reason, ttft_ms=None,
                    latency_ms=(now - t_sub) * 1e3,
                    queue_wait_ms=None,
                    weight_version=self.weight_version)
                self.finished.append(fin)
                if self.tracer is not None:
                    self.tracer.on_finish(fin, evicted=True)
                return fin
        for sid in self.active_slots():
            slot = self.slots[sid]
            if slot.request.uid != uid:
                continue
            latency_ms = (now - slot.t_submit) * 1e3
            fin = FinishedRequest(
                uid=uid, prompt=list(slot.request.prompt),
                tokens=list(slot.tokens), finish_reason=reason,
                ttft_ms=slot.ttft_ms,
                latency_ms=latency_ms,
                queue_wait_ms=slot.queue_wait_ms,
                tokens_per_s=(len(slot.tokens) * 1e3 / latency_ms
                              if slot.tokens and latency_ms > 0
                              else None),
                draft_proposed=slot.draft_proposed,
                draft_accepted=slot.draft_accepted,
                weight_version=self.weight_version)
            self._release(slot)
            self.slots[sid] = None
            self.finished.append(fin)
            if self.tracer is not None:
                self.tracer.on_finish(fin, evicted=True)
            return fin
        return None

    # -------------------------------------------- decode-batch assembly
    def decode_state(self):
        """Host arrays for one decode dispatch over the full slot table:
        (slot_ids, toks, positions, temps, seeds) — inactive rows carry
        zeros and are ignored on the way back. Empty when nothing is
        mid-decode."""
        sids, toks, poss, temps, seeds = [], [], [], [], []
        for sid in self.active_slots():
            slot = self.slots[sid]
            if slot.pending_tok is None:
                continue        # admitted this step; first token pending
            sids.append(sid)
            toks.append(slot.pending_tok)
            poss.append(slot.position)
            temps.append(slot.request.temperature)
            seeds.append(slot.request.seed)
        return sids, toks, poss, temps, seeds

    def block_table_rows(self, rows: int, pages_per_seq: int) -> np.ndarray:
        """The decode dispatch's static-shape block tables: one
        (rows, pages_per_seq) int32 array, active slots' pages in their
        rows, everything else 0 (the null page — inactive rows write
        and read only garbage the mask hides). ``pages_per_seq`` may be
        NARROWER than a slot's full reservation (the engine's
        live-page-bucketed decode width): the tail entries dropped are
        reserved-but-unreached pages this step can neither write nor
        read, so the clamp is exact. Slots with no pending token
        (admitted but not yet claimed by the decode worker, under
        disaggregation) keep all-null rows: their pages — possibly a
        DIFFERENT pool's, or shared prefix pages — must never receive
        the dispatch's garbage row writes."""
        out = np.zeros((rows, pages_per_seq), np.int32)
        for sid in self.active_slots():
            slot = self.slots[sid]
            if slot.pending_tok is None:
                continue
            pages = slot.pages[:pages_per_seq]
            out[sid, :len(pages)] = pages
        return out

    def max_live_pages(self) -> int:
        """Widest live page count across active slots for ONE decode
        step: slot at ``position`` writes its pending token at
        ``position`` and attends positions ``<= position`` —
        ``position // page_size + 1`` pages (slots parked awaiting a
        disagg handoff claim count too: the width clamp is a dispatch
        bucket, and a spuriously wide table is merely unclamped, never
        wrong). The engine buckets this up to a compiled decode width
        (never below 1: an idle table still needs its null column)."""
        if self.allocator is None:
            return 1
        ps = self.allocator.page_size
        return max((s.position // ps + 1
                    for s in self.slots if s is not None),
                   default=1)
