"""Continuous-batching scheduler for the serving engine.

Static-batch serving wastes slots: a batch of 8 runs at the speed of
its longest request while 7 finished rows decode garbage. Continuous
batching (Orca-style iteration-level scheduling) instead treats the
decode batch as SLOTS: every engine step, finished sequences (EOS /
max_tokens) are evicted and waiting requests are admitted into the
freed slots via a bucketed prefill — occupancy stays high under
heterogeneous request lengths.

This module is the pure host-side half: FIFO queue, slot table, bucket
grouping for admission, per-request sampling state (temperature + PRNG
seed — deterministic per request, independent of what else shares the
batch), and completion bookkeeping (TTFT, per-request token counts).
The jit-facing half (padded arrays, cache scatter) lives in
``inference/engine.py``; nothing here imports jax, so scheduler policy
is unit-testable in microseconds.
"""

import itertools
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.inference.buckets import pick_bucket

__all__ = ["Request", "FinishedRequest", "PrefillBatch", "Scheduler"]

_uid_counter = itertools.count()


@dataclass
class Request:
    """One generation request. ``seed`` drives the per-request PRNG key
    (sampling is deterministic per request regardless of batch
    composition); ``temperature <= 0`` decodes greedily."""
    prompt: Sequence[int]
    max_new_tokens: int = 16
    temperature: float = 0.0
    seed: int = 0
    eos_id: Optional[int] = None
    uid: int = field(default_factory=lambda: next(_uid_counter))

    def __post_init__(self):
        self.prompt = [int(t) for t in np.asarray(self.prompt).reshape(-1)]
        if not self.prompt:
            raise ValueError("empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")


@dataclass
class FinishedRequest:
    """A completed request plus its serving telemetry."""
    uid: int
    prompt: List[int]
    tokens: List[int]            # generated tokens (EOS included if hit)
    finish_reason: str           # "eos" | "length"
    ttft_ms: Optional[float]
    latency_ms: float            # submit -> finish wall time


@dataclass
class PrefillBatch:
    """One bucketed prefill the engine must run: ``requests[i]`` lands
    in serving slot ``slot_ids[i]``; the engine pads to
    (batch_bucket, prompt_bucket) and scatters pad rows to scratch."""
    slot_ids: List[int]
    requests: List[Request]
    batch_bucket: int
    prompt_bucket: int


@dataclass
class _Slot:
    request: Request
    position: int                # tokens currently in this row's cache
    pending_tok: Optional[int]   # sampled, not yet written to cache
    tokens: List[int]
    t_submit: float
    ttft_ms: Optional[float] = None


class Scheduler:
    """FIFO continuous-batching scheduler over ``num_slots`` decode
    slots. The engine drives it: ``submit`` -> ``admit`` (bucketed
    prefill batches for free slots) -> ``record_tokens`` (one sampled
    token per active slot; evicts finished sequences and frees their
    slots). ``clock`` is injectable for deterministic tests."""

    def __init__(self, num_slots: int, prompt_buckets: Sequence[int],
                 batch_buckets: Sequence[int], max_len: int,
                 clock=time.monotonic):
        if num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        self.num_slots = int(num_slots)
        self.prompt_buckets = tuple(int(b) for b in prompt_buckets)
        self.batch_buckets = tuple(int(b) for b in batch_buckets)
        self.max_len = int(max_len)
        self._clock = clock
        self.queue: List[Request] = []
        self.slots: List[Optional[_Slot]] = [None] * self.num_slots
        self._submit_time: Dict[int, float] = {}
        self.finished: List[FinishedRequest] = []
        self._new_ttfts: List[float] = []
        # cumulative counters (serving telemetry)
        self.total_admitted = 0
        self.total_tokens = 0

    # ------------------------------------------------------------ state
    def free_slots(self) -> List[int]:
        return [i for i, s in enumerate(self.slots) if s is None]

    def active_slots(self) -> List[int]:
        return [i for i, s in enumerate(self.slots) if s is not None]

    @property
    def queue_depth(self) -> int:
        return len(self.queue)

    @property
    def occupancy(self) -> float:
        return 1.0 - len(self.free_slots()) / self.num_slots

    def idle(self) -> bool:
        return not self.queue and not self.active_slots()

    # ----------------------------------------------------------- submit
    def submit(self, request: Request) -> int:
        """Queue a request; returns its uid. Rejects up front what no
        bucket/cache geometry could ever serve — a queued request never
        dies later of a shape it arrived with."""
        plen = len(request.prompt)
        if plen > max(self.prompt_buckets):
            raise ValueError(
                f"prompt length {plen} exceeds the largest prompt bucket "
                f"{max(self.prompt_buckets)}")
        if plen + request.max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({plen}) + max_new_tokens "
                f"({request.max_new_tokens}) exceeds max_len {self.max_len}")
        self._submit_time[request.uid] = self._clock()
        self.queue.append(request)
        return request.uid

    # ------------------------------------------------------------ admit
    def admit(self) -> List[PrefillBatch]:
        """Assign waiting requests to free slots, grouped into bucketed
        prefill batches.

        FIFO with same-bucket batching: the head of the queue fixes the
        prompt bucket; later queued requests sharing that bucket may
        ride along (up to the largest batch bucket / free slots), which
        keeps arrival order *across admissions* while letting one
        prefill program serve several requests. Repeats until slots or
        queue run out.
        """
        batches: List[PrefillBatch] = []
        free = self.free_slots()
        while free and self.queue:
            head_bucket = pick_bucket(len(self.queue[0].prompt),
                                      self.prompt_buckets)
            cap = min(len(free), max(self.batch_buckets))
            take: List[Request] = []
            for req in self.queue:
                if len(take) >= cap:
                    break
                if pick_bucket(len(req.prompt),
                               self.prompt_buckets) == head_bucket:
                    take.append(req)
            for req in take:
                self.queue.remove(req)
            batch_bucket = pick_bucket(len(take), self.batch_buckets)
            slot_ids = [free.pop(0) for _ in take]
            now = self._clock()
            for sid, req in zip(slot_ids, take):
                self.slots[sid] = _Slot(
                    request=req, position=len(req.prompt),
                    pending_tok=None, tokens=[],
                    t_submit=self._submit_time.pop(req.uid, now))
            self.total_admitted += len(take)
            batches.append(PrefillBatch(
                slot_ids=slot_ids, requests=take,
                batch_bucket=batch_bucket, prompt_bucket=head_bucket))
        return batches

    # ----------------------------------------------------- token stream
    def record_tokens(self, tokens: Dict[int, int]
                      ) -> List[FinishedRequest]:
        """Record one sampled token per slot (``{slot_id: token}``) —
        from a prefill's first token or a decode step — advancing each
        slot's pending/position bookkeeping. Finished sequences (EOS or
        max_new_tokens) are evicted; their slots free immediately for
        the next ``admit``. Returns the newly finished requests."""
        now = self._clock()
        done: List[FinishedRequest] = []
        for sid, tok in tokens.items():
            slot = self.slots[sid]
            if slot is None:
                raise KeyError(f"slot {sid} is not active")
            tok = int(tok)
            if slot.pending_tok is not None:
                # the previous sample was written to the cache by the
                # decode step that produced this one
                slot.position += 1
            if slot.ttft_ms is None:
                slot.ttft_ms = (now - slot.t_submit) * 1e3
                self._new_ttfts.append(slot.ttft_ms)
            slot.tokens.append(tok)
            slot.pending_tok = tok
            self.total_tokens += 1
            req = slot.request
            hit_eos = req.eos_id is not None and tok == req.eos_id
            if hit_eos or len(slot.tokens) >= req.max_new_tokens:
                done.append(FinishedRequest(
                    uid=req.uid, prompt=list(req.prompt),
                    tokens=list(slot.tokens),
                    finish_reason="eos" if hit_eos else "length",
                    ttft_ms=slot.ttft_ms,
                    latency_ms=(now - slot.t_submit) * 1e3))
                self.slots[sid] = None
        self.finished.extend(done)
        return done

    def drain_ttfts(self) -> List[float]:
        """TTFTs recorded since the last drain (telemetry pull — the
        engine writes one ``Serve/ttft_ms`` scalar per admitted
        request)."""
        out = self._new_ttfts
        self._new_ttfts = []
        return out

    # -------------------------------------------- decode-batch assembly
    def decode_state(self):
        """Host arrays for one decode dispatch over the full slot table:
        (slot_ids, toks, positions, temps, seeds) — inactive rows carry
        zeros and are ignored on the way back. Empty when nothing is
        mid-decode."""
        sids, toks, poss, temps, seeds = [], [], [], [], []
        for sid in self.active_slots():
            slot = self.slots[sid]
            if slot.pending_tok is None:
                continue        # admitted this step; first token pending
            sids.append(sid)
            toks.append(slot.pending_tok)
            poss.append(slot.position)
            temps.append(slot.request.temperature)
            seeds.append(slot.request.seed)
        return sids, toks, poss, temps, seeds
