"""Process-boundary RPC for the serving fleet (ISSUE 16).

One replica per child process is the deployment shape DeepSpeed's
launcher exists for: independent failure domains, so a watchdog
``os._exit(87)`` or a segfault takes down ONE engine, not the service.
This module is the wire between :class:`~.fleet.FleetRouter` (parent)
and ``replica_worker`` children (each hosting one
:class:`~.engine.InferenceEngine`): length-prefixed JSON frames with an
optional raw binary segment (KV page slabs ride here — numpy bytes,
never JSON-encoded floats) over a loopback socket. stdio would work
with the same framing, but jax and absl both write to the child's
stdout, so the channel gets its own fd.

Frame layout (both directions)::

    !II header   = (json_len, bin_len), network byte order
    json_len     UTF-8 JSON object
    bin_len      raw payload (page slabs; b"" for control traffic)

Requests are ``{"method": str, "params": {...}}``; replies are
``{"ok": true, "result": ...}`` or ``{"ok": false, "error": {"kind",
"message"}}``. Calls are synchronous and in-order — the fleet router
is single-threaded by design, so one outstanding call per replica.

Error taxonomy (pinned — the router's failure handling branches on
exactly these, and each is a distinct ``runtime/fault.py`` injection
point):

``transport`` (:class:`RpcTransportError`, point ``rpc.transport``)
    transient channel fault (send failed, injected flake). The client
    retries with bounded exponential backoff before escalating.
``timeout`` (:class:`RpcTimeoutError`, point ``rpc.timeout``)
    no reply within the per-call deadline. NOT retried — the request
    may have been applied, and fleet methods are not all idempotent;
    the router decides (usually: treat the replica as wedged).
``replica_dead`` (:class:`ReplicaDeadError`, point ``rpc.replica_dead``)
    the peer closed the channel (EOF) or announced its own death (a
    deathbed frame carrying migration exports). Terminal for this
    connection; the router salvages, migrates, and maybe relaunches.

This module is jax-free (source-level ast pin in
tests/unit/test_inference.py, alongside scheduler/paging/fleet):
framing, retry policy, and the error taxonomy are unit-testable over a
``socket.socketpair()`` in microseconds, no device, no child process.
"""

import json
import socket
import struct
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deepspeed_tpu.runtime import fault
from deepspeed_tpu.utils.logging import logger

__all__ = [
    "RpcError", "RpcTransportError", "RpcTimeoutError",
    "ReplicaDeadError", "RpcRemoteError", "RpcClient", "RpcServer",
    "ServerExit", "send_frame", "recv_frame", "encode_arrays",
    "decode_arrays", "decode_migrations", "migration_to_wire",
    "migration_from_wire", "request_to_wire", "request_from_wire",
    "listen_local", "connect_local",
]

#: frame header: (json_len, bin_len), network byte order
_HEADER = struct.Struct("!II")
#: refuse absurd frames (a desynced stream reads garbage lengths)
MAX_FRAME_BYTES = 1 << 30


# --------------------------------------------------------------- errors
class RpcError(Exception):
    """Base of the pinned taxonomy; ``kind`` is the wire/router key."""
    kind = "transport"

    def __init__(self, message: str, method: Optional[str] = None):
        super().__init__(message)
        self.method = method


class RpcTransportError(RpcError):
    """Transient channel fault — retried with backoff by the client."""
    kind = "transport"


class RpcTimeoutError(RpcError):
    """Per-call deadline exceeded — never retried (not idempotent)."""
    kind = "timeout"


class ReplicaDeadError(RpcError):
    """The peer is gone: EOF, or a deathbed frame. ``exports`` carries
    any :class:`~.disagg.MigrationRecord` the dying replica shipped
    out with its last breath (live KV pages of in-flight requests)."""
    kind = "replica_dead"

    def __init__(self, message: str, method: Optional[str] = None,
                 exports: Optional[List[Any]] = None,
                 reason: Optional[str] = None):
        super().__init__(message, method=method)
        self.exports = list(exports or [])
        self.reason = reason


class RpcRemoteError(RpcError):
    """The replica's handler raised: the engine survived, the call
    failed. Application-level, outside the transport taxonomy."""
    kind = "remote"


# -------------------------------------------------------------- framing
def send_frame(sock, header: Dict[str, Any],
               payload: bytes = b"") -> None:
    """One length-prefixed frame: JSON header + raw binary segment."""
    blob = json.dumps(header, separators=(",", ":")).encode("utf-8")
    sock.sendall(_HEADER.pack(len(blob), len(payload)))
    sock.sendall(blob)
    if payload:
        sock.sendall(payload)


def _recv_exact(sock, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            raise ReplicaDeadError(
                f"peer closed the channel mid-frame "
                f"({len(buf)}/{n} bytes)")
        buf += chunk
    return bytes(buf)


def recv_frame(sock) -> Tuple[Dict[str, Any], bytes]:
    jlen, plen = _HEADER.unpack(_recv_exact(sock, _HEADER.size))
    if jlen > MAX_FRAME_BYTES or plen > MAX_FRAME_BYTES:
        raise RpcTransportError(
            f"frame header implausible ({jlen}/{plen} bytes) — "
            f"stream desynced")
    header = json.loads(_recv_exact(sock, jlen).decode("utf-8"))
    payload = _recv_exact(sock, plen) if plen else b""
    return header, payload


# ----------------------------------------------------------- slab codec
def _resolve_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # bfloat16 et al live in ml_dtypes (jax's dtype extension
        # package — importing it does NOT import jax)
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def encode_arrays(arrays: Sequence[Any]
                  ) -> Tuple[List[Dict[str, Any]], bytes]:
    """numpy arrays -> (JSON-able metadata, concatenated raw bytes).
    The binary segment of a frame; dtype/shape ride in the header."""
    metas, parts = [], []
    for a in arrays:
        a = np.ascontiguousarray(a)
        metas.append({"dtype": a.dtype.name, "shape": list(a.shape),
                      "nbytes": int(a.nbytes)})
        parts.append(a.tobytes())
    return metas, b"".join(parts)


def decode_arrays(metas: Sequence[Dict[str, Any]],
                  payload: bytes) -> List[np.ndarray]:
    out, off = [], 0
    for m in metas:
        dt = _resolve_dtype(m["dtype"])
        n = int(m["nbytes"])
        arr = np.frombuffer(payload, dtype=dt, offset=off,
                            count=n // dt.itemsize)
        out.append(arr.reshape(m["shape"]))
        off += n
    return out


def migration_to_wire(rec) -> Tuple[Dict[str, Any], bytes]:
    """:class:`~.disagg.MigrationRecord` -> (header dict, slab bytes).
    Quantized (int8-pool) records append their fp32 scale slabs as
    arrays 3 and 4 — the payload stays int8 on the wire; the array
    count in the header is what the decoder branches on."""
    slabs = [rec.kslab, rec.vslab]
    if getattr(rec, "kscale_slab", None) is not None:
        slabs += [rec.kscale_slab, rec.vscale_slab]
    metas, payload = encode_arrays(slabs)
    head = rec.to_header()
    head["arrays"] = metas
    return head, payload


def migration_from_wire(head: Dict[str, Any], payload: bytes):
    from deepspeed_tpu.inference.disagg import MigrationRecord
    arrays = decode_arrays(head["arrays"], payload)
    kscale = vscale = None
    if len(arrays) == 4:
        kscale, vscale = arrays[2], arrays[3]
    fields = {k: v for k, v in head.items() if k != "arrays"}
    return MigrationRecord(kslab=arrays[0], vslab=arrays[1],
                           kscale_slab=kscale, vscale_slab=vscale,
                           **fields)


def decode_migrations(headers: Sequence[Dict[str, Any]],
                      payload: bytes) -> List[Any]:
    """Unpack N concatenated migration records from one frame (the
    deathbed shape: every in-flight request in a single reply)."""
    out, off = [], 0
    for h in headers:
        n = sum(int(m["nbytes"]) for m in h["arrays"])
        out.append(migration_from_wire(h, payload[off:off + n]))
        off += n
    return out


def request_to_wire(req) -> Dict[str, Any]:
    """:class:`~.scheduler.Request` -> JSON dict. The uid ships
    explicitly: requests originate in the router process, so one uid
    space spans the fleet regardless of which child answers."""
    return {"prompt": list(req.prompt),
            "max_new_tokens": req.max_new_tokens,
            "temperature": req.temperature, "seed": req.seed,
            "eos_id": req.eos_id,
            "priority": getattr(req, "priority", 0), "uid": req.uid,
            # distributed-trace context: the router's stamp rides every
            # frame, so the replica-side tracer rows correlate across
            # the process boundary (None/0 for unstamped requests)
            "trace_id": getattr(req, "trace_id", None),
            "hop": getattr(req, "hop", 0)}


def request_from_wire(d: Dict[str, Any]):
    from deepspeed_tpu.inference.scheduler import Request
    return Request(prompt=list(d["prompt"]),
                   max_new_tokens=int(d.get("max_new_tokens", 16)),
                   temperature=float(d.get("temperature", 0.0)),
                   seed=int(d.get("seed", 0)), eos_id=d.get("eos_id"),
                   priority=int(d.get("priority", 0)),
                   uid=int(d["uid"]),
                   trace_id=d.get("trace_id"),
                   hop=int(d.get("hop", 0)))


# --------------------------------------------------------------- client
class RpcClient:
    """The router's end of one replica channel: synchronous calls with
    a per-call timeout and bounded exponential-backoff retry on
    transient transport faults (timeouts and EOF are terminal — a
    retried non-idempotent call could double-apply)."""

    def __init__(self, sock, timeout_s: float = 60.0, retries: int = 2,
                 backoff_s: float = 0.05, sleep: Callable = time.sleep,
                 name: str = "replica"):
        self._sock = sock
        self.timeout_s = float(timeout_s)
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self._sleep = sleep
        self.name = name
        self.calls = 0
        self.retried = 0

    def _inject(self, method: str) -> None:
        # the taxonomy's three fault hooks, each its own point so a
        # test (or DSTPU_FAULT_ARM) targets exactly one failure mode
        try:
            fault.fire("rpc.transport", method=method, name=self.name)
        except (fault.InjectedCrash, OSError) as e:
            raise RpcTransportError(
                f"injected transport fault: {e!r}", method=method)
        try:
            fault.fire("rpc.timeout", method=method, name=self.name)
        except (fault.InjectedCrash, OSError) as e:
            raise RpcTimeoutError(
                f"injected timeout: {e!r}", method=method)
        try:
            fault.fire("rpc.replica_dead", method=method,
                       name=self.name)
        except (fault.InjectedCrash, OSError) as e:
            raise ReplicaDeadError(
                f"injected replica death: {e!r}", method=method)

    def _call_once(self, method, params, payload, timeout_s
                   ) -> Tuple[Any, bytes]:
        deadline = self.timeout_s if timeout_s is None else timeout_s
        self._inject(method)
        try:
            self._sock.settimeout(deadline)
            send_frame(self._sock, {"method": method,
                                    "params": params or {}}, payload)
            header, out = recv_frame(self._sock)
        except socket.timeout as e:
            raise RpcTimeoutError(
                f"{method}: no reply within {deadline:g}s",
                method=method) from e
        except ReplicaDeadError as e:
            e.method = e.method or method
            raise
        except OSError as e:
            raise RpcTransportError(f"{method}: {e!r}",
                                    method=method) from e
        if not header.get("ok"):
            err = header.get("error") or {}
            raise RpcRemoteError(
                f"{method}: remote {err.get('kind', '?')}: "
                f"{err.get('message', '')}", method=method)
        return header.get("result"), out

    def call(self, method: str, params: Optional[Dict] = None,
             payload: bytes = b"", timeout_s: Optional[float] = None
             ) -> Tuple[Any, bytes]:
        """Returns ``(result, reply_payload)``; raises the taxonomy."""
        self.calls += 1
        for attempt in range(self.retries + 1):
            try:
                return self._call_once(method, params, payload,
                                       timeout_s)
            except RpcTransportError as e:
                if attempt >= self.retries:
                    raise
                delay = self.backoff_s * (2 ** attempt)
                self.retried += 1
                logger.warning(
                    f"rpc [{self.name}] {method}: transient transport "
                    f"fault ({e}); retry {attempt + 1}/"
                    f"{self.retries} in {delay:.3f}s")
                self._sleep(delay)
        raise AssertionError("unreachable")

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


# --------------------------------------------------------------- server
class ServerExit(Exception):
    """A handler's way to reply-then-stop: the server sends ``result``
    (+ ``payload``) as a normal ok frame and returns from serve().
    The worker's deathbed frame (dying=True + exports) rides this."""

    def __init__(self, result: Any = None, payload: bytes = b""):
        super().__init__("server exit")
        self.result = result
        self.payload = payload


class RpcServer:
    """The replica child's end: a blocking dispatch loop. ``dispatch``
    is ``(method, params, payload) -> (result, reply_payload)``;
    raising :class:`ServerExit` replies then stops the loop, any other
    exception becomes an ``{"ok": false}`` reply (the engine keeps
    serving)."""

    def __init__(self, sock):
        self._sock = sock

    def serve(self, dispatch: Callable) -> None:
        while True:
            try:
                header, payload = recv_frame(self._sock)
            except (ReplicaDeadError, OSError):
                return  # the router went away; nothing left to serve
            method = header.get("method", "")
            try:
                result, out = dispatch(method,
                                       header.get("params") or {},
                                       payload)
            except ServerExit as e:
                send_frame(self._sock, {"ok": True, "result": e.result},
                           e.payload)
                return
            except Exception as e:  # noqa: BLE001 — reply, keep serving
                send_frame(self._sock, {"ok": False, "error": {
                    "kind": "remote",
                    "message": f"{type(e).__name__}: {e}"}})
                continue
            send_frame(self._sock, {"ok": True, "result": result},
                       out or b"")


# ------------------------------------------------------------ transport
def listen_local() -> Tuple[socket.socket, int]:
    """Loopback listener on an ephemeral port (the child connects back
    with the port from its argv)."""
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)
    return srv, srv.getsockname()[1]


def connect_local(port: int, timeout_s: float = 30.0) -> socket.socket:
    sock = socket.create_connection(("127.0.0.1", port),
                                    timeout=timeout_s)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock
