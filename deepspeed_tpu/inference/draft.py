"""Host-side draft-token proposers for speculative decoding.

Speculative decoding amortizes decode dispatches: instead of one
compiled dispatch per generated token, a cheap *drafter* proposes up to
``k`` continuation tokens and the target model verifies all of them in
ONE seq-``k+1`` dispatch through the paged cached forward
(``inference/engine.py`` ``_verify_paged_impl``). Tokens are accepted
greedily-left-to-right while each draft matches what the target would
have sampled at that position; the first mismatch rolls the rest back —
on the paged KV pool that rollback is free (a position clamp: the
rejected positions' K/V writes sit beyond the clamped ``cache_position``
where the causal cache mask hides them, and the next dispatch's
contiguous writes overwrite them before any query can attend them).

The built-in drafter is **prompt-lookup / n-gram** (no second model):
find the most recent earlier occurrence of the current suffix n-gram in
the request's own history (prompt + generated tokens) and propose the
tokens that followed it. On repetitive workloads — code, templated
text, summarization quoting its source — this accepts several tokens
per dispatch with zero extra device work. :class:`CallableDrafter`
wraps an arbitrary ``fn(history, k) -> tokens`` for a small draft
model; the *scheduler-side* contract is identical either way.

Like the scheduler/paging/bucket modules, this is pure host code:
nothing here imports jax (pinned source-level by
tests/unit/test_inference.py) — drafting adds zero device dispatches
and cannot perturb the engine's fixed program set.
"""

from typing import Callable, Dict, List, Optional, Sequence

__all__ = ["NGramDrafter", "CallableDrafter", "make_drafter"]


class NGramDrafter:
    """Prompt-lookup drafting: propose the continuation of the most
    recent earlier occurrence of the history's trailing n-gram.

    Matches are tried longest-first (``ngram_max`` down to
    ``ngram_min``): a longer suffix match is stronger evidence the
    history is repeating, so its continuation is proposed first. The
    scan walks backwards so the MOST RECENT occurrence wins (recency
    beats frequency for serving workloads — the active pattern is the
    one being generated right now). Returns ``[]`` when no suffix
    recurs: the engine then falls back to plain one-token decode for
    that slot (a "draft stall" — traced, never an error).
    """

    def __init__(self, k: int = 4, ngram_min: int = 1,
                 ngram_max: int = 3):
        if k < 1:
            raise ValueError("k must be >= 1")
        if ngram_min < 1 or ngram_max < ngram_min:
            raise ValueError("need 1 <= ngram_min <= ngram_max")
        self.k = int(k)
        self.ngram_min = int(ngram_min)
        self.ngram_max = int(ngram_max)

    def propose(self, history: Sequence[int],
                k: Optional[int] = None) -> List[int]:
        """Up to ``k`` draft tokens continuing ``history`` (the
        request's prompt + all kept tokens, pending included)."""
        k = self.k if k is None else min(int(k), self.k)
        h = list(history)
        L = len(h)
        if k < 1 or L < 2:
            return []
        for n in range(min(self.ngram_max, L - 1), self.ngram_min - 1,
                       -1):
            tail = h[L - n:]
            # most recent earlier occurrence of the suffix n-gram;
            # i + n < L so at least one continuation token exists
            for i in range(L - n - 1, -1, -1):
                if h[i:i + n] == tail:
                    return h[i + n:i + n + k]
        return []


class CallableDrafter:
    """An injected draft model behind the same ``propose`` surface.

    ``fn(history, k)`` may be anything — a distilled model, a trie over
    a corpus, a grammar — as long as it returns at most ``k`` candidate
    int tokens synchronously on the host. The engine treats its output
    exactly like n-gram drafts: every token is verified by the target
    before it is kept, so a bad drafter can only cost acceptance rate,
    never correctness.
    """

    def __init__(self, fn: Callable[[Sequence[int], int], Sequence[int]],
                 k: int = 4):
        if k < 1:
            raise ValueError("k must be >= 1")
        self.fn = fn
        self.k = int(k)

    def propose(self, history: Sequence[int],
                k: Optional[int] = None) -> List[int]:
        k = self.k if k is None else min(int(k), self.k)
        if k < 1:
            return []
        out = [int(t) for t in self.fn(history, k)]
        return out[:k]


def make_drafter(spec_cfg: Dict, draft_fn: Optional[Callable] = None):
    """Build the drafter a parsed ``inference.spec_decode`` section asks
    for (None when the section is disabled). ``method: "callable"``
    requires ``draft_fn`` (the engine's ``draft_fn=`` constructor
    argument)."""
    if not spec_cfg.get("enabled", False):
        return None
    method = spec_cfg.get("method", "ngram")
    k = int(spec_cfg.get("k", 4))
    if method == "ngram":
        return NGramDrafter(k=k,
                            ngram_min=int(spec_cfg.get("ngram_min", 1)),
                            ngram_max=int(spec_cfg.get("ngram_max", 3)))
    if method == "callable":
        if draft_fn is None:
            raise ValueError(
                "spec_decode.method 'callable' needs a draft_fn "
                "(pass draft_fn= to the engine)")
        return CallableDrafter(draft_fn, k=k)
    raise ValueError(f"unknown spec_decode.method {method!r}")
