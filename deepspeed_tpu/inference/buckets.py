"""Shape bucketing for the serving engine.

XLA programs are shape-specialized: a fresh (batch, prompt_len) pair is
a fresh multi-second compile — the classic serving-latency killer. The
engine therefore pads every prefill batch to a configured (batch
bucket, prompt bucket) pair, so steady-state serving dispatches exactly
``len(batch_buckets) × len(prompt_buckets)`` prefill programs plus ONE
decode program, all compiled during warmup — pinned by the engine's
CompileTracker (zero recompiles after warmup is a tier-1 assertion).

Pure host-side helpers; no jax imports.
"""

from typing import List, Sequence, Tuple

import numpy as np

__all__ = ["pick_bucket", "validate_buckets", "pad_prompts",
           "warmup_plan", "chunk_warmup_plan"]


def validate_buckets(buckets: Sequence[int], name: str) -> Tuple[int, ...]:
    """Normalize a bucket list: ints, positive, strictly ascending."""
    if not buckets:
        raise ValueError(f"{name} must be a non-empty list of ints")
    out = tuple(int(b) for b in buckets)
    if any(b <= 0 for b in out):
        raise ValueError(f"{name} must be positive, got {list(out)}")
    if list(out) != sorted(set(out)):
        raise ValueError(f"{name} must be strictly ascending "
                         f"(got {list(out)})")
    return out


def pick_bucket(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= n. Raises when n exceeds the largest bucket —
    the caller (scheduler admission / engine submit) surfaces that as a
    rejected request rather than a silent recompile."""
    for b in buckets:
        if n <= b:
            return int(b)
    raise ValueError(f"{n} exceeds the largest bucket {max(buckets)}")


def pad_prompts(prompts: Sequence[Sequence[int]], bucket_len: int,
                bucket_batch: int) -> Tuple[np.ndarray, np.ndarray]:
    """Right-pad prompts with 0 into an (bucket_batch, bucket_len) int32
    batch plus true lengths (bucket_batch,) int32. Padding rows (beyond
    ``len(prompts)``) carry length 1 so downstream last-token gathers
    stay in range; their outputs are discarded (the engine scatters
    their cache rows into the scratch slot).
    """
    n = len(prompts)
    if n > bucket_batch:
        raise ValueError(f"{n} prompts exceed batch bucket {bucket_batch}")
    ids = np.zeros((bucket_batch, bucket_len), np.int32)
    lengths = np.ones((bucket_batch,), np.int32)
    for i, p in enumerate(prompts):
        arr = np.asarray(p, np.int32).reshape(-1)
        if arr.size == 0 or arr.size > bucket_len:
            raise ValueError(f"prompt length {arr.size} outside (0, "
                             f"{bucket_len}]")
        ids[i, :arr.size] = arr
        lengths[i] = arr.size
    return ids, lengths


def warmup_plan(batch_buckets: Sequence[int],
                prompt_buckets: Sequence[int]) -> List[Tuple[int, int]]:
    """Every (batch_bucket, prompt_bucket) pair the steady state can
    dispatch — the warmup compile set."""
    return [(int(b), int(s)) for b in batch_buckets for s in prompt_buckets]


def chunk_warmup_plan(batch_buckets: Sequence[int],
                      chunk_tokens: int) -> List[Tuple[int, int]]:
    """The chunked-prefill warmup compile set: one (batch_bucket,
    chunk_tokens) shape per batch bucket. This is the ladder collapse —
    chunked prefill replaces the ``len(batch_buckets) ×
    len(prompt_buckets)`` prompt-bucket grid with a single token width,
    so prompt length stops being a compile axis entirely (any length up
    to max_seq_len is a row count of chunk dispatches, not a new
    program)."""
    if chunk_tokens <= 0:
        return []
    return [(int(b), int(chunk_tokens)) for b in batch_buckets]
