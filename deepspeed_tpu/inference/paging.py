"""Host-side page bookkeeping for the paged KV cache.

Split out of ``inference/kv_cache.py`` so the scheduler's imports stay
jax-free (``kv_cache.py`` needs jax for array allocation; nothing here
touches an array — page movement is pure Python, which is exactly why
the compiled program set is untouched by it). ``kv_cache`` re-exports
:class:`PageAllocator` and :func:`pages_for`, so either import path
works.
"""

from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["PageAllocator", "pages_for"]


def pages_for(tokens: int, page_size: int) -> int:
    """Pages needed to hold ``tokens`` cache positions."""
    return -(-int(tokens) // int(page_size))


class PageAllocator:
    """Host-side page bookkeeping: free list, per-page refcounts, and
    the prefix cache (chain-hashed full prompt pages).

    Pure host code, no jax — page allocation happens in the scheduler
    (the jit programs only ever see static-shape block tables), so the
    compiled program set is untouched by how pages move.

    Refcount discipline: every page in a live request's block table
    holds one reference per reader. Shared prefix pages are incref'd by
    each reusing request at admission; a page returns to the free list
    only when its LAST reader evicts (refcount hits 0), at which point
    its prefix-cache entry (if any) is dropped too.

    Prefix chain hash: page *i* of a prompt hashes ``(hash of pages
    <i, tokens of page i)`` — one dict lookup per page, no token-level
    rescans. The hash is ONLY an index: a hit additionally verifies the
    candidate page's own token chunk AND that its registered *parent*
    is the exact physical page the walk just verified at position
    ``i-1``. By induction the matched page's K/V was therefore
    prefilled under precisely the claimed token prefix — a crafted
    chain-hash collision (builtin tuple hashing is predictable) can
    never hand one request K/V computed under another prompt's context,
    even when the colliding page's own chunk matches.
    """

    def __init__(self, num_pages: int, page_size: int,
                 prefix_cache: bool = True):
        if num_pages < 2:
            raise ValueError(
                f"PageAllocator needs >= 2 pages (one is the reserved "
                f"null page), got {num_pages}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.prefix_cache_enabled = bool(prefix_cache)
        self._free: List[int] = list(range(1, self.num_pages))
        self._ref: Dict[int, int] = {}
        self._prefix: Dict[int, int] = {}        # chain hash -> page id
        self._page_hash: Dict[int, int] = {}     # page id -> chain hash
        # page id -> the exact token chunk it holds, and the physical
        # page registered immediately before it (None for a prompt's
        # first page): hits verify CONTENT and PARENT, the hash is only
        # an index — see the class docstring
        self._page_tokens: Dict[int, Tuple[int, ...]] = {}
        self._page_parent: Dict[int, Optional[int]] = {}
        # cumulative telemetry
        self.prefix_hit_tokens = 0
        self.prefix_miss_tokens = 0
        self.prefix_hit_requests = 0     # admissions that reused pages
        self.prefix_evictions = 0        # cache entries dropped on free

    # ------------------------------------------------------------ state
    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - 1 - len(self._free)

    def refcount(self, page: int) -> int:
        return self._ref.get(page, 0)

    @property
    def prefix_entries(self) -> int:
        """Live prefix-cache entries (pages currently matchable)."""
        return len(self._prefix)

    def debug_state(self) -> dict:
        """Pool snapshot for live introspection (engine.debug_state()):
        occupancy, sharing, and prefix-cache accounting — pure host
        reads, no device touch."""
        shared = sum(1 for c in self._ref.values() if c > 1)
        return {
            "num_pages": self.num_pages,
            "page_size": self.page_size,
            "pages_free": self.free_pages,
            "pages_in_use": self.pages_in_use,
            "pages_shared": shared,
            "shared_duplicate_tokens": self.shared_duplicate_tokens,
            "prefix_cache": {
                "enabled": self.prefix_cache_enabled,
                "entries": self.prefix_entries,
                "hit_requests": self.prefix_hit_requests,
                "hit_tokens": self.prefix_hit_tokens,
                "miss_tokens": self.prefix_miss_tokens,
                "evictions": self.prefix_evictions,
            },
        }

    @property
    def shared_duplicate_tokens(self) -> int:
        """Tokens counted more than once when summing per-reader context
        lengths. Only prefix sharing ever raises a refcount above 1, and
        shared prefix pages are always FULL pages, so each extra reader
        of a page duplicates exactly ``page_size`` tokens."""
        return sum((c - 1) * self.page_size
                   for c in self._ref.values() if c > 1)

    # ------------------------------------------------------ alloc / free
    def alloc(self, n: int) -> Optional[List[int]]:
        """Take ``n`` pages off the free list (refcount 1 each), or
        None — never a partial grab — when the pool can't supply them."""
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._ref[p] = 1
        return pages

    def incref(self, pages: Sequence[int]):
        for p in pages:
            if self._ref.get(p, 0) < 1:
                raise ValueError(f"incref of unowned page {p}")
            self._ref[p] += 1

    def free(self, pages: Sequence[int]):
        """Drop one reference per page; pages whose count hits 0 return
        to the free list and lose their prefix-cache entry — shared
        prefix pages survive exactly until their last reader evicts."""
        for p in pages:
            c = self._ref.get(p, 0)
            if c < 1:
                raise ValueError(f"free of unowned page {p}")
            if c == 1:
                del self._ref[p]
                h = self._page_hash.pop(p, None)
                if h is not None and self._prefix.get(h) == p:
                    del self._prefix[h]
                    self.prefix_evictions += 1
                self._page_tokens.pop(p, None)
                self._page_parent.pop(p, None)
                self._free.append(p)
            else:
                self._ref[p] = c - 1

    # ----------------------------------------------------- prefix cache
    def _chain_hashes(self, tokens: Sequence[int]):
        """Chain hash per FULL page of ``tokens`` (partial tail pages
        are private — they still take decode writes). Lazy: admission
        re-scans blocked candidates every step, and a first-page miss
        should cost one page hash, not the whole prompt's."""
        ps = self.page_size
        h = 0
        for i in range(len(tokens) // ps):
            h = hash((h, tuple(tokens[i * ps:(i + 1) * ps])))
            yield h

    def match_prefix(self, tokens: Sequence[int]) -> Tuple[List[int], int]:
        """Longest cached page-aligned prefix of ``tokens``: returns
        ``(page_ids, n_tokens)``. Does NOT take references — the caller
        increfs once it commits to reusing them."""
        if not self.prefix_cache_enabled or not self._prefix:
            return [], 0
        ps = self.page_size
        pages: List[int] = []
        prev: Optional[int] = None
        for i, h in enumerate(self._chain_hashes(tokens)):
            p = self._prefix.get(h)
            if p is None or self._ref.get(p, 0) < 1:
                break
            # the hash only located the candidate: verify its chunk AND
            # that it was registered directly after the page matched at
            # i-1 — deep-layer K/V depends on the WHOLE prefix, so a
            # colliding page with the right chunk but a different
            # registered context must not serve (class docstring)
            if self._page_tokens.get(p) != tuple(
                    tokens[i * ps:(i + 1) * ps]):
                break
            if self._page_parent.get(p, -1) != prev:
                break
            pages.append(p)
            prev = p
        return pages, len(pages) * ps

    def register_prefix(self, tokens: Sequence[int],
                        pages: Sequence[int]):
        """Publish a request's full prompt pages into the prefix cache
        (``pages`` = its complete block-table pages, shared prefix
        included; only the full-prompt-page span registers). First
        registration of a hash wins — concurrent identical prompts all
        map to one physical page set."""
        if not self.prefix_cache_enabled:
            return
        ps = self.page_size
        for i, h in enumerate(self._chain_hashes(tokens)):
            if h in self._prefix:
                continue
            p = pages[i]
            self._prefix[h] = p
            self._page_hash[p] = h
            self._page_tokens[p] = tuple(tokens[i * ps:(i + 1) * ps])
            self._page_parent[p] = pages[i - 1] if i > 0 else None
