"""KV-cache allocation and accounting for the serving engine.

Two cache geometries live here:

**Dense (legacy, ``paged_kv.enabled: false``)** — ONE preallocated pair
of arrays ``(kc, vc)``, each shaped ``(layers, batch_rows, kv_heads,
max_len, head_dim)``: every serving slot owns a full ``max_len`` stripe
whether its request is 6 tokens or 6000. ``batch_rows`` is
``max_batch_size + 1``: the extra row is the *scratch slot* — padding
rows of a partially-filled prefill bucket scatter their (garbage) K/V
there instead of corrupting a live request's slot.

**Paged (default)** — a fixed pool of ``num_pages`` pages, each
``(kv_heads, page_size, head_dim)``, as one pair of arrays shaped
``(layers, num_pages, kv_heads, page_size, head_dim)``, plus a
host-side :class:`PageAllocator`. A request occupies
``ceil(total_tokens / page_size)`` pages mapped through a static-shape
per-slot *block table*; HBM occupancy is therefore bounded by the
tokens actually reserved in flight, not ``slots x max_len``. Page 0 is
reserved as the *null page*: unallocated block-table entries and
padding-row writes all land there (its contents are garbage by design
and never read unmasked — ``causal_cache_mask`` hides every position a
query has not reached). The allocator also implements **prefix
caching**: full, page-aligned prompt prefixes are chain-hashed and
refcounted, so concurrent requests sharing a system prompt prefill the
shared pages once.

Writes happen inside the model forwards via
:func:`deepspeed_tpu.models.gpt2.write_kv_cache` (dense) /
:func:`deepspeed_tpu.models.gpt2.write_paged_kv_cache` (paged); this
module only owns allocation, the family-specific geometry (GQA caches
are kv_heads-sized), and byte accounting for telemetry.
"""

from typing import Any, NamedTuple, Tuple

import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.paging import PageAllocator, pages_for

__all__ = ["KVCacheSpec", "cache_spec_for", "init_kv_cache",
           "kv_cache_bytes", "PagedKVSpec", "paged_spec_for",
           "init_paged_kv_cache", "paged_kv_bytes", "pages_for",
           "PageAllocator"]


class KVCacheSpec(NamedTuple):
    """Static geometry of the dense serving KV cache."""
    num_layers: int
    batch_rows: int      # serving slots + 1 scratch row
    kv_heads: int        # GQA: the cache stays kv_heads-sized
    max_len: int
    head_dim: int
    dtype: Any = jnp.bfloat16

    @property
    def shape(self) -> Tuple[int, int, int, int, int]:
        return (self.num_layers, self.batch_rows, self.kv_heads,
                self.max_len, self.head_dim)


def _model_kv_geometry(model_config):
    kv_heads = getattr(model_config, "kv_heads", None) or \
        model_config.num_heads
    head_dim = getattr(model_config, "head_dim", None) or (
        model_config.hidden_size // model_config.num_heads)
    return kv_heads, head_dim


def cache_spec_for(model_config, batch_rows: int, max_len: int,
                   dtype=jnp.bfloat16) -> KVCacheSpec:
    """Dense cache geometry from a model config (GPT2Config /
    LlamaConfig): kv_heads-sized for GQA families, head-count-sized
    otherwise."""
    kv_heads, head_dim = _model_kv_geometry(model_config)
    if max_len > model_config.max_position_embeddings:
        raise ValueError(
            f"kv cache max_len {max_len} exceeds the model's "
            f"max_position_embeddings {model_config.max_position_embeddings}")
    return KVCacheSpec(num_layers=model_config.num_layers,
                       batch_rows=batch_rows, kv_heads=kv_heads,
                       max_len=max_len, head_dim=head_dim, dtype=dtype)


def init_kv_cache(spec: KVCacheSpec):
    """Allocate the zeroed ``(kc, vc)`` pair."""
    return (jnp.zeros(spec.shape, spec.dtype),
            jnp.zeros(spec.shape, spec.dtype))


def _pair_bytes(spec) -> int:
    """Bytes of a (kc, vc) array pair with ``spec.shape``/``spec.dtype``
    — the one accounting both cache geometries report."""
    return 2 * int(np.prod(spec.shape)) * jnp.dtype(spec.dtype).itemsize


def kv_cache_bytes(spec: KVCacheSpec) -> int:
    """Total bytes of the (kc, vc) pair — the serving memory headline."""
    return _pair_bytes(spec)


# --------------------------------------------------------------------- #
# paged cache
# --------------------------------------------------------------------- #
class PagedKVSpec(NamedTuple):
    """Static geometry of the paged serving KV cache. ``pages_per_seq``
    is the block-table width: every slot's table maps that many logical
    page positions (covering ``max_len`` tokens), entries beyond its
    reservation pointing at the null page 0.

    **Quantized pool (PR 17)** — ``dtype=int8`` switches the pool to
    int8 payload with per-token-row fp32 absmax scales stored alongside
    (the EQuARX/qwZ recipe applied to the KV pool): the cache tree
    becomes the 4-tuple ``(kc, vc, kscale, vscale)`` where the scale
    pools are shaped ``(layers, num_pages, kv_heads, page_size,
    scale_blocks)``. ``quant_block`` is the scale granularity along
    head_dim (0 = one scale per token row, i.e. the whole head_dim);
    scales are per token row because decode fills pages one token at a
    time — a page-wide scale would be rewritten (and degrade) on every
    append."""
    num_layers: int
    num_pages: int       # pool size, INCLUDING the reserved null page 0
    page_size: int
    kv_heads: int
    head_dim: int
    pages_per_seq: int
    dtype: Any = jnp.bfloat16
    quant_block: int = 0  # scale block over head_dim (0 = head_dim)

    @property
    def shape(self) -> Tuple[int, int, int, int, int]:
        return (self.num_layers, self.num_pages, self.kv_heads,
                self.page_size, self.head_dim)

    @property
    def quantized(self) -> bool:
        return jnp.dtype(self.dtype) == jnp.dtype(jnp.int8)

    @property
    def scale_blocks(self) -> int:
        """Scales per token row: head_dim / quant_block."""
        block = self.quant_block or self.head_dim
        return self.head_dim // block

    @property
    def scale_shape(self) -> Tuple[int, int, int, int, int]:
        return (self.num_layers, self.num_pages, self.kv_heads,
                self.page_size, self.scale_blocks)


def paged_spec_for(model_config, num_pages: int, page_size: int,
                   max_len: int, dtype=jnp.bfloat16,
                   kv_quant_block: int = 0) -> PagedKVSpec:
    """Paged cache geometry from a model config. ``num_pages == 0``
    auto-sizes the pool to the dense worst case (every slot is not known
    here, so callers pass the resolved count); the engine resolves 0
    before calling. ``dtype=int8`` selects the quantized pool;
    ``kv_quant_block`` (0 = head_dim) sets the per-row scale block and
    must divide head_dim."""
    kv_heads, head_dim = _model_kv_geometry(model_config)
    if max_len > model_config.max_position_embeddings:
        raise ValueError(
            f"paged kv cache max_len {max_len} exceeds the model's "
            f"max_position_embeddings {model_config.max_position_embeddings}")
    if page_size < 1 or num_pages < 2:
        raise ValueError(
            f"paged kv cache needs page_size >= 1 and num_pages >= 2 "
            f"(one null + one usable), got page_size={page_size}, "
            f"num_pages={num_pages}")
    quantized = jnp.dtype(dtype) == jnp.dtype(jnp.int8)
    block = int(kv_quant_block) if quantized else 0
    if quantized and block and head_dim % block != 0:
        raise ValueError(
            f"paged kv cache kv_quant_block ({block}) must divide "
            f"head_dim ({head_dim})")
    return PagedKVSpec(num_layers=model_config.num_layers,
                       num_pages=num_pages, page_size=page_size,
                       kv_heads=kv_heads, head_dim=head_dim,
                       pages_per_seq=pages_for(max_len, page_size),
                       dtype=dtype, quant_block=block)


def init_paged_kv_cache(spec: PagedKVSpec):
    """Allocate the zeroed paged pool tree: the ``(kc, vc)`` pair, plus
    ``(kscale, vscale)`` fp32 scale pools when the spec is int8-
    quantized (4-tuple). Every engine cache op is leaf-generic over this
    tuple, so the two geometries share one code path."""
    pools = (jnp.zeros(spec.shape, spec.dtype),
             jnp.zeros(spec.shape, spec.dtype))
    if spec.quantized:
        # zero scales are fine: the null page / unwritten rows are never
        # read unmasked, and quantized writes always store a scale > 0
        pools = pools + (jnp.zeros(spec.scale_shape, jnp.float32),
                         jnp.zeros(spec.scale_shape, jnp.float32))
    return pools


def paged_kv_bytes(spec: PagedKVSpec) -> int:
    """Total bytes of the paged pool tree — int8 payload + fp32 scales
    when quantized (the KV lever of ``quant_serving_bytes``)."""
    total = _pair_bytes(spec)
    if spec.quantized:
        total += 2 * int(np.prod(spec.scale_shape)) * 4
    return total
