"""KV-cache allocation and accounting for the serving engine.

The cache is ONE preallocated pair of arrays ``(kc, vc)``, each shaped
``(layers, batch_rows, kv_heads, max_len, head_dim)`` — the static
buffer the jit-compiled prefill/decode programs carry (and donate) so
steady-state serving never allocates, never reshapes, and therefore
never recompiles. ``batch_rows`` is ``max_batch_size + 1``: the extra
row is the *scratch slot* — padding rows of a partially-filled prefill
bucket scatter their (garbage) K/V there instead of corrupting a live
request's slot.

Writes happen inside the model forwards via
:func:`deepspeed_tpu.models.gpt2.write_kv_cache` (per-row
``lax.dynamic_update_slice``); this module only owns allocation, the
family-specific geometry (GQA caches are kv_heads-sized), and byte
accounting for telemetry.
"""

from typing import Any, NamedTuple, Tuple

import jax.numpy as jnp
import numpy as np

__all__ = ["KVCacheSpec", "cache_spec_for", "init_kv_cache",
           "kv_cache_bytes"]


class KVCacheSpec(NamedTuple):
    """Static geometry of the serving KV cache."""
    num_layers: int
    batch_rows: int      # serving slots + 1 scratch row
    kv_heads: int        # GQA: the cache stays kv_heads-sized
    max_len: int
    head_dim: int
    dtype: Any = jnp.bfloat16

    @property
    def shape(self) -> Tuple[int, int, int, int, int]:
        return (self.num_layers, self.batch_rows, self.kv_heads,
                self.max_len, self.head_dim)


def cache_spec_for(model_config, batch_rows: int, max_len: int,
                   dtype=jnp.bfloat16) -> KVCacheSpec:
    """Cache geometry from a model config (GPT2Config / LlamaConfig):
    kv_heads-sized for GQA families, head-count-sized otherwise."""
    kv_heads = getattr(model_config, "kv_heads", None) or \
        model_config.num_heads
    head_dim = getattr(model_config, "head_dim", None) or (
        model_config.hidden_size // model_config.num_heads)
    if max_len > model_config.max_position_embeddings:
        raise ValueError(
            f"kv cache max_len {max_len} exceeds the model's "
            f"max_position_embeddings {model_config.max_position_embeddings}")
    return KVCacheSpec(num_layers=model_config.num_layers,
                       batch_rows=batch_rows, kv_heads=kv_heads,
                       max_len=max_len, head_dim=head_dim, dtype=dtype)


def init_kv_cache(spec: KVCacheSpec):
    """Allocate the zeroed ``(kc, vc)`` pair."""
    return (jnp.zeros(spec.shape, spec.dtype),
            jnp.zeros(spec.shape, spec.dtype))


def kv_cache_bytes(spec: KVCacheSpec) -> int:
    """Total bytes of the (kc, vc) pair — the serving memory headline."""
    return 2 * int(np.prod(spec.shape)) * jnp.dtype(spec.dtype).itemsize
