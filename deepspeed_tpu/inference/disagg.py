"""Host-side bookkeeping for disaggregated prefill/decode serving.

Heavy-traffic serving splits into two phases with opposite resource
profiles: prefill is compute-bound (one big batched matmul pass over
the prompt), decode is bandwidth-bound (one token per request per
dispatch, reads dominated by KV traffic). Interleaving them in one
loop makes every prefill dispatch stall every in-flight request's next
token. Disaggregation runs them as separate worker loops — a *prefill
worker* fed by the admission window and a *decode worker* that owns
token generation — connected by a **page handoff**: a completed
prefill's KV state transfers to the decode loop by moving block-table
ownership.

Two handoff modes (the engine picks per config):

- **shared pool** (same mesh): the pages already live where decode
  reads them — the handoff is a zero-copy host bookkeeping move
  (this module), exactly like a refcount transfer. Cost: queue time
  only.
- **separate pools** (optionally separate meshes): only the LIVE pages
  (``ceil(prompt / page_size)`` — never the full reservation) are
  exported from the prefill pool, shipped to the decode mesh, and
  scattered into the decode pool (the jit half lives in
  ``inference/engine.py``). The wire cost is priced per hop by the
  PR 6 ``LinkModel`` (:func:`price_handoff` duck-types it, so this
  module stays import-clean).

This module is the pure host-side half — the handoff queue, transfer
records, wire pricing, and the dispatch interleaving trace that pins
"no decode dispatch waits behind a prefill dispatch" (the decode phase
of every engine step runs FIRST). Nothing here imports jax (pinned
source-level by tests/unit/test_inference.py, like scheduler/paging/
buckets/draft): handoff POLICY is unit-testable in microseconds and
cannot perturb the compiled program set.
"""

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

__all__ = ["HandoffRecord", "HandoffQueue", "HandoffStats",
           "DispatchTrace", "MigrationRecord", "price_handoff"]


@dataclass
class HandoffRecord:
    """One completed prefill awaiting decode-side adoption.

    ``first_token`` is the token the prefill dispatch sampled — it is
    NOT released to the request until the decode worker claims the
    handoff (TTFT honestly includes handoff wait). ``live_pages`` is
    the page count actually holding prompt K/V (what a cross-pool
    transfer must move); the slot's full reservation never travels.
    """
    uid: int
    slot: int
    first_token: int
    live_pages: int
    prompt_tokens: int
    t_ready: float
    attempts: int = 0


class HandoffQueue:
    """FIFO of completed prefills between the worker loops.

    The decode worker drains it at the START of its phase; a claim can
    fail (decode pool can't reserve the request's lifetime pages yet)
    and the record is then re-queued — decode-side memory pressure
    backpressures the handoff, never the prefill loop. Counters feed
    ``engine.debug_state()`` and the ``serve_handoff`` trail rows.
    """

    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self._q: List[HandoffRecord] = []
        self.total_handoffs = 0       # claims completed
        self.total_requeues = 0       # claims bounced (pool pressure)
        self.total_dropped = 0        # records voided by eviction
        self.peak_depth = 0

    def __len__(self) -> int:
        return len(self._q)

    def push(self, rec: HandoffRecord) -> None:
        self._q.append(rec)
        self.peak_depth = max(self.peak_depth, len(self._q))

    def drain(self) -> List[HandoffRecord]:
        """Take every waiting record (the decode phase claims them in
        arrival order; unclaimable ones come back via :meth:`requeue`)."""
        out, self._q = self._q, []
        return out

    def requeue(self, rec: HandoffRecord) -> None:
        """Put a record back at the FRONT (its arrival order survives a
        bounced claim — the retry next step precedes newer handoffs)."""
        rec.attempts += 1
        self._q.insert(0, rec)
        self.total_requeues += 1

    def claimed(self, rec: HandoffRecord) -> float:
        """Account one completed claim; returns the record's total
        queue wait in ms."""
        self.total_handoffs += 1
        return (self._clock() - rec.t_ready) * 1e3

    def dropped(self, rec: HandoffRecord) -> None:
        """The request was evicted while its handoff waited — the
        record is void (its pages were already freed by the
        scheduler's eviction path)."""
        self.total_dropped += 1

    def pop(self, uid: int) -> Optional[HandoffRecord]:
        """Remove and return the queued record for ``uid`` (None if no
        record waits). Cancellation uses this: a record left in the
        queue after its slot is evicted would sit as a phantom entry
        until the next claim drain — or forever, if the eviction made
        the scheduler idle and the serving loop exits."""
        for i, rec in enumerate(self._q):
            if rec.uid == uid:
                del self._q[i]
                return rec
        return None

    def debug_state(self) -> Dict[str, int]:
        return {"depth": len(self._q), "peak_depth": self.peak_depth,
                "handoffs": self.total_handoffs,
                "requeues": self.total_requeues,
                "dropped": self.total_dropped}


def price_handoff(n_pages: int, page_bytes: int, link,
                  axis: str = "inter", hops: int = 1) -> float:
    """Modeled wire cost (ms) of moving ``n_pages`` pages across
    ``hops`` links, priced by a ``runtime/comm_autotune.LinkModel``
    (duck-typed: anything with ``bytes_per_us(axis)`` /
    ``latency_us(axis)``). Same-pool handoffs cost 0 — no bytes move.
    The priced figure rides the ``serve_handoff`` event row next to
    the measured wall time, so a handoff that costs more than the
    model predicts is visible per request."""
    if n_pages <= 0 or hops <= 0:
        return 0.0
    bytes_moved = float(n_pages) * float(page_bytes)
    us = hops * (link.latency_us(axis)
                 + bytes_moved / link.bytes_per_us(axis))
    return us / 1e3


@dataclass
class MigrationRecord:
    """One in-flight request's complete portable state: everything a
    destination engine needs to resume decode at the same
    ``cache_position`` with bitwise-identical outputs (ISSUE 16 live
    KV migration — the cross-*replica* sibling of the cross-pool
    :class:`HandoffRecord`).

    ``kslab``/``vslab`` are the live pages' K/V contents gathered by
    the warmup-compiled export program, trimmed to ``live_pages``
    (shape ``(layers, live_pages, kv_heads, page_size, head_dim)``,
    host numpy — they ship as the raw binary segment of an RPC frame).
    Quantized (int8) pools additionally carry
    ``kscale_slab``/``vscale_slab`` — the per-token-row fp32 scales,
    shape ``(layers, live_pages, kv_heads, page_size, scale_blocks)``
    — so migrated pages stay int8 on the wire and the destination
    scatters payload + scales as one leaf-generic import. An fp-pool
    record leaves them None; the destination engine rejects any
    payload/scale combination its own pool geometry can't hold.
    Resume is bitwise because sampling keys derive from
    ``(request seed, absolute position)`` — never from batch
    composition or wall clock — and clocks are shipped as *elapsed*
    durations (``elapsed_ms`` since submit, ``queue_wait_ms``,
    ``ttft_ms``), not absolute host times, because source and
    destination perf counters share no epoch.
    """
    uid: int
    prompt: List[int]
    max_new_tokens: int
    temperature: float
    seed: int
    eos_id: Optional[int]
    priority: int
    position: int                 # next write position (cache rows
    pending_tok: int              # 0..position-1 are live content)
    tokens: List[int]             # generated so far (incl. pending)
    live_pages: int               # pages with real content
    page_bytes: int               # source pool page size (pricing)
    ttft_ms: Optional[float]
    queue_wait_ms: float
    elapsed_ms: float             # clock() - t_submit at export time
    draft_proposed: int = 0
    draft_accepted: int = 0
    weight_version: Optional[str] = None
    # distributed-trace context (ISSUE 18): the router-stamped trace id
    # and the hop ordinal AT EXPORT TIME ride the record so the
    # destination's ``serve_migrate_in`` row (hop + 1) links to the
    # source's ``serve_migrate_out`` — request lineage survives replica
    # death. Durations-not-absolute-times doctrine unchanged: trace ids
    # are opaque strings, alignment stays in ``clock_sync`` rows.
    trace_id: Optional[str] = None
    hop: int = 0
    kslab: Optional[object] = None    # numpy (layers, live, kvh, ps, hd)
    vslab: Optional[object] = None
    kscale_slab: Optional[object] = None  # fp32 (layers, live, kvh, ps, nb)
    vscale_slab: Optional[object] = None  # (int8 pools only)

    def to_header(self) -> Dict:
        """The JSON-able half (slabs ride the frame's binary segment —
        see rpc.migration_to_wire)."""
        return {
            "uid": self.uid, "prompt": list(self.prompt),
            "max_new_tokens": self.max_new_tokens,
            "temperature": self.temperature, "seed": self.seed,
            "eos_id": self.eos_id, "priority": self.priority,
            "position": self.position, "pending_tok": self.pending_tok,
            "tokens": list(self.tokens),
            "live_pages": self.live_pages,
            "page_bytes": self.page_bytes, "ttft_ms": self.ttft_ms,
            "queue_wait_ms": self.queue_wait_ms,
            "elapsed_ms": self.elapsed_ms,
            "draft_proposed": self.draft_proposed,
            "draft_accepted": self.draft_accepted,
            "weight_version": self.weight_version,
            "trace_id": self.trace_id, "hop": self.hop,
        }

    @property
    def nbytes(self) -> int:
        return sum(int(getattr(s, "nbytes", 0)) for s in (
            self.kslab, self.vslab, self.kscale_slab, self.vscale_slab))


class DispatchTrace:
    """The interleaving trace of device dispatches under disaggregated
    serving: (step, kind) per dispatch, kind in {"decode", "verify",
    "prefill", "handoff", "chunk"}. The structural serving guarantee —
    no decode dispatch ever waits behind a prefill dispatch — is
    checkable as pure ordering: within every step, all decode/verify
    ordinals precede all prefill ordinals (the engine's disagg step
    runs its decode phase first; chunked prefill slips its at-most-one
    "chunk" dispatch between them, after every decode of the step).
    Bounded (ring of ``cap`` entries) so a serving daemon can leave it
    on."""

    DECODE_KINDS = ("decode", "verify", "handoff")

    def __init__(self, cap: int = 4096):
        self.cap = int(cap)
        self._rows: List[Tuple[int, str]] = []
        self.total = 0

    def record(self, step: int, kind: str) -> None:
        self._rows.append((int(step), str(kind)))
        self.total += 1
        if len(self._rows) > self.cap:
            del self._rows[:len(self._rows) - self.cap]

    def rows(self) -> List[Tuple[int, str]]:
        return list(self._rows)

    def decode_first_fraction(self) -> Optional[float]:
        """Fraction of traced steps where every decode-phase dispatch
        precedes every prefill dispatch of the same step (1.0 = the
        never-blocked-behind-prefill pin holds; None = no step mixed
        both phases, nothing to measure)."""
        by_step: Dict[int, List[str]] = {}
        for step, kind in self._rows:
            by_step.setdefault(step, []).append(kind)
        mixed = ok = 0
        for kinds in by_step.values():
            if "prefill" not in kinds or not any(
                    k in self.DECODE_KINDS for k in kinds):
                continue
            mixed += 1
            first_prefill = kinds.index("prefill")
            if all(k == "prefill" for k in kinds[first_prefill:]):
                ok += 1
        return (ok / mixed) if mixed else None


@dataclass
class HandoffStats:
    """Rolling same-process aggregates for ``debug_state()`` (the
    event rows carry per-request detail; this is the cheap live
    view)."""
    count: int = 0
    queue_ms_sum: float = 0.0
    transfer_ms_sum: float = 0.0
    bytes_moved: int = 0
    pages_moved: int = 0

    def record(self, queue_ms: float, transfer_ms: float,
               pages: int, nbytes: int) -> None:
        self.count += 1
        self.queue_ms_sum += queue_ms
        self.transfer_ms_sum += transfer_ms
        self.pages_moved += pages
        self.bytes_moved += nbytes

    def snapshot(self) -> Dict[str, float]:
        n = max(self.count, 1)
        return {"handoffs": self.count,
                "queue_ms_mean": round(self.queue_ms_sum / n, 3),
                "transfer_ms_mean": round(self.transfer_ms_sum / n, 3),
                "pages_moved": self.pages_moved,
                "bytes_moved": self.bytes_moved}
