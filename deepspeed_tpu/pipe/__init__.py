"""Top-level ``deepspeed_tpu.pipe`` alias (reference deepspeed/pipe/
__init__.py): tutorials write ``from deepspeed.pipe import
PipelineModule`` — the same import path works here."""

from deepspeed_tpu.runtime.pipe import (  # noqa
    LayerSpec, PipelineModule, PipelineSpec, TiedLayerSpec)
