"""Block-sparse attention subsystem (reference:
deepspeed/ops/sparse_attention/__init__.py) — sparsity layout configs,
the fused Pallas block-sparse kernel, and attention modules."""

from deepspeed_tpu.ops.sparse_attention.sparsity_config import (  # noqa
    SparsityConfig, DenseSparsityConfig, FixedSparsityConfig,
    VariableSparsityConfig, BigBirdSparsityConfig,
    BSLongformerSparsityConfig, sparsity_config_from_dict)
from deepspeed_tpu.ops.sparse_attention.blocksparse import (  # noqa
    block_sparse_attention, block_sparse_attention_reference,
    build_row_luts, build_col_luts, layout_additive_mask)
from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (  # noqa
    SparseSelfAttention, BertSparseSelfAttention,
    init_bert_sparse_self_attention_params, SparseAttentionUtils)
from deepspeed_tpu.ops.sparse_attention.ops import (  # noqa
    MatMul, Softmax)
