"""Row-run block-sparse attention kernels (splash v2).

The v1 kernels (blocksparse.py) launch ONE grid program per nonzero
(row, col) block triple: at a 128-block Longformer S=8192 layout that is
~10k sequential program launches of a single 128x128x64 matmul each —
per-program launch overhead dominates and the kernel loses to dense
flash despite doing ~1/3 the FLOPs.

v2 launches one program per nonzero block-ROW and walks the row's
column blocks with an inner ``fori_loop``. K/V stay in HBM, pre-tiled
and TRANSPOSED as (rows, n_blocks, D, block) — Mosaic requires manual
DMA slices to be lane-128-aligned, which the 128+-wide block is and
head_dim often is not — and each (D, block) tile is fetched by a
double-buffered ``pltpu.make_async_copy`` driven by a scalar-prefetched
CSR column list (the program's row is selected inside the DMA: non-VMEM
refs must be unblocked with a trivial index map). Program count drops by
the average row degree (~10x), the online-softmax state lives in loop
registers, and VMEM holds 2 tiles per stream regardless of S. Small
per-row vectors (key-padding mask, lse, delta) are NOT DMA-streamed —
their (block, 1) tiles can never be lane-aligned — they ride as
VMEM-resident (1, 1, S) blocked refs (≤256KB at S=16k) sliced in-kernel
at 128-aligned offsets. The dkv pass mirrors the walk column-major with
CSC metadata (q/do streamed transposed, k/v resident).

Blocked attention masks (``has_am`` — the BERT fine-tune configuration
the reference's sparse speedups are built on,
deepspeed/ops/sparse_attention/trsrc/softmax_fwd.tr:100-119) stream the
same way: the (nq, nk, block, block) additive mask is deduplicated to
the UNIQUE nonzero tiles of the head-union layout (masks are
head-independent, so storing per-item tiles would multiply HBM by H),
and a scalar-prefetched per-item uid list drives a third double-buffered
DMA stream — a (block, block) tile's lane dim is the 128-aligned block,
so the same alignment argument as K/V applies.

Same math as v1 (bf16 MXU operands / fp32 accumulation, scale post-dot,
exact-zero structurally-masked probabilities).
"""

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from deepspeed_tpu.ops.attention.flash import (_compiler_params,
                                               _stream_layout)

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30
VALID_THRESH = -1e29


def build_row_runs(layout: np.ndarray) -> Tuple[np.ndarray, ...]:
    """CSR over block-rows: (rows, offs, cnts, cols) with rows encoding
    h * nr + r. Every row gets a program (cnt may be 0: zero output)."""
    H, nr, _ = layout.shape
    rows, offs, cnts, cols = [], [], [], []
    off = 0
    for h in range(H):
        for r in range(nr):
            idx = np.nonzero(layout[h, r])[0]
            rows.append(h * nr + r)
            offs.append(off)
            cnts.append(len(idx))
            cols.extend(int(c) for c in idx)
            off += len(idx)
    return (np.asarray(rows, np.int32), np.asarray(offs, np.int32),
            np.asarray(cnts, np.int32),
            np.asarray(cols if cols else [0], np.int32))


def build_am_index(layout: np.ndarray):
    """(uq, uk, csr_uids, csc_uids): unique (qb, kb) tile coordinates of
    the head-union layout, plus per-item indices into that unique array
    in CSR (row-run) and CSC (column-run) walk order."""
    H, nq, nk = layout.shape
    union = layout.sum(axis=0) > 0
    pairs = np.argwhere(union)                      # (U, 2) [qb, kb]
    uid_of = {(int(a), int(b)): i for i, (a, b) in enumerate(pairs)}
    csr_uids, csc_uids = [], []
    for h in range(H):
        for r in range(nq):
            for c in np.nonzero(layout[h, r])[0]:
                csr_uids.append(uid_of[(r, int(c))])
    lt = layout.transpose(0, 2, 1)
    for h in range(H):
        for kb in range(nk):
            for rq in np.nonzero(lt[h, kb])[0]:
                csc_uids.append(uid_of[(int(rq), kb)])
    return (np.asarray(pairs[:, 0], np.int32),
            np.asarray(pairs[:, 1], np.int32),
            np.asarray(csr_uids or [0], np.int32),
            np.asarray(csc_uids or [0], np.int32))


def _dma(src_hbm, c, row, buf, slot, sem):
    # src_hbm: full (rows, n_blocks, D, block) in HBM; whole-tile copy
    return pltpu.make_async_copy(src_hbm.at[row, c], buf.at[slot],
                                 sem.at[slot])


def _am_dma(am_hbm, uid, buf, slot, sem):
    # am_hbm: unique tiles (U, block, block) in HBM
    return pltpu.make_async_copy(am_hbm.at[uid], buf.at[slot],
                                 sem.at[slot])


def _stream_start(refs_bufs_sems, cols_ref, base, i, row,
                  am_stream=None, uids_ref=None):
    c = cols_ref[base + i]
    slot = jax.lax.rem(i, 2)
    for src, buf, sem in refs_bufs_sems:
        _dma(src, c, row, buf, slot, sem).start()
    if am_stream is not None:
        am_hbm, ambuf, amsem = am_stream
        _am_dma(am_hbm, uids_ref[base + i], ambuf, slot, amsem).start()


def _stream_wait(refs_bufs_sems, cols_ref, base, i, row,
                 am_stream=None, uids_ref=None):
    c = cols_ref[base + i]
    slot = jax.lax.rem(i, 2)
    out = []
    for src, buf, sem in refs_bufs_sems:
        _dma(src, c, row, buf, slot, sem).wait()
        out.append(buf[slot])
    if am_stream is not None:
        am_hbm, ambuf, amsem = am_stream
        _am_dma(am_hbm, uids_ref[base + i], ambuf, slot, amsem).wait()
        out.append(ambuf[slot])
    return c, out


# --------------------------------------------------------------------- #
# forward: one program per block row
# --------------------------------------------------------------------- #
def _v2_fwd_kernel(*refs, sm_scale, block, heads, nq, has_am):
    if has_am:
        (rows_ref, offs_ref, cnts_ref, cols_ref, uids_ref,
         q_ref, k_hbm, v_hbm, am_hbm, kpm_ref, o_ref, lse_ref,
         kbuf, vbuf, ambuf, ksem, vsem, amsem) = refs
        am_stream = (am_hbm, ambuf, amsem)
    else:
        (rows_ref, offs_ref, cnts_ref, cols_ref,
         q_ref, k_hbm, v_hbm, kpm_ref, o_ref, lse_ref,
         kbuf, vbuf, ksem, vsem) = refs
        uids_ref = am_stream = None
    r = pl.program_id(1)
    n = cnts_ref[r]
    base = offs_ref[r]
    bh = pl.program_id(0) * heads + rows_ref[r] // nq
    q = q_ref[0]                                       # (block, D)
    d = q.shape[-1]
    streams = ((k_hbm, kbuf, ksem), (v_hbm, vbuf, vsem))

    @pl.when(n > 0)
    def _prologue():
        _stream_start(streams, cols_ref, base, 0, bh, am_stream, uids_ref)

    def body(i, carry):
        m, l, acc = carry

        @pl.when(i + 1 < n)
        def _prefetch_next():
            _stream_start(streams, cols_ref, base, i + 1, bh,
                          am_stream, uids_ref)

        # streamed tiles arrive transposed: k, v are (D, block)
        c, tiles = _stream_wait(streams, cols_ref, base, i, bh,
                                am_stream, uids_ref)
        k, v = tiles[0], tiles[1]
        s = jax.lax.dot_general(q, k, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale
        s += kpm_ref[0, 0, pl.ds(c * block, block)][None, :]
        if has_am:
            s += tiles[2].astype(jnp.float32)          # (block, block)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.where(s > VALID_THRESH, jnp.exp(s - m_new[:, None]), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((block,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block,), jnp.float32)
    acc0 = jnp.zeros((block, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n, body, (m0, l0, acc0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0, :, 0] = m + jnp.log(l_safe)


# --------------------------------------------------------------------- #
# dq: same row-run walk
# --------------------------------------------------------------------- #
def _v2_dq_kernel(*refs, sm_scale, block, heads, nq, has_am):
    if has_am:
        (rows_ref, offs_ref, cnts_ref, cols_ref, uids_ref,
         q_ref, k_hbm, v_hbm, am_hbm, kpm_ref, do_ref, lse_ref, delta_ref,
         dq_ref, kbuf, vbuf, ambuf, ksem, vsem, amsem) = refs
        am_stream = (am_hbm, ambuf, amsem)
    else:
        (rows_ref, offs_ref, cnts_ref, cols_ref,
         q_ref, k_hbm, v_hbm, kpm_ref, do_ref, lse_ref, delta_ref,
         dq_ref, kbuf, vbuf, ksem, vsem) = refs
        uids_ref = am_stream = None
    r = pl.program_id(1)
    n = cnts_ref[r]
    base = offs_ref[r]
    bh = pl.program_id(0) * heads + rows_ref[r] // nq
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0, :, 0]
    delta = delta_ref[0, :, 0]
    d = q.shape[-1]
    streams = ((k_hbm, kbuf, ksem), (v_hbm, vbuf, vsem))

    @pl.when(n > 0)
    def _prologue():
        _stream_start(streams, cols_ref, base, 0, bh, am_stream, uids_ref)

    def body(i, dq):
        @pl.when(i + 1 < n)
        def _prefetch_next():
            _stream_start(streams, cols_ref, base, i + 1, bh,
                          am_stream, uids_ref)

        # streamed tiles arrive transposed: k, v are (D, block)
        c, tiles = _stream_wait(streams, cols_ref, base, i, bh,
                                am_stream, uids_ref)
        k, v = tiles[0], tiles[1]
        s = jax.lax.dot_general(q, k, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale
        s += kpm_ref[0, 0, pl.ds(c * block, block)][None, :]
        if has_am:
            s += tiles[2].astype(jnp.float32)
        p = jnp.where(s > VALID_THRESH, jnp.exp(s - lse[:, None]), 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        return dq + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, n, body, jnp.zeros((block, d), jnp.float32))
    dq_ref[0] = (dq * sm_scale).astype(dq_ref.dtype)


# --------------------------------------------------------------------- #
# dk/dv: one program per block column, streaming q/do
# --------------------------------------------------------------------- #
def _v2_dkv_kernel(*refs, sm_scale, block, heads, nk, has_am):
    if has_am:
        (crows_ref, coffs_ref, ccnts_ref, crowids_ref, uids_ref,
         k_ref, v_ref, kpm_ref, q_hbm, do_hbm, am_hbm, lse_ref, delta_ref,
         dk_ref, dv_ref, qbuf, dobuf, ambuf, qsem, dosem, amsem) = refs
        am_stream = (am_hbm, ambuf, amsem)
    else:
        (crows_ref, coffs_ref, ccnts_ref, crowids_ref,
         k_ref, v_ref, kpm_ref, q_hbm, do_hbm, lse_ref, delta_ref,
         dk_ref, dv_ref, qbuf, dobuf, qsem, dosem) = refs
        uids_ref = am_stream = None
    t = pl.program_id(1)
    n = ccnts_ref[t]
    base = coffs_ref[t]
    bh = pl.program_id(0) * heads + crows_ref[t] // nk
    k = k_ref[0]                                       # (block, D)
    v = v_ref[0]
    d = k.shape[-1]
    kpm_row = kpm_ref[0, 0, 0, :]                      # this col's mask
    streams = ((q_hbm, qbuf, qsem), (do_hbm, dobuf, dosem))

    @pl.when(n > 0)
    def _prologue():
        _stream_start(streams, crowids_ref, base, 0, bh,
                      am_stream, uids_ref)

    def body(i, carry):
        dk, dv = carry

        @pl.when(i + 1 < n)
        def _prefetch_next():
            _stream_start(streams, crowids_ref, base, i + 1, bh,
                          am_stream, uids_ref)

        # streamed tiles arrive transposed: q, do are (D, block)
        rq, tiles = _stream_wait(streams, crowids_ref, base, i, bh,
                                 am_stream, uids_ref)
        q, do = tiles[0], tiles[1]
        lse = lse_ref[0, 0, pl.ds(rq * block, block)]
        delta = delta_ref[0, 0, pl.ds(rq * block, block)]
        s = jax.lax.dot_general(q, k, (((0,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale                               # (bq, bk)
        s += kpm_row[None, :]
        if has_am:
            s += tiles[2].astype(jnp.float32)          # (bq, bk) tile
        p = jnp.where(s > VALID_THRESH, jnp.exp(s - lse[:, None]), 0.0)
        dv_new = dv + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # (bk, D)
        dp = jax.lax.dot_general(do, v, (((0,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        dk_new = dk + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # (bk, D)
        return dk_new, dv_new

    z = jnp.zeros((block, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, n, body, (z, z))
    dk_ref[0] = (dk * sm_scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# --------------------------------------------------------------------- #
# layout coarsening: trade masked FLOPs for per-iteration efficiency
# --------------------------------------------------------------------- #
def build_coarse_index(fine_layout: np.ndarray, fine_block: int,
                       coarse_block: int, per_coord: bool,
                       count_only: bool = False):
    """Coarsen a fine block layout to ``coarse_block`` tiles, expressing
    the fine structure as additive NEG_INF mask tiles streamed through
    the existing attn-mask DMA channel. Masked entries produce
    EXACT-ZERO probabilities (same guarantee as the fine walk); the
    unmasked math is numerically equivalent but not bit-identical — the
    online-softmax running max and f32 accumulation group per coarse
    tile instead of per fine tile, so outputs agree to normal fp32
    reduction tolerance (see test_coarse_walk_matches_fine).

    Tiles are deduplicated by CONTENT of the (f, f) fine-bit pattern
    (banded layouts like BSLongformer collapse to a handful of uniques);
    with ``per_coord`` (a user attention mask must be folded in per
    coordinate) the key also includes (R, C). Returns
    (coarse_layout, tiles, csr_uids, csc_uids, qrows, kcols); with
    ``count_only`` returns just (coarse_nnz, n_unique) for cost/memory
    planning without materializing anything."""
    H, nqf, nkf = fine_layout.shape
    f = coarse_block // fine_block
    nqc, nkc = nqf // f, nkf // f
    fine = fine_layout.astype(bool)
    coarse = fine.reshape(H, nqc, f, nkc, f).any(axis=(2, 4))

    pat_of = {}
    pats, coords = [], []

    def uid_for(h, R, C):
        patt = np.ascontiguousarray(fine[h, R * f:(R + 1) * f,
                                         C * f:(C + 1) * f])
        key = patt.tobytes() + (b"|%d,%d" % (R, C) if per_coord else b"")
        uid = pat_of.get(key)
        if uid is None:
            uid = len(pats)
            pat_of[key] = uid
            pats.append(patt)
            coords.append((R, C))
        return uid

    csr, csc = [], []
    for h in range(H):
        for R in range(nqc):
            for C in np.nonzero(coarse[h, R])[0]:
                csr.append(uid_for(h, R, int(C)))
    if count_only:
        return len(csr), len(pats)
    for h in range(H):
        for C in range(nkc):
            for R in np.nonzero(coarse[h, :, C])[0]:
                csc.append(uid_for(h, int(R), C))

    b = fine_block
    ones = np.ones((b, b), bool)
    tiles = np.stack([np.where(np.kron(p, ones), 0.0, NEG_INF)
                      for p in pats]).astype(np.float32) \
        if pats else np.zeros((1, coarse_block, coarse_block), np.float32)
    qrows = np.asarray([[R * f + i for i in range(f)]
                        for R, _ in coords] or [[0] * f], np.int32)
    kcols = np.asarray([[C * f + j for j in range(f)]
                        for _, C in coords] or [[0] * f], np.int32)
    return (coarse.astype(fine_layout.dtype), tiles,
            np.asarray(csr or [0], np.int32),
            np.asarray(csc or [0], np.int32), qrows, kcols)


# --------------------------------------------------------------------- #
# builders
# --------------------------------------------------------------------- #
def build_v2_impls(layout: np.ndarray, block: int, sm_scale: float,
                   interpret: bool, has_am: bool = False,
                   coarse_block=None):
    """Returns (fwd_impl, bwd_impl) with the v1 signatures. When
    ``has_am`` the impls take a pre-blocked additive (nq, nk, block,
    block) mask; it is deduplicated to unique head-union tiles and
    DMA-streamed per item.

    With ``coarse_block`` the walk runs over coarsened tiles and the
    fine structure (plus any user mask) rides the same DMA mask channel
    — see build_coarse_index. The public signature stays in FINE blocks;
    per-iteration work grows from (block, block) to (coarse, coarse),
    which is what makes a 128-block Longformer walk competitive with
    dense flash tile sizes."""
    fine_layout, fine_block = layout, block
    if coarse_block is not None:
        (layout, _struct_tiles, csr_uids, csc_uids,
         _uq_rows, _uk_cols) = build_coarse_index(
            fine_layout, fine_block, coarse_block, per_coord=has_am)
        block = coarse_block
    H, nq, nk = layout.shape
    rr = build_row_runs(layout)
    cr = build_row_runs(np.ascontiguousarray(layout.transpose(0, 2, 1)))
    R = rr[0].shape[0]
    C = cr[0].shape[0]
    stream_am = has_am or coarse_block is not None
    if has_am and coarse_block is None:
        uq, uk, csr_uids, csc_uids = build_am_index(layout)
    compiler_params = _compiler_params(interpret, stream=True)
    hbm_spec = pl.BlockSpec(memory_space=pltpu.HBM)

    # Pure structural tiles (coarsening without a user mask) stream in
    # bf16: 0 is exact and bf16(NEG_INF) ~ -1.0003e30 still clears
    # VALID_THRESH = -1e29 by 10x (the margin, not exactness, is the
    # invariant), and at a 512 walk tile the fp32 mask DMA is 8x the
    # K/V tile bytes.  User-mask folding keeps fp32 (arbitrary additive
    # values).
    am_dtype = (jnp.bfloat16 if coarse_block is not None and not has_am
                else jnp.float32)

    def _unique_am(am):
        if coarse_block is None:
            # (nq, nk, block, block) additive -> (U, block, block) fp32
            return am.astype(jnp.float32)[jnp.asarray(uq), jnp.asarray(uk)]
        st = jnp.asarray(_struct_tiles)
        if am is None:
            return st.astype(am_dtype)
        # fold the user's FINE mask tiles into each unique coarse tile:
        # gather the (f, f) grid of fine (b, b) tiles and re-lay as
        # (coarse, coarse)
        g = am.astype(jnp.float32)[jnp.asarray(_uq_rows)[:, :, None],
                                   jnp.asarray(_uk_cols)[:, None, :]]
        g = g.transpose(0, 1, 3, 2, 4).reshape(st.shape)
        return st + g

    def _am_scratch(dtype=jnp.float32):
        return [pltpu.VMEM((2, block, block), dtype),
                pltpu.SemaphoreType.DMA((2,))]

    def fwd_impl(q, k, v, kpm, am):
        assert (am is not None) == has_am
        B, _, S, D = q.shape
        qr = q.reshape(B * H, S, D)
        kr = _stream_layout(k.reshape(B * H, S, D), block)
        vr = _stream_layout(v.reshape(B * H, S, D), block)
        kpmr = kpm.reshape(B, 1, S)   # VMEM-resident, sliced in-kernel
        kernel = functools.partial(_v2_fwd_kernel, sm_scale=sm_scale,
                                   block=block, heads=H, nq=nq,
                                   has_am=stream_am)
        in_specs = [
            pl.BlockSpec((1, block, D),
                         lambda i, r, rw, *_: (i * H + rw[r] // nq,
                                               rw[r] % nq, 0)),
            hbm_spec,
            hbm_spec,
        ]
        args = [qr, kr, vr]
        scalars = list(rr)
        if stream_am:
            scalars.append(csr_uids)
            in_specs.append(hbm_spec)
            args.append(_unique_am(am))
        in_specs.append(pl.BlockSpec((1, 1, S), lambda i, r, *_: (i, 0, 0)))
        args.append(kpmr)
        scratch = [
            pltpu.VMEM((2, D, block), k.dtype),
            pltpu.VMEM((2, D, block), v.dtype),
        ] + (_am_scratch(am_dtype)[:1] if stream_am else []) + [
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ] + (_am_scratch(am_dtype)[1:] if stream_am else [])
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=len(scalars),
            grid=(B, R),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, block, D),
                             lambda i, r, rw, *_: (i * H + rw[r] // nq,
                                                   rw[r] % nq, 0)),
                pl.BlockSpec((1, block, 1),
                             lambda i, r, rw, *_: (i * H + rw[r] // nq,
                                                   rw[r] % nq, 0)),
            ],
            scratch_shapes=scratch)
        o, lse = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
                jax.ShapeDtypeStruct((B * H, S, 1), jnp.float32),
            ],
            interpret=interpret,
            compiler_params=compiler_params,
        )(*(jnp.asarray(x) for x in scalars), *args)
        return o.reshape(B, H, S, D), lse

    def bwd_impl(q, k, v, kpm, am, o, lse, g):
        assert (am is not None) == has_am
        B, _, S, D = q.shape
        qr = q.reshape(B * H, S, D)
        kr = k.reshape(B * H, S, D)
        vr = v.reshape(B * H, S, D)
        dor = g.reshape(B * H, S, D)
        kpmr = kpm.reshape(B, 1, S)
        am_u = _unique_am(am) if stream_am else None
        delta = jnp.sum(dor.astype(jnp.float32) *
                        o.reshape(B * H, S, D).astype(jnp.float32),
                        axis=-1, keepdims=True)           # (B*H, S, 1)

        # ---- dq (row runs) ----
        kernel = functools.partial(_v2_dq_kernel, sm_scale=sm_scale,
                                   block=block, heads=H, nq=nq,
                                   has_am=stream_am)
        row_spec = pl.BlockSpec(
            (1, block, D),
            lambda i, r, rw, *_: (i * H + rw[r] // nq, rw[r] % nq, 0))
        row_vec_spec = pl.BlockSpec(
            (1, block, 1),
            lambda i, r, rw, *_: (i * H + rw[r] // nq, rw[r] % nq, 0))
        in_specs = [row_spec, hbm_spec, hbm_spec]
        args = [qr, _stream_layout(kr, block), _stream_layout(vr, block)]
        scalars = list(rr)
        if stream_am:
            scalars.append(csr_uids)
            in_specs.append(hbm_spec)
            args.append(am_u)
        in_specs += [
            pl.BlockSpec((1, 1, S), lambda i, r, *_: (i, 0, 0)),
            row_spec, row_vec_spec, row_vec_spec,
        ]
        args += [kpmr, dor, lse, delta]
        scratch = [
            pltpu.VMEM((2, D, block), k.dtype),
            pltpu.VMEM((2, D, block), v.dtype),
        ] + (_am_scratch(am_dtype)[:1] if stream_am else []) + [
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ] + (_am_scratch(am_dtype)[1:] if stream_am else [])
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=len(scalars),
            grid=(B, R),
            in_specs=in_specs,
            out_specs=row_spec,
            scratch_shapes=scratch)
        dq = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
            interpret=interpret,
            compiler_params=compiler_params,
        )(*(jnp.asarray(x) for x in scalars), *args)

        # ---- dk, dv (column runs) ----
        kernel = functools.partial(_v2_dkv_kernel, sm_scale=sm_scale,
                                   block=block, heads=H, nk=nk,
                                   has_am=stream_am)
        lser = lse.reshape(B * H, 1, S)   # VMEM-resident per program
        deltar = delta.reshape(B * H, 1, S)
        col_spec = pl.BlockSpec(
            (1, block, D),
            lambda i, t, cw, *_: (i * H + cw[t] // nk, cw[t] % nk, 0))
        in_specs = [
            col_spec,
            col_spec,
            pl.BlockSpec((1, 1, 1, block),
                         lambda i, t, cw, *_: (i, cw[t] % nk, 0, 0)),
            hbm_spec,
            hbm_spec,
        ]
        args = [kr, vr, kpm.reshape(B, nk, 1, block),  # fine->walk re-block
                _stream_layout(qr, block), _stream_layout(dor, block)]
        scalars = list(cr)
        if stream_am:
            scalars.append(csc_uids)
            in_specs.append(hbm_spec)
            args.append(am_u)
        in_specs += [
            pl.BlockSpec((1, 1, S),
                         lambda i, t, cw, *_: (i * H + cw[t] // nk, 0, 0)),
            pl.BlockSpec((1, 1, S),
                         lambda i, t, cw, *_: (i * H + cw[t] // nk, 0, 0)),
        ]
        args += [lser, deltar]
        scratch = [
            pltpu.VMEM((2, D, block), q.dtype),
            pltpu.VMEM((2, D, block), g.dtype),
        ] + (_am_scratch(am_dtype)[:1] if stream_am else []) + [
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ] + (_am_scratch(am_dtype)[1:] if stream_am else [])
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=len(scalars),
            grid=(B, C),
            in_specs=in_specs,
            out_specs=[col_spec, col_spec],
            scratch_shapes=scratch)
        dk, dv = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((B * H, S, D), k.dtype),
                jax.ShapeDtypeStruct((B * H, S, D), v.dtype),
            ],
            interpret=interpret,
            compiler_params=compiler_params,
        )(*(jnp.asarray(x) for x in scalars), *args)
        return (dq.reshape(q.shape), dk.reshape(k.shape),
                dv.reshape(v.shape))

    return fwd_impl, bwd_impl
