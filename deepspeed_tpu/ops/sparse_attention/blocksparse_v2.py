"""Row-run block-sparse attention kernels (splash v2).

The v1 kernels (blocksparse.py) launch ONE grid program per nonzero
(row, col) block triple: at a 128-block Longformer S=8192 layout that is
~10k sequential program launches of a single 128x128x64 matmul each —
per-program launch overhead dominates and the kernel loses to dense
flash despite doing ~1/3 the FLOPs.

v2 launches one program per nonzero block-ROW and walks the row's
column blocks with an inner ``fori_loop``; K/V stay in HBM
(``memory_space=ANY``) and each (block, D) tile is fetched by a
double-buffered ``pltpu.make_async_copy`` DMA driven by a
scalar-prefetched CSR column list — program count drops by the average
row degree (~10x), the online-softmax state lives in loop registers
(no cross-program scratch carry), and VMEM holds only 2 tiles per
stream regardless of S. The dkv pass mirrors it column-major with CSC
metadata (q/do streamed, k/v resident).

Same math as v1 (bf16 MXU operands / fp32 accumulation, scale post-dot,
exact-zero structurally-masked probabilities); used for the
``has_am=False`` path — the blocked attn-mask variant stays on v1.
"""

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30
VALID_THRESH = -1e29


def build_row_runs(layout: np.ndarray) -> Tuple[np.ndarray, ...]:
    """CSR over block-rows: (rows, offs, cnts, cols) with rows encoding
    h * nr + r. Every row gets a program (cnt may be 0: zero output)."""
    H, nr, _ = layout.shape
    rows, offs, cnts, cols = [], [], [], []
    off = 0
    for h in range(H):
        for r in range(nr):
            idx = np.nonzero(layout[h, r])[0]
            rows.append(h * nr + r)
            offs.append(off)
            cnts.append(len(idx))
            cols.extend(int(c) for c in idx)
            off += len(idx)
    return (np.asarray(rows, np.int32), np.asarray(offs, np.int32),
            np.asarray(cnts, np.int32),
            np.asarray(cols if cols else [0], np.int32))


def _dma(src_hbm, c, block, buf, slot, sem):
    return pltpu.make_async_copy(
        src_hbm.at[0, pl.ds(c * block, block), :], buf.at[slot],
        sem.at[slot])


def _stream_start(refs_bufs_sems, cols_ref, base, i, block):
    c = cols_ref[base + i]
    slot = jax.lax.rem(i, 2)
    for src, buf, sem in refs_bufs_sems:
        _dma(src, c, block, buf, slot, sem).start()


def _stream_wait(refs_bufs_sems, cols_ref, base, i, block):
    c = cols_ref[base + i]
    slot = jax.lax.rem(i, 2)
    out = []
    for src, buf, sem in refs_bufs_sems:
        _dma(src, c, block, buf, slot, sem).wait()
        out.append(buf[slot])
    return c, out


# --------------------------------------------------------------------- #
# forward: one program per block row
# --------------------------------------------------------------------- #
def _v2_fwd_kernel(rows_ref, offs_ref, cnts_ref, cols_ref,
                   q_ref, k_hbm, v_hbm, kpm_hbm, o_ref, lse_ref,
                   kbuf, vbuf, mbuf, ksem, vsem, msem, *, sm_scale, block):
    r = pl.program_id(1)
    n = cnts_ref[r]
    base = offs_ref[r]
    q = q_ref[0]                                       # (block, D)
    d = q.shape[-1]
    streams = ((k_hbm, kbuf, ksem), (v_hbm, vbuf, vsem),
               (kpm_hbm, mbuf, msem))

    @pl.when(n > 0)
    def _prologue():
        _stream_start(streams, cols_ref, base, 0, block)

    def body(i, carry):
        m, l, acc = carry

        @pl.when(i + 1 < n)
        def _prefetch_next():
            _stream_start(streams, cols_ref, base, i + 1, block)

        c, (k, v, kpm) = _stream_wait(streams, cols_ref, base, i, block)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale
        s += kpm[:, 0][None, :]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.where(s > VALID_THRESH, jnp.exp(s - m_new[:, None]), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((block,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block,), jnp.float32)
    acc0 = jnp.zeros((block, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n, body, (m0, l0, acc0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0, :, 0] = m + jnp.log(l_safe)


# --------------------------------------------------------------------- #
# dq: same row-run walk
# --------------------------------------------------------------------- #
def _v2_dq_kernel(rows_ref, offs_ref, cnts_ref, cols_ref,
                  q_ref, k_hbm, v_hbm, kpm_hbm, do_ref, lse_ref, delta_ref,
                  dq_ref, kbuf, vbuf, mbuf, ksem, vsem, msem,
                  *, sm_scale, block):
    r = pl.program_id(1)
    n = cnts_ref[r]
    base = offs_ref[r]
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0, :, 0]
    delta = delta_ref[0, :, 0]
    d = q.shape[-1]
    streams = ((k_hbm, kbuf, ksem), (v_hbm, vbuf, vsem),
               (kpm_hbm, mbuf, msem))

    @pl.when(n > 0)
    def _prologue():
        _stream_start(streams, cols_ref, base, 0, block)

    def body(i, dq):
        @pl.when(i + 1 < n)
        def _prefetch_next():
            _stream_start(streams, cols_ref, base, i + 1, block)

        c, (k, v, kpm) = _stream_wait(streams, cols_ref, base, i, block)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale
        s += kpm[:, 0][None, :]
        p = jnp.where(s > VALID_THRESH, jnp.exp(s - lse[:, None]), 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        return dq + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, n, body, jnp.zeros((block, d), jnp.float32))
    dq_ref[0] = (dq * sm_scale).astype(dq_ref.dtype)


# --------------------------------------------------------------------- #
# dk/dv: one program per block column, streaming q/do
# --------------------------------------------------------------------- #
def _v2_dkv_kernel(crows_ref, coffs_ref, ccnts_ref, crowids_ref,
                   k_ref, v_ref, kpm_ref, q_hbm, do_hbm, lse_hbm, delta_hbm,
                   dk_ref, dv_ref, qbuf, dobuf, ldbuf, qsem, dosem, ldsem,
                   *, sm_scale, block):
    t = pl.program_id(1)
    n = ccnts_ref[t]
    base = coffs_ref[t]
    k = k_ref[0]                                       # (block, D)
    v = v_ref[0]
    d = k.shape[-1]
    kpm_row = kpm_ref[0, 0, 0, :]                      # this col's mask
    streams = ((q_hbm, qbuf, qsem), (do_hbm, dobuf, dosem))

    def start_ld(i, slot):
        rq = crowids_ref[base + i]
        pltpu.make_async_copy(
            lse_hbm.at[0, pl.ds(rq * block, block), :],
            ldbuf.at[slot, 0], ldsem.at[slot, 0]).start()
        pltpu.make_async_copy(
            delta_hbm.at[0, pl.ds(rq * block, block), :],
            ldbuf.at[slot, 1], ldsem.at[slot, 1]).start()

    def wait_ld(i, slot):
        rq = crowids_ref[base + i]
        pltpu.make_async_copy(
            lse_hbm.at[0, pl.ds(rq * block, block), :],
            ldbuf.at[slot, 0], ldsem.at[slot, 0]).wait()
        pltpu.make_async_copy(
            delta_hbm.at[0, pl.ds(rq * block, block), :],
            ldbuf.at[slot, 1], ldsem.at[slot, 1]).wait()

    @pl.when(n > 0)
    def _prologue():
        _stream_start(streams, crowids_ref, base, 0, block)
        start_ld(0, 0)

    def body(i, carry):
        dk, dv = carry
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < n)
        def _prefetch_next():
            _stream_start(streams, crowids_ref, base, i + 1, block)
            start_ld(i + 1, jax.lax.rem(i + 1, 2))

        _, (q, do) = _stream_wait(streams, crowids_ref, base, i, block)
        wait_ld(i, slot)
        lse = ldbuf[slot, 0, :, 0]
        delta = ldbuf[slot, 1, :, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale
        s += kpm_row[None, :]
        p = jnp.where(s > VALID_THRESH, jnp.exp(s - lse[:, None]), 0.0)
        dv_new = dv + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        dk_new = dk + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    z = jnp.zeros((block, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, n, body, (z, z))
    dk_ref[0] = (dk * sm_scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# --------------------------------------------------------------------- #
# builders
# --------------------------------------------------------------------- #
def build_v2_impls(layout: np.ndarray, block: int, sm_scale: float,
                   interpret: bool):
    """Returns (fwd_impl, bwd_impl) with the v1 signatures (am must be
    None)."""
    H, nq, nk = layout.shape
    rr = build_row_runs(layout)
    cr = build_row_runs(np.ascontiguousarray(layout.transpose(0, 2, 1)))
    R = rr[0].shape[0]
    C = cr[0].shape[0]
    compiler_params = None
    if pltpu is not None and not interpret:
        compiler_params = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"))

    def fwd_impl(q, k, v, kpm, am):
        assert am is None
        B, _, S, D = q.shape
        qr = q.reshape(B * H, S, D)
        kr = k.reshape(B * H, S, D)
        vr = v.reshape(B * H, S, D)
        kpmr = kpm.reshape(B, S, 1)    # (B, nk, 1, block) -> DMA-sliceable
        kernel = functools.partial(_v2_fwd_kernel, sm_scale=sm_scale,
                                   block=block)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(B, R),
            in_specs=[
                pl.BlockSpec((1, block, D),
                             lambda i, r, rw, *_: (i * H + rw[r] // nq,
                                                   rw[r] % nq, 0)),
                pl.BlockSpec((1, S, D),
                             lambda i, r, rw, *_: (i * H + rw[r] // nq,
                                                   0, 0),
                             memory_space=pl.ANY),
                pl.BlockSpec((1, S, D),
                             lambda i, r, rw, *_: (i * H + rw[r] // nq,
                                                   0, 0),
                             memory_space=pl.ANY),
                pl.BlockSpec((1, S, 1), lambda i, r, *_: (i, 0, 0),
                             memory_space=pl.ANY),
            ],
            out_specs=[
                pl.BlockSpec((1, block, D),
                             lambda i, r, rw, *_: (i * H + rw[r] // nq,
                                                   rw[r] % nq, 0)),
                pl.BlockSpec((1, block, 1),
                             lambda i, r, rw, *_: (i * H + rw[r] // nq,
                                                   rw[r] % nq, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((2, block, D), k.dtype),
                pltpu.VMEM((2, block, D), v.dtype),
                pltpu.VMEM((2, block, 1), jnp.float32),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
            ])
        o, lse = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
                jax.ShapeDtypeStruct((B * H, S, 1), jnp.float32),
            ],
            interpret=interpret,
            compiler_params=compiler_params,
        )(*(jnp.asarray(x) for x in rr), qr, kr, vr, kpmr)
        return o.reshape(B, H, S, D), lse

    def bwd_impl(q, k, v, kpm, am, o, lse, g):
        assert am is None
        B, _, S, D = q.shape
        qr = q.reshape(B * H, S, D)
        kr = k.reshape(B * H, S, D)
        vr = v.reshape(B * H, S, D)
        dor = g.reshape(B * H, S, D)
        kpmr = kpm.reshape(B, S, 1)
        delta = jnp.sum(dor.astype(jnp.float32) *
                        o.reshape(B * H, S, D).astype(jnp.float32),
                        axis=-1, keepdims=True)           # (B*H, S, 1)

        # ---- dq (row runs) ----
        kernel = functools.partial(_v2_dq_kernel, sm_scale=sm_scale,
                                   block=block)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(B, R),
            in_specs=[
                pl.BlockSpec((1, block, D),
                             lambda i, r, rw, *_: (i * H + rw[r] // nq,
                                                   rw[r] % nq, 0)),
                pl.BlockSpec((1, S, D),
                             lambda i, r, rw, *_: (i * H + rw[r] // nq,
                                                   0, 0),
                             memory_space=pl.ANY),
                pl.BlockSpec((1, S, D),
                             lambda i, r, rw, *_: (i * H + rw[r] // nq,
                                                   0, 0),
                             memory_space=pl.ANY),
                pl.BlockSpec((1, S, 1), lambda i, r, *_: (i, 0, 0),
                             memory_space=pl.ANY),
                pl.BlockSpec((1, block, D),
                             lambda i, r, rw, *_: (i * H + rw[r] // nq,
                                                   rw[r] % nq, 0)),
                pl.BlockSpec((1, block, 1),
                             lambda i, r, rw, *_: (i * H + rw[r] // nq,
                                                   rw[r] % nq, 0)),
                pl.BlockSpec((1, block, 1),
                             lambda i, r, rw, *_: (i * H + rw[r] // nq,
                                                   rw[r] % nq, 0)),
            ],
            out_specs=pl.BlockSpec(
                (1, block, D),
                lambda i, r, rw, *_: (i * H + rw[r] // nq, rw[r] % nq, 0)),
            scratch_shapes=[
                pltpu.VMEM((2, block, D), k.dtype),
                pltpu.VMEM((2, block, D), v.dtype),
                pltpu.VMEM((2, block, 1), jnp.float32),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
            ])
        dq = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
            interpret=interpret,
            compiler_params=compiler_params,
        )(*(jnp.asarray(x) for x in rr), qr, kr, vr, kpmr, dor, lse, delta)

        # ---- dk, dv (column runs) ----
        kernel = functools.partial(_v2_dkv_kernel, sm_scale=sm_scale,
                                   block=block)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(B, C),
            in_specs=[
                pl.BlockSpec((1, block, D),
                             lambda i, t, cw, *_: (i * H + cw[t] // nk,
                                                   cw[t] % nk, 0)),
                pl.BlockSpec((1, block, D),
                             lambda i, t, cw, *_: (i * H + cw[t] // nk,
                                                   cw[t] % nk, 0)),
                pl.BlockSpec((1, 1, 1, block),
                             lambda i, t, cw, *_: (i, cw[t] % nk, 0, 0)),
                pl.BlockSpec((1, S, D),
                             lambda i, t, cw, *_: (i * H + cw[t] // nk,
                                                   0, 0),
                             memory_space=pl.ANY),
                pl.BlockSpec((1, S, D),
                             lambda i, t, cw, *_: (i * H + cw[t] // nk,
                                                   0, 0),
                             memory_space=pl.ANY),
                pl.BlockSpec((1, S, 1),
                             lambda i, t, cw, *_: (i * H + cw[t] // nk,
                                                   0, 0),
                             memory_space=pl.ANY),
                pl.BlockSpec((1, S, 1),
                             lambda i, t, cw, *_: (i * H + cw[t] // nk,
                                                   0, 0),
                             memory_space=pl.ANY),
            ],
            out_specs=[
                pl.BlockSpec((1, block, D),
                             lambda i, t, cw, *_: (i * H + cw[t] // nk,
                                                   cw[t] % nk, 0)),
                pl.BlockSpec((1, block, D),
                             lambda i, t, cw, *_: (i * H + cw[t] // nk,
                                                   cw[t] % nk, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((2, block, D), q.dtype),
                pltpu.VMEM((2, block, D), g.dtype),
                pltpu.VMEM((2, 2, block, 1), jnp.float32),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2, 2)),
            ])
        dk, dv = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((B * H, S, D), k.dtype),
                jax.ShapeDtypeStruct((B * H, S, D), v.dtype),
            ],
            interpret=interpret,
            compiler_params=compiler_params,
        )(*(jnp.asarray(x) for x in cr), kr, vr, kpm, qr, dor, lse, delta)
        return (dq.reshape(q.shape), dk.reshape(k.shape),
                dv.reshape(v.shape))

    return fwd_impl, bwd_impl
