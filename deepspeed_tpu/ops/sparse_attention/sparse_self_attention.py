"""Sparse self-attention modules on top of the unified Pallas kernel.

Parity targets (reference):
- SparseSelfAttention            deepspeed/ops/sparse_attention/sparse_self_attention.py:13
- BertSparseSelfAttention        deepspeed/ops/sparse_attention/bert_sparse_self_attention.py:9
- SparseAttentionUtils           deepspeed/ops/sparse_attention/sparse_attention_utils.py:13

Where the reference caches three Triton ops per sequence length
(sparse_self_attention.py:44 get_ops), we cache one fused differentiable
Pallas function per (layout, seq-len) via blocksparse._sparse_attention_fn;
layout construction itself is cached here per seq len. Since PR 11 that
dispatch resolves layouts to the ONE mask-parameterized flash kernel
(``ops/attention/masked_flash.py`` — the same kernel dense training
attention compiles); the legacy banded/v2/v1 kernels stay behind
``blocksparse.USE_MASKED_FLASH = False`` as numerics oracles.

Modules follow the repo's functional convention: configs are plain
objects, parameters are pytrees created by ``init_*_params``, forward
passes are pure functions.
"""

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.sparse_attention.blocksparse import (
    block_sparse_attention)
from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    FixedSparsityConfig, SparsityConfig)


class SparseSelfAttention:
    """Applies block-sparse attention with a SparsityConfig-driven layout.

    forward(query, key, value, rpe=None, key_padding_mask=None,
    attn_mask=None) with q/k/v of shape (B, H, S, D), key_padding_mask
    (B, S), attn_mask (S, S) — mirroring sparse_self_attention.py:84-142
    (including scaling = head_dim ** -0.5 and the add/mul mask modes).
    """

    _layout_cache: Dict[Any, np.ndarray] = {}

    def __init__(self, sparsity_config: Optional[SparsityConfig] = None,
                 key_padding_mask_mode: str = "add",
                 attn_mask_mode: str = "mul"):
        self.sparsity_config = sparsity_config or FixedSparsityConfig(
            num_heads=4)
        self.key_padding_mask_mode = key_padding_mask_mode
        self.attn_mask_mode = attn_mask_mode

    def get_layout(self, seq_len: int) -> np.ndarray:
        key = self.sparsity_config.layout_cache_key() + (seq_len,)
        if key not in SparseSelfAttention._layout_cache:
            SparseSelfAttention._layout_cache[key] = \
                self.sparsity_config.make_layout(seq_len)
        return SparseSelfAttention._layout_cache[key]

    def __call__(self, query, key, value, rpe=None, key_padding_mask=None,
                 attn_mask=None, **kw):
        B, H, S, D = query.shape
        if query.shape != key.shape or key.shape != value.shape:
            raise NotImplementedError(
                "only self-attention (q/k/v same shape) is supported")
        layout = self.get_layout(S)
        return block_sparse_attention(
            query, key, value, layout,
            sm_scale=float(D) ** -0.5,
            key_padding_mask=key_padding_mask,
            key_padding_mask_mode=self.key_padding_mask_mode,
            attn_mask=attn_mask, attn_mask_mode=self.attn_mask_mode,
            rpe=rpe, **kw)

    forward = __call__


def init_bert_sparse_self_attention_params(hidden_size: int, key,
                                           initializer_range: float = 0.02
                                           ) -> Dict[str, Any]:
    """Q/K/V projection parameters for BertSparseSelfAttention
    (bert_sparse_self_attention.py:40-42's three nn.Linear layers)."""
    ks = jax.random.split(key, 3)
    def lin(k):
        return {"w": jax.random.normal(k, (hidden_size, hidden_size),
                                       jnp.float32) * initializer_range,
                "b": jnp.zeros((hidden_size,), jnp.float32)}
    return {"query": lin(ks[0]), "key": lin(ks[1]), "value": lin(ks[2])}


class BertSparseSelfAttention:
    """BERT-style self-attention block with a sparse core
    (bert_sparse_self_attention.py:9). ``config`` needs hidden_size and
    num_attention_heads (our BertConfig uses hidden_size/num_heads; both
    spellings accepted)."""

    def __init__(self, config,
                 sparsity_config: Optional[SparsityConfig] = None):
        hidden = config.hidden_size
        heads = getattr(config, "num_attention_heads",
                        getattr(config, "num_heads", None))
        if heads is None:
            raise ValueError(
                "config must define num_attention_heads (or num_heads)")
        if hidden % heads != 0:
            raise ValueError(
                f"hidden size {hidden} not a multiple of heads {heads}")
        self.num_attention_heads = heads
        self.attention_head_size = hidden // heads
        self.hidden_size = hidden
        self.sparse_self_attention = SparseSelfAttention(
            sparsity_config or FixedSparsityConfig(num_heads=heads))

    def init_params(self, key, initializer_range: float = 0.02):
        return init_bert_sparse_self_attention_params(
            self.hidden_size, key, initializer_range)

    def _split_heads(self, x):
        B, S, _ = x.shape
        return x.reshape(B, S, self.num_attention_heads,
                         self.attention_head_size).transpose(0, 2, 1, 3)

    def __call__(self, params, hidden_states, attention_mask=None):
        """hidden_states (B, S, H_total); attention_mask (B, S) with 1=keep
        (applied as key padding). Returns (B, S, H_total)."""
        dtype = hidden_states.dtype
        def proj(p):
            return hidden_states @ p["w"].astype(dtype) + \
                p["b"].astype(dtype)
        q = self._split_heads(proj(params["query"]))
        k = self._split_heads(proj(params["key"]))
        v = self._split_heads(proj(params["value"]))
        ctx = self.sparse_self_attention(
            q, k, v, key_padding_mask=attention_mask)
        B, H, S, D = ctx.shape
        return ctx.transpose(0, 2, 1, 3).reshape(B, S, H * D)


class SparseAttentionUtils:
    """Helpers to adapt models/inputs to block-sparse attention
    (sparse_attention_utils.py:13) — re-targeted at this repo's functional
    param pytrees instead of torch module surgery."""

    @staticmethod
    def extend_position_embedding(params: Dict[str, Any],
                                  max_position: int) -> Dict[str, Any]:
        """Tile an existing position-embedding table up to max_position
        (sparse_attention_utils.py:19's weight-copy loop, functionally).
        Expects params['pos_emb'] of shape (P, H)."""
        pos = params["pos_emb"]
        original, h = pos.shape
        if max_position <= original:
            raise ValueError(
                f"max_position {max_position} must exceed current table "
                f"size {original}")
        reps = -(-max_position // original)
        new = jnp.tile(pos, (reps, 1))[:max_position]
        out = dict(params)
        out["pos_emb"] = new
        return out

    @staticmethod
    def update_tokenizer_model_max_length(tokenizer, max_position: int):
        """Bump a (HF-style) tokenizer's max length to the extended
        position-table size (sparse_attention_utils.py:68)."""
        tokenizer.model_max_length = max_position
        if hasattr(tokenizer, "init_kwargs"):
            tokenizer.init_kwargs["model_max_length"] = max_position
        return tokenizer

    @staticmethod
    def replace_model_self_attention_with_sparse_self_attention(
            params, config, max_position: Optional[int] = None,
            sparsity_config=None):
        """Functional analogue of the reference's module surgery
        (sparse_attention_utils.py:85): returns ``(params, config,
        encoder_fn)`` where ``encoder_fn(params, input_ids, ...)`` runs the
        BERT encoder with block-sparse core attention, reusing the dense
        QKV/output projection weights unchanged. Optionally extends the
        position table to ``max_position`` first."""
        from deepspeed_tpu.models.bert import bert_encoder
        if sparsity_config is None:
            sparsity_config = FixedSparsityConfig(
                num_heads=getattr(config, "num_heads", 4))
        if max_position is not None and \
                max_position > config.max_position_embeddings:
            params = SparseAttentionUtils.extend_position_embedding(
                params, max_position)
            config = config._replace(max_position_embeddings=max_position)
        cfg = config

        def encoder_fn(params, input_ids, **kw):
            return bert_encoder(params, cfg, input_ids,
                                sparsity_config=sparsity_config, **kw)

        return params, config, encoder_fn

    # reference-name alias (sparse_attention_utils.py:123 operates on one
    # layer; with a pluggable attention_fn the per-layer and whole-model
    # operations coincide)
    replace_self_attention_layer_with_sparse_self_attention_layer = \
        replace_model_self_attention_with_sparse_self_attention

    @staticmethod
    def pad_to_block_size(block_size: int, input_ids, pad_token_id: int,
                          attention_mask=None, token_type_ids=None,
                          position_ids=None, labels=None,
                          label_pad: int = -100):
        """Right-pad sequence inputs so seq_len % block_size == 0
        (sparse_attention_utils.py:151). Returns (pad_len, padded tensors
        with None passed through)."""
        B, S = input_ids.shape
        pad_len = (-S) % block_size
        if pad_len == 0:
            return 0, input_ids, attention_mask, token_type_ids, \
                position_ids, labels

        def pad(x, value):
            if x is None:
                return None
            return jnp.pad(x, ((0, 0), (0, pad_len)), constant_values=value)

        input_ids = pad(input_ids, pad_token_id)
        attention_mask = pad(attention_mask, 0)
        token_type_ids = pad(token_type_ids, 0)
        labels = pad(labels, label_pad)
        if position_ids is not None:
            position_ids = jnp.pad(position_ids, ((0, 0), (0, pad_len)),
                                   mode="edge")
        return pad_len, input_ids, attention_mask, token_type_ids, \
            position_ids, labels

    @staticmethod
    def unpad_sequence_output(pad_len: int, sequence_output):
        """Strip pad_to_block_size padding from the model output
        (sparse_attention_utils.py:210)."""
        if pad_len == 0:
            return sequence_output
        return sequence_output[:, :-pad_len]
