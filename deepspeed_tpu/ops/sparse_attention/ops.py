"""Standalone block-sparse MatMul / Softmax ops.

API parity with the reference's composable sparse ops
(deepspeed/ops/sparse_attention/matmul.py:595 MatMul,
softmax.py:207 Softmax): users building their OWN sparse kernels
compose ``sdd`` (dense x dense -> sparse), softmax-on-sparse, and
``dsd``/``dds`` (sparse x dense / dense x sparse -> dense) directly,
with the same compressed block format — a (batch, nnz, block, block)
tensor whose block order is the layout's nonzero order (head-major,
then block-row, then block-col; np.nonzero order).

Implementation is layout-driven jnp gather/einsum/scatter: the MXU
executes the per-block GEMMs batched over the nonzero list and XLA
fuses the rest. (The fused attention path — SparseSelfAttention — uses
the splash Pallas kernels in blocksparse*.py instead; these classes
exist for composability parity, differentiable by construction.)

Softmax normalizes each query row over the row's nonzero blocks only
(structural zeros excluded exactly), with the reference's mask
semantics: ``rpe`` (same compressed shape as x, added), key-padding
mask (B, S), attention mask (S, S), each in 'add' (values added) or
'mul' (zeros drop entries) mode.
"""

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30


def _nonzeros(layout: np.ndarray):
    hs, rs, cs = np.nonzero(np.asarray(layout))
    return (hs.astype(np.int32), rs.astype(np.int32), cs.astype(np.int32))


class MatMul:
    """Block-sparse matmul (reference matmul.py:595): one of
    - 'sdd': dense x dense -> sparse (compressed (B, nnz, blk, blk))
    - 'dsd': sparse x dense -> dense
    - 'dds': dense x sparse -> dense
    ``trans_a``/``trans_b`` transpose the last two dims of the
    corresponding operand first (e.g. sdd + trans_b=True is the
    attention Q @ K^T)."""

    def __init__(self, layout, block: int, mode: str,
                 trans_a: bool = False, trans_b: bool = False,
                 bench: bool = False):
        if mode not in ("sdd", "dsd", "dds"):
            raise NotImplementedError(
                f"Supported modes are: sdd, dsd, dds; got {mode}")
        self.layout = np.asarray(layout)
        self.block = int(block)
        self.mode = mode
        self.trans_a = trans_a
        self.trans_b = trans_b
        self.bench = bench                       # accepted for parity
        self.spdims = self.layout.shape
        self.hs, self.rs, self.cs = _nonzeros(self.layout)
        self.nnz = len(self.hs)

    def _dense_blocks(self, x, block_idx, seq_axis_blocks):
        """Gather (B, nnz, blk, D) row/col blocks out of a dense
        (B, H, S, D) operand: head hs[n], seq block ``block_idx[n]``."""
        B, H, S, D = x.shape
        blk = self.block
        xb = x.reshape(B, H, S // blk, blk, D)
        return xb[:, self.hs, block_idx]          # (B, nnz, blk, D)

    def __call__(self, a, b):
        blk = self.block
        if self.mode == "sdd":
            if self.trans_a:
                a = jnp.swapaxes(a, -1, -2)
            if self.trans_b:
                b = jnp.swapaxes(b, -1, -2)
            # a: (B, H, Sq, K), b: (B, H, K, Sk) -> blocks of a @ b
            a_blocks = self._dense_blocks(a, self.rs, None)  # (B,nnz,blk,K)
            bT = jnp.swapaxes(b, -1, -2)                     # (B, H, Sk, K)
            b_blocks = self._dense_blocks(bT, self.cs, None)  # (B,nnz,blk,K)
            return jnp.einsum("bnik,bnjk->bnij", a_blocks, b_blocks)
        if self.mode == "dsd":
            # a: sparse (B, nnz, blk, blk), b: dense (B, H, Sk, D)
            if self.trans_a:
                a = jnp.swapaxes(a, -1, -2)
                rs, cs = self.cs, self.rs
                out_blocks = self.spdims[2]
            else:
                rs, cs = self.rs, self.cs
                out_blocks = self.spdims[1]
            if self.trans_b:
                b = jnp.swapaxes(b, -1, -2)
            B, H, Sk, D = b.shape
            b_blocks = self._dense_blocks(b, cs, None)        # (B,nnz,blk,D)
            contrib = jnp.einsum("bnij,bnjd->bnid", a, b_blocks)
            # scatter-add into (B, H, out_blocks, blk, D) rows
            out = jnp.zeros((B, self.spdims[0], out_blocks, blk, D),
                            contrib.dtype)
            out = out.at[:, self.hs, rs].add(contrib)
            return out.reshape(B, self.spdims[0], out_blocks * blk, D)
        # dds: a dense (B, H, Sq, K) x b sparse -> dense (B, H, Sq, Sk)
        if self.trans_a:
            a = jnp.swapaxes(a, -1, -2)
        if self.trans_b:
            b = jnp.swapaxes(b, -1, -2)
            rs, cs = self.cs, self.rs
            out_blocks = self.spdims[1]
        else:
            rs, cs = self.rs, self.cs
            out_blocks = self.spdims[2]
        B, H, Sq, K = a.shape
        # a's K dim is blocked by the sparse operand's row blocks
        ab = a.reshape(B, H, Sq, K // blk, blk)
        a_blocks = ab[:, self.hs, :, rs.astype(np.int64)]
        # advanced-index quirk: result is (nnz, B, Sq, blk) — move axes
        a_blocks = jnp.moveaxis(a_blocks, 0, 1)               # (B,nnz,Sq,blk)
        contrib = jnp.einsum("bnqj,bnjk->bnqk", a_blocks, b)
        out = jnp.zeros((B, self.spdims[0], Sq, out_blocks, blk),
                        contrib.dtype)
        out = out.at[:, self.hs, :, cs].add(
            jnp.moveaxis(contrib, 1, 0))
        return out.reshape(B, self.spdims[0], Sq, out_blocks * blk)


def _to_additive(mask, mode):
    mask = mask.astype(jnp.float32)
    if mode == "mul":
        return jnp.where(mask == 0, NEG_INF, 0.0)
    return mask


class Softmax:
    """Block-sparse softmax (reference softmax.py:207): normalizes each
    query row over the row's nonzero blocks; structural zeros never
    contribute. Masks as in the reference: rpe (compressed, added),
    key_padding_mask (B, S), attn_mask (S, S), each 'add'/'mul'."""

    def __init__(self, layout, block: int, bench: bool = False):
        self.layout = np.asarray(layout)
        self.block = int(block)
        self.bench = bench
        self.spdims = self.layout.shape
        self.num_blocks = int(self.layout.sum())
        self.hs, self.rs, self.cs = _nonzeros(self.layout)
        # group the nonzeros by (head, block-row) and pad to max degree
        H, nq, _ = self.spdims
        groups = [[] for _ in range(H * nq)]
        for n, (h, r) in enumerate(zip(self.hs, self.rs)):
            groups[h * nq + r].append(n)
        self.maxdeg = max((len(g) for g in groups), default=1) or 1
        lut = np.zeros((H * nq, self.maxdeg), np.int32)
        valid = np.zeros((H * nq, self.maxdeg), bool)
        for g, ns in enumerate(groups):
            lut[g, :len(ns)] = ns
            valid[g, :len(ns)] = True
        self.lut, self.valid = lut, valid
        # inverse: block n -> (group, slot)
        self.g_of_n = (self.hs.astype(np.int64) * nq
                       + self.rs.astype(np.int64))
        slot = np.zeros(len(self.hs), np.int32)
        for g, ns in enumerate(groups):
            for i, n in enumerate(ns):
                slot[n] = i
        self.slot_of_n = slot

    def __call__(self, x, scale=1.0, rpe=None, key_padding_mask=None,
                 attn_mask=None, key_padding_mask_mode: str = "add",
                 attn_mask_mode: str = "add"):
        blk = self.block
        B = x.shape[0]
        s = x.astype(jnp.float32) * scale
        if rpe is not None:
            s = s + rpe.astype(jnp.float32)
        if attn_mask is not None:
            am = _to_additive(jnp.asarray(attn_mask), attn_mask_mode)
            amb = am.reshape(self.spdims[1], blk, self.spdims[2], blk
                             ).transpose(0, 2, 1, 3)
            s = s + amb[self.rs, self.cs][None]
        if key_padding_mask is not None:
            kpm = _to_additive(jnp.asarray(key_padding_mask),
                               key_padding_mask_mode)    # (B, S)
            kpmb = kpm.reshape(B, self.spdims[2], blk)
            s = s + kpmb[:, self.cs][:, :, None, :]
        # gather each (head, block-row) group: (B, G, maxdeg, blk, blk)
        sg = s[:, self.lut]
        sg = jnp.where(self.valid[None, :, :, None, None], sg, NEG_INF)
        # softmax jointly over (maxdeg, blk_k) per query row
        Bn, G, Dg, _, _ = sg.shape
        flat = jnp.swapaxes(sg, 2, 3).reshape(Bn, G, blk, Dg * blk)
        m = jnp.max(flat, axis=-1, keepdims=True)
        # all-masked rows normalize to exact zeros, like the kernels
        e = jnp.where(flat > NEG_INF / 2, jnp.exp(flat - m), 0.0)
        denom = jnp.sum(e, axis=-1, keepdims=True)
        p = e / jnp.where(denom == 0.0, 1.0, denom)
        pg = jnp.swapaxes(p.reshape(Bn, G, blk, Dg, blk), 2, 3)
        out = pg[:, self.g_of_n, self.slot_of_n]
        return out.astype(x.dtype)
