"""Hybrid banded + residual block-sparse attention (BigBird fast path).

BigBird layouts (reference
deepspeed/ops/sparse_attention/sparsity_config.py:421: random blocks +
sliding window + ITC globals) are MOSTLY banded: the window and the
global prefix are exactly the structure banded.py runs at dense-flash
per-step cost, and only the ~1-block-per-row random residue needs the
generic machinery. Routing the whole layout to the generic v2 walk —
round-4 status — priced every cell at the overhead-bound generic rate.

The hybrid splits the layout exactly:

    banded part   = the maximal global-prefix + band predicate UNDER the
                    head-INTERSECTION of the layout (so the banded
                    kernels stay head-uniform even when random blocks
                    differ per head)
    residual part = layout & ~banded  (per head; the random blocks)

and runs each part's existing kernels unchanged. Because the parts
partition the kept cells, the full softmax is recovered with the
flash-decoding merge on the per-part log-sum-exp:

    L   = logaddexp(lse_banded, lse_residual)
    out = exp(lse_banded - L) * o_banded + exp(lse_residual - L) * o_res

Backward needs no new kernels either: the flash backward identity
ds = p * (dp - delta) only consumes the GLOBAL row statistics — the
merged L (for p = exp(s - L)) and delta = sum(do * o_merged) — so each
part's existing bwd impl is called with the merged L and merged output,
and their dq/dk/dv contributions add (each part touches exactly its own
cells).

Dispatch (blocksparse._sparse_attention_fn) tries: exact banded ->
hybrid -> coarse/v2/v1. The hybrid engages only when the banded part
covers enough of the layout to pay for the second kernel pass
(_MIN_COVERAGE) and when the v2 walk can actually stream the residual
(128-multiple blocks, same constraint v2 itself has).
"""

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.sparse_attention.banded import (
    NEG_INF, BandedParams, _blocks_valid, _ceil_div, build_banded_impls,
    pick_blocks, walk_stats)

# the banded part must cover at least this fraction of the layout's
# active cells: below it the residual walk dominates anyway and the
# extra banded pass + merge is pure overhead
_MIN_COVERAGE = 0.5


class HybridPlan(NamedTuple):
    params: BandedParams
    blocks: tuple             # (bq, bkv) banded walk tiles
    residual: np.ndarray      # (H, nb, nb) 0/1 residual layout
    coverage: float           # banded cells / total active cells


def detect_banded_subpattern(layout: np.ndarray) \
        -> Optional[tuple]:
    """Maximal (BandedParams, residual, coverage) with the banded
    predicate a SUBSET of every head's layout. Unlike
    banded.detect_banded this never demands equality — the leftover
    cells become the residual — and per-head layouts are fine (the
    predicate is fit under the head intersection)."""
    L = np.asarray(layout).astype(bool)
    if L.ndim != 3 or L.shape[1] != L.shape[2] or L.shape[1] == 0:
        return None
    base = L.all(axis=0)                  # head-intersection
    n = base.shape[0]
    idx = np.arange(n)
    rb, cb = idx[:, None], idx[None, :]
    best = None
    for causal in (False, True):
        clip = (cb <= rb) if causal else np.ones((n, n), bool)
        covered = base | ~clip            # cells set-or-clipped-away
        row_full = covered.all(axis=1)
        col_full = covered.all(axis=0)
        g_r = 0
        while g_r < n and row_full[g_r]:
            g_r += 1
        g_c = 0
        while g_c < n and col_full[g_c]:
            g_c += 1
        if g_r >= n:                      # fully dense under this clip
            continue
        # max w with every |rb-cb| <= w diagonal fully set inside the
        # non-global region (w = -1: no full diagonal -> no band)
        region = (rb >= g_r) & (cb >= g_c) & clip
        w = -1
        for cand in range(n):
            diag = region & (np.abs(rb - cb) == cand)
            if not base[diag].all():
                break
            w = cand
        if w < 0:
            continue
        pred = ((rb < g_r) | (cb < g_c) | (np.abs(rb - cb) <= w)) & clip
        total = int(L.sum())
        if total == 0:
            continue
        coverage = L.shape[0] * int(pred.sum()) / total
        if best is None or coverage > best[2]:
            residual = (L & ~pred[None]).astype(np.int32)
            best = (BandedParams(g_r, g_c, w, bool(causal)),
                    residual, coverage)
    return best


def plan_hybrid(layout: np.ndarray, fine_block: int,
                interpret: bool) -> Optional[HybridPlan]:
    """THE hybrid-dispatch decision (mirrors banded.plan): a HybridPlan
    when the split pays, else None. Declines when the residual is empty
    (the exact banded path owns that), when coverage is too low, or
    when the v2 walk could not stream the residual (non-128-multiple
    block, compiled)."""
    if not interpret and fine_block % 128 != 0:
        return None
    det = detect_banded_subpattern(layout)
    if det is None:
        return None
    params, residual, coverage = det
    if residual.sum() == 0 or coverage < _MIN_COVERAGE:
        return None
    S = np.asarray(layout).shape[1] * fine_block
    blocks = pick_blocks(S, fine_block, params, interpret)
    if blocks is None or not _blocks_valid(S, *blocks, interpret):
        return None
    return HybridPlan(params, blocks, residual, coverage)


def build_hybrid_fn(layout: np.ndarray, fine_block: int,
                    plan: HybridPlan, sm_scale: float, interpret: bool):
    """Differentiable f(q, k, v, kpm_blocked) -> o for the hybrid path;
    public signature identical to the banded/v2 builders (kpm arrives
    pre-blocked (B, nk, 1, fine_block) additive)."""
    from deepspeed_tpu.ops.sparse_attention.blocksparse_v2 import (
        build_v2_impls)
    H, nb, _ = np.asarray(layout).shape
    S = nb * fine_block
    bq, bkv = plan.blocks
    params = plan.params
    fwd_b, bwd_b = build_banded_impls(H, S, fine_block, params,
                                      sm_scale, bq, bkv, interpret)
    fwd_r, bwd_r = build_v2_impls(plan.residual, fine_block, sm_scale,
                                  interpret, has_am=False,
                                  coarse_block=None)
    GQ = _ceil_div(params.g_r * fine_block, bq) if params.g_r else 0

    def _flat_kpm(kpm):
        B = kpm.shape[0]
        return kpm.transpose(0, 2, 1, 3).reshape(B, S)

    def _merged_fwd(q, k, v, kpm):
        B = q.shape[0]
        o_b, lse_b, lse_g = fwd_b(q, k, v, _flat_kpm(kpm))
        o_r, lse_r = fwd_r(q, k, v, kpm, None)
        # fold the global-rows instance lse into a full-length banded
        # lse: per row exactly one of (band, gr) holds real mass, the
        # other is NEG_INF, so logaddexp selects it
        if GQ:
            pad = jnp.full((lse_b.shape[0], S - GQ * bq, 1), NEG_INF,
                           jnp.float32)
            lse_bf = jnp.logaddexp(lse_b,
                                   jnp.concatenate([lse_g, pad], axis=1))
        else:
            lse_bf = lse_b
        L = jnp.logaddexp(lse_bf, lse_r)
        wb = jnp.exp(lse_bf - L).reshape(B, H, S, 1)
        wr = jnp.exp(lse_r - L).reshape(B, H, S, 1)
        o = (wb * o_b.astype(jnp.float32) +
             wr * o_r.astype(jnp.float32)).astype(q.dtype)
        return o, L

    @jax.custom_vjp
    def f(q, k, v, kpm):
        return _merged_fwd(q, k, v, kpm)[0]

    def f_fwd(q, k, v, kpm):
        o, L = _merged_fwd(q, k, v, kpm)
        return o, (q, k, v, kpm, o, L)

    def f_bwd(res, g):
        q, k, v, kpm, o, L = res
        # both parts get the MERGED row stats: p = exp(s - L) inside
        # each kernel is then the true global probability of its cells,
        # and delta = sum(do * o_merged) is computed from the merged
        # output each impl receives
        L_g = L[:, :GQ * bq] if GQ else L[:, :0]
        dq_b, dk_b, dv_b = bwd_b(q, k, v, _flat_kpm(kpm), o, L, L_g, g)
        dq_r, dk_r, dv_r = bwd_r(q, k, v, kpm, None, o, L, g)
        dq = (dq_b.astype(jnp.float32) +
              dq_r.astype(jnp.float32)).astype(q.dtype)
        dk = (dk_b.astype(jnp.float32) +
              dk_r.astype(jnp.float32)).astype(k.dtype)
        dv = (dv_b.astype(jnp.float32) +
              dv_r.astype(jnp.float32)).astype(v.dtype)
        return dq, dk, dv, jnp.zeros_like(kpm)

    f.defvjp(f_fwd, f_bwd)
    f.kernel_kind = "hybrid"
    f.banded_blocks = (bq, bkv)
    f.hybrid_coverage = plan.coverage
    return f


def hybrid_stats(layout: np.ndarray, fine_block: int, plan: HybridPlan):
    """Static FLOP accounting for the hybrid at a geometry (the
    walk_stats analog): banded walk cost + residual v2 cost vs the
    exact-sparse bound of the WHOLE layout. Lets tests pin the waste
    factor (computed/exact) without hardware."""
    H, nb, _ = np.asarray(layout).shape
    S = nb * fine_block
    bq, bkv = plan.blocks
    # banded part: uniform across heads -> use one head's pred count
    L = np.asarray(layout).astype(bool)
    pred = L[0] & ~plan.residual[0].astype(bool)
    banded = walk_stats(S, fine_block, plan.params, bq, bkv,
                        n_active_blocks=int(pred.sum()))
    # residual v2 walk: 9 tile dots per active fine block per head
    # (fwd s/pv = 2, dq s/dp/dq = 3, dkv s/dv/dp/dk = 4) — the v2 walk
    # computes exactly the active cells, its overhead is per-step, not
    # per-cell
    res_nnz = int(plan.residual.sum())
    res_cells = 9 * res_nnz * fine_block * fine_block
    total_nnz = int(L.sum())
    exact = 9 * total_nnz * fine_block * fine_block
    computed = H * banded["computed_cell_dots"] + res_cells
    return {
        "banded_steps": banded["steps"],
        "banded_cell_dots_per_head": banded["computed_cell_dots"],
        "residual_nnz_blocks": res_nnz,
        "residual_cell_dots": res_cells,
        "computed_cell_dots": computed,
        "exact_cell_dots": exact,
        "waste": computed / exact if exact else None,
        "coverage": plan.coverage,
    }
