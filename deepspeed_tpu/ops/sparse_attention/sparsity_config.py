"""Block-sparsity layout configurations for sparse attention.

TPU-native re-design of the reference's sparsity pattern zoo
(reference: deepspeed/ops/sparse_attention/sparsity_config.py — classes
SparsityConfig:9, DenseSparsityConfig:63, FixedSparsityConfig:94,
VariableSparsityConfig:243, BigBirdSparsityConfig:421,
BSLongformerSparsityConfig:544). Each config produces a block-level layout
tensor of shape ``(num_heads, seq_len // block, seq_len // block)`` with 1
marking an attended (query-block, key-block) pair. The layout is *static*
numpy data consumed at trace time by the Pallas block-sparse attention
kernel (blocksparse.py), which turns it into per-row look-up tables.

Deviations from the reference, on purpose:
- layouts are numpy ``int32`` (not torch int64) — they are host-side trace
  constants, never device data;
- random patterns draw from a seeded ``np.random.Generator`` (``seed``
  knob, default 0) instead of the global ``random`` module: under SPMD
  every host must build the *identical* layout or the compiled programs
  diverge;
- default ``block`` is 64 (reference: 16): the MXU wants >= 64x64 tiles;
  16 is still accepted for parity tests.
"""

from typing import List, Optional, Sequence

import numpy as np


class SparsityConfig:
    """Base class: shared knobs + layout allocation/propagation helpers.

    Reference parity: sparsity_config.py:9 (num_heads / block /
    different_layout_per_head; setup_layout:29 seq-divisibility check;
    check_and_propagate_first_head_layout:48).
    """

    def __init__(self, num_heads: int, block: int = 64,
                 different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block != 0:
            raise ValueError(
                f"sequence length {seq_len} must be divisible by block size "
                f"{self.block}")
        num_blocks = seq_len // self.block
        return np.zeros((self.num_heads, num_blocks, num_blocks),
                        dtype=np.int32)

    def propagate_first_head(self, layout: np.ndarray) -> np.ndarray:
        """Broadcast head 0's layout to all heads when layouts are shared."""
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError

    def make_block_mask(self, seq_len: int, walk_block=None):
        """Resolve this config to the unified masked-flash kernel's
        :class:`~deepspeed_tpu.ops.attention.masked_flash.BlockMask` —
        the one object the training kernel consumes (PR 11). Head-
        uniform layouts collapse to a single mask head; banded layouts
        (BSLongformer-class) coarsen their walk tile automatically, the
        fine structure riding in-register predicates. ``walk_block``
        forces a tile size (0 = the config's own block)."""
        from deepspeed_tpu.ops.attention.masked_flash import BlockMask
        return BlockMask.from_layout(self.make_layout(seq_len),
                                     self.block, walk_block=walk_block)

    def layout_cache_key(self):
        """Hashable identity used by SparseSelfAttention's per-seq-len op
        cache. Subclasses with extra knobs extend this tuple."""
        return (type(self).__name__, self.num_heads, self.block,
                self.different_layout_per_head)


class DenseSparsityConfig(SparsityConfig):
    """All blocks active — for comparison/debugging only.
    Reference parity: sparsity_config.py:63."""

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


def _check_attention(attention: str, horizontal_global_attention: bool):
    if attention not in ("unidirectional", "bidirectional"):
        raise NotImplementedError(
            "attention must be 'unidirectional' or 'bidirectional'")
    if attention != "bidirectional" and horizontal_global_attention:
        raise ValueError("horizontal global attention requires "
                         "bidirectional attention")


class FixedSparsityConfig(SparsityConfig):
    """Fixed local windows + periodic global blocks (Sparse-Transformer
    style, arXiv:1904.10509). Reference parity: sparsity_config.py:94
    (set_local_layout:154, set_global_layout:175).

    Each contiguous window of ``num_local_blocks`` block-rows attends within
    itself (lower-triangular only when unidirectional). The last
    ``num_global_blocks`` of each window act as global: every (later, when
    unidirectional) row attends to them; with
    ``horizontal_global_attention`` they also attend to everything. Heads
    can rotate which window slot is global via
    ``num_different_global_patterns``.
    """

    def __init__(self, num_heads: int, block: int = 64,
                 different_layout_per_head: bool = False,
                 num_local_blocks: int = 4, num_global_blocks: int = 1,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 num_different_global_patterns: int = 1):
        super().__init__(num_heads, block, different_layout_per_head)
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError(
                f"num_local_blocks ({num_local_blocks}) must be divisible "
                f"by num_global_blocks ({num_global_blocks})")
        _check_attention(attention, horizontal_global_attention)
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError(
                "num_different_global_patterns > 1 requires "
                "different_layout_per_head=True")
        if num_different_global_patterns > num_local_blocks // \
                num_global_blocks:
            raise ValueError(
                f"num_different_global_patterns "
                f"({num_different_global_patterns}) cannot exceed "
                f"num_local_blocks/num_global_blocks "
                f"({num_local_blocks // num_global_blocks})")
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = num_different_global_patterns

    def layout_cache_key(self):
        return super().layout_cache_key() + (
            self.num_local_blocks, self.num_global_blocks, self.attention,
            self.horizontal_global_attention,
            self.num_different_global_patterns)

    def _set_local(self, h: int, layout: np.ndarray):
        nb = layout.shape[1]
        uni = self.attention == "unidirectional"
        for start in range(0, nb, self.num_local_blocks):
            end = min(start + self.num_local_blocks, nb)
            win = np.ones((end - start, end - start), dtype=np.int32)
            if uni:
                win = np.tril(win)
            layout[h, start:end, start:end] |= win

    def _set_global(self, h: int, layout: np.ndarray):
        nb = layout.shape[1]
        g = self.num_global_blocks
        # which slot (counted from the window's end) is global for this head
        slot = self.num_local_blocks - \
            (1 + h % self.num_different_global_patterns) * g
        full_windows_end = nb - nb % self.num_local_blocks
        starts = list(range(slot, full_windows_end, self.num_local_blocks))
        if full_windows_end < nb:  # short trailing window
            starts.append(min(full_windows_end + slot, nb - g))
        for s in starts:
            first_row = 0 if self.attention == "bidirectional" else s
            layout[h, first_row:, s:s + g] = 1
            if self.horizontal_global_attention:
                layout[h, s:s + g, :] = 1

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            self._set_local(h, layout)
            self._set_global(h, layout)
        return self.propagate_first_head(layout)


class VariableSparsityConfig(SparsityConfig):
    """Fixed-style layout with per-window sizes, explicit global block
    (ranges), and optional random blocks. Reference parity:
    sparsity_config.py:243 (set_random_layout:309, set_local_layout:331,
    set_global_layout:364)."""

    def __init__(self, num_heads: int, block: int = 64,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 0,
                 local_window_blocks: Optional[Sequence[int]] = None,
                 global_block_indices: Optional[Sequence[int]] = None,
                 global_block_end_indices: Optional[Sequence[int]] = None,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = list(local_window_blocks or [4])
        self.global_block_indices = list(global_block_indices or [0])
        if global_block_end_indices is not None:
            ends = list(global_block_end_indices)
            if len(self.global_block_indices) != len(ends):
                raise ValueError(
                    "global_block_indices and global_block_end_indices must "
                    "have the same length")
            for s, e in zip(self.global_block_indices, ends):
                if s >= e:
                    raise ValueError(
                        f"global block start {s} must be < end {e}")
            self.global_block_end_indices: Optional[List[int]] = ends
        else:
            self.global_block_end_indices = None
        _check_attention(attention, horizontal_global_attention)
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.seed = seed

    def layout_cache_key(self):
        return super().layout_cache_key() + (
            self.num_random_blocks, tuple(self.local_window_blocks),
            tuple(self.global_block_indices),
            None if self.global_block_end_indices is None
            else tuple(self.global_block_end_indices),
            self.attention, self.horizontal_global_attention, self.seed)

    def _set_random(self, h: int, layout: np.ndarray,
                    rng: np.random.Generator):
        nb = layout.shape[1]
        if self.num_random_blocks == 0:
            return
        if nb < self.num_random_blocks:
            raise ValueError(
                f"num_random_blocks ({self.num_random_blocks}) must be <= "
                f"blocks per row ({nb})")
        for row in range(nb):
            cols = rng.choice(nb, size=self.num_random_blocks, replace=False)
            layout[h, row, cols] = 1

    def _set_local(self, h: int, layout: np.ndarray):
        nb = layout.shape[1]
        uni = self.attention == "unidirectional"

        def fill(start, end):
            if start >= nb:
                return
            end = min(end, nb)
            win = np.ones((end - start, end - start), dtype=np.int32)
            if uni:
                win = np.tril(win)
            layout[h, start:end, start:end] |= win

        start = 0
        for size in self.local_window_blocks:
            fill(start, start + size)
            start += size
        # remaining rows reuse the last window size
        size = self.local_window_blocks[-1]
        while start < nb:
            fill(start, start + size)
            start += size

    def _set_global(self, h: int, layout: np.ndarray):
        nb = layout.shape[1]
        if self.global_block_end_indices is None:
            spans = [(i, i + 1) for i in self.global_block_indices]
        else:
            spans = list(zip(self.global_block_indices,
                             self.global_block_end_indices))
        for s, e in spans:
            if s >= nb:
                continue
            e = min(e, nb)
            first_row = 0 if self.attention == "bidirectional" else s
            layout[h, first_row:, s:e] = 1
            if self.horizontal_global_attention:
                layout[h, s:e, :] = 1

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        rng = np.random.default_rng(self.seed)
        for h in range(self.num_layout_heads):
            self._set_random(h, layout, rng)
            self._set_local(h, layout)
            self._set_global(h, layout)
        return self.propagate_first_head(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """Random + sliding-window + ITC-global blocks (arXiv:2007.14062).
    Reference parity: sparsity_config.py:421 (set_random_layout:452,
    set_sliding_window_layout:475, set_global_layout_itc:499)."""

    def __init__(self, num_heads: int, block: int = 64,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 1,
                 num_sliding_window_blocks: int = 3,
                 num_global_blocks: int = 1, seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.seed = seed

    def layout_cache_key(self):
        return super().layout_cache_key() + (
            self.num_random_blocks, self.num_sliding_window_blocks,
            self.num_global_blocks, self.seed)

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        for name, n in (("num_random_blocks", self.num_random_blocks),
                        ("num_sliding_window_blocks",
                         self.num_sliding_window_blocks),
                        ("num_global_blocks", self.num_global_blocks)):
            if nb < n:
                raise ValueError(f"{name} ({n}) must be <= blocks per row "
                                 f"({nb})")
        rng = np.random.default_rng(self.seed)
        w = self.num_sliding_window_blocks // 2
        band = np.abs(np.arange(nb)[:, None] - np.arange(nb)[None, :]) <= w
        for h in range(self.num_layout_heads):
            for row in range(nb):
                cols = rng.choice(nb, size=self.num_random_blocks,
                                  replace=False)
                layout[h, row, cols] = 1
            layout[h][band] = 1
            layout[h, :self.num_global_blocks, :] = 1     # global rows
            layout[h, :, :self.num_global_blocks] = 1     # global columns
        return self.propagate_first_head(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer: sliding window + chosen global block
    (ranges) (arXiv:2004.05150). Reference parity: sparsity_config.py:544
    (set_sliding_window_layout:590, set_global_layout:614)."""

    def __init__(self, num_heads: int, block: int = 64,
                 different_layout_per_head: bool = False,
                 num_sliding_window_blocks: int = 3,
                 global_block_indices: Optional[Sequence[int]] = None,
                 global_block_end_indices: Optional[Sequence[int]] = None):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = list(global_block_indices or [0])
        if global_block_end_indices is not None:
            ends = list(global_block_end_indices)
            if len(self.global_block_indices) != len(ends):
                raise ValueError(
                    "global_block_indices and global_block_end_indices must "
                    "have the same length")
            for s, e in zip(self.global_block_indices, ends):
                if s >= e:
                    raise ValueError(
                        f"global block start {s} must be < end {e}")
            self.global_block_end_indices: Optional[List[int]] = ends
        else:
            self.global_block_end_indices = None

    def layout_cache_key(self):
        return super().layout_cache_key() + (
            self.num_sliding_window_blocks,
            tuple(self.global_block_indices),
            None if self.global_block_end_indices is None
            else tuple(self.global_block_end_indices))

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        if nb < self.num_sliding_window_blocks:
            raise ValueError(
                f"num_sliding_window_blocks "
                f"({self.num_sliding_window_blocks}) must be <= blocks per "
                f"row ({nb})")
        w = self.num_sliding_window_blocks // 2
        band = np.abs(np.arange(nb)[:, None] - np.arange(nb)[None, :]) <= w
        if self.global_block_end_indices is None:
            spans = [(i, i + 1) for i in self.global_block_indices]
        else:
            spans = list(zip(self.global_block_indices,
                             self.global_block_end_indices))
        for h in range(self.num_layout_heads):
            layout[h][band] = 1
            for s, e in spans:
                if s >= nb:
                    continue
                e = min(e, nb)
                layout[h, s:e, :] = 1   # global rows
                layout[h, :, s:e] = 1   # global columns
        return self.propagate_first_head(layout)


def sparsity_config_from_dict(cfg, num_heads: int):
    """Build a SparsityConfig from the parsed ``sparse_attention`` JSON
    sub-config (runtime/config.py get_sparse_attention, mirroring the
    reference's key schema, deepspeed/runtime/config.py:156-317).

    The reference leaves this glue to client model code (its examples
    repo); here it is part of the framework so a JSON config alone can
    turn on block-sparse attention: the dict's keys ARE the class
    constructor keywords, ``mode`` selects the class, and ``num_heads``
    comes from the model.
    """
    if cfg is None:
        return None
    kwargs = {k: v for k, v in cfg.items() if k != "mode" and v is not None}
    classes = {
        "dense": DenseSparsityConfig,
        "fixed": FixedSparsityConfig,
        "variable": VariableSparsityConfig,
        "bigbird": BigBirdSparsityConfig,
        "bslongformer": BSLongformerSparsityConfig,
    }
    mode = cfg.get("mode", "fixed")
    if mode not in classes:
        raise ValueError(
            f"sparse_attention mode {mode!r} not in {sorted(classes)}")
    if "block" not in cfg:
        # the parse-first contract, enforced (ADVICE r3 #3): a raw
        # (unparsed) dict would silently get the CLASS defaults
        # (block=64) instead of the JSON-schema defaults (block=16)
        # that runtime/config.py get_sparse_attention applies
        raise ValueError(
            "sparsity_config_from_dict expects the PARSED sparse_attention "
            "sub-config (runtime/config.py get_sparse_attention), which "
            "always carries 'block'; got a raw dict without it")
    return classes[mode](num_heads=num_heads, **kwargs)
