"""Pallas block-sparse attention — TPU-native long-sequence kernel.

TPU re-design of the reference's Triton block-sparse stack
(deepspeed/ops/sparse_attention: matmul.py:18 SDD/DSD `_sparse_matmul`,
softmax.py:17 `_sparse_softmax`, trsrc/{matmul.tr,softmax_fwd.tr,
softmax_bwd.tr}). The reference decomposes sparse attention into three
kernels (SDD scores → sparse softmax → DSD context) with materialized
block-sparse score storage. On TPU we fuse all three into ONE
flash-attention-style kernel driven by per-row look-up tables: each
program owns a (query-block, head) tile, streams only the *active*
key/value blocks named by its LUT through VMEM, and never materializes
scores — O(S * active_blocks) compute with O(S) memory, which beats the
reference's sparse-storage scheme on both HBM traffic and fusion.

Layouts come from sparsity_config.py as static numpy (H, nb, nb) 0/1
tensors; LUTs are delivered to the kernel via scalar prefetch (SMEM), the
canonical Mosaic pattern for block-sparse grids.

Mask semantics (parity with trsrc/softmax_fwd.tr:100-119): scores are
scaled, then rpe added, then key-padding mask and attention mask applied —
'add' mode adds the mask values; 'mul' mode maps zero entries to -inf and
nonzero to 0 (a hard keep/drop mask).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30
# scores below this are "structurally masked": several -1e30 mask terms may
# stack, so the threshold sits well above any sum of them but far below any
# finite score
VALID_THRESH = -1e28


# --------------------------------------------------------------------- #
# layout utilities
# --------------------------------------------------------------------- #
def build_row_luts(layout: np.ndarray):
    """Per-(head, query-block) list of active key-block indices.

    Returns (lut, cnt): lut (H, nq, A) int32 padded with 0, cnt (H, nq)
    int32; A = max active blocks over all rows (>= 1)."""
    H, nq, _ = layout.shape
    cnt = layout.sum(axis=-1).astype(np.int32)
    A = max(int(cnt.max()) if cnt.size else 0, 1)
    lut = np.zeros((H, nq, A), dtype=np.int32)
    for h in range(H):
        for r in range(nq):
            idx = np.nonzero(layout[h, r])[0]
            lut[h, r, :len(idx)] = idx
    return lut, cnt


def build_col_luts(layout: np.ndarray):
    """Column-wise LUTs (which query blocks touch each key block) — drives
    the dk/dv backward pass."""
    return build_row_luts(np.ascontiguousarray(layout.transpose(0, 2, 1)))


def layout_additive_mask(layout: np.ndarray, block: int) -> np.ndarray:
    """Expand a block layout to a dense (H, S, S) additive mask (0 keep /
    NEG_INF drop) — the oracle path."""
    dense = np.kron(layout, np.ones((block, block), dtype=np.int32))
    return np.where(dense != 0, 0.0, NEG_INF).astype(np.float32)


def _to_additive(mask, mode):
    mask = mask.astype(jnp.float32)
    if mode == "add":
        return mask
    if mode == "mul":
        return jnp.where(mask == 0, NEG_INF, 0.0)
    raise ValueError(f"mask mode must be 'add' or 'mul', got {mode!r}")


def _block_kpm(kpm, block):
    """(B, S) -> (B, nk, 1, block): the key-block index becomes a leading
    (untiled) dimension so the kernel can gather it with a LUT value —
    dynamic offsets on the lane dimension would need 128-alignment proofs
    Mosaic can't make for arbitrary block sizes."""
    B, S = kpm.shape
    return kpm.reshape(B, S // block, 1, block)


def _block_am(am, block):
    """(S, S) -> (nq, nk, block, block) with the same leading-dim gather
    rationale as _block_kpm."""
    S = am.shape[0]
    nb = S // block
    return am.reshape(nb, block, nb, block).transpose(0, 2, 1, 3)


# --------------------------------------------------------------------- #
# oracle / fallback implementation
# --------------------------------------------------------------------- #
def block_sparse_attention_reference(q, k, v, layout, sm_scale=None,
                                     key_padding_mask=None,
                                     key_padding_mask_mode="add",
                                     attn_mask=None, attn_mask_mode="mul",
                                     rpe=None):
    """Dense-masked jnp attention equivalent to the block-sparse kernel.

    q, k, v: (B, H, S, D). layout: numpy (H, nb, nb). Rows with no valid
    key (structurally or via masks) produce zero output, matching the
    kernel (the reference Triton softmax yields 0/0 there; we define it)."""
    B, H, S, D = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(D)
    block = S // layout.shape[1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if rpe is not None:
        s = s + rpe.astype(jnp.float32)
    if key_padding_mask is not None:
        kpm = _to_additive(key_padding_mask, key_padding_mask_mode)
        s = s + kpm[:, None, None, :]
    if attn_mask is not None:
        am = _to_additive(attn_mask, attn_mask_mode)
        s = s + am[None, None, :, :]
    s = s + jnp.asarray(layout_additive_mask(layout, block))[None]
    m = jnp.max(s, axis=-1, keepdims=True)
    m_safe = jnp.where(m <= VALID_THRESH, 0.0, m)
    p = jnp.where(s > VALID_THRESH, jnp.exp(s - m_safe), 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    p = p / jnp.where(l == 0.0, 1.0, l)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


# --------------------------------------------------------------------- #
# pallas kernels
# --------------------------------------------------------------------- #
# Grid-iterated ("splash") design: the grid's second axis walks the
# *nonzero blocks themselves* — one grid step per active (head, q-block,
# k-block) triple, nothing per empty block. Scalar-prefetch index maps
# translate the triple id through LUTs to pick which Q/K/V/mask tiles
# Mosaic DMAs, so every load is an aligned BlockSpec copy the pipeline
# double-buffers. Online-softmax state lives in VMEM scratch, reset on a
# triple flagged row-first and flushed to the output block on row-last
# (Pallas holds the output tile in VMEM until its index changes, and
# triples are sorted row-major so the index is constant within a row).
# Rows with no active block get one dummy triple (valid=0) so their output
# still gets written (as zeros).


def build_triples(layout: np.ndarray):
    """Flatten a (H, nr, nc) layout into row-major nonzero triples.

    Returns int32 arrays (trow, tcol, tfirst, tlast, tvalid), each (T,):
    trow = h * nr + r, tcol = c, tfirst/tlast mark row boundaries, and
    empty rows contribute a single valid=0 dummy so every output block is
    produced."""
    H, nr, _ = layout.shape
    trow, tcol, tfirst, tlast, tvalid = [], [], [], [], []
    for h in range(H):
        for r in range(nr):
            idx = np.nonzero(layout[h, r])[0]
            valid = 1
            if len(idx) == 0:
                idx, valid = np.array([0]), 0
            n = len(idx)
            trow.extend([h * nr + r] * n)
            tcol.extend(int(c) for c in idx)
            tfirst.extend([1] + [0] * (n - 1))
            tlast.extend([0] * (n - 1) + [1])
            tvalid.extend([valid] * n)
    return tuple(np.asarray(x, np.int32)
                 for x in (trow, tcol, tfirst, tlast, tvalid))


def _bs_fwd_kernel(trow_ref, tcol_ref, tfirst_ref, tlast_ref, tvalid_ref,
                   q_ref, k_ref, v_ref, kpm_ref, am_ref, o_ref, lse_ref,
                   m_scr, l_scr, acc_scr, *, sm_scale):
    t = pl.program_id(1)

    @pl.when(tfirst_ref[t] == 1)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # MXU fast path: bf16 operands / fp32 accumulation (fp32 converts
    # both halve the MXU rate and bloat VMEM); scale applies to the
    # fp32 scores post-dot
    q = q_ref[0]                                         # (block, D)
    k = k_ref[0]
    v = v_ref[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    s = s * sm_scale
    s += kpm_ref[0, 0, 0, :][None, :]
    if am_ref is not None:
        s += am_ref[0, 0]
    s = jnp.where(tvalid_ref[t] == 1, s, NEG_INF)
    m = m_scr[:, 0]
    l = l_scr[:, 0]
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # exact-zero probability for structurally masked entries; rows with no
    # valid entry keep l == 0 and fall out as zero output
    p = jnp.where(s > VALID_THRESH, jnp.exp(s - m_new[:, None]), 0.0)
    alpha = jnp.exp(m - m_new)
    m_scr[:, 0] = m_new
    l_scr[:, 0] = l * alpha + jnp.sum(p, axis=-1)
    acc_scr[...] = acc_scr[...] * alpha[:, None] + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(tlast_ref[t] == 1)
    def _finalize():
        l = l_scr[:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[...] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0, :, 0] = m_scr[:, 0] + jnp.log(l_safe)


def _bs_dq_kernel(trow_ref, tcol_ref, tfirst_ref, tlast_ref, tvalid_ref,
                  q_ref, k_ref, v_ref, kpm_ref, am_ref, do_ref, lse_ref,
                  delta_ref, dq_ref, dq_scr, *, sm_scale):
    t = pl.program_id(1)

    @pl.when(tfirst_ref[t] == 1)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]
    lse = lse_ref[0, :, 0]
    delta = delta_ref[0, :, 0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    s = s * sm_scale
    s += kpm_ref[0, 0, 0, :][None, :]
    if am_ref is not None:
        s += am_ref[0, 0]
    s = jnp.where(tvalid_ref[t] == 1, s, NEG_INF)
    p = jnp.where(s > VALID_THRESH, jnp.exp(s - lse[:, None]), 0.0)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta[:, None])
    dq_scr[...] += jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(tlast_ref[t] == 1)
    def _finalize():
        dq_ref[0] = (dq_scr[...] * sm_scale).astype(dq_ref.dtype)


def _bs_dkv_kernel(crow_ref, ccol_ref, cfirst_ref, clast_ref, cvalid_ref,
                   q_ref, k_ref, v_ref, kpm_ref, am_ref, do_ref, lse_ref,
                   delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *, sm_scale):
    t = pl.program_id(1)

    @pl.when(cfirst_ref[t] == 1)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    k = k_ref[0]                                         # (block, D)
    v = v_ref[0]
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0, :, 0]
    delta = delta_ref[0, :, 0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    s = s * sm_scale
    s += kpm_ref[0, 0, 0, :][None, :]
    if am_ref is not None:
        s += am_ref[0, 0]
    s = jnp.where(cvalid_ref[t] == 1, s, NEG_INF)
    p = jnp.where(s > VALID_THRESH, jnp.exp(s - lse[:, None]), 0.0)
    dv_scr[...] += jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta[:, None])
    dk_scr[...] += jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(clast_ref[t] == 1)
    def _finalize():
        # dk carries sm_scale (scores were scaled post-dot)
        dk_ref[0] = (dk_scr[...] * sm_scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _drop_am(kernel, n_before):
    """Adapter for the no-attn-mask variant: inserts am_ref=None at the
    right positional slot (after `n_before` refs)."""
    def wrapped(*refs, **kw):
        return kernel(*refs[:n_before], None, *refs[n_before:], **kw)
    return wrapped


# --------------------------------------------------------------------- #
# builder: layout -> differentiable fused function (cached)
# --------------------------------------------------------------------- #
_FN_CACHE = {}

# unified mask-parameterized flash kernel (ops/attention/masked_flash.py,
# PR 11): the DEFAULT for every layout without a user attention mask —
# dense, causal, banded and BigBird are BlockMask choices of ONE kernel.
# Flip off to reach the legacy dispatch below (banded / hybrid / v2 /
# coarse), kept as numerics oracles and A/B baselines.
USE_MASKED_FLASH = True

# row-run DMA kernels (blocksparse_v2.py) for the no-attn-mask path
# within the LEGACY dispatch; flip off to fall back to the per-triple v1
# kernels. DEPRECATED AS A DISPATCH TARGET: the v1 one-program-per-
# nonzero-block grid loses to dense flash on launch overhead (~10k
# sequential launches at a 128-block Longformer S=8192 layout), so the
# automatic dispatch NEVER selects it anymore — an unstreamable block
# size now routes to the unified masked kernel instead. v1 stays
# importable/buildable (set USE_SPLASH_V2 = False explicitly) as a test
# oracle only.
USE_SPLASH_V2 = True

# banded fast path (banded.py): layouts that match the global-prefix +
# sliding-window predicate (BSLongformer-class) skip all CSR/DMA-stream
# machinery — masks are computed from iota block arithmetic in registers
USE_BANDED = True

# hybrid banded+residual path (hybrid.py): mostly-banded layouts with a
# small non-banded residue (BigBird random blocks) run the banded
# kernels on the banded sub-pattern and the v2 walk on the residue,
# merged by per-part log-sum-exp (flash-decoding style)
USE_HYBRID = True

# layout coarsening (blocksparse_v2.build_coarse_index): walk coarse
# tiles, express fine structure as streamed NEG_INF mask tiles. Auto by
# cost model; _FORCE_COARSE_BLOCK: None = auto, 0 = off, N = force N.
USE_COARSE = True
_FORCE_COARSE_BLOCK = None
_COARSE_TILE_BUDGET = 256 * 2 ** 20   # bytes of unique (CB, CB) tiles


def _iter_cost_us(blk):
    """Empirical per-inner-iteration cost (v5e, 2026-07-31 ladder): a
    ~2us fixed floor (DMA latency + loop/VPU epilogue) plus ~22us of
    MXU+VPU work at a 512-wide tile, linear in tile width below that.
    Only RATIOS matter — this picks between walking many fine tiles and
    fewer coarse tiles with masked lanes."""
    return 2.0 + 22.0 * (blk / 512.0)


def _pick_coarse_block(layout: np.ndarray, block: int, has_am: bool):
    """Choose a coarse walk-tile size (or None): coarsening must beat the
    fine walk's modeled cost by >10% and keep the unique mask tiles under
    the HBM budget. Fine blocks that v2 cannot stream (block % 128 != 0)
    are costed at the v1 per-triple launch overhead (~30us/block), which
    coarsening almost always beats."""
    if not USE_COARSE:
        return None
    if _FORCE_COARSE_BLOCK is not None:
        cb = _FORCE_COARSE_BLOCK
        if not cb:
            return None
        H, nq, nk = layout.shape
        assert cb > block and cb % block == 0 and cb % 128 == 0 and \
            (nq * block) % cb == 0 and (nk * block) % cb == 0, (
                f"_FORCE_COARSE_BLOCK={cb} incompatible with block={block}, "
                f"S=({nq * block},{nk * block})")
        return cb
    from deepspeed_tpu.ops.sparse_attention.blocksparse_v2 import (
        build_coarse_index)
    H, nq, nk = layout.shape
    nnz_f = int(np.count_nonzero(layout))
    fine_cost = nnz_f * (_iter_cost_us(block) if block % 128 == 0
                         else 30.0)
    best = None
    for cb in (512, 256):
        if cb <= block or cb % block or (nq * block) % cb or \
                (nk * block) % cb:
            continue
        # count_only passes here + the winner's full build in
        # build_v2_impls re-hash the (f, f) patterns up to 3x per fn-cache
        # miss — a few thousand tiny tobytes() calls, negligible next to
        # the kernel compile the miss is about to pay
        nnz_c, n_unique = build_coarse_index(layout, block, cb,
                                             per_coord=has_am,
                                             count_only=True)
        if n_unique * cb * cb * 4 > _COARSE_TILE_BUDGET:
            continue
        cost = nnz_c * _iter_cost_us(cb)
        if cost < fine_cost * 0.9 and (best is None or cost < best[0]):
            best = (cost, cb)
    return best[1] if best else None


def planned_kernel(layout, block, has_am=False, interpret=False) -> str:
    """Which kernel family _sparse_attention_fn would build for this
    layout — diagnostic/bench reporting only: 'masked[-coarse<N>]'
    (unified kernel, the default) | 'banded' | 'hybrid' | 'v2-coarse<N>'
    | 'v2' | 'masked-fallback' | 'v1' (explicit USE_SPLASH_V2=False
    only — retired as an automatic dispatch target)."""
    layout = np.asarray(layout)
    if USE_MASKED_FLASH and not has_am:
        from deepspeed_tpu.ops.attention.masked_flash import BlockMask
        bm = BlockMask.from_layout(layout, block)
        return (f"masked-coarse{bm.block}" if bm.block != block
                else "masked")
    if USE_BANDED and not has_am:
        from deepspeed_tpu.ops.sparse_attention import banded as _b
        if _b.plan(layout, block, interpret) is not None:
            return "banded"
        if USE_HYBRID and USE_SPLASH_V2:
            from deepspeed_tpu.ops.sparse_attention import hybrid as _h
            if _h.plan_hybrid(layout, block, interpret) is not None:
                return "hybrid"
    coarse = (_pick_coarse_block(layout, block, has_am)
              if USE_SPLASH_V2 else None)
    if USE_SPLASH_V2 and (interpret or block % 128 == 0
                          or coarse is not None):
        return f"v2-coarse{coarse}" if coarse else "v2"
    if USE_SPLASH_V2:
        # the v1-retirement route: plain layouts land on the unified
        # kernel; a user attn mask lands on the differentiable dense
        # reference (_build_masked_fn has_am) — report what actually
        # runs, O(S^2) included
        return "reference-fallback" if has_am else "masked-fallback"
    return "v1"


def _use_pallas():
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _build_masked_fn(layout: np.ndarray, block: int, sm_scale: float,
                     interpret: bool, has_am: bool = False):
    """The unified masked-kernel implementation with the legacy impl
    signature ``f(q, k, v, kpm[, am])`` (kpm pre-blocked additive
    ``(B, nk, 1, block)``). The layout becomes a :class:`BlockMask`
    (head-uniform layouts collapse; banded layouts coarsen to MXU-sized
    walk tiles with the fine structure in register predicates).

    ``has_am``: the unified kernel carries no streamed user-mask
    channel, so a pre-blocked attention mask falls back to the
    DIFFERENTIABLE dense reference — only reachable from the
    v1-retirement branch (unstreamable block + user mask), never for
    the plain layout path."""
    from deepspeed_tpu.ops.attention.masked_flash import (
        BlockMask, masked_flash_attention)
    if has_am:
        from deepspeed_tpu.utils.logging import log_once
        log_once(("masked-am-reference", layout.shape, block),
                 "block_sparse_attention: user attention mask with an "
                 "unstreamable block size — using the O(S^2) dense "
                 "reference (differentiable) instead of the retired v1 "
                 "kernels.")

        def fref(q, k, v, kpm, am):
            B, _, S, _ = q.shape
            am_flat = am.transpose(0, 2, 1, 3).reshape(S, S)
            return block_sparse_attention_reference(
                q, k, v, layout, sm_scale=sm_scale,
                key_padding_mask=kpm.reshape(B, S),
                key_padding_mask_mode="add",
                attn_mask=am_flat, attn_mask_mode="add")
        return fref

    bm = BlockMask.from_layout(layout, block)

    def fm(q, k, v, kpm):
        B, _, S, _ = q.shape
        return masked_flash_attention(q, k, v, bm,
                                      key_mask=kpm.reshape(B, S),
                                      sm_scale=sm_scale,
                                      interpret=interpret)
    return fm


def _sparse_attention_fn(layout: np.ndarray, block: int, sm_scale: float,
                         has_am: bool, interpret: bool):
    """Returns f(q, k, v, kpm[, am]) -> o with a custom VJP, where q/k/v are
    (B, H, S, D), kpm a pre-blocked additive (B, nk, 1, block) mask and am a
    pre-blocked additive (nq, nk, block, block) mask. Nonzero-block triples
    are closed over as static data and fed to Mosaic via scalar prefetch."""
    from deepspeed_tpu.ops.sparse_attention import banded as _banded
    key = (layout.shape, layout.tobytes(), block, float(sm_scale), has_am,
           interpret, USE_MASKED_FLASH, USE_SPLASH_V2, USE_COARSE,
           _FORCE_COARSE_BLOCK, _COARSE_TILE_BUDGET, USE_BANDED,
           USE_HYBRID, _banded._FORCE_BLOCKS)
    if key in _FN_CACHE:
        return _FN_CACHE[key]

    if USE_MASKED_FLASH and not has_am:
        fm = _build_masked_fn(layout, block, float(sm_scale), interpret)
        _FN_CACHE[key] = fm
        return fm

    if USE_BANDED and not has_am:
        planned = _banded.plan(layout, block, interpret)
        if planned is not None:
            bp, blocks = planned
            fb = _banded.build_banded_fn(layout.shape, block, bp,
                                         float(sm_scale), blocks,
                                         interpret)
            _FN_CACHE[key] = fb
            return fb
        if USE_HYBRID and USE_SPLASH_V2:
            from deepspeed_tpu.ops.sparse_attention import hybrid as _h
            hplan = _h.plan_hybrid(layout, block, interpret)
            if hplan is not None:
                fh = _h.build_hybrid_fn(layout, block, hplan,
                                        float(sm_scale), interpret)
                _FN_CACHE[key] = fh
                return fh

    H, nq, nk = layout.shape
    coarse_block = (_pick_coarse_block(layout, block, has_am)
                    if USE_SPLASH_V2 else None)
    use_v2 = USE_SPLASH_V2 and (interpret or block % 128 == 0
                                or coarse_block is not None)
    if not use_v2 and USE_SPLASH_V2 and not interpret:
        # v2 wanted but the block width can't be a DMA lane dim and no
        # coarse walk tile fits either. The v1 per-triple kernels are
        # RETIRED as a dispatch target (launch overhead ~row-degree x):
        # route to the unified masked kernel, whose resident mode
        # handles any block size, instead of silently selecting v1.
        from deepspeed_tpu.utils.logging import log_once
        log_once(("v1-retired", block, layout.shape),
                 f"block_sparse_attention: block={block} cannot "
                 "DMA-stream (not a 128 multiple) and no coarse walk "
                 "tile divides the sequence — routing to the unified "
                 "masked kernel (resident K/V) instead of the retired "
                 "per-triple v1 kernels.")
        fm = _build_masked_fn(layout, block, float(sm_scale), interpret,
                              has_am=has_am)
        _FN_CACHE[key] = fm
        return fm
    if use_v2:
        # row-run kernels: one program per block row, K/V (and the
        # deduped attn-mask tiles) streamed by DMA (blocksparse_v2.py)
        # — ~row-degree x fewer program launches. Compiled mode needs
        # 128-multiple WALK blocks: a streamed tile puts the block width
        # in the DMA lane dim, which Mosaic requires to be 128-aligned.
        # When the cost model picked a coarse walk tile, the fine layout
        # (any block size) rides the streamed-mask channel instead.
        from deepspeed_tpu.ops.sparse_attention.blocksparse_v2 import (
            build_v2_impls)
        fwd2, bwd2 = build_v2_impls(layout, block, sm_scale, interpret,
                                    has_am=has_am,
                                    coarse_block=coarse_block)

        if has_am:
            @jax.custom_vjp
            def f2(q, k, v, kpm, am):
                return fwd2(q, k, v, kpm, am)[0]

            def f2_fwd(q, k, v, kpm, am):
                o, lse = fwd2(q, k, v, kpm, am)
                return o, (q, k, v, kpm, am, o, lse)

            def f2_bwd(res, g):
                q, k, v, kpm, am, o, lse = res
                dq, dk, dv = bwd2(q, k, v, kpm, am, o, lse, g)
                return (dq, dk, dv, jnp.zeros_like(kpm),
                        jnp.zeros_like(am))
        else:
            @jax.custom_vjp
            def f2(q, k, v, kpm):
                return fwd2(q, k, v, kpm, None)[0]

            def f2_fwd(q, k, v, kpm):
                o, lse = fwd2(q, k, v, kpm, None)
                return o, (q, k, v, kpm, o, lse)

            def f2_bwd(res, g):
                q, k, v, kpm, o, lse = res
                dq, dk, dv = bwd2(q, k, v, kpm, None, o, lse, g)
                return dq, dk, dv, jnp.zeros_like(kpm)

        f2.defvjp(f2_fwd, f2_bwd)
        _FN_CACHE[key] = f2
        return f2
    rt = build_triples(layout)                            # row-major walk
    ct = build_triples(np.ascontiguousarray(layout.transpose(0, 2, 1)))
    T = rt[0].shape[0]
    CT = ct[0].shape[0]
    compiler_params = None
    if pltpu is not None and not interpret:
        compiler_params = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"))

    # index-map convention (repeated inline in every BlockSpec below):
    # i = batch, t = triple id; row triples encode h * nq + qb, so
    # bh = i * H + tr[t] // nq and qb = tr[t] % nq; column-major triples
    # (cr) encode h * nk + kb analogously.

    def fwd_impl(q, k, v, kpm, am):
        B, _, S, D = q.shape
        qr = q.reshape(B * H, S, D)
        kr = k.reshape(B * H, S, D)
        vr = v.reshape(B * H, S, D)

        kernel = functools.partial(_bs_fwd_kernel, sm_scale=sm_scale)
        in_specs = [
            pl.BlockSpec((1, block, D),
                         lambda i, t, tr, *_: (i * H + tr[t] // nq,
                                               tr[t] % nq, 0)),
            pl.BlockSpec((1, block, D),
                         lambda i, t, tr, tc, *_: (i * H + tr[t] // nq,
                                                   tc[t], 0)),
            pl.BlockSpec((1, block, D),
                         lambda i, t, tr, tc, *_: (i * H + tr[t] // nq,
                                                   tc[t], 0)),
            pl.BlockSpec((1, 1, 1, block),
                         lambda i, t, tr, tc, *_: (i, tc[t], 0, 0)),
        ]
        args = [qr, kr, vr, kpm]
        if has_am:
            in_specs.append(pl.BlockSpec(
                (1, 1, block, block),
                lambda i, t, tr, tc, *_: (tr[t] % nq, tc[t], 0, 0)))
            args.append(am)
        else:
            kernel = _drop_am(kernel, 9)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=5,
            grid=(B, T),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, block, D),
                             lambda i, t, tr, *_: (i * H + tr[t] // nq,
                                                   tr[t] % nq, 0)),
                pl.BlockSpec((1, block, 1),
                             lambda i, t, tr, *_: (i * H + tr[t] // nq,
                                                   tr[t] % nq, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((block, 1), jnp.float32),      # running max
                pltpu.VMEM((block, 1), jnp.float32),      # running sum
                pltpu.VMEM((block, D), jnp.float32),      # output accum
            ])
        o, lse = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
                jax.ShapeDtypeStruct((B * H, S, 1), jnp.float32),
            ],
            interpret=interpret,
            compiler_params=compiler_params,
        )(*(jnp.asarray(x) for x in rt), *args)
        return o.reshape(B, H, S, D), lse

    def bwd_impl(q, k, v, kpm, am, o, lse, g):
        B, _, S, D = q.shape
        qr = q.reshape(B * H, S, D)
        kr = k.reshape(B * H, S, D)
        vr = v.reshape(B * H, S, D)
        dor = g.reshape(B * H, S, D)
        delta = jnp.sum(dor.astype(jnp.float32) *
                        o.reshape(B * H, S, D).astype(jnp.float32),
                        axis=-1, keepdims=True)           # (B*H, S, 1)

        # ---- dq (row-major triples) ----
        kernel = functools.partial(_bs_dq_kernel, sm_scale=sm_scale)
        in_specs = [
            pl.BlockSpec((1, block, D),
                         lambda i, t, tr, *_: (i * H + tr[t] // nq,
                                               tr[t] % nq, 0)),
            pl.BlockSpec((1, block, D),
                         lambda i, t, tr, tc, *_: (i * H + tr[t] // nq,
                                                   tc[t], 0)),
            pl.BlockSpec((1, block, D),
                         lambda i, t, tr, tc, *_: (i * H + tr[t] // nq,
                                                   tc[t], 0)),
            pl.BlockSpec((1, 1, 1, block),
                         lambda i, t, tr, tc, *_: (i, tc[t], 0, 0)),
        ]
        args = [qr, kr, vr, kpm]
        if has_am:
            in_specs.append(pl.BlockSpec(
                (1, 1, block, block),
                lambda i, t, tr, tc, *_: (tr[t] % nq, tc[t], 0, 0)))
            args.append(am)
        else:
            kernel = _drop_am(kernel, 9)
        in_specs += [
            pl.BlockSpec((1, block, D),
                         lambda i, t, tr, *_: (i * H + tr[t] // nq,
                                               tr[t] % nq, 0)),
            pl.BlockSpec((1, block, 1),
                         lambda i, t, tr, *_: (i * H + tr[t] // nq,
                                               tr[t] % nq, 0)),
            pl.BlockSpec((1, block, 1),
                         lambda i, t, tr, *_: (i * H + tr[t] // nq,
                                               tr[t] % nq, 0)),
        ]
        args += [dor, lse, delta]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=5,
            grid=(B, T),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, block, D),
                                   lambda i, t, tr, *_: (i * H + tr[t] // nq,
                                                         tr[t] % nq, 0)),
            scratch_shapes=[pltpu.VMEM((block, D), jnp.float32)])
        dq = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
            interpret=interpret,
            compiler_params=compiler_params,
        )(*(jnp.asarray(x) for x in rt), *args)

        # ---- dk, dv (column-major triples; crow = h * nk + kb) ----
        kernel = functools.partial(_bs_dkv_kernel, sm_scale=sm_scale)
        in_specs = [
            pl.BlockSpec((1, block, D),
                         lambda i, t, cr, cc, *_: (i * H + cr[t] // nk,
                                                   cc[t], 0)),
            pl.BlockSpec((1, block, D),
                         lambda i, t, cr, *_: (i * H + cr[t] // nk,
                                               cr[t] % nk, 0)),
            pl.BlockSpec((1, block, D),
                         lambda i, t, cr, *_: (i * H + cr[t] // nk,
                                               cr[t] % nk, 0)),
            pl.BlockSpec((1, 1, 1, block),
                         lambda i, t, cr, *_: (i, cr[t] % nk, 0, 0)),
        ]
        args = [qr, kr, vr, kpm]
        if has_am:
            in_specs.append(pl.BlockSpec(
                (1, 1, block, block),
                lambda i, t, cr, cc, *_: (cc[t], cr[t] % nk, 0, 0)))
            args.append(am)
        else:
            kernel = _drop_am(kernel, 9)
        in_specs += [
            pl.BlockSpec((1, block, D),
                         lambda i, t, cr, cc, *_: (i * H + cr[t] // nk,
                                                   cc[t], 0)),
            pl.BlockSpec((1, block, 1),
                         lambda i, t, cr, cc, *_: (i * H + cr[t] // nk,
                                                   cc[t], 0)),
            pl.BlockSpec((1, block, 1),
                         lambda i, t, cr, cc, *_: (i * H + cr[t] // nk,
                                                   cc[t], 0)),
        ]
        args += [dor, lse, delta]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=5,
            grid=(B, CT),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, block, D),
                             lambda i, t, cr, *_: (i * H + cr[t] // nk,
                                                   cr[t] % nk, 0)),
                pl.BlockSpec((1, block, D),
                             lambda i, t, cr, *_: (i * H + cr[t] // nk,
                                                   cr[t] % nk, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((block, D), jnp.float32),
                pltpu.VMEM((block, D), jnp.float32),
            ])
        dk, dv = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((B * H, S, D), k.dtype),
                jax.ShapeDtypeStruct((B * H, S, D), v.dtype),
            ],
            interpret=interpret,
            compiler_params=compiler_params,
        )(*(jnp.asarray(x) for x in ct), *args)
        return (dq.reshape(q.shape), dk.reshape(k.shape),
                dv.reshape(v.shape))

    if has_am:
        @jax.custom_vjp
        def f(q, k, v, kpm, am):
            return fwd_impl(q, k, v, kpm, am)[0]

        def f_fwd(q, k, v, kpm, am):
            o, lse = fwd_impl(q, k, v, kpm, am)
            return o, (q, k, v, kpm, am, o, lse)

        def f_bwd(res, g):
            q, k, v, kpm, am, o, lse = res
            dq, dk, dv = bwd_impl(q, k, v, kpm, am, o, lse, g)
            return dq, dk, dv, jnp.zeros_like(kpm), jnp.zeros_like(am)
    else:
        @jax.custom_vjp
        def f(q, k, v, kpm):
            return fwd_impl(q, k, v, kpm, None)[0]

        def f_fwd(q, k, v, kpm):
            o, lse = fwd_impl(q, k, v, kpm, None)
            return o, (q, k, v, kpm, o, lse)

        def f_bwd(res, g):
            q, k, v, kpm, o, lse = res
            dq, dk, dv = bwd_impl(q, k, v, kpm, None, o, lse, g)
            return dq, dk, dv, jnp.zeros_like(kpm)

    f.defvjp(f_fwd, f_bwd)
    _FN_CACHE[key] = f
    return f


# --------------------------------------------------------------------- #
# public API
# --------------------------------------------------------------------- #
def block_sparse_attention(q, k, v, layout, sm_scale: Optional[float] = None,
                           key_padding_mask=None,
                           key_padding_mask_mode: str = "add",
                           attn_mask=None, attn_mask_mode: str = "mul",
                           rpe=None, interpret: Optional[bool] = None,
                           force_reference: bool = False):
    """Fused block-sparse attention.

    q, k, v: (B, H, S, D); layout: numpy int (H, nb, nb) from a
    SparsityConfig (block size = S // nb). key_padding_mask: (B, S);
    attn_mask: (S, S); modes per the reference's sparse softmax ('add' adds
    values, 'mul' drops zero entries). rpe (dense additive (B, H, S, S))
    routes through the jnp oracle — it defeats sparse storage anyway.
    """
    B, H, S, D = q.shape
    layout = np.asarray(layout)
    assert layout.ndim == 3 and layout.shape[0] == H, \
        f"layout heads {layout.shape} vs q heads {H}"
    assert S % layout.shape[1] == 0, (S, layout.shape)
    block = S // layout.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(D)
    if interpret is None:
        interpret = not _use_pallas()
    if force_reference or rpe is not None:
        return block_sparse_attention_reference(
            q, k, v, layout, sm_scale=sm_scale,
            key_padding_mask=key_padding_mask,
            key_padding_mask_mode=key_padding_mask_mode,
            attn_mask=attn_mask, attn_mask_mode=attn_mask_mode, rpe=rpe)

    kpm = jnp.zeros((B, S), jnp.float32) if key_padding_mask is None else \
        _to_additive(key_padding_mask, key_padding_mask_mode)
    kpm = _block_kpm(kpm, block)
    f = _sparse_attention_fn(layout, block, float(sm_scale),
                             has_am=attn_mask is not None,
                             interpret=interpret)
    if attn_mask is not None:
        am = _block_am(_to_additive(attn_mask, attn_mask_mode), block)
        return f(q, k, v, kpm, am)
    return f(q, k, v, kpm)
