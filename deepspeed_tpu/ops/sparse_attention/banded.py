"""Banded block-sparse attention ("splash banded") — the structured fast
path for Longformer-class layouts.

Most of the reference's sparse-attention value lives in ONE family of
layouts: a global prefix (blocks every token attends to, and whose
tokens attend to everything) plus a sliding window around the diagonal —
BSLongformerSparsityConfig and friends (reference
deepspeed/ops/sparse_attention/sparsity_config.py:544, the configuration
behind the 6.3x-faster / 10x-longer-sequences claims in
docs/_posts/2020-09-09-sparse-attention.md:28-33). The generic kernels
(blocksparse.py v1, blocksparse_v2.py) treat the layout as arbitrary:
CSR metadata, scalar-prefetched walks, DMA-streamed tiles, and (for the
coarse walk) additive mask tiles streamed from HBM. Hardware profiling
showed the fixed per-iteration machinery — stream re-arm, mask tile
bytes, tiny MXU dots — eating nearly all of the density win: at 128-block
win=3 S=8192 the generic walk ran ~0.7-1.3x dense flash despite ~10x
fewer FLOPs.

For banded structure none of that machinery is needed, because the
reference's mask semantics are BLOCK-level (an active block computes all
its cells; intra-block masking only ever comes from the separate user
masks). A banded layout is therefore a closed-form predicate on block
indices:

    keep(rb, cb) = (rb < g_r) | (cb < g_c) | (|rb - cb| <= w)
                   [optionally causally clipped: cb <= rb]

so the kernel computes masks from iota arithmetic in registers — zero
mask bytes from HBM, zero CSR metadata — and every fetch is a plain
pipelined BlockSpec tile, exactly as lean as a dense flash inner step.
Work is partitioned into instances whose per-step walk extent is uniform
(so each is a dense rectangular grid XLA/Mosaic pipelines well):

    fwd/dq  "band"  grid (B*H, S/bq, GT+WT): global-col phase + band
                     phase, online softmax across the walk
    fwd/dq  "gr"    grid (B*H, GQ, ·): the g_r global ROWS attend
                     everything — a thin dense-attention strip
    dkv     "band"  grid (B*H, S/bkv, J2): transposed band walk
    dkv     "gc"    grid (B*H, GT, ·): global columns hear from all rows
    dkv     "gr"    grid (B*H, ·, GQ): the global rows' contribution

The instances partition the kept cells exactly (band excludes rows
< g_r and cols < g_c; gc excludes rows < g_r), so their outputs add.
Per-row softmax state never crosses instances for the same row: rows
< g_r*fb live entirely in "gr", all other rows entirely in "band".

Detection is structural — `detect_banded` matches the realized layout
bits, not the config class — so any SparsityConfig that produces
global-prefix + band (BSLongformer defaults, Variable with prefix
globals, ...) rides this path; everything else (BigBird random blocks,
per-head layouts, user block masks) falls back to the generic kernels.

Same numerics as v1/v2: bf16 MXU operands / fp32 accumulation, scale
applied post-dot, exact-zero structurally-masked probabilities, zero
output for fully-masked rows.
"""

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30
VALID_THRESH = -1e28     # matches blocksparse.py (several -1e30 may stack)

# test/autotune override for the walk tile sizes; None = pick automatically
_FORCE_BLOCKS: Optional[Tuple[int, int]] = None


class BandedParams(NamedTuple):
    g_r: int      # global ROW prefix, in fine blocks (rows that see all)
    g_c: int      # global COL prefix, in fine blocks (cols all rows see)
    w: int        # band half-width, in fine blocks
    causal: bool  # block-level lower-triangular clip


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def detect_banded(layout: np.ndarray) -> Optional[BandedParams]:
    """Match a (H, nb, nb) 0/1 layout against the global-prefix + band
    predicate. Returns params or None (per-head layouts, non-prefix
    globals, random blocks, fully dense all decline)."""
    L = np.asarray(layout).astype(bool)
    if L.ndim != 3 or L.shape[1] != L.shape[2] or L.shape[1] == 0:
        return None
    l = L[0]
    if not (L == l[None]).all():
        return None
    n = l.shape[0]
    idx = np.arange(n)
    rb, cb = idx[:, None], idx[None, :]
    for causal in (False, True):
        clip = (cb <= rb) if causal else np.ones((n, n), bool)
        # global prefixes: leading rows/cols equal to their clip pattern
        row_full = (l == clip).all(axis=1)
        col_full = (l == clip).all(axis=0)
        g_r = 0
        while g_r < n and row_full[g_r]:
            g_r += 1
        g_c = 0
        while g_c < n and col_full[g_c]:
            g_c += 1
        if g_r >= n:          # fully dense: let flash handle it
            continue
        # infer w from the last row (never a global row here): its
        # non-global cols must be a contiguous run ending at the diagonal
        last = np.nonzero(l[n - 1, g_c:])[0] + g_c
        if len(last) == 0:
            # pure-global layout (no band): the banded kernels would need
            # a w=-1 "empty band" special case — leave it to the generic
            # kernels (rare, and tiny at any realistic density)
            continue
        run = np.arange(int(last.min()), n)
        if len(last) != len(run) or not (last == run).all():
            continue
        w = (n - 1) - int(last.min())
        pred = ((rb < g_r) | (cb < g_c) | (np.abs(rb - cb) <= w)) & clip
        if (pred == l).all():
            return BandedParams(g_r, g_c, w, bool(causal))
    return None


# --------------------------------------------------------------------- #
# walk-tile selection
# --------------------------------------------------------------------- #
def _largest_div(S: int, cap: int) -> Optional[int]:
    for b in (512, 384, 256, 128):
        if b <= cap and S % b == 0:
            return b
    return None


def _blocks_valid(S: int, bq: int, bkv: int, interpret: bool) -> bool:
    return (S % bq == 0 and S % bkv == 0 and
            (interpret or (bq % 128 == 0 and bkv % 128 == 0)))


def pick_blocks(S: int, fine_block: int, params: "BandedParams",
                interpret: bool) -> Optional[Tuple[int, int]]:
    """VALID walk tile sizes (bq, bkv), or None. Compiled tiles must be
    128-multiples (lane alignment) dividing S; interpret mode (CPU
    tests) walks at the fine block so small layouts exercise multi-tile
    paths. A bad table entry or force override falls back to the
    heuristic rather than disabling the fast path."""
    if _FORCE_BLOCKS is not None and \
            _blocks_valid(S, *_FORCE_BLOCKS, interpret):
        return _FORCE_BLOCKS
    if interpret:
        b = min(fine_block, 256)
        while b > 1 and S % b:
            b //= 2
        return (b, b)
    from deepspeed_tpu.ops.attention.flash import lookup_banded_blocks
    hit = lookup_banded_blocks(S, fine_block, band_w=params.w,
                               causal=params.causal)
    if hit is not None and _blocks_valid(S, *hit, interpret):
        return hit
    # heuristic pending a hardware sweep: mid-size q tiles bound the
    # band-edge waste, matching kv tiles keep the strip walk short
    bq = _largest_div(S, 256)
    bkv = _largest_div(S, 256)
    if bq is None or bkv is None:
        return None
    return bq, bkv


def _band_extents(S, fb, w, causal, bq, bkv):
    """(bstart, bend, WT): per-q-tile kv-tile range of the band walk —
    the ONE definition shared by the builder's index maps/grids and
    walk_stats' cost accounting (they must never drift)."""
    NQ = S // bq
    bstart = np.zeros(NQ, np.int32)
    bend = np.zeros(NQ, np.int32)
    for i in range(NQ):
        lo = max(((i * bq) // fb - w) * fb, 0)
        hi = min(((i * bq + bq - 1) // fb + (0 if causal else w)) * fb
                 + fb - 1, S - 1)
        bstart[i] = lo // bkv
        bend[i] = hi // bkv
    return bstart, bend, int((bend - bstart).max()) + 1


def _band_dkv_extents(S, fb, w, causal, bq, bkv):
    """(qstart, qend, J2): per-kv-tile q-tile range of the transposed
    band walk (dkv)."""
    NK = S // bkv
    qstart = np.zeros(NK, np.int32)
    qend = np.zeros(NK, np.int32)
    for t in range(NK):
        lo = max(((t * bkv) // fb - (0 if causal else w)) * fb, 0)
        hi = min(((t * bkv + bkv - 1) // fb + w) * fb + fb - 1, S - 1)
        qstart[t] = lo // bq
        qend[t] = hi // bq
    return qstart, qend, int((qend - qstart).max()) + 1


def _gr_kv_walk(S, fb, g_r, causal, bkv):
    """kv-tile walk length of the global-rows instance (0 when g_r=0;
    causal global rows only reach cols < g_r*fb)."""
    if not g_r:
        return 0
    return _ceil_div(g_r * fb, bkv) if causal else S // bkv


def _cparams(interpret):
    if pltpu is None or interpret:
        return None
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"))


def _call(kernel, grid, in_specs, out_specs, out_shape, scratch, scalars,
          interpret):
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(scalars),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
        compiler_params=_cparams(interpret))


# --------------------------------------------------------------------- #
# kernel bodies (shared by all instances via closures)
# --------------------------------------------------------------------- #
def _fwd_body(*refs, nsc, J, kt_fn, keep_fn, sm_scale):
    sc = refs[:nsc]
    (q_ref, k_ref, v_ref, kpm_ref, o_ref, lse_ref,
     m_scr, l_scr, acc_scr) = refs[nsc:]
    i, j = pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    q = q_ref[0]                                        # (bq, D)
    k = k_ref[0]                                        # (bkv, D)
    v = v_ref[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    s = s * sm_scale
    s += kpm_ref[0, 0, :][None, :]
    s = jnp.where(keep_fn(i, j, sc), s, NEG_INF)
    m = m_scr[:, 0]
    l = l_scr[:, 0]
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    p = jnp.where(s > VALID_THRESH, jnp.exp(s - m_new[:, None]), 0.0)
    alpha = jnp.exp(m - m_new)
    m_scr[:, 0] = m_new
    l_scr[:, 0] = l * alpha + jnp.sum(p, axis=-1)
    acc_scr[...] = acc_scr[...] * alpha[:, None] + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(j == J - 1)
    def _finalize():
        l = l_scr[:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[...] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0, :, 0] = m_scr[:, 0] + jnp.log(l_safe)


def _dq_body(*refs, nsc, J, kt_fn, keep_fn, sm_scale):
    sc = refs[:nsc]
    (q_ref, k_ref, v_ref, kpm_ref, do_ref, lse_ref, delta_ref,
     dq_ref, dq_scr) = refs[nsc:]
    i, j = pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]
    lse = lse_ref[0, :, 0]
    delta = delta_ref[0, :, 0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    s = s * sm_scale
    s += kpm_ref[0, 0, :][None, :]
    s = jnp.where(keep_fn(i, j, sc), s, NEG_INF)
    p = jnp.where(s > VALID_THRESH, jnp.exp(s - lse[:, None]), 0.0)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta[:, None])
    dq_scr[...] += jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(j == J - 1)
    def _finalize():
        dq_ref[0] = (dq_scr[...] * sm_scale).astype(dq_ref.dtype)


def _dkv_body(*refs, nsc, J, qt_fn, keep_fn, sm_scale):
    sc = refs[:nsc]
    (k_ref, v_ref, kpm_ref, q_ref, do_ref, lse_ref, delta_ref,
     dk_ref, dv_ref, dk_scr, dv_scr) = refs[nsc:]
    t, j = pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    k = k_ref[0]                                        # (bkv, D)
    v = v_ref[0]
    q = q_ref[0]                                        # (bq, D)
    do = do_ref[0]
    lse = lse_ref[0, :, 0]
    delta = delta_ref[0, :, 0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    s = s * sm_scale                                    # (bq, bkv)
    s += kpm_ref[0, 0, :][None, :]
    s = jnp.where(keep_fn(t, j, sc), s, NEG_INF)
    p = jnp.where(s > VALID_THRESH, jnp.exp(s - lse[:, None]), 0.0)
    dv_scr[...] += jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)             # (bkv, D)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta[:, None])
    dk_scr[...] += jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)             # (bkv, D)

    @pl.when(j == J - 1)
    def _finalize():
        dk_ref[0] = (dk_scr[...] * sm_scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


# --------------------------------------------------------------------- #
# builder
# --------------------------------------------------------------------- #
def build_banded_impls(H: int, S: int, fb: int, params: BandedParams,
                       sm_scale: float, bq: int, bkv: int,
                       interpret: bool):
    """Returns (fwd_impl, bwd_impl):
    fwd_impl(q, k, v, kpm_flat) -> (o, lse_band, lse_gr)
    bwd_impl(q, k, v, kpm_flat, o, lse_band, lse_gr, g) -> (dq, dk, dv)
    with q/k/v (B, H, S, D) and kpm_flat an additive (B, S) float mask.
    """
    g_r, g_c, w, causal = params
    assert S % bq == 0 and S % bkv == 0, (S, bq, bkv)
    NQ, NK = S // bq, S // bkv
    GQ = _ceil_div(g_r * fb, bq) if g_r else 0     # q tiles holding g-rows
    GT = _ceil_div(g_c * fb, bkv) if g_c else 0    # kv tiles holding g-cols

    # ---- static walk extents (shared with walk_stats — ONE source) ----
    bstart, bend, WT = _band_extents(S, fb, w, causal, bq, bkv)
    J_band = GT + WT
    qstart, qend, J2 = _band_dkv_extents(S, fb, w, causal, bq, bkv)

    # global-row instances: causal global rows only reach cols < g_r*fb
    GRK = _gr_kv_walk(S, fb, g_r, causal, bkv)         # kv walk for gr
    # global-col dkv: first contributing q tile (rows >= g_r only)
    gc_q0 = (g_r * fb) // bq
    J_gc = NQ - gc_q0

    upper = 0 if causal else w                      # band extent above diag

    # ---- cell predicates (iota block arithmetic, all in registers) ----
    def _rbcb(row0, col0):
        r = row0 + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
        c = col0 + jax.lax.broadcasted_iota(jnp.int32, (1, bkv), 1)
        return r // fb, c // fb

    def _clip(rb, cb, keep):
        return keep & (cb <= rb) if causal else keep

    def band_kt(i, j, sc):
        bs, be = sc[0], sc[1]
        if GT:
            return jnp.where(j < GT, j,
                             jnp.minimum(bs[i] + (j - GT), be[i]))
        return jnp.minimum(bs[i] + j, be[i])

    def band_keep(i, j, sc):
        bs, be = sc[0], sc[1]
        kt = band_kt(i, j, sc)
        rb, cb = _rbcb(i * bq, kt * bkv)
        band = ((rb >= g_r) & (cb >= g_c) &
                (rb - cb <= w) & (cb - rb <= upper))
        step_ok = bs[i] + (j - GT) <= be[i]
        if GT:
            gcol = (rb >= g_r) & (cb < g_c)
            keep = jnp.where(j < GT, gcol, band & step_ok)
        else:
            keep = band & step_ok
        return _clip(rb, cb, keep)

    def gr_kt(i, j, sc):
        return j

    def gr_keep(i, j, sc):
        rb, cb = _rbcb(i * bq, j * bkv)
        return _clip(rb, cb, rb < g_r)

    def band_qt(t, j, sc):
        qs, qe = sc[0], sc[1]
        return jnp.minimum(qs[t] + j, qe[t])

    def band_dkv_keep(t, j, sc):
        qs, qe = sc[0], sc[1]
        qt = band_qt(t, j, sc)
        rb, cb = _rbcb(qt * bq, t * bkv)
        keep = ((rb >= g_r) & (cb >= g_c) &
                (rb - cb <= w) & (cb - rb <= upper) &
                (qs[t] + j <= qe[t]))
        return _clip(rb, cb, keep)

    def gc_qt(t, j, sc):
        return gc_q0 + j

    def gc_keep(t, j, sc):
        rb, cb = _rbcb((gc_q0 + j) * bq, t * bkv)
        return _clip(rb, cb, (cb < g_c) & (rb >= g_r))

    def gr_dkv_qt(t, j, sc):
        return j

    def gr_dkv_keep(t, j, sc):
        rb, cb = _rbcb(j * bq, t * bkv)
        return _clip(rb, cb, rb < g_r)

    band_scalars = (bstart, bend)
    dkv_scalars = (qstart, qend)

    def fwd_impl(q, k, v, kpm_flat):
        B, _, S_, D = q.shape
        assert S_ == S
        qr = q.reshape(B * H, S, D)
        kr = k.reshape(B * H, S, D)
        vr = v.reshape(B * H, S, D)
        kpm3 = kpm_flat.reshape(B, 1, S).astype(jnp.float32)

        def run_fwd(grid, kt_fn, keep_fn, scalars, nq_tiles):
            nsc = len(scalars)
            kernel = functools.partial(
                _fwd_body, nsc=nsc, J=grid[2], kt_fn=kt_fn,
                keep_fn=keep_fn, sm_scale=sm_scale)
            in_specs = [
                pl.BlockSpec((1, bq, D),
                             lambda bh, i, j, *sc: (bh, i, 0)),
                pl.BlockSpec((1, bkv, D),
                             lambda bh, i, j, *sc: (bh, kt_fn(i, j, sc), 0)),
                pl.BlockSpec((1, bkv, D),
                             lambda bh, i, j, *sc: (bh, kt_fn(i, j, sc), 0)),
                pl.BlockSpec((1, 1, bkv),
                             lambda bh, i, j, *sc: (bh // H, 0,
                                                    kt_fn(i, j, sc))),
            ]
            out_specs = [
                pl.BlockSpec((1, bq, D), lambda bh, i, j, *sc: (bh, i, 0)),
                pl.BlockSpec((1, bq, 1), lambda bh, i, j, *sc: (bh, i, 0)),
            ]
            out_shape = [
                jax.ShapeDtypeStruct((B * H, nq_tiles * bq, D), q.dtype),
                jax.ShapeDtypeStruct((B * H, nq_tiles * bq, 1),
                                     jnp.float32),
            ]
            scratch = [
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, D), jnp.float32),
            ]
            return _call(kernel, grid, in_specs, out_specs, out_shape,
                         scratch, scalars, interpret)(
                *(jnp.asarray(x) for x in scalars), qr, kr, vr, kpm3)

        o_b, lse_b = run_fwd((B * H, NQ, J_band), band_kt, band_keep,
                             band_scalars, NQ)
        if g_r:
            o_g, lse_g = run_fwd((B * H, GQ, GRK), gr_kt, gr_keep, (), GQ)
            o_b = o_b + jnp.pad(
                o_g.astype(jnp.float32),
                ((0, 0), (0, S - GQ * bq), (0, 0))).astype(o_b.dtype)
        else:
            lse_g = jnp.zeros((B * H, 0, 1), jnp.float32)
        return o_b.reshape(B, H, S, D), lse_b, lse_g

    def bwd_impl(q, k, v, kpm_flat, o, lse_b, lse_g, g):
        B, _, S_, D = q.shape
        qr = q.reshape(B * H, S, D)
        kr = k.reshape(B * H, S, D)
        vr = v.reshape(B * H, S, D)
        dor = g.reshape(B * H, S, D)
        kpm3 = kpm_flat.reshape(B, 1, S).astype(jnp.float32)
        delta = jnp.sum(dor.astype(jnp.float32) *
                        o.reshape(B * H, S, D).astype(jnp.float32),
                        axis=-1, keepdims=True)          # (B*H, S, 1)

        def run_dq(grid, kt_fn, keep_fn, scalars, nq_tiles, lse):
            nsc = len(scalars)
            kernel = functools.partial(
                _dq_body, nsc=nsc, J=grid[2], kt_fn=kt_fn,
                keep_fn=keep_fn, sm_scale=sm_scale)
            row = pl.BlockSpec((1, bq, D),
                               lambda bh, i, j, *sc: (bh, i, 0))
            rowv = pl.BlockSpec((1, bq, 1),
                                lambda bh, i, j, *sc: (bh, i, 0))
            in_specs = [
                row,
                pl.BlockSpec((1, bkv, D),
                             lambda bh, i, j, *sc: (bh, kt_fn(i, j, sc), 0)),
                pl.BlockSpec((1, bkv, D),
                             lambda bh, i, j, *sc: (bh, kt_fn(i, j, sc), 0)),
                pl.BlockSpec((1, 1, bkv),
                             lambda bh, i, j, *sc: (bh // H, 0,
                                                    kt_fn(i, j, sc))),
                row, rowv, rowv,
            ]
            out_shape = jax.ShapeDtypeStruct((B * H, nq_tiles * bq, D),
                                             q.dtype)
            scratch = [pltpu.VMEM((bq, D), jnp.float32)]
            return _call(kernel, grid, in_specs, row, out_shape,
                         scratch, scalars, interpret)(
                *(jnp.asarray(x) for x in scalars),
                qr, kr, vr, kpm3, dor, lse, delta)

        def run_dkv(grid, qt_fn, keep_fn, scalars, nk_tiles, lse):
            nsc = len(scalars)
            kernel = functools.partial(
                _dkv_body, nsc=nsc, J=grid[2], qt_fn=qt_fn,
                keep_fn=keep_fn, sm_scale=sm_scale)
            col = pl.BlockSpec((1, bkv, D),
                               lambda bh, t, j, *sc: (bh, t, 0))
            qrow = pl.BlockSpec((1, bq, D),
                                lambda bh, t, j, *sc: (bh, qt_fn(t, j, sc),
                                                       0))
            qvec = pl.BlockSpec((1, bq, 1),
                                lambda bh, t, j, *sc: (bh, qt_fn(t, j, sc),
                                                       0))
            in_specs = [
                col, col,
                pl.BlockSpec((1, 1, bkv),
                             lambda bh, t, j, *sc: (bh // H, 0, t)),
                qrow, qrow, qvec, qvec,
            ]
            out_specs = [col, col]
            out_shape = [
                jax.ShapeDtypeStruct((B * H, nk_tiles * bkv, D), k.dtype),
                jax.ShapeDtypeStruct((B * H, nk_tiles * bkv, D), v.dtype),
            ]
            scratch = [
                pltpu.VMEM((bkv, D), jnp.float32),
                pltpu.VMEM((bkv, D), jnp.float32),
            ]
            return _call(kernel, grid, in_specs, out_specs, out_shape,
                         scratch, scalars, interpret)(
                *(jnp.asarray(x) for x in scalars),
                kr, vr, kpm3, qr, dor, lse, delta)

        dq = run_dq((B * H, NQ, J_band), band_kt, band_keep,
                    band_scalars, NQ, lse_b)
        dk, dv = run_dkv((B * H, NK, J2), band_qt, band_dkv_keep,
                         dkv_scalars, NK, lse_b)
        if g_r:
            # lse_g covers rows [0, GQ*bq); the gr instances only ever
            # read q tiles < GQ, so no padding to S is needed
            dq_g = run_dq((B * H, GQ, GRK), gr_kt, gr_keep, (), GQ, lse_g)
            dq = dq + jnp.pad(
                dq_g.astype(jnp.float32),
                ((0, 0), (0, S - GQ * bq), (0, 0))).astype(dq.dtype)
            # global columns (rows >= g_r) + global rows' dk/dv
            dk_c, dv_c = run_dkv((B * H, GT, J_gc), gc_qt, gc_keep,
                                 (), GT, lse_b) if GT else (None, None)
            dk_g, dv_g = run_dkv((B * H, GRK, GQ), gr_dkv_qt, gr_dkv_keep,
                                 (), GRK, lse_g)
            acc_k = dk.astype(jnp.float32)
            acc_v = dv.astype(jnp.float32)
            if dk_c is not None:
                acc_k = acc_k + jnp.pad(
                    dk_c.astype(jnp.float32),
                    ((0, 0), (0, S - GT * bkv), (0, 0)))
                acc_v = acc_v + jnp.pad(
                    dv_c.astype(jnp.float32),
                    ((0, 0), (0, S - GT * bkv), (0, 0)))
            acc_k = acc_k + jnp.pad(
                dk_g.astype(jnp.float32),
                ((0, 0), (0, S - GRK * bkv), (0, 0)))
            acc_v = acc_v + jnp.pad(
                dv_g.astype(jnp.float32),
                ((0, 0), (0, S - GRK * bkv), (0, 0)))
            dk = acc_k.astype(k.dtype)
            dv = acc_v.astype(v.dtype)
        elif g_c and GT:
            dk_c, dv_c = run_dkv((B * H, GT, J_gc), gc_qt, gc_keep,
                                 (), GT, lse_b)
            dk = (dk.astype(jnp.float32) + jnp.pad(
                dk_c.astype(jnp.float32),
                ((0, 0), (0, S - GT * bkv), (0, 0)))).astype(k.dtype)
            dv = (dv.astype(jnp.float32) + jnp.pad(
                dv_c.astype(jnp.float32),
                ((0, 0), (0, S - GT * bkv), (0, 0)))).astype(v.dtype)
        return (dq.reshape(q.shape), dk.reshape(k.shape),
                dv.reshape(v.shape))

    return fwd_impl, bwd_impl


def walk_stats(S: int, fb: int, params: BandedParams, bq: int, bkv: int,
               n_active_blocks: Optional[int] = None):
    """Static cost accounting for the banded walk at a geometry: grid
    step counts per instance and total fwd/bwd MXU MACs per (batch,
    head), plus the exact-sparse bound from the layout cell count.
    Pure arithmetic on the same extent formulas the builder uses — lets
    tests pin the kernel's FLOP overhead (waste = computed/bound) and
    the A/B tool print an honest roofline without hardware."""
    g_r, g_c, w, causal = params
    NQ, NK = S // bq, S // bkv
    GQ = _ceil_div(g_r * fb, bq) if g_r else 0
    GT = _ceil_div(g_c * fb, bkv) if g_c else 0
    _, _, WT = _band_extents(S, fb, w, causal, bq, bkv)
    _, _, J2 = _band_dkv_extents(S, fb, w, causal, bq, bkv)
    GRK = _gr_kv_walk(S, fb, g_r, causal, bkv)
    steps = {
        "band_fwd": NQ * (GT + WT),
        "gr_fwd": GQ * GRK,
        "band_dq": NQ * (GT + WT),
        "gr_dq": GQ * GRK,
        "band_dkv": NK * J2,
        "gc_dkv": GT * (NQ - (g_r * fb) // bq) if GT else 0,
        "gr_dkv": GRK * GQ,
    }
    tile = bq * bkv
    # tile dots per step per (b, h): fwd 2 (s, pv), dq 3 (s, dp, dq),
    # dkv 4 (s, dv, dp, dk) — matches the kernel bodies
    macs = (2 * (steps["band_fwd"] + steps["gr_fwd"]) +
            3 * (steps["band_dq"] + steps["gr_dq"]) +
            4 * (steps["band_dkv"] + steps["gc_dkv"] + steps["gr_dkv"]))
    computed_cells = macs * tile
    bound = None
    if n_active_blocks is not None:
        # exact sparse bound: 9 tile dots per active fine block
        # (fwd s/pv = 2, dq s/dp/dq = 3, dkv s/dv/dp/dk = 4)
        bound = 9 * n_active_blocks * fb * fb
    return {"steps": steps, "computed_cell_dots": computed_cells,
            "exact_cell_dots": bound,
            "waste": (computed_cells / bound) if bound else None}


def plan(layout, fine_block: int, interpret: bool):
    """THE banded-dispatch decision, shared by _sparse_attention_fn and
    planned_kernel so report and reality cannot drift: (params, (bq,
    bkv)) when the fast path will run, else None."""
    params = detect_banded(layout)
    if params is None:
        return None
    S = np.asarray(layout).shape[1] * fine_block
    blocks = pick_blocks(S, fine_block, params, interpret)
    if blocks is None or not _blocks_valid(S, *blocks, interpret):
        return None
    return params, blocks


def build_banded_fn(layout_shape, fine_block: int, params: BandedParams,
                    sm_scale: float, blocks: Tuple[int, int],
                    interpret: bool):
    """Differentiable f(q, k, v, kpm_blocked) -> o for the banded fast
    path (inputs pre-validated by plan()). kpm arrives in the generic
    kernels' pre-blocked (B, nk, 1, fb) form so the public signature
    matches blocksparse._sparse_attention_fn exactly."""
    H, nb, _ = layout_shape
    S = nb * fine_block
    bq, bkv = blocks
    fwd_impl, bwd_impl = build_banded_impls(
        H, S, fine_block, params, sm_scale, bq, bkv, interpret)

    def _flat_kpm(kpm):
        # invert blocksparse._block_kpm: (B, nk, 1, fb) -> (B, S)
        B = kpm.shape[0]
        return kpm.transpose(0, 2, 1, 3).reshape(B, S)

    @jax.custom_vjp
    def f(q, k, v, kpm):
        return fwd_impl(q, k, v, _flat_kpm(kpm))[0]

    def f_fwd(q, k, v, kpm):
        o, lse_b, lse_g = fwd_impl(q, k, v, _flat_kpm(kpm))
        return o, (q, k, v, kpm, o, lse_b, lse_g)

    def f_bwd(res, g):
        q, k, v, kpm, o, lse_b, lse_g = res
        dq, dk, dv = bwd_impl(q, k, v, _flat_kpm(kpm), o, lse_b, lse_g, g)
        return dq, dk, dv, jnp.zeros_like(kpm)

    f.defvjp(f_fwd, f_bwd)
    f.kernel_kind = "banded"
    f.banded_blocks = (bq, bkv)
    return f
