"""Native optimizer kernels (pure-JAX, pytree-at-a-time).

TPU-native replacements for the reference's fused CUDA optimizers:
- Adam/AdamW  ≈ apex FusedAdam selected at ``engine.py:544`` and the CPU
  AVX Adam (``csrc/adam/cpu_adam.cpp``) — on TPU one fused XLA update over
  each leaf; XLA fuses the whole elementwise chain into a single kernel, so
  no hand-written "fused" kernel is needed for the update math itself.
- LAMB ≈ ``csrc/lamb/fused_lamb_cuda_kernel.cu`` (3-phase norm + trust-ratio
  update, clamped to [0.08, 0.5] by default like the reference's
  max_coeff/min_coeff at fused_lamb_cuda_kernel.cu:252).
- SGD ≈ torch.optim.SGD passthrough the reference allowed.

Design: an Optimizer holds static hyperparameters; ``init`` builds a state
pytree shaped like params (so it shards the same way — this is what makes
ZeRO = "shard this pytree over the data axis"); ``update`` is pure and
jit-traceable, taking the dynamic learning rate as an argument.
"""

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

Params = Any
Grads = Any


def _tree_zeros_like(params, dtype=None):
    return jax.tree_util.tree_map(
        lambda p: jnp.zeros_like(p, dtype=dtype or p.dtype), params)


def _cast_out(new_p32, p, sr_key, i):
    """fp32 update result -> param dtype. With ``sr_key`` set and a bf16
    param (master-weight-free mode, engine config
    ``bf16: {"master_weights": false}``) the cast uses stochastic rounding
    so sub-ulp updates accumulate in expectation — the TPU-native analog
    of the reference's ``__STOCHASTIC_MODE__`` kernels. ``i`` is the flat
    leaf index, folded in so leaves see independent noise."""
    if sr_key is not None and p.dtype == jnp.bfloat16:
        from deepspeed_tpu.ops.functional import stochastic_round_bf16
        return stochastic_round_bf16(new_p32, jax.random.fold_in(sr_key, i))
    return new_p32.astype(p.dtype)


class AdamState(NamedTuple):
    step: jnp.ndarray  # int32 scalar
    exp_avg: Params    # first moment
    exp_avg_sq: Params  # second moment


class SGDState(NamedTuple):
    step: jnp.ndarray
    momentum_buf: Params


class LambState(NamedTuple):
    step: jnp.ndarray
    exp_avg: Params
    exp_avg_sq: Params


class Optimizer:
    """Base: subclasses implement init/update."""

    def init(self, params: Params):
        raise NotImplementedError

    def update(self, grads: Grads, state, params: Params,
               lr: jnp.ndarray) -> Tuple[Params, Any]:
        raise NotImplementedError


class Adam(Optimizer):
    """Adam/AdamW. ``adamw_mode`` selects decoupled weight decay (AdamW),
    matching the reference cpu_adam kernel's compile-time mode
    (csrc/adam/cpu_adam.cpp step functions apply decoupled decay)."""

    def __init__(self, lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adamw_mode: bool = True,
                 bias_correction: bool = True):
        self.lr = lr
        self.b1, self.b2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adamw_mode = adamw_mode
        self.bias_correction = bias_correction

    def init(self, params):
        return AdamState(
            step=jnp.zeros((), jnp.int32),
            exp_avg=_tree_zeros_like(params, jnp.float32),
            exp_avg_sq=_tree_zeros_like(params, jnp.float32),
        )

    def _preamble(self, state, lr, momentum):
        """(lr, b1, step, bc1, bc2) shared by the fp32- and 8-bit-state
        updates — bias correction and the OneCycle beta1 override must
        never diverge between them."""
        lr = self.lr if lr is None else lr
        b1 = self.b1 if momentum is None else momentum
        step = state.step + 1
        if self.bias_correction:
            bc1 = 1.0 - b1 ** step.astype(jnp.float32)
            bc2 = 1.0 - self.b2 ** step.astype(jnp.float32)
        else:
            bc1 = bc2 = jnp.float32(1.0)
        return lr, b1, step, bc1, bc2

    def update(self, grads, state, params, lr=None, momentum=None,
               sr_key=None):
        """``momentum``: optional (traced) beta1 override — the OneCycle
        momentum-cycling hook (reference lr_schedules.py:518 mutates
        param_groups betas every step; here the scheduled value flows
        into the compiled update like the lr does). ``sr_key``: PRNG key
        enabling stochastic rounding of bf16 params (see _cast_out)."""
        lr, b1, step, bc1, bc2 = self._preamble(state, lr, momentum)
        b2, eps, wd = self.b2, self.eps, self.weight_decay

        def leaf(i, p, g, m, v):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if wd != 0.0 and not self.adamw_mode:
                g = g + wd * p32  # L2-style (classic Adam)
            m = b1 * m + (1.0 - b1) * g
            v = b2 * v + (1.0 - b2) * (g * g)
            denom = jnp.sqrt(v / bc2) + eps
            update = (m / bc1) / denom
            if wd != 0.0 and self.adamw_mode:
                update = update + wd * p32  # decoupled (AdamW)
            new_p = p32 - lr * update
            return _cast_out(new_p, p, sr_key, i), m, v

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state.exp_avg)
        flat_v = treedef.flatten_up_to(state.exp_avg_sq)
        out = [leaf(i, p, g, m, v)
               for i, (p, g, m, v)
               in enumerate(zip(flat_p, flat_g, flat_m, flat_v))]
        new_params = treedef.unflatten([o[0] for o in out])
        new_m = treedef.unflatten([o[1] for o in out])
        new_v = treedef.unflatten([o[2] for o in out])
        return new_params, AdamState(step=step, exp_avg=new_m, exp_avg_sq=new_v)


class Adam8bitState(NamedTuple):
    step: jnp.ndarray
    m_codes: Params    # int8 (nblocks, block) per leaf
    m_scales: Params   # fp32 (nblocks, 1) per leaf
    v_codes: Params    # int8 codes of SQRT(exp_avg_sq) — see Adam8bit
    v_scales: Params


def _quantize8(x32, block):
    """Block-wise symmetric absmax int8: (codes, scales). x32 is the
    flattened-and-padded (nblocks*block,) fp32 tensor. An all-zero block
    stores scale 0 (NOT a placeholder): downstream the scale doubles as
    'was anything ever observed here' — a phantom scale would let the
    code-0 dequant floor inject a fake second moment into frozen/unused
    blocks and suppress their first real update ~60x."""
    xb = x32.reshape(-1, block)
    absmax = jnp.max(jnp.abs(xb), axis=1, keepdims=True)
    scale = absmax / 127.0
    codes = jnp.clip(jnp.round(xb / jnp.where(scale > 0.0, scale, 1.0)),
                     -127.0, 127.0).astype(jnp.int8)
    return codes, scale.astype(jnp.float32)


def _dequantize8(codes, scales):
    return (codes.astype(jnp.float32) * scales).reshape(-1)


class Adam8bit(Adam):
    """Adam/AdamW with block-wise 8-bit optimizer states (TPU extension
    beyond the reference; the 8-bit-optimizer idea of Dettmers et al.
    re-done the XLA way — no custom CUDA, the de/re-quantization fuses
    into the compiled update). exp_avg/exp_avg_sq live as int8 codes +
    per-block fp32 absmax scales: ~4x less optimizer-state HBM (capacity
    AND update-step bandwidth) than fp32 moments, with dynamics
    preserved by block-local scaling. Composes with ZeRO sharding, the
    OneCycle momentum override, and stochastic-rounded bf16 params like
    the fp32-state Adam.

    The second moment is stored as int8 codes of SQRT(v), not v: linear
    int8 spans only a 127:1 range per block, so any v below
    absmax/254 quantized to exact zero — and a zero denominator turns a
    surviving first moment into an exploding update (observed: a +2.36
    parameter jump in one step). sqrt-space squares the representable
    range (~64k:1 in v), and code-0 entries dequantize to a
    quarter-granularity floor instead of zero (damps the rare
    under-resolved coordinate rather than dividing by eps)."""

    def __init__(self, *args, block_size: int = 256, **kw):
        super().__init__(*args, **kw)
        self.block_size = block_size

    def _padded(self, n):
        b = self.block_size
        return (n + b - 1) // b * b

    def init(self, params):
        def zc(p):
            return jnp.zeros((self._padded(p.size) // self.block_size,
                              self.block_size), jnp.int8)

        def zs(p):
            return jnp.zeros((self._padded(p.size) // self.block_size, 1),
                             jnp.float32)
        return Adam8bitState(
            step=jnp.zeros((), jnp.int32),
            m_codes=jax.tree_util.tree_map(zc, params),
            m_scales=jax.tree_util.tree_map(zs, params),
            v_codes=jax.tree_util.tree_map(zc, params),
            v_scales=jax.tree_util.tree_map(zs, params),
        )

    def update(self, grads, state, params, lr=None, momentum=None,
               sr_key=None):
        lr, b1, step, bc1, bc2 = self._preamble(state, lr, momentum)
        b2, eps, wd = self.b2, self.eps, self.weight_decay

        def leaf(i, p, g, mc, ms, vc, vs):
            n, np_ = p.size, self._padded(p.size)
            g32 = jnp.pad(g.astype(jnp.float32).reshape(-1), (0, np_ - n))
            p32 = p.astype(jnp.float32).reshape(-1)
            if wd != 0.0 and not self.adamw_mode:
                g32 = g32 + wd * jnp.pad(p32, (0, np_ - n))
            m = b1 * _dequantize8(mc, ms) + (1.0 - b1) * g32
            # v codes encode sqrt(v); code 0 floors at quarter-granularity
            # (init scales are 0, so the true-zero initial state is exact)
            r_prev = jnp.maximum(_dequantize8(vc, vs),
                                 jnp.broadcast_to(vs * 0.25,
                                                  vc.shape).reshape(-1))
            v = b2 * (r_prev * r_prev) + (1.0 - b2) * (g32 * g32)
            denom = jnp.sqrt(v[:n] / bc2) + eps
            update = (m[:n] / bc1) / denom
            if wd != 0.0 and self.adamw_mode:
                update = update + wd * p32
            new_p = (p32 - lr * update).reshape(p.shape)
            mc2, ms2 = _quantize8(m, self.block_size)
            vc2, vs2 = _quantize8(jnp.sqrt(v), self.block_size)
            return _cast_out(new_p, p, sr_key, i), mc2, ms2, vc2, vs2

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat = zip(flat_p, treedef.flatten_up_to(grads),
                   treedef.flatten_up_to(state.m_codes),
                   treedef.flatten_up_to(state.m_scales),
                   treedef.flatten_up_to(state.v_codes),
                   treedef.flatten_up_to(state.v_scales))
        out = [leaf(i, *args) for i, args in enumerate(flat)]
        unf = lambda j: treedef.unflatten([o[j] for o in out])
        return unf(0), Adam8bitState(step=step, m_codes=unf(1),
                                     m_scales=unf(2), v_codes=unf(3),
                                     v_scales=unf(4))


class SGD(Optimizer):

    def __init__(self, lr: float = 1e-3, momentum: float = 0.0,
                 weight_decay: float = 0.0, nesterov: bool = False):
        self.lr = lr
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.nesterov = nesterov

    def init(self, params):
        return SGDState(
            step=jnp.zeros((), jnp.int32),
            momentum_buf=_tree_zeros_like(params, jnp.float32),
        )

    def update(self, grads, state, params, lr=None, momentum=None,
               sr_key=None):
        lr = self.lr if lr is None else lr
        mu = self.momentum if momentum is None else momentum
        wd = self.weight_decay

        def leaf(i, p, g, buf):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if wd != 0.0:
                g = g + wd * p32
            buf = mu * buf + g
            d = (g + mu * buf) if self.nesterov else buf
            return _cast_out(p32 - lr * d, p, sr_key, i), buf

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_b = treedef.flatten_up_to(state.momentum_buf)
        out = [leaf(i, p, g, b)
               for i, (p, g, b) in enumerate(zip(flat_p, flat_g, flat_b))]
        return (treedef.unflatten([o[0] for o in out]),
                SGDState(step=state.step + 1,
                         momentum_buf=treedef.unflatten([o[1] for o in out])))


class Lamb(Optimizer):
    """LAMB: layerwise-adaptive Adam for large batches.

    Per-leaf trust ratio ‖w‖/‖update‖ clamped to [min_coeff, max_coeff]
    (reference fused_lamb_cuda_kernel.cu:252 part3; defaults 0.01/0.3 follow
    ops/lamb/fused_lamb.py:12 FusedLamb(max_coeff=10.0, min_coeff=0.01) —
    we keep the reference's 10.0/0.01)."""

    def __init__(self, lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, max_coeff: float = 10.0,
                 min_coeff: float = 0.01, bias_correction: bool = True):
        self.lr = lr
        self.b1, self.b2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.max_coeff = max_coeff
        self.min_coeff = min_coeff
        self.bias_correction = bias_correction
        self.last_lamb_coeffs = []  # mirrors FusedLamb.get_lamb_coeffs:195

    def init(self, params):
        return LambState(
            step=jnp.zeros((), jnp.int32),
            exp_avg=_tree_zeros_like(params, jnp.float32),
            exp_avg_sq=_tree_zeros_like(params, jnp.float32),
        )

    def update(self, grads, state, params, lr=None, momentum=None,
               sr_key=None):
        lr = self.lr if lr is None else lr
        b1 = self.b1 if momentum is None else momentum
        step = state.step + 1
        b2, eps, wd = self.b2, self.eps, self.weight_decay
        if self.bias_correction:
            bc1 = 1.0 - b1 ** step.astype(jnp.float32)
            bc2 = 1.0 - b2 ** step.astype(jnp.float32)
        else:
            bc1 = bc2 = jnp.float32(1.0)

        def leaf(i, p, g, m, v):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m = b1 * m + (1.0 - b1) * g
            v = b2 * v + (1.0 - b2) * (g * g)
            update = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if wd != 0.0:
                update = update + wd * p32
            w_norm = jnp.linalg.norm(p32.reshape(-1))
            u_norm = jnp.linalg.norm(update.reshape(-1))
            trust = jnp.where(
                (w_norm > 0) & (u_norm > 0),
                jnp.clip(w_norm / u_norm, self.min_coeff, self.max_coeff),
                jnp.float32(1.0))
            new_p = p32 - lr * trust * update
            return _cast_out(new_p, p, sr_key, i), m, v, trust

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state.exp_avg)
        flat_v = treedef.flatten_up_to(state.exp_avg_sq)
        out = [leaf(i, p, g, m, v)
               for i, (p, g, m, v)
               in enumerate(zip(flat_p, flat_g, flat_m, flat_v))]
        coeffs = [o[3] for o in out]
        if not any(isinstance(c, jax.core.Tracer) for c in coeffs):
            # only capture concrete values; under jit tracing the coeffs are
            # tracers and must not escape (use lamb_coeffs() instead)
            self.last_lamb_coeffs = coeffs
        return (treedef.unflatten([o[0] for o in out]),
                LambState(step=step,
                          exp_avg=treedef.unflatten([o[1] for o in out]),
                          exp_avg_sq=treedef.unflatten([o[2] for o in out])))

    def get_lamb_coeffs(self):
        """Last concrete trust ratios (reference fused_lamb.py:195). Empty if
        every update so far ran under jit; use :meth:`lamb_coeffs` then."""
        return self.last_lamb_coeffs

    def lamb_coeffs(self, grads, state, params):
        """Recompute the per-leaf trust ratios for the given (grads, state,
        params) outside jit — the engine-safe way to inspect coefficients."""
        _, _ = params, state
        coeffs = []
        step = state.step + 1
        bc1 = 1.0 - self.b1 ** step.astype(jnp.float32)
        bc2 = 1.0 - self.b2 ** step.astype(jnp.float32)
        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state.exp_avg)
        flat_v = treedef.flatten_up_to(state.exp_avg_sq)
        for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m2 = self.b1 * m + (1.0 - self.b1) * g
            v2 = self.b2 * v + (1.0 - self.b2) * (g * g)
            update = (m2 / bc1) / (jnp.sqrt(v2 / bc2) + self.eps)
            if self.weight_decay != 0.0:
                update = update + self.weight_decay * p32
            w_norm = jnp.linalg.norm(p32.reshape(-1))
            u_norm = jnp.linalg.norm(update.reshape(-1))
            coeffs.append(float(jnp.where(
                (w_norm > 0) & (u_norm > 0),
                jnp.clip(w_norm / u_norm, self.min_coeff, self.max_coeff),
                jnp.float32(1.0))))
        return coeffs


# Reference-compatible aliases (ops/adam, ops/lamb public names)
FusedAdam = Adam
FusedLamb = Lamb


def build_optimizer(name: str, params_dict: Optional[dict]) -> Optimizer:
    """Construct from JSON config (reference engine.py:544
    _configure_basic_optimizer)."""
    p = dict(params_dict or {})
    p.pop("torch_adam", None)
    name = (name or "adam").lower()
    if name in ("adam", "deepspeed_adam"):
        adamw = p.pop("adam_w_mode", True)
        return Adam(lr=p.pop("lr", 1e-3),
                    betas=tuple(p.pop("betas", (0.9, 0.999))),
                    eps=p.pop("eps", 1e-8),
                    weight_decay=p.pop("weight_decay", 0.0),
                    adamw_mode=adamw,
                    bias_correction=p.pop("bias_correction", True))
    if name in ("adam8bit", "adam_8bit", "8bit_adam"):
        adamw = p.pop("adam_w_mode", True)
        return Adam8bit(lr=p.pop("lr", 1e-3),
                        betas=tuple(p.pop("betas", (0.9, 0.999))),
                        eps=p.pop("eps", 1e-8),
                        weight_decay=p.pop("weight_decay", 0.0),
                        adamw_mode=adamw,
                        bias_correction=p.pop("bias_correction", True),
                        block_size=p.pop("block_size", 256))
    if name == "adamw":
        return Adam(lr=p.pop("lr", 1e-3),
                    betas=tuple(p.pop("betas", (0.9, 0.999))),
                    eps=p.pop("eps", 1e-8),
                    weight_decay=p.pop("weight_decay", 0.01),
                    adamw_mode=True,
                    bias_correction=p.pop("bias_correction", True))
    if name == "lamb":
        return Lamb(lr=p.pop("lr", 1e-3),
                    betas=tuple(p.pop("betas", (0.9, 0.999))),
                    eps=p.pop("eps", 1e-8),
                    weight_decay=p.pop("weight_decay", 0.0),
                    max_coeff=p.pop("max_coeff", 10.0),
                    min_coeff=p.pop("min_coeff", 0.01),
                    bias_correction=p.pop("bias_correction", True))
    if name == "sgd":
        return SGD(lr=p.pop("lr", 1e-3),
                   momentum=p.pop("momentum", 0.0),
                   weight_decay=p.pop("weight_decay", 0.0),
                   nesterov=p.pop("nesterov", False))
    if name in ("onebitadam", "onebit_adam", "one_bit_adam"):
        # (reference engine.py:544 selects ONEBIT_ADAM_OPTIMIZER)
        from deepspeed_tpu.runtime.fp16.onebit_adam import OnebitAdam
        return OnebitAdam(lr=p.pop("lr", 1e-3),
                          freeze_step=p.pop("freeze_step", 100000),
                          betas=tuple(p.pop("betas", (0.9, 0.999))),
                          eps=p.pop("eps", 1e-8),
                          weight_decay=p.pop("weight_decay", 0.0),
                          cuda_aware=p.pop("cuda_aware", False))
    raise ValueError(f"Unknown optimizer: {name}")
