"""Ring attention — sequence/context parallelism over a mesh axis.

Long-context support beyond the reference snapshot (whose only answer is
block-sparse attention, docs/_posts/2020-09-09-sparse-attention.md): the
sequence dimension is sharded over the ``seq`` mesh axis and K/V chunks
rotate around the ring via ``lax.ppermute`` (ICI neighbor exchange), while
each device's Q stays resident. Per visiting chunk the local Pallas flash
kernel (ops/attention/flash.py) produces a normalized partial output plus
its log-sum-exp; partials combine exactly with online-softmax reweighting,
so the result is bitwise the same attention math at 1/P sequence memory
per device — attention over sequences no single chip could hold.

Algorithm (RingAttention, arXiv:2310.01889, re-derived on the flash
kernel's (o, lse) interface — no kernel changes needed):

forward, P = ring size, idx = my shard index, step j holds chunk
``src = (idx - j) mod P``:
- j = 0: the diagonal chunk (src == idx): local causal flash.
- j > 0: non-causal flash against the visiting chunk; for causal
  attention a chunk from the future (src > idx) is discarded by masking
  its combine weight — computed uniformly on every device, so the
  ppermute stays uniform (same invariant as the pipeline executor,
  runtime/pipe/spmd.py).
- combine: running (o, lse) with logaddexp reweighting in fp32.

backward re-runs the ring: dq accumulates locally; (dk, dv) for the
visiting chunk accumulate in buffers that rotate *with* k/v and arrive
back at their owner after the full cycle. Each per-chunk backward calls
the flash backward with the TOTAL lse/delta, which is exactly the
decomposition ds = p * (dp - delta) with p = exp(s - lse_total).

Causal cost note: the plain ring computes all P chunks and discards the
future ones (~2x the minimal causal work, like the unbalanced ring in the
paper). ``zigzag=True`` runs the load-balanced schedule instead: the
global sequence is cut into 2P chunks and shard i owns chunks
(i, 2P-1-i) — its local sequence is the concatenation of those two
halves. Each ring step then computes exactly TWO half-chunk flash calls
per device: (late half vs visiting early half), which causality always
needs, plus one call whose operands are SELECTED by the uniform
predicate ``src < idx`` — (early vs visiting-early) for past sources,
(late vs visiting-late) for future ones — merged into the right half's
accumulator by masked combines. Work is identical on every device and
totals the minimal causal 2P+1 half-chunk pairs per device (~half the
plain ring's FLOPs), with the same single rotating KV channel.
Use :func:`zigzag_layout_indices` to lay the global sequence out.

Dropout: each chunk pair derives a distinct seed (seed ^ mix(src) plain,
seed ^ mix(q_chunk, k_chunk) zigzag) so the in-kernel counter-based mask
never repeats across chunks and regenerates identically in forward and
backward.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.attention.flash import (
    _flash_bwd, _flash_fwd, _use_pallas, dropout_seed_from_rng)

NEG_BIG = -1e30
VALID_THRESH = -1e29


def _chunk_seed(seed, src):
    # distinct per-chunk dropout stream; int32 wraparound is fine
    return seed + (src * jnp.int32(-1640531527))  # 2654435761 as int32


def _combine(o_acc, lse_acc, o_j, lse_j):
    """Exact online-softmax merge of normalized partials (fp32)."""
    lse_new = jnp.maximum(lse_acc, lse_j) + jnp.log1p(
        jnp.exp(-jnp.abs(lse_acc - lse_j)))
    w_acc = jnp.where(lse_acc <= VALID_THRESH, 0.0,
                      jnp.exp(lse_acc - lse_new))
    w_j = jnp.where(lse_j <= VALID_THRESH, 0.0, jnp.exp(lse_j - lse_new))
    o_new = o_acc * w_acc[..., None] + o_j.astype(jnp.float32) * \
        w_j[..., None]
    lse_new = jnp.where(
        jnp.logical_and(lse_acc <= VALID_THRESH, lse_j <= VALID_THRESH),
        NEG_BIG, lse_new)
    return o_new, lse_new


def _rot(x, axis_name, P, shift=1):
    return jax.lax.ppermute(x, axis_name,
                            [(i, (i + shift) % P) for i in range(P)])


def _ring_fwd_impl(q, k, v, kpm, seed, axis_name, causal, sm_scale,
                   interpret, rate):
    P = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)

    # step 0: diagonal chunk, local causal (or plain) flash
    o0, lse0 = _flash_fwd(q, k, v, kpm, causal, sm_scale, interpret,
                          dropout_rate=rate,
                          seed=_chunk_seed(seed, idx) if rate > 0.0 else seed)
    o_acc = o0.astype(jnp.float32)
    lse_acc = lse0

    def step(carry, j):
        k_cur, v_cur, kpm_cur, o_acc, lse_acc = carry
        k_cur = _rot(k_cur, axis_name, P)
        v_cur = _rot(v_cur, axis_name, P)
        if kpm_cur is not None:
            kpm_cur = _rot(kpm_cur, axis_name, P)
        src = (idx - j) % P
        sj = _chunk_seed(seed, src) if rate > 0.0 else seed
        o_j, lse_j = _flash_fwd(q, k_cur, v_cur, kpm_cur, False, sm_scale,
                                interpret, dropout_rate=rate, seed=sj)
        if causal:
            valid = src < idx          # strictly-past chunk
            lse_j = jnp.where(valid, lse_j, NEG_BIG)
        o_acc, lse_acc = _combine(o_acc, lse_acc, o_j, lse_j)
        return (k_cur, v_cur, kpm_cur, o_acc, lse_acc), None

    if P > 1:
        (_, _, _, o_acc, lse_acc), _ = jax.lax.scan(
            step, (k, v, kpm, o_acc, lse_acc), jnp.arange(1, P))
    return o_acc.astype(q.dtype), lse_acc


def _ring_bwd_impl(res, do, axis_name, causal, sm_scale, interpret, rate):
    q, k, v, kpm, seed, o, lse = res
    P = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)

    # diagonal chunk
    dq, dk0, dv0, _ = _flash_bwd(
        (q, k, v, kpm,
         _chunk_seed(seed, idx) if rate > 0.0 else seed, o, lse),
        do, causal, sm_scale, interpret, dropout_rate=rate)
    dq = dq.astype(jnp.float32)
    dk_acc = dk0.astype(jnp.float32)
    dv_acc = dv0.astype(jnp.float32)

    def step(carry, j):
        k_cur, v_cur, kpm_cur, dk_cur, dv_cur, dq = carry
        # rotate k/v (+ their key mask) and grad accumulators together
        k_cur = _rot(k_cur, axis_name, P)
        v_cur = _rot(v_cur, axis_name, P)
        if kpm_cur is not None:
            kpm_cur = _rot(kpm_cur, axis_name, P)
        dk_cur = _rot(dk_cur, axis_name, P)
        dv_cur = _rot(dv_cur, axis_name, P)
        src = (idx - j) % P
        sj = _chunk_seed(seed, src) if rate > 0.0 else seed
        dq_j, dk_j, dv_j, _ = _flash_bwd(
            (q, k_cur, v_cur, kpm_cur, sj, o, lse), do, False, sm_scale,
            interpret, dropout_rate=rate)
        if causal:
            valid = (src < idx).astype(jnp.float32)
            dq_j = dq_j * valid
            dk_j = dk_j * valid
            dv_j = dv_j * valid
        dq = dq + dq_j.astype(jnp.float32)
        dk_cur = dk_cur + dk_j.astype(jnp.float32)
        dv_cur = dv_cur + dv_j.astype(jnp.float32)
        return (k_cur, v_cur, kpm_cur, dk_cur, dv_cur, dq), None

    if P > 1:
        (_, _, _, dk_acc, dv_acc, dq), _ = jax.lax.scan(
            step, (k, v, kpm, dk_acc, dv_acc, dq), jnp.arange(1, P))
        # one final rotation completes the cycle: each (dk, dv) buffer
        # returns to the device owning that chunk
        dk_acc = _rot(dk_acc, axis_name, P)
        dv_acc = _rot(dv_acc, axis_name, P)
    return dq.astype(q.dtype), dk_acc.astype(k.dtype), \
        dv_acc.astype(v.dtype)


# --------------------------------------------------------------------- #
# zigzag (load-balanced causal) schedule
# --------------------------------------------------------------------- #
def zigzag_layout_indices(P: int, seq: int) -> np.ndarray:
    """Global gather indices for the zigzag layout: shard i's local
    sequence = global chunks (i, 2P-1-i) concatenated. ``g`` is laid out
    shard-major, so with a (seq,)-sharded array x over P shards,
    ``x[..., g, :]`` re-distributes it into the zigzag layout (one XLA
    all-to-all under GSPMD); apply ``np.argsort(g)`` to invert."""
    assert seq % (2 * P) == 0, (seq, P)
    lc = seq // (2 * P)
    out = []
    for i in range(P):
        out.extend(range(i * lc, (i + 1) * lc))
        out.extend(range((2 * P - 1 - i) * lc, (2 * P - i) * lc))
    return np.asarray(out, np.int64)


def _zz_seed(seed, qc, kc, P):
    # distinct stream per (q-chunk, k-chunk) pair, fwd/bwd reproducible
    return seed + ((qc * 2 * P + kc + 1) * jnp.int32(-1640531527))


def _halves(x, axis=2):
    if x is None:
        return None, None
    lc = x.shape[axis] // 2
    lo = jax.lax.slice_in_dim(x, 0, lc, axis=axis)
    hi = jax.lax.slice_in_dim(x, lc, 2 * lc, axis=axis)
    return lo, hi


def _sel(pred, a, b):
    return None if a is None else jnp.where(pred, a, b)


def _zz_fwd_impl(q, k, v, kpm, seed, axis_name, sm_scale, interpret, rate):
    P = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    a1, a2 = idx, 2 * P - 1 - idx
    q1, q2 = _halves(q)
    k1, k2 = _halves(k)
    v1, v2 = _halves(v)
    m1, m2 = _halves(kpm, axis=3)

    def fwd(qc, kc, vc, mc, causal, sq, sk):
        s = _zz_seed(seed, sq, sk, P) if rate > 0.0 else seed
        return _flash_fwd(qc, kc, vc, mc, causal, sm_scale, interpret,
                          dropout_rate=rate, seed=s)

    # local: causal diagonals of both halves + (late vs own early)
    o1, l1 = fwd(q1, k1, v1, m1, True, a1, a1)
    o1 = o1.astype(jnp.float32)
    o2a, l2a = fwd(q2, k2, v2, m2, True, a2, a2)
    o2b, l2b = fwd(q2, k1, v1, m1, False, a2, a1)
    o2, l2 = _combine(o2a.astype(jnp.float32), l2a, o2b, l2b)

    def step(carry, j):
        k_cur, v_cur, m_cur, o1, l1, o2, l2 = carry
        k_cur = _rot(k_cur, axis_name, P)
        v_cur = _rot(v_cur, axis_name, P)
        if m_cur is not None:
            m_cur = _rot(m_cur, axis_name, P)
        src = (idx - j) % P
        b1, b2 = src, 2 * P - 1 - src
        kb1, kb2 = _halves(k_cur)
        vb1, vb2 = _halves(v_cur)
        mb1, mb2 = _halves(m_cur, axis=3)
        # call A: late half vs visiting early half — always causal-valid
        oA, lA = fwd(q2, kb1, vb1, mb1, False, a2, b1)
        o2, l2 = _combine(o2, l2, oA, lA)
        # call B: operand-selected by the uniform predicate src < idx
        pred = src < idx
        qB = _sel(pred, q1, q2)
        kB = _sel(pred, kb1, kb2)
        vB = _sel(pred, vb1, vb2)
        mB = _sel(pred, mb1, mb2) if m_cur is not None else None
        sq_ = jnp.where(pred, a1, a2)
        sk_ = jnp.where(pred, b1, b2)
        oB, lB = fwd(qB, kB, vB, mB, False, sq_, sk_)
        o1, l1 = _combine(o1, l1, oB, jnp.where(pred, lB, NEG_BIG))
        o2, l2 = _combine(o2, l2, oB, jnp.where(pred, NEG_BIG, lB))
        return (k_cur, v_cur, m_cur, o1, l1, o2, l2), None

    if P > 1:
        (_, _, _, o1, l1, o2, l2), _ = jax.lax.scan(
            step, (k, v, kpm, o1, l1, o2, l2), jnp.arange(1, P))
    o = jnp.concatenate([o1, o2], axis=2).astype(q.dtype)
    lse = jnp.concatenate([l1, l2], axis=2)
    return o, lse


def _zz_bwd_impl(res, do, axis_name, sm_scale, interpret, rate):
    q, k, v, kpm, seed, o, lse = res
    P = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    a1, a2 = idx, 2 * P - 1 - idx
    q1, q2 = _halves(q)
    k1, k2 = _halves(k)
    v1, v2 = _halves(v)
    m1, m2 = _halves(kpm, axis=3)
    o1, o2 = _halves(o)
    l1, l2 = _halves(lse)
    do1, do2 = _halves(do)

    def bwd(qc, kc, vc, mc, oc, lc_, doc, causal, sq, sk):
        s = _zz_seed(seed, sq, sk, P) if rate > 0.0 else seed
        dq_, dk_, dv_, _ = _flash_bwd(
            (qc, kc, vc, mc, s, oc, lc_), doc, causal, sm_scale,
            interpret, dropout_rate=rate)
        return (dq_.astype(jnp.float32), dk_.astype(jnp.float32),
                dv_.astype(jnp.float32))

    # local pairs
    dq1, dk1, dv1 = bwd(q1, k1, v1, m1, o1, l1, do1, True, a1, a1)
    dq2, dk2, dv2 = bwd(q2, k2, v2, m2, o2, l2, do2, True, a2, a2)
    g2b = bwd(q2, k1, v1, m1, o2, l2, do2, False, a2, a1)
    dq2 = dq2 + g2b[0]
    dk1 = dk1 + g2b[1]
    dv1 = dv1 + g2b[2]
    dk_buf = jnp.concatenate([dk1, dk2], axis=2)
    dv_buf = jnp.concatenate([dv1, dv2], axis=2)

    def step(carry, j):
        k_cur, v_cur, m_cur, dk_buf, dv_buf, dq1, dq2 = carry
        k_cur = _rot(k_cur, axis_name, P)
        v_cur = _rot(v_cur, axis_name, P)
        if m_cur is not None:
            m_cur = _rot(m_cur, axis_name, P)
        dk_buf = _rot(dk_buf, axis_name, P)
        dv_buf = _rot(dv_buf, axis_name, P)
        src = (idx - j) % P
        b1, b2 = src, 2 * P - 1 - src
        kb1, kb2 = _halves(k_cur)
        vb1, vb2 = _halves(v_cur)
        mb1, mb2 = _halves(m_cur, axis=3)
        dkb1, dkb2 = _halves(dk_buf)
        dvb1, dvb2 = _halves(dv_buf)
        # call A: q2 vs visiting early half — always valid
        gA = bwd(q2, kb1, vb1, mb1, o2, l2, do2, False, a2, b1)
        dq2 = dq2 + gA[0]
        dkb1 = dkb1 + gA[1]
        dvb1 = dvb1 + gA[2]
        # call B: operand-selected
        pred = src < idx
        qB = _sel(pred, q1, q2)
        kB = _sel(pred, kb1, kb2)
        vB = _sel(pred, vb1, vb2)
        mB = _sel(pred, mb1, mb2) if m_cur is not None else None
        oB = _sel(pred, o1, o2)
        lB = _sel(pred, l1, l2)
        doB = _sel(pred, do1, do2)
        sq_ = jnp.where(pred, a1, a2)
        sk_ = jnp.where(pred, b1, b2)
        gB = bwd(qB, kB, vB, mB, oB, lB, doB, False, sq_, sk_)
        w = pred.astype(jnp.float32)
        dq1 = dq1 + gB[0] * w
        dq2 = dq2 + gB[0] * (1.0 - w)
        dkb1 = dkb1 + gB[1] * w
        dkb2 = dkb2 + gB[1] * (1.0 - w)
        dvb1 = dvb1 + gB[2] * w
        dvb2 = dvb2 + gB[2] * (1.0 - w)
        dk_buf = jnp.concatenate([dkb1, dkb2], axis=2)
        dv_buf = jnp.concatenate([dvb1, dvb2], axis=2)
        return (k_cur, v_cur, m_cur, dk_buf, dv_buf, dq1, dq2), None

    if P > 1:
        (_, _, _, dk_buf, dv_buf, dq1, dq2), _ = jax.lax.scan(
            step, (k, v, kpm, dk_buf, dv_buf, dq1, dq2), jnp.arange(1, P))
        # final rotation returns each (dk, dv) buffer to its chunk owner
        dk_buf = _rot(dk_buf, axis_name, P)
        dv_buf = _rot(dv_buf, axis_name, P)
    dq = jnp.concatenate([dq1, dq2], axis=2)
    return dq.astype(q.dtype), dk_buf.astype(k.dtype), \
        dv_buf.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _zz_attention(q, k, v, seed, has_kpm, axis_name, sm_scale,
                  interpret, rate):
    kpm, seed = seed if has_kpm else (None, seed)
    o, _ = _zz_fwd_impl(q, k, v, kpm, seed, axis_name, sm_scale,
                        interpret, rate)
    return o


def _zz_attention_fwd(q, k, v, seed, has_kpm, axis_name, sm_scale,
                      interpret, rate):
    kpm, seed = seed if has_kpm else (None, seed)
    o, lse = _zz_fwd_impl(q, k, v, kpm, seed, axis_name, sm_scale,
                          interpret, rate)
    return o, (q, k, v, kpm, seed, o, lse)


def _zz_attention_bwd(has_kpm, axis_name, sm_scale, interpret, rate,
                      res, g):
    dq, dk, dv = _zz_bwd_impl(res, g, axis_name, sm_scale, interpret,
                              rate)
    return dq, dk, dv, ((None, None) if has_kpm else None)


_zz_attention.defvjp(_zz_attention_fwd, _zz_attention_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _ring_attention(q, k, v, seed, has_kpm, axis_name, causal, sm_scale,
                    interpret, rate):
    kpm, seed = seed if has_kpm else (None, seed)
    o, _ = _ring_fwd_impl(q, k, v, kpm, seed, axis_name, causal, sm_scale,
                          interpret, rate)
    return o


def _ring_attention_fwd(q, k, v, seed, has_kpm, axis_name, causal,
                        sm_scale, interpret, rate):
    kpm, seed = seed if has_kpm else (None, seed)
    o, lse = _ring_fwd_impl(q, k, v, kpm, seed, axis_name, causal,
                            sm_scale, interpret, rate)
    return o, (q, k, v, kpm, seed, o, lse)


def _ring_attention_bwd(has_kpm, axis_name, causal, sm_scale, interpret,
                        rate, res, g):
    dq, dk, dv = _ring_bwd_impl(res, g, axis_name, causal, sm_scale,
                                interpret, rate)
    return dq, dk, dv, ((None, None) if has_kpm else None)


_ring_attention.defvjp(_ring_attention_fwd, _ring_attention_bwd)


def ring_attention(q, k, v, axis_name: str = "seq", causal: bool = False,
                   sm_scale: Optional[float] = None,
                   dropout_rate: float = 0.0, dropout_rng=None,
                   key_padding_mask=None,
                   interpret: Optional[bool] = None,
                   zigzag: bool = False):
    """Sequence-parallel flash attention over ``axis_name``.

    Call INSIDE ``shard_map`` with ``axis_name`` manual; q/k/v are this
    device's sequence shard, shape (batch, heads, seq_local, head_dim)
    with identical seq_local on every shard. Plain layout: shard i owns
    positions [i*seq_local, (i+1)*seq_local). ``zigzag=True`` (causal
    only) uses the load-balanced layout instead — shard i owns global
    chunks (i, 2P-1-i) of 2P, concatenated (:func:`zigzag_layout_indices`)
    — for ~half the causal FLOPs at identical math (module docstring).

    ``key_padding_mask``: optional *additive* (B, 1, 1, seq_local) mask
    for this shard's keys (BERT padding); it rotates around the ring
    with its K/V chunk.
    """
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(q.shape[-1])
    if interpret is None:
        interpret = not _use_pallas()
    dropout_rate = float(dropout_rate)
    if dropout_rate > 0.0:
        assert dropout_rng is not None, \
            "ring_attention: dropout_rate > 0 requires dropout_rng"
        seed = dropout_seed_from_rng(dropout_rng)
    else:
        seed = jnp.zeros((1, 1), jnp.int32)
    if zigzag:
        assert causal, "zigzag schedule is a causal-attention optimization"
        assert q.shape[2] % 2 == 0, \
            f"zigzag needs an even local seq, got {q.shape[2]}"
        if key_padding_mask is not None:
            return _zz_attention(q, k, v, (key_padding_mask, seed), True,
                                 axis_name, float(sm_scale), interpret,
                                 dropout_rate)
        return _zz_attention(q, k, v, seed, False, axis_name,
                             float(sm_scale), interpret, dropout_rate)
    if key_padding_mask is not None:
        return _ring_attention(q, k, v, (key_padding_mask, seed), True,
                               axis_name, causal, float(sm_scale),
                               interpret, dropout_rate)
    return _ring_attention(q, k, v, seed, False, axis_name, causal,
                           float(sm_scale), interpret, dropout_rate)


# --------------------------------------------------------------------- #
# forward-only ring prefill over a paged-KV stripe (serving, ISSUE 19)
# --------------------------------------------------------------------- #
def _ring_prefill_shard(q, kc, vc, cache_position, axis_name, P, Sl, Ll,
                        sm_scale):
    """Per-shard body of :func:`ring_prefill_attention` (inside the
    shard_map): my Q block stays resident while K/V stripe blocks
    rotate around the ring; each visit contributes a normalized fp32
    partial (o_j, lse_j) masked by the ABSOLUTE-position causal rule of
    ``models/gpt2.causal_cache_mask`` — q position ``cache_position +
    global_q_idx`` attends stripe slots ``<=`` it — and partials merge
    with the exact online-softmax combine. GQA runs group-wise like
    the llama gather fallback (q heads fold onto their kv head)."""
    idx = jax.lax.axis_index(axis_name)
    B, H, _, hd = q.shape
    KH = kc.shape[1]
    G = H // KH
    qg = q.astype(jnp.float32).reshape(B, KH, G, Sl, hd)
    q_pos = (cache_position[:, None] + idx * Sl
             + jnp.arange(Sl)[None, :])                       # (B, Sl)

    def partial(k_blk, v_blk, src):
        scores = jnp.einsum("bkgsd,bkld->bkgsl", qg,
                            k_blk.astype(jnp.float32)) * sm_scale
        kv_pos = src * Ll + jnp.arange(Ll)                    # (Ll,)
        valid = kv_pos[None, None, :] <= q_pos[:, :, None]    # (B,Sl,Ll)
        scores = jnp.where(valid[:, None, None], scores, NEG_BIG)
        m = jnp.max(scores, axis=-1)
        p = jnp.exp(scores - m[..., None])
        s = jnp.sum(p, axis=-1)
        o = jnp.einsum("bkgsl,bkld->bkgsd", p,
                       v_blk.astype(jnp.float32)) / \
            jnp.maximum(s, 1e-30)[..., None]
        lse = jnp.where(m <= VALID_THRESH, NEG_BIG,
                        m + jnp.log(jnp.maximum(s, 1e-30)))
        return o, lse

    o_acc, lse_acc = partial(kc, vc, idx)

    def step(carry, j):
        k_cur, v_cur, o_acc, lse_acc = carry
        k_cur = _rot(k_cur, axis_name, P)
        v_cur = _rot(v_cur, axis_name, P)
        src = (idx - j) % P
        o_j, lse_j = partial(k_cur, v_cur, src)
        o_acc, lse_acc = _combine(o_acc, lse_acc, o_j, lse_j)
        return (k_cur, v_cur, o_acc, lse_acc), None

    if P > 1:
        (_, _, o_acc, lse_acc), _ = jax.lax.scan(
            step, (kc, vc, o_acc, lse_acc), jnp.arange(1, P))
    return o_acc.reshape(B, H, Sl, hd).astype(q.dtype)


def ring_prefill_attention(q, kc, vc, cache_position, mesh,
                           axis: str = "model",
                           sm_scale: Optional[float] = None):
    """Context-parallel PREFILL attention for the serving engine's
    chunk dispatches (forward-only — serving never needs the ring
    backward): ``q`` (B, H, S, hd) is the chunk's queries, ``kc``/
    ``vc`` (B, KH, L, hd) the gathered (dequantized) paged-KV stripe,
    ``cache_position`` (B,) each row's absolute prefilled offset —
    exactly the operands of the models' gather-fallback attention,
    same masking rule, same fp32 math, with the sequence axes sharded
    over ``(mesh, axis)``: Q blocks stay resident, K/V stripe blocks
    ring via ppermute, partials merge with the exact online-softmax
    combine. Requires S and L divisible by the axis size (the engine
    validates at init and logs the fallback otherwise)."""
    from jax.sharding import PartitionSpec as P_

    P = mesh.shape[axis]
    B, H, S, hd = q.shape
    L = kc.shape[2]
    assert S % P == 0 and L % P == 0, (
        f"ring_prefill_attention: seq ({S}) and stripe ({L}) must be "
        f"divisible by mesh axis {axis!r} ({P}-way)")
    assert H % kc.shape[1] == 0, (H, kc.shape[1])
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(hd)

    def inner(q, kc, vc, cache_position):
        return _ring_prefill_shard(q, kc, vc, cache_position, axis, P,
                                   S // P, L // P, float(sm_scale))

    seq_spec = P_(None, None, axis, None)
    f = jax.shard_map(
        inner, mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec, P_()),
        out_specs=seq_spec, check_vma=False)
    return f(q, kc, vc, cache_position)
