"""Ring attention — sequence/context parallelism over a mesh axis.

Long-context support beyond the reference snapshot (whose only answer is
block-sparse attention, docs/_posts/2020-09-09-sparse-attention.md): the
sequence dimension is sharded over the ``seq`` mesh axis and K/V chunks
rotate around the ring via ``lax.ppermute`` (ICI neighbor exchange), while
each device's Q stays resident. Per visiting chunk the local Pallas flash
kernel (ops/attention/flash.py) produces a normalized partial output plus
its log-sum-exp; partials combine exactly with online-softmax reweighting,
so the result is bitwise the same attention math at 1/P sequence memory
per device — attention over sequences no single chip could hold.

Algorithm (RingAttention, arXiv:2310.01889, re-derived on the flash
kernel's (o, lse) interface — no kernel changes needed):

forward, P = ring size, idx = my shard index, step j holds chunk
``src = (idx - j) mod P``:
- j = 0: the diagonal chunk (src == idx): local causal flash.
- j > 0: non-causal flash against the visiting chunk; for causal
  attention a chunk from the future (src > idx) is discarded by masking
  its combine weight — computed uniformly on every device, so the
  ppermute stays uniform (same invariant as the pipeline executor,
  runtime/pipe/spmd.py).
- combine: running (o, lse) with logaddexp reweighting in fp32.

backward re-runs the ring: dq accumulates locally; (dk, dv) for the
visiting chunk accumulate in buffers that rotate *with* k/v and arrive
back at their owner after the full cycle. Each per-chunk backward calls
the flash backward with the TOTAL lse/delta, which is exactly the
decomposition ds = p * (dp - delta) with p = exp(s - lse_total).

Causal cost note: the plain ring computes all P chunks and discards the
future ones (~2x the minimal causal work, like the unbalanced ring in the
paper); the zigzag load-balanced schedule is a follow-up optimization.

Dropout: each chunk derives a distinct seed (seed ^ mix(src)) so the
in-kernel counter-based mask never repeats across chunks and regenerates
identically in forward and backward.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.attention.flash import (
    _flash_bwd, _flash_fwd, _use_pallas, dropout_seed_from_rng)

NEG_BIG = -1e30
VALID_THRESH = -1e29


def _chunk_seed(seed, src):
    # distinct per-chunk dropout stream; int32 wraparound is fine
    return seed + (src * jnp.int32(-1640531527))  # 2654435761 as int32


def _combine(o_acc, lse_acc, o_j, lse_j):
    """Exact online-softmax merge of normalized partials (fp32)."""
    lse_new = jnp.maximum(lse_acc, lse_j) + jnp.log1p(
        jnp.exp(-jnp.abs(lse_acc - lse_j)))
    w_acc = jnp.where(lse_acc <= VALID_THRESH, 0.0,
                      jnp.exp(lse_acc - lse_new))
    w_j = jnp.where(lse_j <= VALID_THRESH, 0.0, jnp.exp(lse_j - lse_new))
    o_new = o_acc * w_acc[..., None] + o_j.astype(jnp.float32) * \
        w_j[..., None]
    lse_new = jnp.where(
        jnp.logical_and(lse_acc <= VALID_THRESH, lse_j <= VALID_THRESH),
        NEG_BIG, lse_new)
    return o_new, lse_new


def _rot(x, axis_name, P, shift=1):
    return jax.lax.ppermute(x, axis_name,
                            [(i, (i + shift) % P) for i in range(P)])


def _ring_fwd_impl(q, k, v, kpm, seed, axis_name, causal, sm_scale,
                   interpret, rate):
    P = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)

    # step 0: diagonal chunk, local causal (or plain) flash
    o0, lse0 = _flash_fwd(q, k, v, kpm, causal, sm_scale, interpret,
                          dropout_rate=rate,
                          seed=_chunk_seed(seed, idx) if rate > 0.0 else seed)
    o_acc = o0.astype(jnp.float32)
    lse_acc = lse0

    def step(carry, j):
        k_cur, v_cur, kpm_cur, o_acc, lse_acc = carry
        k_cur = _rot(k_cur, axis_name, P)
        v_cur = _rot(v_cur, axis_name, P)
        if kpm_cur is not None:
            kpm_cur = _rot(kpm_cur, axis_name, P)
        src = (idx - j) % P
        sj = _chunk_seed(seed, src) if rate > 0.0 else seed
        o_j, lse_j = _flash_fwd(q, k_cur, v_cur, kpm_cur, False, sm_scale,
                                interpret, dropout_rate=rate, seed=sj)
        if causal:
            valid = src < idx          # strictly-past chunk
            lse_j = jnp.where(valid, lse_j, NEG_BIG)
        o_acc, lse_acc = _combine(o_acc, lse_acc, o_j, lse_j)
        return (k_cur, v_cur, kpm_cur, o_acc, lse_acc), None

    if P > 1:
        (_, _, _, o_acc, lse_acc), _ = jax.lax.scan(
            step, (k, v, kpm, o_acc, lse_acc), jnp.arange(1, P))
    return o_acc.astype(q.dtype), lse_acc


def _ring_bwd_impl(res, do, axis_name, causal, sm_scale, interpret, rate):
    q, k, v, kpm, seed, o, lse = res
    P = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)

    # diagonal chunk
    dq, dk0, dv0, _ = _flash_bwd(
        (q, k, v, kpm,
         _chunk_seed(seed, idx) if rate > 0.0 else seed, o, lse),
        do, causal, sm_scale, interpret, dropout_rate=rate)
    dq = dq.astype(jnp.float32)
    dk_acc = dk0.astype(jnp.float32)
    dv_acc = dv0.astype(jnp.float32)

    def step(carry, j):
        k_cur, v_cur, kpm_cur, dk_cur, dv_cur, dq = carry
        # rotate k/v (+ their key mask) and grad accumulators together
        k_cur = _rot(k_cur, axis_name, P)
        v_cur = _rot(v_cur, axis_name, P)
        if kpm_cur is not None:
            kpm_cur = _rot(kpm_cur, axis_name, P)
        dk_cur = _rot(dk_cur, axis_name, P)
        dv_cur = _rot(dv_cur, axis_name, P)
        src = (idx - j) % P
        sj = _chunk_seed(seed, src) if rate > 0.0 else seed
        dq_j, dk_j, dv_j, _ = _flash_bwd(
            (q, k_cur, v_cur, kpm_cur, sj, o, lse), do, False, sm_scale,
            interpret, dropout_rate=rate)
        if causal:
            valid = (src < idx).astype(jnp.float32)
            dq_j = dq_j * valid
            dk_j = dk_j * valid
            dv_j = dv_j * valid
        dq = dq + dq_j.astype(jnp.float32)
        dk_cur = dk_cur + dk_j.astype(jnp.float32)
        dv_cur = dv_cur + dv_j.astype(jnp.float32)
        return (k_cur, v_cur, kpm_cur, dk_cur, dv_cur, dq), None

    if P > 1:
        (_, _, _, dk_acc, dv_acc, dq), _ = jax.lax.scan(
            step, (k, v, kpm, dk_acc, dv_acc, dq), jnp.arange(1, P))
        # one final rotation completes the cycle: each (dk, dv) buffer
        # returns to the device owning that chunk
        dk_acc = _rot(dk_acc, axis_name, P)
        dv_acc = _rot(dv_acc, axis_name, P)
    return dq.astype(q.dtype), dk_acc.astype(k.dtype), \
        dv_acc.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _ring_attention(q, k, v, seed, has_kpm, axis_name, causal, sm_scale,
                    interpret, rate):
    kpm, seed = seed if has_kpm else (None, seed)
    o, _ = _ring_fwd_impl(q, k, v, kpm, seed, axis_name, causal, sm_scale,
                          interpret, rate)
    return o


def _ring_attention_fwd(q, k, v, seed, has_kpm, axis_name, causal,
                        sm_scale, interpret, rate):
    kpm, seed = seed if has_kpm else (None, seed)
    o, lse = _ring_fwd_impl(q, k, v, kpm, seed, axis_name, causal,
                            sm_scale, interpret, rate)
    return o, (q, k, v, kpm, seed, o, lse)


def _ring_attention_bwd(has_kpm, axis_name, causal, sm_scale, interpret,
                        rate, res, g):
    dq, dk, dv = _ring_bwd_impl(res, g, axis_name, causal, sm_scale,
                                interpret, rate)
    return dq, dk, dv, ((None, None) if has_kpm else None)


_ring_attention.defvjp(_ring_attention_fwd, _ring_attention_bwd)


def ring_attention(q, k, v, axis_name: str = "seq", causal: bool = False,
                   sm_scale: Optional[float] = None,
                   dropout_rate: float = 0.0, dropout_rng=None,
                   key_padding_mask=None,
                   interpret: Optional[bool] = None):
    """Sequence-parallel flash attention over ``axis_name``.

    Call INSIDE ``shard_map`` with ``axis_name`` manual; q/k/v are this
    device's sequence shard, shape (batch, heads, seq_local, head_dim)
    with identical seq_local on every shard (global seq = P * seq_local,
    shard i owning positions [i*seq_local, (i+1)*seq_local)).

    ``key_padding_mask``: optional *additive* (B, 1, 1, seq_local) mask
    for this shard's keys (BERT padding); it rotates around the ring
    with its K/V chunk.
    """
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(q.shape[-1])
    if interpret is None:
        interpret = not _use_pallas()
    dropout_rate = float(dropout_rate)
    if dropout_rate > 0.0:
        assert dropout_rng is not None, \
            "ring_attention: dropout_rate > 0 requires dropout_rng"
        seed = dropout_seed_from_rng(dropout_rng)
    else:
        seed = jnp.zeros((1, 1), jnp.int32)
    if key_padding_mask is not None:
        return _ring_attention(q, k, v, (key_padding_mask, seed), True,
                               axis_name, causal, float(sm_scale),
                               interpret, dropout_rate)
    return _ring_attention(q, k, v, seed, False, axis_name, causal,
                           float(sm_scale), interpret, dropout_rate)
