"""Pallas paged-attention decode kernel — serve from pages in place.

The paged KV cache (PR 7, ``inference/kv_cache.py``) made serving
*capacity* paged, but the decode step still materialized each row's
full ``max_len``-bounded K/V stripe through
:func:`~deepspeed_tpu.models.gpt2.gather_paged_kv` before running dense
attention — per-step decode bandwidth stayed O(max_len) regardless of
how many tokens were actually in flight. This module is the missing
half of that design (vLLM's PagedAttention, PAPERS.md, fused with the
flash online-softmax core this repo already carries in
``ops/attention/flash.py``): a Pallas TPU kernel that computes decode
attention *directly against the page pool*, so a row at cache position
``p`` reads exactly its ``p // page_size + 1`` live pages — O(live
tokens), not O(max_len).

Design:

- **Grid** ``(batch, kv_heads)``. Each program owns one sequence's
  page walk for one kv head; the ``q_heads / kv_heads`` query rows of
  that head's GQA group ride in the program's q block — K/V pages are
  read once per group, never replicated per q head (llama serves with
  no head expansion).
- **Block tables in SMEM.** The per-slot block tables and cache
  positions enter through ``PrefetchScalarGridSpec`` scalar prefetch,
  so page ids are available to index DMAs before the kernel body runs.
  The page walk is bounded by each row's OWN live page count — the
  kernel never touches reserved-but-unwritten pages.
- **Double-buffered DMA.** K and V page tiles stream
  ``pool[page_id, kv_head]`` → VMEM through 2-deep async-copy buffers
  (``flash.py``'s streaming idiom): page ``i+1``'s copy is issued
  before page ``i`` is consumed — 2 tiles of VMEM per stream at any
  pool size.
- **Online softmax in fp32.** Running (m, l, acc) across the page walk,
  MXU dots take the pool dtype (bf16 in production) with fp32
  accumulation — the flash kernels' precision. Positions past the
  row's cache position AND anything mapped to the reserved null page 0
  are masked *inside* the kernel, so the all-null tables of inactive
  slots produce finite garbage (discarded by the host) rather than
  NaN.

The same kernel runs under ``interpret=True`` on CPU — scalar
prefetch, HBM refs, dynamic-index async copies and semaphores are all
interpretable — which is what makes exact greedy parity against the
gather path tier-1-testable without hardware
(tests/unit/test_paged_attention.py).

Compiled-TPU legality: Mosaic requires the DMA tile's lane (minor) dim
to be 128-aligned; the streamed tile is ``(page_size, head_dim)``, so
the compiled path needs ``head_dim % 128 == 0`` (plus a sublane-tile
page size). :func:`paged_decode_supported` is the one predicate the
serving engine consults; unsupported geometries fall back to the
gather path with a one-line log (see docs/inference.md's fallback
matrix) — the gather path remains the numerics oracle either way.
"""

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from deepspeed_tpu.ops.attention.flash import NEG_INF

__all__ = ["paged_decode_attention", "paged_decode_reference",
           "paged_decode_supported", "decode_read_bytes",
           "live_pages", "dequantize_pool", "quantize_kv"]


def live_pages(cache_position, page_size: int):
    """Pages a row at ``cache_position`` (its just-written token's
    position) actually reads: positions ``0..cache_position`` span
    ``cache_position // page_size + 1`` pages. Works on ints and
    arrays."""
    return cache_position // page_size + 1


def paged_decode_supported(page_size: int, head_dim: int,
                           dtype=jnp.bfloat16,
                           backend: Optional[str] = None
                           ) -> Tuple[bool, str]:
    """Can the Pallas decode kernel run for this cache geometry on this
    backend? Returns ``(ok, reason)`` — the one predicate the serving
    engine consults before compiling the paged decode program.

    Off-TPU the kernel runs in interpret mode (pure jax semantics, no
    layout constraints) — always supported. On TPU the DMA tile is
    ``(page_size, head_dim)``: Mosaic needs the lane dim 128-aligned
    (``head_dim % 128``) and the sublane dim a full tile
    (8 fp32 / 16 bf16 / 32 int8 rows), so small pages or narrow heads
    fall back to the gather path.
    """
    if pltpu is None:
        return False, "pallas tpu backend unavailable"
    if backend is None:
        try:
            backend = jax.default_backend()
        except Exception:
            backend = "cpu"
    if backend != "tpu":
        return True, "interpret mode (CPU oracle path)"
    if head_dim % 128 != 0:
        return False, (f"head_dim {head_dim} not a multiple of 128 "
                       "(DMA lane dim)")
    itemsize = jnp.dtype(dtype).itemsize
    sublane = {1: 32, 2: 16}.get(itemsize, 8)
    if page_size % sublane != 0:
        return False, (f"page_size {page_size} not a multiple of the "
                       f"{sublane}-row sublane tile for "
                       f"{jnp.dtype(dtype).name}")
    return True, "compiled pallas kernel"


def decode_read_bytes(cache_positions: Sequence[int], page_size: int,
                      pages_per_seq: int, kv_heads: int, head_dim: int,
                      dtype_bytes: int = 2, scale_blocks: int = 0):
    """Modeled K+V bytes one decode step reads from the pool, paged
    kernel vs gather stripe — the ``paged_decode_bytes`` bench row's
    cost model (mfu_cost_model pattern: analytic accounting that the
    compiled-HLO audit cross-checks structurally).

    The kernel reads each row's live pages once per layer:
    ``live_pages * page_size * kv_heads * head_dim`` K plus the same V.
    The gather fallback materializes the full ``pages_per_seq``-wide
    stripe per row regardless of how short the row is. Returns
    ``(pallas_bytes, gather_bytes)`` per layer for the whole batch.

    For the int8 pool pass ``dtype_bytes=1`` and
    ``scale_blocks=spec.scale_blocks``: each token row also streams its
    per-row fp32 scales (K and V), the ``quant_serving_bytes`` KV lever.
    """
    positions = [int(p) for p in cache_positions]
    per_tok = kv_heads * head_dim * dtype_bytes * 2          # K and V
    per_tok += kv_heads * scale_blocks * 4 * 2               # fp32 scales
    pallas = sum(live_pages(p, page_size) * page_size * per_tok
                 for p in positions)
    gather = len(positions) * pages_per_seq * page_size * per_tok
    return pallas, gather


# --------------------------------------------------------------------- #
# reference (oracle / fallback) — the gather path's math, kept here so
# kernel tests can pin parity without importing a model family
# --------------------------------------------------------------------- #
def quantize_kv(x, scale_blocks: int = 1):
    """Symmetric int8 absmax quantization of new K/V values per token
    row: ``x`` (..., hd) float -> (q (..., hd) int8, scales (..., nb)
    fp32) with ``nb = scale_blocks`` blocks along head_dim. The inverse
    of :func:`dequantize_pool`'s math — the models' paged write path
    quantizes each appended row with this before scattering into the
    int8 pool (EQuARX: the bytes at rest are int8, attention math stays
    fp32)."""
    hd = x.shape[-1]
    nb = max(int(scale_blocks), 1)
    blk = hd // nb
    xb = x.astype(jnp.float32).reshape(x.shape[:-1] + (nb, blk))
    absmax = jnp.max(jnp.abs(xb), axis=-1)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(xb / scale[..., None]), -127, 127)
    return (q.reshape(x.shape).astype(jnp.int8),
            scale.astype(jnp.float32))


def dequantize_pool(pool, scales):
    """fp32 view of an int8 page pool: ``pool`` (..., page_size, hd)
    int8, ``scales`` (..., page_size, nb) fp32 per-token-row absmax
    scales with nb dividing hd. The gather/oracle-path dequant — the
    Pallas kernel applies the same math per streamed tile in VMEM."""
    hd = pool.shape[-1]
    nb = scales.shape[-1]
    s = jnp.repeat(scales, hd // nb, axis=-1)
    return pool.astype(jnp.float32) * s


def paged_decode_reference(q, kpool, vpool, block_tables, cache_position,
                           sm_scale: Optional[float] = None,
                           k_scales=None, v_scales=None):
    """Dense oracle: gather each row's full logical stripe from the
    pool, mask positions past ``cache_position``, softmax in fp32 —
    exactly what the models' gather fallback computes for a seq-1
    query. q: (B, H, hd); pools: (num_pages, kv_heads, page_size, hd);
    block_tables: (B, P) int32; cache_position: (B,) int32 (position of
    the already-written current token). With ``k_scales``/``v_scales``
    ((num_pages, kv_heads, page_size, nb) fp32) the pools are int8 and
    dequantized before the gather. Returns (B, H, hd)."""
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(q.shape[-1])
    if k_scales is not None:
        kpool = dequantize_pool(kpool, k_scales)
        vpool = dequantize_pool(vpool, v_scales)
    B, H, hd = q.shape
    _, KH, ps, _ = kpool.shape
    kc = kpool[block_tables].transpose(0, 2, 1, 3, 4).reshape(
        B, KH, -1, hd)
    vc = vpool[block_tables].transpose(0, 2, 1, 3, 4).reshape(
        B, KH, -1, hd)
    qg = q.reshape(B, KH, H // KH, hd)
    s = jnp.einsum("bkgd,bkld->bkgl", qg.astype(jnp.float32),
                   kc.astype(jnp.float32)) * sm_scale
    k_idx = jnp.arange(kc.shape[2])
    mask = k_idx[None, :] <= cache_position[:, None]        # (B, L)
    s = jnp.where(mask[:, None, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    ctx = jnp.einsum("bkgl,bkld->bkgd", p, vc.astype(jnp.float32))
    return ctx.reshape(B, H, hd).astype(q.dtype)


# --------------------------------------------------------------------- #
# the kernel
# --------------------------------------------------------------------- #
def _decode_kernel(tables_ref, pos_ref, q_ref, k_ref, v_ref, *rest,
                   sm_scale, page_size, quantized):
    """One (sequence, kv head) program: walk the row's live pages from
    the pool via double-buffered DMA, online-softmax the GQA group's
    queries against each streamed page tile.

    ``quantized`` adds two operand refs (the per-token-row fp32 scale
    pools) and two scale scratch buffers: each walked page streams its
    int8 K/V tile AND its (page_size, nb) scale tile, and the dequant
    happens right after the DMA'd tile lands in VMEM — the int8 bytes
    are what crossed HBM, the math below (scores, online softmax,
    accumulation) stays fp32 exactly like the dense-pool path."""
    if quantized:
        (ks_ref, vs_ref, o_ref, kbuf, vbuf, ksbuf, vsbuf,
         ksem, vsem, kssem, vssem) = rest
    else:
        o_ref, kbuf, vbuf, ksem, vsem = rest
    b = pl.program_id(0)
    kh = pl.program_id(1)
    pos = pos_ref[b]
    # positions 0..pos are attended (this call's token was written
    # BEFORE attention — write_paged_kv_cache runs first), spanning
    # exactly pos // page_size + 1 pages: the O(live tokens) bound
    num_pg = pos // page_size + 1
    q = q_ref[0, 0]                                   # (G, hd)
    if quantized:
        q = q.astype(jnp.float32)   # dequantized tiles are fp32

    def _start(i):
        page = tables_ref[b, i]
        slot = jax.lax.rem(i, 2)
        pltpu.make_async_copy(k_ref.at[page, kh], kbuf.at[slot],
                              ksem.at[slot]).start()
        pltpu.make_async_copy(v_ref.at[page, kh], vbuf.at[slot],
                              vsem.at[slot]).start()
        if quantized:
            pltpu.make_async_copy(ks_ref.at[page, kh], ksbuf.at[slot],
                                  kssem.at[slot]).start()
            pltpu.make_async_copy(vs_ref.at[page, kh], vsbuf.at[slot],
                                  vssem.at[slot]).start()

    _start(0)                                         # num_pg >= 1 always

    def body(i, carry):
        m, l, acc = carry

        @pl.when(i + 1 < num_pg)
        def _prefetch_next():
            _start(i + 1)
        page = tables_ref[b, i]
        slot = jax.lax.rem(i, 2)
        pltpu.make_async_copy(k_ref.at[page, kh], kbuf.at[slot],
                              ksem.at[slot]).wait()
        pltpu.make_async_copy(v_ref.at[page, kh], vbuf.at[slot],
                              vsem.at[slot]).wait()
        kt = kbuf[slot]                               # (page_size, hd)
        vt = vbuf[slot]
        if quantized:
            pltpu.make_async_copy(ks_ref.at[page, kh], ksbuf.at[slot],
                                  kssem.at[slot]).wait()
            pltpu.make_async_copy(vs_ref.at[page, kh], vsbuf.at[slot],
                                  vssem.at[slot]).wait()
            hd = kt.shape[-1]
            nb = ksbuf.shape[-1]
            blk = hd // nb
            # per-token-row blockwise dequant of the landed tile:
            # (ps, hd) int8 * (ps, nb) scales broadcast per block
            kt = (kt.astype(jnp.float32).reshape(page_size, nb, blk)
                  * ksbuf[slot][:, :, None]).reshape(page_size, hd)
            vt = (vt.astype(jnp.float32).reshape(page_size, nb, blk)
                  * vsbuf[slot][:, :, None]).reshape(page_size, hd)
        s = jax.lax.dot_general(
            q, kt, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale   # (G, ps)
        # in-kernel masking: positions past the row's cache position,
        # and anything the table maps to the reserved null page 0 (the
        # all-null tables of inactive slots) — finite garbage out,
        # never NaN
        offs = i * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        valid = (offs <= pos) & (page != 0)
        s = jnp.where(valid, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        # a fully-masked tile leaves m_new at NEG_INF and p at
        # exp(0) = 1 — re-mask so masked positions never reach l/acc
        p = jnp.where(valid, p, 0.0)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(vt.dtype), vt, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    G, hd = q.shape
    m0 = jnp.full((G,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((G,), jnp.float32)
    acc0 = jnp.zeros((G, hd), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_pg, body, (m0, l0, acc0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0, 0] = (acc / l_safe[:, None]).astype(o_ref.dtype)


def _use_pallas():
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _compiler_params(interpret):
    if pltpu is None or interpret:
        return None
    # 0.4.x spells it TPUCompilerParams; newer releases CompilerParams
    cls = getattr(pltpu, "CompilerParams", None) or \
        getattr(pltpu, "TPUCompilerParams", None)
    if cls is None:                                   # pragma: no cover
        return None
    # batch programs are independent; the kv-head dim drives the DMA
    # sequence and stays arbitrary
    return cls(dimension_semantics=("parallel", "arbitrary"))


def _paged_decode_pallas(q, kpool, vpool, scales, block_tables,
                         cache_position, sm_scale, interpret):
    """Shared pallas_call builder for the dense-pool and int8-pool
    arities; ``scales`` is None or the (k_scales, v_scales) pair."""
    B, H, hd = q.shape
    num_pages, KH, ps, _ = kpool.shape
    G = H // KH
    qg = q.reshape(B, KH, G, hd)
    quantized = scales is not None
    kernel = functools.partial(_decode_kernel, sm_scale=sm_scale,
                               page_size=ps, quantized=quantized)
    in_specs = [
        pl.BlockSpec((1, 1, G, hd), lambda b, k, *_: (b, k, 0, 0)),
        # pools stay pinned in HBM; the kernel DMAs one
        # (page_size, hd) tile per walked page — never the stripe
        pl.BlockSpec(memory_space=pltpu.HBM),
        pl.BlockSpec(memory_space=pltpu.HBM),
    ]
    scratch = [
        pltpu.VMEM((2, ps, hd), kpool.dtype),
        pltpu.VMEM((2, ps, hd), vpool.dtype),
    ]
    operands = [block_tables, cache_position, qg, kpool, vpool]
    if quantized:
        nb = scales[0].shape[-1]
        # scale pools ride in HBM too: one (page_size, nb) fp32 tile
        # DMAs alongside each int8 page tile
        in_specs += [pl.BlockSpec(memory_space=pltpu.HBM),
                     pl.BlockSpec(memory_space=pltpu.HBM)]
        scratch += [pltpu.VMEM((2, ps, nb), jnp.float32),
                    pltpu.VMEM((2, ps, nb), jnp.float32)]
        operands += [scales[0], scales[1]]
    scratch += [pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,))]
    if quantized:
        scratch += [pltpu.SemaphoreType.DMA((2,)),
                    pltpu.SemaphoreType.DMA((2,))]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        # tables + positions prefetch into SMEM: page ids must be
        # available to index the DMAs before the body runs
        num_scalar_prefetch=2,
        grid=(B, KH),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, G, hd),
                               lambda b, k, *_: (b, k, 0, 0)),
        scratch_shapes=scratch,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KH, G, hd), q.dtype),
        interpret=interpret,
        compiler_params=_compiler_params(interpret),
    )(*operands)
    return out.reshape(B, H, hd)


@functools.partial(jax.jit, static_argnames=("sm_scale", "interpret"))
def _paged_decode_call(q, kpool, vpool, block_tables, cache_position,
                       sm_scale, interpret):
    return _paged_decode_pallas(q, kpool, vpool, None, block_tables,
                                cache_position, sm_scale, interpret)


@functools.partial(jax.jit, static_argnames=("sm_scale", "interpret"))
def _paged_decode_call_quant(q, kpool, vpool, k_scales, v_scales,
                             block_tables, cache_position, sm_scale,
                             interpret):
    return _paged_decode_pallas(q, kpool, vpool, (k_scales, v_scales),
                                block_tables, cache_position, sm_scale,
                                interpret)


def paged_decode_attention(q, kpool, vpool, block_tables, cache_position,
                           sm_scale: Optional[float] = None,
                           interpret: Optional[bool] = None,
                           k_scales=None, v_scales=None):
    """Decode attention straight from the page pool — O(live tokens).

    q: ``(B, q_heads, head_dim)`` — ONE query token per row (the seq-1
    decode specialization; q post-RoPE for llama). kpool/vpool:
    ``(num_pages, kv_heads, page_size, head_dim)`` with
    ``q_heads % kv_heads == 0`` (GQA served natively — each group of
    ``q_heads/kv_heads`` query rows shares its kv head's page stream).
    block_tables: ``(B, pages_per_seq)`` int32 (entries past a row's
    reservation = the null page 0). cache_position: ``(B,)`` int32 —
    the position of this call's ALREADY-WRITTEN token; the row attends
    positions ``<= cache_position`` across its
    ``cache_position // page_size + 1`` live pages, and nothing else is
    read from HBM. Returns ``(B, q_heads, head_dim)`` in q's dtype,
    matching the gather path's math (fp32 softmax, masked identically).

    ``k_scales``/``v_scales`` ((num_pages, kv_heads, page_size, nb)
    fp32, both or neither) select the int8-pool arity: the pools are
    int8 payload and each walked page's scale tile streams alongside,
    dequantized in VMEM after the DMA lands (PR 17 — the decode step
    moves ~half the bytes per live token).

    ``interpret=None`` auto-selects: compiled on TPU, interpret mode
    elsewhere (the tier-1 CPU parity path). Callers gate the compiled
    path on :func:`paged_decode_supported`.
    """
    assert q.ndim == 3, f"paged decode takes (B, H, hd) queries, got " \
        f"{q.shape}"
    B, H, hd = q.shape
    KH = kpool.shape[1]
    assert H % KH == 0 and kpool.shape == vpool.shape, (q.shape,
                                                        kpool.shape,
                                                        vpool.shape)
    assert block_tables.shape[0] == B and cache_position.shape == (B,), (
        block_tables.shape, cache_position.shape)
    assert (k_scales is None) == (v_scales is None), \
        "int8 pool needs BOTH k_scales and v_scales"
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(hd)
    if interpret is None:
        interpret = not _use_pallas()
    if k_scales is not None:
        assert k_scales.shape[:3] == kpool.shape[:3] and \
            hd % k_scales.shape[-1] == 0, (k_scales.shape, kpool.shape)
        return _paged_decode_call_quant(
            q, kpool, vpool, k_scales, v_scales,
            block_tables.astype(jnp.int32),
            cache_position.astype(jnp.int32), float(sm_scale),
            bool(interpret))
    return _paged_decode_call(q, kpool, vpool,
                              block_tables.astype(jnp.int32),
                              cache_position.astype(jnp.int32),
                              float(sm_scale), bool(interpret))
