"""Pallas flash attention — the MXU-native core of the transformer stack.

Since PR 11 :func:`flash_attention` is a DISPATCHER: the default path
compiles the ONE mask-parameterized kernel in ``masked_flash.py`` with
a dense/causal BlockMask (same math, same dropout hash, one code path
with the sparse layouts — docs/attention.md). The per-path kernels in
this module remain the numerics oracles behind
``set_attention_options(kernel="flash")``, and this module still owns
the shared machinery (dropout hash, streaming layout, block autotune
table, reference oracle, once-logging).

TPU-native replacement for the reference's fused CUDA attention pipeline
(csrc/transformer/ds_transformer_cuda.cpp Forward :153: QK^T strided GEMM →
launch_attn_softmax → PV) — but O(S) memory instead of materializing the
(S, S) score matrix, which is what buys the long-sequence headroom the
reference gets from block-sparse attention (and more).

Design: online-softmax tiling. Grid = (batch*heads, Sq/block_q); each program
walks K/V blocks with running max/sum in fp32. Backward recomputes the
score tiles (flash-style) in two passes (dq; dk+dv). All dots take bf16
operands with fp32 accumulation (MXU fast path; fp32 converts would halve
the MXU rate and bloat VMEM). Below STREAM_THRESHOLD the per-head K/V
arrays are VMEM-resident; at/above it they stay in HBM pre-tiled and
TRANSPOSED as (row, n_blocks, D, block) and (D, block) tiles stream
through double-buffered async-copy DMA — 2 tiles of VMEM per stream at
any sequence length (S=16k+ trains where the resident design could not
compile). Tiles are transposed because Mosaic requires DMA lane dims to
be 128-aligned, which the block width is and head_dim often is not; the
kernels contract the transposed tiles directly.

Attention dropout runs *inside* the kernel (reference: the fused
softmax-dropout CUDA kernels, csrc/transformer/dropout_kernels.cu +
softmax_kernels.cu): a counter-based hash PRNG keyed on
(seed, batch*head, q_idx, k_idx) regenerates the identical keep-mask in the
forward and both backward kernels without ever materializing an (S, S)
mask. The softmax statistics (m, l, lse) stay un-dropped — dropout masks the
normalized probabilities — so the flash backward's delta = rowsum(dO * O)
identity still holds exactly.

Falls back to a jnp reference implementation off-TPU (same math incl. the
same hash mask, used as the numerics oracle in tests).
"""

import dataclasses
import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from deepspeed_tpu.utils.logging import logger

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30
# dropout-hash finalizer rounds: 2 = lowbias32-quality (default), 1 =
# single multiply-xorshift round (A/B knob BENCH_DROPOUT_HASH1=1 via
# bench.py; same keep statistics, cheaper tile-wide VPU work)
_HASH_FINAL_ROUNDS = 2


@dataclasses.dataclass
class AttentionOptions:
    """Process-wide attention-kernel selection (replaces the old
    ``_FORCE_REFERENCE`` / ``_WARNED_*`` mutable module globals, whose
    state leaked across tests and configs).

    kernel: which implementation :func:`flash_attention` compiles —
      ``"masked"`` (default): the unified mask-parameterized kernel
      (``masked_flash.py``) with a dense/causal BlockMask;
      ``"flash"``: the legacy per-path kernels in this module (kept as
      numerics oracles);
      ``"reference"``: the XLA-fused O(S^2) ``attention_reference``
      path with MXU bf16 operands (A/B knob — at short sequences XLA's
      batched fused attention may beat a Pallas launch grid). Ignored
      (loudly, once) above STREAM_THRESHOLD where O(S^2) is not
      meaningful.
    """
    kernel: str = os.environ.get("DSTPU_ATTENTION_KERNEL", "masked")

    def __post_init__(self):
        assert self.kernel in ("masked", "flash", "reference"), self.kernel


_OPTIONS = AttentionOptions()


def get_attention_options() -> AttentionOptions:
    return _OPTIONS


def set_attention_options(**kw) -> AttentionOptions:
    """Update kernel-selection knobs; returns the PREVIOUS options so
    callers (tests, bench A/B) can restore them."""
    global _OPTIONS
    old = _OPTIONS
    _OPTIONS = dataclasses.replace(_OPTIONS, **kw)
    return old


# once-per-(reason, shape) which-path logging lives in utils/logging
# (shared infrastructure); re-exported here because every attention
# fallback logs through it and tests/benches reach it via this module
from deepspeed_tpu.utils.logging import (_ONCE_KEYS, log_once,  # noqa
                                         reset_once_logging)


# --------------------------------------------------------------------- #
# counter-based dropout PRNG (shared by kernels and the jnp oracle)
# --------------------------------------------------------------------- #
def dropout_keep_mask(seed, bh, q_idx, k_idx, seq_k, rate):
    """Stateless keep-mask for attention dropout.

    One lowbias32-style integer hash per (seed, batch*head, q, k)
    coordinate; pure jnp uint32 ops so the *identical* bits regenerate in
    the forward kernel, both backward kernels (which tile the (Sq, Sk)
    plane in different orders), interpret mode, and the dense oracle.
    TPU-native replacement for the reference's stored dropout bitmask
    (csrc/transformer/dropout_kernels.cu) — recompute beats storing O(S^2)
    bits on HBM-bound hardware.

    seed: uint32/int32 scalar; bh: scalar index; q_idx/k_idx: broadcastable
    integer arrays; rate: static python float in (0, 1).
    Returns a boolean array, True = keep.
    """
    del seq_k  # row coordinate gets its own mixing round — no linear
    # q*seq_k+k counter, which would wrap (and alias rows) at seq >= 2^16

    def mix(x):
        x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
        x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
        return x ^ (x >> 16)

    # pass q_idx/k_idx as broadcastable (bq, 1)/(1, bk) VECTORS: the row
    # round then costs O(bq), and only the final round runs on the full
    # (bq, bk) tile
    row = mix(q_idx.astype(jnp.uint32)
              ^ (jnp.uint32(bh) * jnp.uint32(0x9E3779B9))
              ^ seed.astype(jnp.uint32))
    x = row ^ k_idx.astype(jnp.uint32)
    if _HASH_FINAL_ROUNDS == 1:
        # cheaper tile-wide finalizer (half the multiplies): one
        # multiply-xorshift round on top of an already-mixed row hash.
        # Keep-rate statistics and fwd/bwd bit-consistency are unchanged
        # (tests pin both); only the mask pattern differs. A/B knob —
        # promote to default if the hardware ladder shows dropout-MFU
        # gains without convergence drift.
        x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
        x = x ^ (x >> 15)
    else:
        x = mix(x)
    keep_thresh = min(int(round((1.0 - rate) * 2.0**32)), 2**32 - 1)
    return x < jnp.uint32(keep_thresh)


def dropout_mask_reference(seed, b, h, sq, sk, rate):
    """Materialized (B, H, Sq, Sk) keep-mask — the oracle view of what the
    kernels regenerate tile-by-tile. Test/small-shape use only."""
    bh = jnp.arange(b * h, dtype=jnp.uint32)[:, None, None]
    q_idx = jnp.arange(sq, dtype=jnp.uint32)[None, :, None]
    k_idx = jnp.arange(sk, dtype=jnp.uint32)[None, None, :]
    keep = dropout_keep_mask(jnp.asarray(seed).reshape(()), bh, q_idx, k_idx,
                             sk, rate)
    return keep.reshape(b, h, sq, sk)


# --------------------------------------------------------------------- #
# reference (oracle / fallback) implementation
# --------------------------------------------------------------------- #
def attention_reference(q, k, v, mask=None, causal=False,
                        sm_scale: Optional[float] = None,
                        dropout_rate: float = 0.0, dropout_seed=None,
                        mxu_bf16: bool = False):
    """Plain jnp attention. q,k,v: (B, H, S, D); mask: additive, broadcastable
    to (B, H, Sq, Sk). With dropout_rate > 0 applies the same hash keep-mask
    the Pallas kernels use (seed: scalar). GQA: k/v may carry H/G heads.
    mxu_bf16: keep MXU operands in the input dtype with fp32 accumulation
    (the Pallas kernels' precision) instead of the oracle's fp32 operands
    — used when this path serves as a PERFORMANCE alternative
    (kernel="reference"), not as the accuracy oracle."""
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(q.shape[-1])
    if k.shape[1] != q.shape[1]:
        rep = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    if mxu_bf16:
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                       preferred_element_type=jnp.float32) * sm_scale
    else:
        s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * sm_scale
    if mask is not None:
        s = s + mask.astype(jnp.float32)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        idx_q = jnp.arange(sq)[:, None]
        idx_k = jnp.arange(sk)[None, :]
        s = jnp.where(idx_q >= idx_k, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_rate > 0.0:
        b_, h_, sq_, sk_ = p.shape
        keep = dropout_mask_reference(dropout_seed, b_, h_, sq_, sk_,
                                      dropout_rate)
        p = jnp.where(keep, p, 0.0) / (1.0 - dropout_rate)
    if mxu_bf16:
        return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                          preferred_element_type=jnp.float32
                          ).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)
                      ).astype(q.dtype)


# --------------------------------------------------------------------- #
# pallas kernels
# --------------------------------------------------------------------- #
def _tile_idx(q0, k0, block_q, block_k):
    # (bq, 1) and (1, bk) VECTORS, not full tiles: every consumer (the
    # causal compare and the dropout hash) broadcasts, and the hash's
    # row-mixing round then runs on bq elements instead of bq*bk — the
    # dominant share of the in-kernel dropout tax (VERDICT r3 #3). The
    # generated bits are identical to the full-tile form.
    q_idx = q0 + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
    k_idx = k0 + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
    return q_idx, k_idx


def _unpack_refs(refs, has_mask, has_seed, n_out):
    """Kernel ref layout: q, k, v, [mask], [seed], *outs."""
    q_ref, k_ref, v_ref = refs[:3]
    i = 3
    mask_ref = refs[i] if has_mask else None
    i += int(has_mask)
    seed_ref = refs[i] if has_seed else None
    i += int(has_seed)
    outs = refs[i:]
    assert len(outs) == n_out, (len(refs), has_mask, has_seed, n_out)
    return q_ref, k_ref, v_ref, mask_ref, seed_ref, outs


def _stream_layout(x, block):
    # the one place that defines the streamed-operand HBM layout the
    # kernel-side DMA (_stream_kv_start) depends on:
    # (rows, S, D) -> (rows, n_blocks, D, block), transposed per block
    rows, s, d = x.shape
    return x.reshape(rows, s // block, block, d).swapaxes(2, 3)


def _stream_kv_start(k_ref, v_ref, kbuf, vbuf, ksem, vsem, i, row):
    # k_ref/v_ref are FULL (b*h, n_blocks, D, block) arrays pinned to HBM,
    # stored TRANSPOSED per block. TPU Pallas requires non-VMEM refs
    # unblocked (trivial index map), so the program's row is selected here
    # in the DMA, not via BlockSpec; and Mosaic requires every DMA slice
    # lane dim to be a multiple of 128 — head_dim 64 can never be the lane
    # dim of a streamed tile, but the 128/256/512-wide block can. The
    # kernels contract against the transposed tiles directly (the MXU
    # takes either operand orientation).
    slot = jax.lax.rem(i, 2)
    pltpu.make_async_copy(k_ref.at[row, i], kbuf.at[slot],
                          ksem.at[slot]).start()
    pltpu.make_async_copy(v_ref.at[row, i], vbuf.at[slot],
                          vsem.at[slot]).start()


def _stream_kv_wait(k_ref, v_ref, kbuf, vbuf, ksem, vsem, i, row):
    slot = jax.lax.rem(i, 2)
    pltpu.make_async_copy(k_ref.at[row, i], kbuf.at[slot],
                          ksem.at[slot]).wait()
    pltpu.make_async_copy(v_ref.at[row, i], vbuf.at[slot],
                          vsem.at[slot]).wait()
    return kbuf[slot], vbuf[slot]


def _fwd_kernel(*refs, sm_scale, block_k, causal, seq_k, block_q,
                has_mask, dropout_rate, stream=False, q_per_kv=1):
    if stream:
        refs, (kbuf, vbuf, ksem, vsem) = refs[:-4], refs[-4:]
    q_ref, k_ref, v_ref, mask_ref, seed_ref, (o_ref, lse_ref) = \
        _unpack_refs(refs, has_mask, dropout_rate > 0.0, 2)
    bh = pl.program_id(0)
    qb = pl.program_id(1)
    # GQA: q_per_kv consecutive q-head rows share one kv row (the dropout
    # hash stays keyed on the q row, matching repeat-KV semantics)
    kv_row = bh // q_per_kv if q_per_kv > 1 else bh
    # MXU fast path: bf16 operands, fp32 accumulation — converting K/V to
    # fp32 both halves the MXU rate and makes Mosaic keep full fp32 K/V
    # copies in VMEM (the S>=8k scoped-vmem blowup). Scale is applied to
    # the fp32 scores instead of Q (mathematically identical).
    q = q_ref[0]                                          # (bq, d) bf16
    d = q.shape[-1]

    if causal:
        # process K blocks up to (and including) the diagonal
        num_kb = (qb * block_q + block_q + block_k - 1) // block_k
    else:
        num_kb = seq_k // block_k

    if stream:
        @pl.when(num_kb > 0)
        def _prologue():
            _stream_kv_start(k_ref, v_ref, kbuf, vbuf, ksem, vsem, 0,
                             kv_row)

    def body(i, carry):
        m, l, acc = carry
        if stream:
            @pl.when(i + 1 < num_kb)
            def _prefetch_next():
                _stream_kv_start(k_ref, v_ref, kbuf, vbuf, ksem, vsem,
                                 i + 1, kv_row)
            # streamed tiles arrive transposed: k, v are (D, block)
            k, v = _stream_kv_wait(k_ref, v_ref, kbuf, vbuf, ksem, vsem,
                                   i, kv_row)
        else:
            k = k_ref[0, pl.ds(i * block_k, block_k), :]
            v = v_ref[0, pl.ds(i * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (0 if stream else 1,)), ((), ())),
            preferred_element_type=jnp.float32)
        s = s * sm_scale
        if mask_ref is not None:
            s += mask_ref[0, 0, pl.ds(i * block_k, block_k)][None, :]
        if causal or dropout_rate > 0.0:
            q_idx, k_idx = _tile_idx(qb * block_q, i * block_k,
                                     block_q, block_k)
        if causal:
            s = jnp.where(q_idx >= k_idx, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        # softmax stats (l, lse) use the un-dropped p; dropout masks only
        # the PV accumulation (normalize-then-drop, like the reference)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        if dropout_rate > 0.0:
            keep = dropout_keep_mask(seed_ref[0, 0], bh, q_idx, k_idx,
                                     seq_k, dropout_rate)
            p = jnp.where(keep, p, 0.0)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (1 if stream else 0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, acc0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = acc / l_safe[:, None]
    if dropout_rate > 0.0:
        out = out * (1.0 / (1.0 - dropout_rate))
    o_ref[0] = out.astype(o_ref.dtype)
    lse_ref[0, :, 0] = m + jnp.log(l_safe)


def _bwd_dq_kernel(*refs, sm_scale, block_k, causal, seq_k, block_q,
                   has_mask, dropout_rate, stream=False, q_per_kv=1):
    if stream:
        refs, (kbuf, vbuf, ksem, vsem) = refs[:-4], refs[-4:]
    (q_ref, k_ref, v_ref, mask_ref, seed_ref,
     (do_ref, lse_ref, delta_ref, dq_ref)) = \
        _unpack_refs(refs, has_mask, dropout_rate > 0.0, 4)
    bh = pl.program_id(0)
    qb = pl.program_id(1)
    kv_row = bh // q_per_kv if q_per_kv > 1 else bh
    q = q_ref[0]                                           # (bq, d) bf16
    do = do_ref[0]
    lse = lse_ref[0, :, 0]
    delta = delta_ref[0, :, 0]
    d = q.shape[-1]

    if causal:
        num_kb = (qb * block_q + block_q + block_k - 1) // block_k
    else:
        num_kb = seq_k // block_k

    if stream:
        @pl.when(num_kb > 0)
        def _prologue():
            _stream_kv_start(k_ref, v_ref, kbuf, vbuf, ksem, vsem, 0,
                             kv_row)

    def body(i, dq):
        if stream:
            @pl.when(i + 1 < num_kb)
            def _prefetch_next():
                _stream_kv_start(k_ref, v_ref, kbuf, vbuf, ksem, vsem,
                                 i + 1, kv_row)
            # streamed tiles arrive transposed: k, v are (D, block)
            k, v = _stream_kv_wait(k_ref, v_ref, kbuf, vbuf, ksem, vsem,
                                   i, kv_row)
        else:
            k = k_ref[0, pl.ds(i * block_k, block_k), :]
            v = v_ref[0, pl.ds(i * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (0 if stream else 1,)), ((), ())),
            preferred_element_type=jnp.float32)
        s = s * sm_scale
        if mask_ref is not None:
            s += mask_ref[0, 0, pl.ds(i * block_k, block_k)][None, :]
        if causal or dropout_rate > 0.0:
            q_idx, k_idx = _tile_idx(qb * block_q, i * block_k,
                                     block_q, block_k)
        if causal:
            s = jnp.where(q_idx >= k_idx, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])                      # (bq, bk)
        dp = jax.lax.dot_general(
            do, v, (((1,), (0 if stream else 1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            keep = dropout_keep_mask(seed_ref[0, 0], bh, q_idx, k_idx,
                                     seq_k, dropout_rate)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_rate)), 0.0)
        ds = p * (dp - delta[:, None])
        return dq + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (1 if stream else 0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, num_kb, body, jnp.zeros((block_q, d),
                                                      jnp.float32))
    dq_ref[0] = (dq * sm_scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, sm_scale, block_q, causal, seq_q, seq_k, block_k,
                    has_mask, dropout_rate, stream=False):
    if stream:
        refs, (qbuf, dobuf, qsem, dosem) = refs[:-4], refs[-4:]
    (q_ref, k_ref, v_ref, mask_ref, seed_ref,
     (do_ref, lse_ref, delta_ref, dk_ref, dv_ref)) = \
        _unpack_refs(refs, has_mask, dropout_rate > 0.0, 5)
    bh = pl.program_id(0)
    kb = pl.program_id(1)
    k = k_ref[0]                                           # (bk, d) bf16
    v = v_ref[0]
    d = k.shape[-1]

    if causal:
        # only q blocks at/after this k block contribute
        first_qb = (kb * block_k) // block_q
    else:
        first_qb = 0
    num_qb = seq_q // block_q

    if stream:
        @pl.when(num_qb > first_qb)
        def _prologue():
            _stream_kv_start(q_ref, do_ref, qbuf, dobuf, qsem, dosem,
                             first_qb, bh)

    def body(i, carry):
        dk, dv = carry
        if stream:
            @pl.when(i + 1 < num_qb)
            def _prefetch_next():
                _stream_kv_start(q_ref, do_ref, qbuf, dobuf, qsem, dosem,
                                 i + 1, bh)
            # streamed tiles arrive transposed: q, do are (D, block_q)
            q, do = _stream_kv_wait(q_ref, do_ref, qbuf, dobuf, qsem,
                                    dosem, i, bh)
        else:
            q = q_ref[0, pl.ds(i * block_q, block_q), :]
            do = do_ref[0, pl.ds(i * block_q, block_q), :]
        lse = lse_ref[0, pl.ds(i * block_q, block_q), 0]
        delta = delta_ref[0, pl.ds(i * block_q, block_q), 0]
        s = jax.lax.dot_general(
            q, k, (((0 if stream else 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # (bq, bk)
        s = s * sm_scale
        if mask_ref is not None:
            s += mask_ref[0, 0, pl.ds(kb * block_k, block_k)][None, :]
        if causal or dropout_rate > 0.0:
            q_idx, k_idx = _tile_idx(i * block_q, kb * block_k,
                                     block_q, block_k)
        if causal:
            s = jnp.where(q_idx >= k_idx, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])                      # (bq, bk)
        dp = jax.lax.dot_general(
            do, v, (((0 if stream else 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # (bq, bk)
        if dropout_rate > 0.0:
            keep = dropout_keep_mask(seed_ref[0, 0], bh, q_idx, k_idx,
                                     seq_k, dropout_rate)
            inv_kp = 1.0 / (1.0 - dropout_rate)
            pd = jnp.where(keep, p * inv_kp, 0.0)
            dp = jnp.where(keep, dp * inv_kp, 0.0)
        else:
            pd = p
        dv_new = dv + jax.lax.dot_general(
            pd.astype(do.dtype), do,
            (((0,), (1 if stream else 0,)), ((), ())),
            preferred_element_type=jnp.float32)            # (bk, D)
        ds = p * (dp - delta[:, None])
        dk_new = dk + jax.lax.dot_general(
            ds.astype(q.dtype), q,
            (((0,), (1 if stream else 0,)), ((), ())),
            preferred_element_type=jnp.float32)            # (bk, D)
        return dk_new, dv_new

    dk0 = jnp.zeros((k.shape[0], d), jnp.float32)
    dv0 = jnp.zeros((k.shape[0], d), jnp.float32)
    dk, dv = jax.lax.fori_loop(first_qb, num_qb, body, (dk0, dv0))
    # dk carries the sm_scale factor (scores were scaled post-dot)
    dk_ref[0] = (dk * sm_scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# --------------------------------------------------------------------- #
# pallas_call wrappers
# --------------------------------------------------------------------- #
def _largest_divisor_block(seq, cap=512):
    # 512 first: measured on v5e (B=8,H=16,S=1024,D=64 fwd+bwd) 512/512 is
    # ~1.2x faster than 256/256 and beats every mixed combination; smaller
    # blocks only when the sequence doesn't divide
    for b in (512, 256, 128, 64, 32, 16):
        if b <= cap and seq % b == 0:
            return b
    return min(seq, cap)


# beyond this sequence length the kernels stream K/V (or q/do in the dkv
# pass) from HBM through double-buffered DMA tiles instead of keeping the
# full per-head arrays resident in VMEM — unbounded S at 2 tiles of VMEM
STREAM_THRESHOLD = 8192


def _compiler_params(interpret, stream):
    if pltpu is None or interpret:
        return None
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "arbitrary"),
        # streaming: XLA stack-allocates one full blocked operand in VMEM
        # at S>=16k; the 16MB default cap is a compiler soft limit, v5e
        # VMEM is 128MB (observed: S=16k bwd needs 33MB)
        **({"vmem_limit_bytes": 100 * 1024 * 1024} if stream else {}))


def _use_stream(seq_q, seq_k):
    # streamed tiles put the block width in the DMA lane dim, which Mosaic
    # requires to be a multiple of 128 — both seqs must 128-divide so
    # _largest_divisor_block picks 128/256/512 blocks; irregular long
    # sequences stay on the resident path with tiny blocks (much slower,
    # and may exceed scoped VMEM at S>=16k — flash_attention warns)
    if seq_q % 128 != 0 or seq_k % 128 != 0:
        if max(seq_q, seq_k) >= STREAM_THRESHOLD:
            log_once(
                ("irregular-stream", seq_q, seq_k),
                f"flash_attention: seq ({seq_q}, {seq_k}) >= "
                f"{STREAM_THRESHOLD} but not divisible by 128 — the "
                "DMA-streaming kernel needs 128-multiple sequences, "
                "so K/V stay VMEM-resident with small blocks (slow, "
                "and may fail to compile at S>=16k). Pad the "
                "sequence to a multiple of 128.", warn=True)
        return False
    return max(seq_q, seq_k) >= STREAM_THRESHOLD


def _block_cap(seq, stream):
    # resident mode keeps full K/V per (batch, head) program in VMEM, so
    # 512-blocks overflow the ~16MB scoped budget at S=8192 (observed
    # v5e: 16.5M > 16M on the bwd); streaming mode holds only 2 tiles,
    # so the big MXU-friendly blocks stay legal at any S
    if stream:
        return 512
    if seq >= 8192:
        return 256
    return 512


# measured block-size table (VERDICT r2 #6: the reference ships a GemmTest
# autotuner, csrc/includes/gemm_test.h:27). tools/autotune_blocks.py sweeps
# (bq, bk) combinations per shape class on the real chip and writes
# block_table.json next to this module; unknown shapes fall back to the
# hand-measured heuristic below. Entries carry:
#   kind: "flash" (default) keyed (seq_q, seq_k, d, stream, gqa)
#         "banded" keyed (seq, fine_block, band_w, causal)
#   device_kind: jax device_kind the entry was measured on. An entry with
#         device_kind applies ONLY on that exact chip generation (a v5p
#         must never consume v5e-tuned blocks); entries without it are a
#         legacy global fallback, used when no exact-device entry matches.
_BLOCK_ENTRIES = None
_BLOCK_TABLE = None      # test hook: when set, overrides entry matching
_FORCE_BLOCKS = None     # (bq, bk) override used by the autotune sweep


def _load_block_entries():
    global _BLOCK_ENTRIES
    if _BLOCK_ENTRIES is None:
        import json
        import os
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "block_table.json")
        try:
            with open(path) as f:
                _BLOCK_ENTRIES = [e for e in json.load(f)
                                  if isinstance(e, dict)]
        except (OSError, ValueError):
            _BLOCK_ENTRIES = []
    return _BLOCK_ENTRIES


def _device_kind():
    try:
        return jax.devices()[0].device_kind
    except Exception:
        return None


def _table_lookup(match):
    """Best matching table entry for the current device: exact
    device_kind match wins; entries without device_kind are the global
    (legacy) fallback; a wrong-device entry never matches."""
    kind = _device_kind()
    fallback = None
    for e in _load_block_entries():
        try:
            # ms <= 0 is an RTT-subtraction artifact from an old sweep
            # harness, never a real measurement — skip it
            if e.get("ms", 1.0) <= 0.0 or not match(e):
                continue
        except (KeyError, TypeError):
            continue
        dk = e.get("device_kind")
        if dk is not None:
            if dk == kind:
                return e
        elif fallback is None:
            fallback = e
    return fallback


def _pick_blocks(seq_q, seq_k, d=None, gqa=1):
    if _FORCE_BLOCKS is not None:
        return _FORCE_BLOCKS
    stream = _use_stream(seq_q, seq_k)
    if d is not None:
        if _BLOCK_TABLE is not None:                    # test hook
            hit = _BLOCK_TABLE.get((seq_q, seq_k, d, stream))
            if hit is not None:
                return hit
        else:
            e = _table_lookup(
                lambda e: e.get("kind", "flash") == "flash"
                and e["seq_q"] == seq_q and e["seq_k"] == seq_k
                and e["d"] == d and bool(e["stream"]) == stream
                and e.get("gqa", 1) == gqa
                and seq_q % e["bq"] == 0 and seq_k % e["bk"] == 0)
            if e is not None:
                return (e["bq"], e["bk"])
    cap = _block_cap(max(seq_q, seq_k), stream)
    return (_largest_divisor_block(seq_q, cap),
            _largest_divisor_block(seq_k, cap))


def lookup_banded_blocks(seq, fine_block, band_w=None, causal=None):
    """Measured walk-tile sizes for the banded sparse kernels
    (ops/sparse_attention/banded.py), or None. band_w/causal narrow the
    match when given; an entry without those fields matches any."""
    def m(e):
        if e.get("kind") != "banded" or e["seq"] != seq or \
                e["fine_block"] != fine_block:
            return False
        if band_w is not None and e.get("band_w") is not None and \
                e["band_w"] != band_w:
            return False
        if causal is not None and e.get("causal") is not None and \
                bool(e["causal"]) != causal:
            return False
        return seq % e["bq"] == 0 and seq % e["bk"] == 0
    e = _table_lookup(m)
    return (e["bq"], e["bk"]) if e is not None else None


def lookup_masked_blocks(seq_q, seq_k, d, stream) -> Optional[int]:
    """Measured SQUARE walk-tile size for the unified masked kernel
    (ops/attention/masked_flash.py), or None. Entries carry
    kind="masked" and a single ``b`` (the CSR walk uses square tiles so
    the mask block granularity is one number)."""
    e = _table_lookup(
        lambda e: e.get("kind") == "masked"
        and e["seq_q"] == seq_q and e["seq_k"] == seq_k and e["d"] == d
        and bool(e["stream"]) == stream
        and seq_q % e["b"] == 0 and seq_k % e["b"] == 0)
    return e["b"] if e is not None else None


def pick_masked_block(seq_q, seq_k, d=None, stream=None) -> int:
    """Walk-tile size for a dense/causal BlockMask: autotune-table hit,
    else the measured-block heuristic with a single logged line per
    unknown shape (the block_table.json contract)."""
    if _FORCE_BLOCKS is not None:
        return _FORCE_BLOCKS[0]
    if stream is None:
        stream = _use_stream(seq_q, seq_k)
    if d is not None:
        hit = lookup_masked_blocks(seq_q, seq_k, d, stream)
        if hit is not None:
            return hit
        log_once(("masked-block", seq_q, seq_k, d, stream),
                 f"masked_flash: no autotuned block for shape "
                 f"(seq_q={seq_q}, seq_k={seq_k}, d={d}, "
                 f"stream={stream}) — using the heuristic walk tile")
    cap = _block_cap(max(seq_q, seq_k), stream)
    for b in (512, 256, 128, 64, 32, 16):
        if b <= cap and seq_q % b == 0 and seq_k % b == 0:
            return b
    return min(seq_q, seq_k, cap)


def _seed_spec():
    # (1, 1) int32 seed broadcast to every program; tiny, lives in VMEM
    return pl.BlockSpec((1, 1), lambda i, j: (0, 0))


def _flash_fwd(q, k, v, mask, causal, sm_scale, interpret,
               dropout_rate=0.0, seed=None):
    b, h, sq, d = q.shape
    hkv = k.shape[1]
    G = h // hkv       # GQA group size (1 = MHA); validated in the API
    sk = k.shape[2]
    bq, bk = _pick_blocks(sq, sk, d, gqa=G)
    assert sq % bq == 0 and sk % bk == 0, (sq, sk, bq, bk)
    qr = q.reshape(b * h, sq, d)
    kr = k.reshape(b * hkv, sk, d)
    vr = v.reshape(b * hkv, sk, d)

    stream = _use_stream(sq, sk)
    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale, block_k=bk,
                               causal=causal, seq_k=sk, block_q=bq,
                               has_mask=mask is not None,
                               dropout_rate=dropout_rate, stream=stream,
                               q_per_kv=G)
    if stream:
        # streamed operands live unblocked in HBM pre-tiled TRANSPOSED
        # to (row, n_blocks, D, block) so each DMA moves whole trailing
        # (D, block) tiles — non-VMEM refs need a trivial index map, and
        # a partial slice of the lane-padded D dim would be illegal
        kr = _stream_layout(kr, bk)
        vr = _stream_layout(vr, bk)
        kv_spec = pl.BlockSpec(memory_space=pltpu.HBM)
    else:
        # q-head row i reads its group's kv row (GQA: i // G)
        kv_spec = pl.BlockSpec((1, sk, d), lambda i, j, G=G: (i // G, 0, 0))
    in_specs = [
        pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0)),
        kv_spec,
        kv_spec,
    ]
    args = [qr, kr, vr]
    if mask is not None:
        # additive key mask (B, 1, 1, Sk) -> (B, 1, Sk); shared across heads
        maskr = mask.reshape(b, 1, sk)
        in_specs.append(pl.BlockSpec((1, 1, sk), lambda i, j: (i // h, 0, 0)))
        args.append(maskr)
    if dropout_rate > 0.0:
        in_specs.append(_seed_spec())
        args.append(seed.reshape(1, 1).astype(jnp.int32))

    out_shape = [
        jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        # trailing singleton keeps the (sublane, lane) tile legal for any bq
        jax.ShapeDtypeStruct((b * h, sq, 1), jnp.float32),
    ]
    out_specs = [
        pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0)),
        pl.BlockSpec((1, bq, 1), lambda i, j: (i, j, 0)),
    ]
    scratch_shapes = []
    if stream:
        scratch_shapes = [
            pltpu.VMEM((2, d, bk), k.dtype),
            pltpu.VMEM((2, d, bk), v.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ]
    compiler_params = _compiler_params(interpret, stream)
    o, lse = pl.pallas_call(
        kernel,
        grid=(b * h, sq // bq),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch_shapes,
        interpret=interpret,
        compiler_params=compiler_params,
    )(*args)
    return o.reshape(b, h, sq, d), lse.reshape(b, h, sq)


def _flash_bwd(res, g, causal, sm_scale, interpret,
               dropout_rate=0.0):
    q, k, v, mask, seed, o, lse = res
    b, h, sq, d = q.shape
    hkv = k.shape[1]
    G = h // hkv
    sk = k.shape[2]
    bq, bk = _pick_blocks(sq, sk, d, gqa=G)
    do = g
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                               # (b,h,sq)

    qr = q.reshape(b * h, sq, d)
    kr = k.reshape(b * hkv, sk, d)
    vr = v.reshape(b * hkv, sk, d)
    dor = do.reshape(b * h, sq, d)
    lser = lse.reshape(b * h, sq, 1)
    deltar = delta.reshape(b * h, sq, 1)

    common = [qr, kr, vr]
    if mask is not None:
        maskr = mask.reshape(b, 1, sk)
    if dropout_rate > 0.0:
        seedr = seed.reshape(1, 1).astype(jnp.int32)

    # ---- dq ----
    stream = _use_stream(sq, sk)
    kernel = functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, block_k=bk,
                               causal=causal, seq_k=sk, block_q=bq,
                               has_mask=mask is not None,
                               dropout_rate=dropout_rate, stream=stream,
                               q_per_kv=G)
    if stream:
        kv_spec = pl.BlockSpec(memory_space=pltpu.HBM)
        args = [qr, _stream_layout(kr, bk), _stream_layout(vr, bk)]
    else:
        kv_spec = pl.BlockSpec((1, sk, d), lambda i, j, G=G: (i // G, 0, 0))
        args = list(common)
    in_specs = [
        pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0)),   # q
        kv_spec,                                            # k
        kv_spec,                                            # v
    ]
    if mask is not None:
        in_specs.append(pl.BlockSpec((1, 1, sk), lambda i, j: (i // h, 0, 0)))
        args.append(maskr)
    if dropout_rate > 0.0:
        in_specs.append(_seed_spec())
        args.append(seedr)
    in_specs += [
        pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0)),   # do
        pl.BlockSpec((1, bq, 1), lambda i, j: (i, j, 0)),   # lse
        pl.BlockSpec((1, bq, 1), lambda i, j: (i, j, 0)),   # delta
    ]
    args += [dor, lser, deltar]
    scratch_shapes = []
    if stream:
        scratch_shapes = [
            pltpu.VMEM((2, d, bk), k.dtype),
            pltpu.VMEM((2, d, bk), v.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ]
    compiler_params = _compiler_params(interpret, stream)
    dq = pl.pallas_call(
        kernel,
        grid=(b * h, sq // bq),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        scratch_shapes=scratch_shapes,
        interpret=interpret,
        compiler_params=compiler_params,
    )(*args)

    # ---- dk, dv ----
    kernel = functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, block_q=bq,
                               causal=causal, seq_q=sq, seq_k=sk, block_k=bk,
                               has_mask=mask is not None,
                               dropout_rate=dropout_rate, stream=stream)
    if stream:
        q_spec = pl.BlockSpec(memory_space=pltpu.HBM)
        qr_s = _stream_layout(qr, bq)
        dor_s = _stream_layout(dor, bq)
        args = [qr_s, kr, vr]
    else:
        q_spec = pl.BlockSpec((1, sq, d), lambda i, j: (i, 0, 0))
        args = list(common)
    in_specs = [
        q_spec,                                             # q (full)
        pl.BlockSpec((1, bk, d), lambda i, j, G=G: (i // G, j, 0)),  # k
        pl.BlockSpec((1, bk, d), lambda i, j, G=G: (i // G, j, 0)),  # v
    ]
    if mask is not None:
        in_specs.append(pl.BlockSpec((1, 1, sk), lambda i, j: (i // h, 0, 0)))
        args.append(maskr)
    if dropout_rate > 0.0:
        in_specs.append(_seed_spec())
        args.append(seedr)
    in_specs += [
        q_spec,                                             # do (full)
        pl.BlockSpec((1, sq, 1), lambda i, j: (i, 0, 0)),   # lse (full)
        pl.BlockSpec((1, sq, 1), lambda i, j: (i, 0, 0)),   # delta (full)
    ]
    args += [dor_s if stream else dor, lser, deltar]
    scratch_shapes = []
    if stream:
        scratch_shapes = [
            pltpu.VMEM((2, d, bq), q.dtype),
            pltpu.VMEM((2, d, bq), do.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ]
    dk, dv = pl.pallas_call(
        kernel,
        grid=(b * h, sk // bk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, bk, d), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            # GQA: keep the per-q-head partials fp32 so the group sum
            # below really accumulates at fp32 (the in-kernel
            # accumulators are fp32 either way)
            jax.ShapeDtypeStruct((b * h, sk, d),
                                 jnp.float32 if G > 1 else k.dtype),
            jax.ShapeDtypeStruct((b * h, sk, d),
                                 jnp.float32 if G > 1 else v.dtype),
        ],
        scratch_shapes=scratch_shapes,
        interpret=interpret,
        compiler_params=compiler_params,
    )(*args)

    dq = dq.reshape(b, h, sq, d)
    if G > 1:
        # fp32 per-q-head partials -> kv-head grads. This materializes
        # G x the final dk/dv in HBM for one fused reduction (simple,
        # never worse than the MHA layout); an in-kernel G-accumulating
        # grid over (b*hkv, sk//bk) would avoid it — future optimization
        dk = dk.reshape(b, hkv, G, sk, d).sum(2).astype(k.dtype)
        dv = dv.reshape(b, hkv, G, sk, d).sum(2).astype(v.dtype)
    else:
        dk = dk.reshape(b, h, sk, d)
        dv = dv.reshape(b, h, sk, d)
    dmask = None if mask is None else jnp.zeros_like(mask)
    return dq, dk, dv, dmask


# --------------------------------------------------------------------- #
# public API
# --------------------------------------------------------------------- #
def _use_pallas():
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# seed rides as a traced (1,1) int32 arg (not static — a per-step seed must
# not trigger recompilation); its cotangent is None, like segment_ids in
# jax's reference flash kernels
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_attention(q, k, v, seed, causal, sm_scale, interpret, rate):
    o, _ = _flash_fwd(q, k, v, None, causal, sm_scale, interpret,
                      dropout_rate=rate, seed=seed)
    return o


def _flash_attention_fwd(q, k, v, seed, causal, sm_scale, interpret, rate):
    o, lse = _flash_fwd(q, k, v, None, causal, sm_scale, interpret,
                        dropout_rate=rate, seed=seed)
    return o, (q, k, v, None, seed, o, lse)


def _flash_attention_bwd(causal, sm_scale, interpret, rate, res, g):
    dq, dk, dv, _ = _flash_bwd(res, g, causal, sm_scale, interpret,
                               dropout_rate=rate)
    return dq, dk, dv, None


_flash_attention.defvjp(_flash_attention_fwd, _flash_attention_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _flash_attention_masked(q, k, v, mask, seed, causal, sm_scale, interpret,
                            rate):
    o, _ = _flash_fwd(q, k, v, mask, causal, sm_scale, interpret,
                      dropout_rate=rate, seed=seed)
    return o


def _flash_attention_masked_fwd(q, k, v, mask, seed, causal, sm_scale,
                                interpret, rate):
    o, lse = _flash_fwd(q, k, v, mask, causal, sm_scale, interpret,
                        dropout_rate=rate, seed=seed)
    return o, (q, k, v, mask, seed, o, lse)


def _flash_attention_masked_bwd(causal, sm_scale, interpret, rate, res, g):
    dq, dk, dv, dmask = _flash_bwd(res, g, causal, sm_scale, interpret,
                                   dropout_rate=rate)
    return dq, dk, dv, dmask, None


_flash_attention_masked.defvjp(_flash_attention_masked_fwd,
                               _flash_attention_masked_bwd)


def dropout_seed_from_rng(rng):
    """Derive the (1,1) int32 kernel seed from a jax PRNG key."""
    return jax.random.randint(rng, (1, 1), minval=-(2**31), maxval=2**31 - 1,
                              dtype=jnp.int32)


def flash_attention(q, k, v, mask=None, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    dropout_rate: float = 0.0,
                    dropout_rng=None,
                    interpret: Optional[bool] = None,
                    force_reference: bool = False):
    """Flash attention with O(S) memory and in-kernel attention dropout.

    q: (batch, heads, seq, head_dim); k, v: (batch, kv_heads, seq_k,
    head_dim) with heads % kv_heads == 0 — kv_heads < heads is
    grouped-query attention (GQA; kv_heads == 1 is MQA), served natively
    by the kernels: each group of heads/kv_heads consecutive q heads
    reads its shared K/V row via the block index map (resident) or the
    DMA row select (streamed) — K/V are never materialized per q head.
    mask: optional *additive* key mask of shape (batch, 1, 1, seq_k)
    (BERT-style padding mask). For 2D masks use the reference path.
    dropout_rate: attention-probability dropout (reference
    attn_dropout_ratio); requires dropout_rng (a jax PRNG key) — pass
    rate 0.0 / rng None for eval.
    """
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(q.shape[-1])
    assert q.shape[1] % k.shape[1] == 0 and k.shape[1] == v.shape[1], (
        "flash_attention: heads must be a multiple of kv_heads",
        q.shape, k.shape, v.shape)
    if interpret is None:
        interpret = not _use_pallas()
    dropout_rate = float(dropout_rate)
    if dropout_rate > 0.0:
        assert dropout_rng is not None, \
            "flash_attention: dropout_rate > 0 requires dropout_rng"
        assert dropout_rate < 1.0, dropout_rate
        seed = dropout_seed_from_rng(dropout_rng)
    else:
        seed = jnp.zeros((1, 1), jnp.int32)
    sq, sk = q.shape[2], k.shape[2]
    force_ref = _OPTIONS.kernel == "reference"
    if force_ref and max(sq, sk) >= STREAM_THRESHOLD:
        # the A/B knob must never silently re-route a long-context
        # measurement onto the O(S^2) path (it would OOM or be
        # mis-attributed as the flash baseline — ADVICE r3 #2): above
        # the streaming threshold the knob is ignored, loudly
        log_once(("ref-stream", sq, sk),
                 f"flash_attention: kernel='reference' ignored at seq "
                 f"({sq}, {sk}) >= {STREAM_THRESHOLD} — the O(S^2) "
                 "reference path is not meaningful (or feasible) in the "
                 "DMA-streaming regime.", warn=True)
        force_ref = False
    if force_reference or force_ref or sq % 16 != 0 or sk % 16 != 0:
        if not force_reference and not force_ref \
                and max(sq, sk) > 2048:
            log_once(("irregular-fallback", sq, sk),
                     f"flash_attention: seq ({sq}, {sk}) not divisible "
                     "by 16 — falling back to the O(S^2)-memory dense "
                     "reference path. Pad the sequence to a multiple of "
                     "16 to use the Pallas kernel.", warn=True)
        return attention_reference(q, k, v, mask=mask, causal=causal,
                                   sm_scale=sm_scale,
                                   dropout_rate=dropout_rate,
                                   dropout_seed=seed.reshape(())
                                   if dropout_rate > 0.0 else None,
                                   # perf knob only: an explicit
                                   # force_reference caller gets the
                                   # fp32 accuracy oracle
                                   mxu_bf16=force_ref
                                   and not force_reference)
    if (max(sq, sk) >= STREAM_THRESHOLD
            and (sq % 128 != 0 or sk % 128 != 0)):
        # long irregular sequences: the resident path may fail to compile
        # at S>=16k (VMEM), so pad to the next 128 multiple and let the
        # DMA-streaming path engage. Padded keys get a NEG_INF additive
        # mask (their probabilities are exactly squashed, so valid rows
        # are unchanged); padded query rows are sliced away, which also
        # zeroes their gradient contribution under autodiff.
        pq, pk = (-sq) % 128, (-sk) % 128
        b = q.shape[0]
        qp = jnp.pad(q, ((0, 0), (0, 0), (0, pq), (0, 0)))
        kp = jnp.pad(k, ((0, 0), (0, 0), (0, pk), (0, 0)))
        vp = jnp.pad(v, ((0, 0), (0, 0), (0, pk), (0, 0)))
        if mask is None and pk == 0:
            mp = None   # query-only padding needs no mask: stay unmasked
        else:
            key_pad = jnp.concatenate(
                [jnp.zeros((b, 1, 1, sk), jnp.float32),
                 jnp.full((b, 1, 1, pk), -1e30, jnp.float32)], axis=-1)
            mp = key_pad if mask is None else (
                jnp.pad(mask.astype(jnp.float32),
                        ((0, 0), (0, 0), (0, 0), (0, pk))) + key_pad)
        out = flash_attention(qp, kp, vp, mask=mp, causal=causal,
                              sm_scale=sm_scale,
                              dropout_rate=dropout_rate,
                              dropout_rng=dropout_rng,
                              interpret=interpret)
        return out[:, :, :sq, :]
    if mask is not None:
        assert mask.ndim == 4 and mask.shape[1] == 1 and \
            mask.shape[2] == 1, \
            f"flash path expects (B,1,1,Sk) additive mask, got {mask.shape}"
    if _OPTIONS.kernel == "masked" and (not causal or sq == sk):
        # default path (PR 11): dense and causal are mask choices of the
        # ONE unified kernel — same math, same dropout hash, one code
        # path with the sparse layouts. (A causal cross-attention with
        # sq != sk has no square-block mask; it stays on the legacy
        # kernels below.)
        return _masked_dense_attention(q, k, v, mask, seed, causal,
                                       float(sm_scale), interpret,
                                       dropout_rate)
    if mask is None:
        return _flash_attention(q, k, v, seed, causal, float(sm_scale),
                                interpret, dropout_rate)
    return _flash_attention_masked(q, k, v, mask, seed, causal,
                                   float(sm_scale), interpret, dropout_rate)


# dense/causal BlockMasks for the unified-kernel route, cached per
# geometry (bounded: shapes are bucketed in practice)
_DENSE_MASK_CACHE = {}
_DENSE_MASK_CAP = 256


def _dense_block_mask(sq, sk, d, causal):
    key = (sq, sk, d, causal, _FORCE_BLOCKS)
    bm = _DENSE_MASK_CACHE.get(key)
    if bm is None:
        from deepspeed_tpu.ops.attention.masked_flash import BlockMask
        block = pick_masked_block(sq, sk, d)
        if len(_DENSE_MASK_CACHE) >= _DENSE_MASK_CAP:
            _DENSE_MASK_CACHE.clear()
        bm = BlockMask.causal(sq, block) if causal else \
            BlockMask.dense(sq, sk, block)
        _DENSE_MASK_CACHE[key] = bm
    return bm


def _masked_dense_attention(q, k, v, mask, seed, causal, sm_scale,
                            interpret, rate):
    from deepspeed_tpu.ops.attention.masked_flash import masked_flash_call
    sq, sk = q.shape[2], k.shape[2]
    b = q.shape[0]
    bm = _dense_block_mask(sq, sk, q.shape[-1], causal)
    # no mask: a dummy kpm + has_kpm=False keeps the hot path free of
    # an all-zero mask operand/add
    kpm = jnp.zeros((b, 1), jnp.float32) if mask is None else \
        mask.reshape(b, sk).astype(jnp.float32)
    return masked_flash_call(q, k, v, kpm, seed, bm, sm_scale, interpret,
                             rate, mask is not None)
