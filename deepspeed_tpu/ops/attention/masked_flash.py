"""ONE mask-parameterized Pallas flash-attention kernel (training side).

The repo grew four separate XLA/Pallas training attention paths — dense
flash (``flash.py``), banded (``sparse_attention/banded.py``), generic
block-sparse (``sparse_attention/blocksparse.py`` v1 +
``blocksparse_v2.py``) and ring — each re-implementing the same
online-softmax core with a different way of deciding *which K/V tiles a
query block touches*. This module collapses the mask-shaped ones into a
single kernel parameterized by a static :class:`BlockMask`: dense,
causal, banded (Longformer-class) and BigBird block-sparse are just mask
choices.

Design (the PR 8 paged-decode recipe applied to training):

- **Scalar-prefetched CSR walk.** The mask compiles to a per-(head,
  query-block) column list delivered through
  ``pltpu.PrefetchScalarGridSpec`` (SMEM), the walk ``blocksparse_v2.py``
  proved: each program walks only its row's nonzero K/V tiles with an
  inner ``fori_loop``, so FLOPs and HBM bytes scale with nonzero blocks,
  not S².
- **Partial tiles mask in registers.** A mask item is FULL (every cell
  computed — the reference's block-level mask semantics) or PARTIAL: an
  elementwise predicate evaluated from iota arithmetic in registers —
  the causal diagonal (``q_idx >= k_idx``) and/or the banded fine
  structure (global prefix + sliding window at the layout's fine block
  granularity). That is what lets a 128-fine-block Longformer layout
  *walk 512-wide MXU tiles* with zero mask bytes from HBM — the banded
  kernel's efficiency with the generic walk's generality.
- **Stream vs resident.** Below ``flash.STREAM_THRESHOLD`` the per-head
  K/V arrays ride as VMEM-resident blocked refs sliced at
  ``cols[i] * block``; at/above it they stay in HBM pre-tiled TRANSPOSED
  as ``(rows, n_blocks, D, block)`` and stream through double-buffered
  ``make_async_copy`` DMA (2 tiles of VMEM at any S; the block width is
  the 128-aligned lane dim).
- **Forward + custom-vjp backward.** dq re-walks the CSR rows; dk/dv
  walk column-major via CSC metadata (one program per key block,
  streaming q/do), flash-style recompute from the stored lse. The
  in-kernel counter-hash dropout (``flash.dropout_keep_mask``) is keyed
  on absolute ``(seed, batch*head, q_idx, k_idx)`` so the forward and
  both backward passes regenerate identical bits — and so a dense
  BlockMask reproduces ``flash.py``'s dropout pattern exactly.
- **GQA native.** ``kv_heads < heads``: each group of consecutive q
  heads reads its shared K/V row via the index map (resident) or the
  DMA row select (streamed); dk/dv accumulate per-q-head fp32 partials
  summed per group outside (the ``flash.py`` scheme).

The IDENTICAL kernel runs ``interpret=True`` on CPU (scalar prefetch,
HBM refs, dynamic-index DMA all interpret), which is what makes parity
against the existing oracles (``attention_reference``,
``block_sparse_attention_reference``) tier-1-testable hardware-free.

Sharding: a pallas_call cannot be auto-partitioned by GSPMD — wrap it
with ``parallel/pallas_shard.sharded_masked_flash`` to run under a mesh
(head-sharded; requires a head-uniform mask).
"""

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from deepspeed_tpu.ops.attention import flash as _flash
from deepspeed_tpu.ops.attention.flash import (NEG_INF, STREAM_THRESHOLD,
                                               _stream_layout,
                                               dropout_keep_mask,
                                               dropout_seed_from_rng)

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

__all__ = ["BlockMask", "masked_flash_attention", "masked_flash_cost",
           "masked_flash_reference"]

# scores below this are structurally masked (several -1e30 additive
# terms may stack; finite bf16 scores never approach it)
VALID_THRESH = -1e28

# partial-tile predicate bits (BlockMask.kinds cell values)
KIND_FULL = 0          # every cell computed (block-level mask semantics)
KIND_CAUSAL = 1        # elementwise q_idx >= k_idx (diagonal tiles)
KIND_BAND = 2          # banded fine structure (global prefix + window)

# test hooks: force the streamed / resident K-V path regardless of
# sequence length (None = auto by STREAM_THRESHOLD)
_FORCE_STREAM: Optional[bool] = None


def _iter_cost_us(blk: int) -> float:
    # same shape as blocksparse._iter_cost_us: a fixed per-iteration
    # floor (loop + DMA re-arm) plus MXU work linear in tile width.
    # Only ratios matter — it picks between walking many fine tiles and
    # fewer coarse tiles whose masked lanes ride register predicates.
    return 2.0 + 22.0 * (blk / 512.0)


class BlockMask:
    """Static block-level attention mask for the unified kernel.

    ``active``: (Hm, nq, nk) bool — which (q-block, k-block) tiles are
    walked; ``kinds``: (Hm, nq, nk) uint8 bitmask over active tiles
    (KIND_CAUSAL / KIND_BAND; 0 = full). ``Hm`` is 1 for head-uniform
    masks (dense, causal, propagated sparse layouts — the common case,
    and the only one the shard_map head wrap accepts) or the full head
    count for per-head layouts. ``band`` carries the static fine
    structure for KIND_BAND tiles:
    ``(fine_block, w, g_r, g_c, causal_clip)`` in fine-block units.

    Instances are immutable, hashable (usable as a ``custom_vjp``
    static argument) and cache their CSR/CSC walk metadata.
    """

    def __init__(self, active: np.ndarray, kinds: np.ndarray, block: int,
                 seq_q: int, seq_k: int,
                 band: Optional[Tuple[int, int, int, int, bool]] = None,
                 fine_block: Optional[int] = None):
        active = np.ascontiguousarray(np.asarray(active, bool))
        kinds = np.ascontiguousarray(np.asarray(kinds, np.uint8))
        assert active.ndim == 3 and active.shape == kinds.shape, (
            active.shape, kinds.shape)
        Hm, nq, nk = active.shape
        assert nq * block == seq_q and nk * block == seq_k, (
            active.shape, block, seq_q, seq_k)
        self.active = active
        self.kinds = kinds
        self.block = int(block)
        self.seq_q = int(seq_q)
        self.seq_k = int(seq_k)
        self.heads = Hm
        self.band = tuple(band) if band is not None else None
        # the layout's original block granularity (== block unless the
        # walk was coarsened); reporting/bench only
        self.fine_block = int(fine_block or block)
        self._key = (self.block, self.seq_q, self.seq_k, self.band,
                     active.tobytes(), kinds.tobytes())
        self._csr = None
        self._csc = None

    # ---------------------------------------------------- constructors
    @classmethod
    def dense(cls, seq_q: int, seq_k: int, block: int) -> "BlockMask":
        nq, nk = seq_q // block, seq_k // block
        return cls(np.ones((1, nq, nk), bool),
                   np.zeros((1, nq, nk), np.uint8), block, seq_q, seq_k)

    @classmethod
    def causal(cls, seq: int, block: int) -> "BlockMask":
        """Square causal mask: tiles below the diagonal are FULL, the
        diagonal tiles apply the elementwise clip, above is skipped."""
        nb = seq // block
        r = np.arange(nb)[:, None]
        c = np.arange(nb)[None, :]
        active = (r >= c)[None]
        kinds = np.where(r == c, KIND_CAUSAL, KIND_FULL
                         ).astype(np.uint8)[None]
        return cls(active, kinds * active, block, seq, seq)

    @classmethod
    def from_layout(cls, layout: np.ndarray, fine_block: int,
                    walk_block: Optional[int] = None) -> "BlockMask":
        """A SparsityConfig layout (H, nb, nb) as a BlockMask.

        Head-identical layouts collapse to one mask head (metadata
        shrinks by H and the head-sharded wrap becomes legal). When the
        realized layout matches the banded predicate
        (``banded.detect_banded`` — BSLongformer-class), the walk is
        COARSENED to a larger MXU-friendly tile and the fine structure
        rides the in-register KIND_BAND predicate; tiles fully inside
        the band stay FULL. Non-banded layouts (BigBird random blocks,
        per-head layouts) walk at the fine block. ``walk_block`` forces
        a specific coarse tile (0 forces the fine walk)."""
        layout = np.asarray(layout)
        assert layout.ndim == 3 and layout.shape[1] == layout.shape[2], \
            layout.shape
        if (layout == layout[:1]).all():
            layout = layout[:1]                  # head-uniform: collapse
        H, nb, _ = layout.shape
        S = nb * fine_block
        fine = layout.astype(bool)

        bp = None
        if H == 1:
            from deepspeed_tpu.ops.sparse_attention.banded import \
                detect_banded
            bp = detect_banded(layout)
        cb = cls._pick_walk_block(fine, fine_block, S, bp, walk_block)
        if cb is None:
            return cls(fine, np.zeros_like(fine, np.uint8), fine_block,
                       S, S, fine_block=fine_block)
        f = cb // fine_block
        nc = nb // f
        sub = fine.reshape(1, nc, f, nc, f)
        coarse_any = sub.any(axis=(2, 4))
        coarse_all = sub.all(axis=(2, 4))
        kinds = np.where(coarse_any & ~coarse_all, KIND_BAND, KIND_FULL
                         ).astype(np.uint8)
        band = (fine_block, bp.w, bp.g_r, bp.g_c, bool(bp.causal))
        return cls(coarse_any, kinds, cb, S, S, band=band,
                   fine_block=fine_block)

    @staticmethod
    def _pick_walk_block(fine, fine_block, S, bp, walk_block):
        """Coarse walk tile (or None for the fine walk): requires a
        banded-describable layout (the predicate must reproduce every
        partial tile's content exactly) and a modeled win over the fine
        walk's per-iteration overhead. An explicitly requested
        walk_block that cannot be honored raises rather than silently
        measuring the fine walk."""
        if walk_block == 0:
            return None
        if bp is None:
            if walk_block is not None:
                raise ValueError(
                    f"walk_block={walk_block} requested but the layout "
                    "is not banded-describable (per-head, random blocks, "
                    "or non-prefix globals) — coarse partial tiles need "
                    "the register band predicate. Use walk_block=0 (fine "
                    "walk) or a banded layout.")
            return None
        if walk_block is not None:
            assert walk_block > fine_block and \
                walk_block % fine_block == 0 and S % walk_block == 0, (
                    walk_block, fine_block, S)
            return walk_block
        nnz_f = int(fine.sum())
        best = None
        for cb in (512, 256):
            if cb <= fine_block or cb % fine_block or S % cb:
                continue
            f = cb // fine_block
            nc = (S // fine_block) // f
            nnz_c = int(fine.reshape(1, nc, f, nc, f).any(
                axis=(2, 4)).sum())
            cost = nnz_c * _iter_cost_us(cb)
            if cost < nnz_f * _iter_cost_us(fine_block) * 0.9 and \
                    (best is None or cost < best[0]):
                best = (cost, cb)
        return best[1] if best else None

    # ------------------------------------------------------- metadata
    @property
    def nq(self) -> int:
        return self.seq_q // self.block

    @property
    def nk(self) -> int:
        return self.seq_k // self.block

    @property
    def nnz(self) -> int:
        return int(self.active.sum())

    @property
    def has_partials(self) -> bool:
        return bool((self.kinds[self.active] != 0).any())

    def csr(self):
        """(offs, cnts, cols, kinds) flattened over rows mh * nq + r."""
        if self._csr is None:
            self._csr = self._runs(self.active, self.kinds)
        return self._csr

    def csc(self):
        """(offs, cnts, rows, kinds) flattened over cols mh * nk + c —
        the column-major walk the dk/dv pass follows."""
        if self._csc is None:
            self._csc = self._runs(
                np.ascontiguousarray(self.active.transpose(0, 2, 1)),
                np.ascontiguousarray(self.kinds.transpose(0, 2, 1)))
        return self._csc

    @staticmethod
    def _runs(active, kinds):
        offs, cnts, idxs, iks = [], [], [], []
        off = 0
        H, nr, _ = active.shape
        for h in range(H):
            for r in range(nr):
                nz = np.nonzero(active[h, r])[0]
                offs.append(off)
                cnts.append(len(nz))
                idxs.extend(int(c) for c in nz)
                iks.extend(int(kinds[h, r, c]) for c in nz)
                off += len(nz)
        return (np.asarray(offs, np.int32), np.asarray(cnts, np.int32),
                np.asarray(idxs if idxs else [0], np.int32),
                np.asarray(iks if iks else [0], np.int32))

    def dense_additive(self) -> np.ndarray:
        """(Hm, Sq, Sk) additive 0 / NEG_INF expansion — the oracle view
        of what the kernel computes tile-by-tile."""
        b = self.block
        keep = np.kron(self.active, np.ones((b, b), bool))
        qi = np.arange(self.seq_q)[:, None]
        ki = np.arange(self.seq_k)[None, :]
        kinds = np.kron(self.kinds, np.ones((b, b), np.uint8))
        if (kinds & KIND_CAUSAL).any():
            keep &= ~((kinds & KIND_CAUSAL).astype(bool)) | (qi >= ki)
        if self.band is not None and (kinds & KIND_BAND).any():
            fb, w, g_r, g_c, clip = self.band
            qf, kf = qi // fb, ki // fb
            ok = (qf < g_r) | (kf < g_c) | (np.abs(qf - kf) <= w)
            if clip:
                ok &= kf <= qf
            keep &= ~((kinds & KIND_BAND).astype(bool)) | ok
        return np.where(keep, 0.0, NEG_INF).astype(np.float32)

    def describe(self) -> str:
        s = f"masked(block={self.block}, nnz={self.nnz}/" \
            f"{self.heads * self.nq * self.nk}"
        if self.block != self.fine_block:
            s += f", coarsened from {self.fine_block}"
        return s + ")"

    # ----------------------------------------------------- hash / eq
    def __hash__(self):
        return hash(self._key)

    def __eq__(self, other):
        return isinstance(other, BlockMask) and self._key == other._key


# --------------------------------------------------------------------- #
# cost model (the masked_flash_flops_bytes bench row; mfu_cost_model
# pattern — analytic accounting proportional to nonzero blocks)
# --------------------------------------------------------------------- #
def masked_flash_cost(mask: BlockMask, batch: int, heads: int,
                      head_dim: int, dtype_bytes: int = 2,
                      backward: bool = False):
    """Modeled MXU FLOPs and HBM bytes for one forward (optionally +
    backward) pass — the ``masked_flash_flops_bytes`` bench row's
    engine (mfu_cost_model pattern: analytic accounting cross-checked
    structurally against the CSR metadata the kernel actually walks).

    The mask-proportional work is separated from the constant terms:
    ``flops`` (QK^T + PV dots per walked item; the dq/dkv recompute and
    grad dots with ``backward``) and ``kv_bytes`` (the K and V tiles
    each item DMAs — what the CSR walk saves vs S^2) scale with nonzero
    blocks; ``io_bytes`` (q read, o/lse write per block row — S*D
    regardless of the mask) does not. ``bytes`` is their sum."""
    hm = heads if mask.heads == 1 else 1       # items cover heads/Hm heads
    items = mask.nnz * hm * batch
    rows = mask.heads * mask.nq * hm * batch
    b, d = mask.block, head_dim
    dots_per_item = 2 if not backward else 2 + 6   # fwd QK+PV; bwd dq:
    # QK+dOV+dsK, dkv: QK+dOV+pdO+dsQ minus shared recompute accounting
    flops = items * dots_per_item * 2 * b * b * d
    kv_tile = b * d * dtype_bytes
    q_tile = b * d * dtype_bytes
    row_io = q_tile + q_tile + b * 4               # q read, o write, lse
    kv_bytes = items * 2 * kv_tile
    io_bytes = rows * row_io
    if backward:
        kv_bytes *= 2                              # dq pass + dkv pass
        io_bytes += rows * 3 * q_tile              # do read, dq/dkv out
    return {"flops": int(flops), "kv_bytes": int(kv_bytes),
            "io_bytes": int(io_bytes),
            "bytes": int(kv_bytes + io_bytes),
            "items": int(items), "block": b}


# --------------------------------------------------------------------- #
# reference (oracle) implementation
# --------------------------------------------------------------------- #
def masked_flash_reference(q, k, v, mask: BlockMask, key_mask=None,
                           sm_scale=None, dropout_rate: float = 0.0,
                           dropout_seed=None):
    """Dense jnp oracle with the mask expanded additively — exact-zero
    probabilities for structurally masked cells, zero output for fully
    masked rows (``block_sparse_attention_reference`` semantics), the
    kernels' hash dropout."""
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(q.shape[-1])
    if k.shape[1] != q.shape[1]:
        rep = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if key_mask is not None:
        s = s + key_mask.reshape(
            key_mask.shape[0], 1, 1, -1).astype(jnp.float32)
    s = s + jnp.asarray(mask.dense_additive())[None]
    m = jnp.max(s, axis=-1, keepdims=True)
    m_safe = jnp.where(m <= VALID_THRESH, 0.0, m)
    p = jnp.where(s > VALID_THRESH, jnp.exp(s - m_safe), 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    p = p / jnp.where(l == 0.0, 1.0, l)
    if dropout_rate > 0.0:
        b_, h_, sq_, sk_ = p.shape
        keep = _flash.dropout_mask_reference(dropout_seed, b_, h_, sq_,
                                             sk_, dropout_rate)
        p = jnp.where(keep, p, 0.0) / (1.0 - dropout_rate)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


# --------------------------------------------------------------------- #
# in-kernel helpers
# --------------------------------------------------------------------- #
def _tile_idx(q0, k0, bq, bk):
    # (bq, 1) / (1, bk) vectors — every consumer broadcasts (flash.py's
    # dropout-hash optimization carries over unchanged)
    q_idx = q0 + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
    k_idx = k0 + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)
    return q_idx, k_idx


def _partial_keep(kind, q_idx, k_idx, band):
    """Elementwise keep for a walked tile: FULL items (kind == 0) keep
    everything; the causal bit clips to q_idx >= k_idx; the band bit
    applies the fine-block structure (global prefix | window, plus the
    layout's own block-level causal clip)."""
    keep = jnp.where(kind & KIND_CAUSAL, q_idx >= k_idx, True)
    if band is not None:
        fb, w, g_r, g_c, clip = band
        qf = q_idx // fb
        kf = k_idx // fb
        ok = (qf < g_r) | (kf < g_c) | (jnp.abs(qf - kf) <= w)
        if clip:
            ok &= kf <= qf
        keep = keep & jnp.where(kind & KIND_BAND, ok, True)
    return keep


def _dma(src, row, c, buf, slot, sem):
    # src: full (rows, n_blocks, D, block) in HBM, pre-tiled TRANSPOSED
    # (Mosaic requires the DMA lane dim 128-aligned — the block width
    # is, head_dim often is not); whole-tile copy
    return pltpu.make_async_copy(src.at[row, c], buf.at[slot],
                                 sem.at[slot])


def _drop_kpm(kernel, n_before):
    """No-key-mask variant: the dense/causal training path (the hot
    loop) must not pay an all-zero (B, Sk) mask operand + per-tile add
    — insert kpm_ref=None at its positional slot instead."""
    def wrapped(*refs, **kw):
        return kernel(*refs[:n_before], None, *refs[n_before:], **kw)
    return wrapped


# --------------------------------------------------------------------- #
# kernels
# --------------------------------------------------------------------- #
def _mf_fwd_kernel(offs_ref, cnts_ref, cols_ref, kinds_ref, seed_ref,
                   q_ref, k_ref, v_ref, kpm_ref, o_ref, lse_ref,
                   *scratch, sm_scale, block, H, Hkv, Hm, nq, seq_k,
                   band, has_partials, dropout_rate, stream):
    if stream:
        kbuf, vbuf, ksem, vsem = scratch
    i = pl.program_id(0)                       # b * H + h
    j = pl.program_id(1)                       # q block
    h = jax.lax.rem(i, H)
    row = jax.lax.rem(h, Hm) * nq + j
    n = cnts_ref[row]
    base = offs_ref[row]
    kv_row = (i // H) * Hkv + h // (H // Hkv)
    q = q_ref[0]                               # (block, D)
    d = q.shape[-1]

    if stream:
        @pl.when(n > 0)
        def _prologue():
            c0 = cols_ref[base]
            _dma(k_ref, kv_row, c0, kbuf, 0, ksem).start()
            _dma(v_ref, kv_row, c0, vbuf, 0, vsem).start()

    def body(t, carry):
        m, l, acc = carry
        c = cols_ref[base + t]
        kind = kinds_ref[base + t]
        if stream:
            @pl.when(t + 1 < n)
            def _prefetch_next():
                cn = cols_ref[base + t + 1]
                slot = jax.lax.rem(t + 1, 2)
                _dma(k_ref, kv_row, cn, kbuf, slot, ksem).start()
                _dma(v_ref, kv_row, cn, vbuf, slot, vsem).start()
            slot = jax.lax.rem(t, 2)
            _dma(k_ref, kv_row, c, kbuf, slot, ksem).wait()
            _dma(v_ref, kv_row, c, vbuf, slot, vsem).wait()
            k, v = kbuf[slot], vbuf[slot]      # transposed: (D, block)
        else:
            k = k_ref[0, pl.ds(c * block, block), :]
            v = v_ref[0, pl.ds(c * block, block), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (0 if stream else 1,)), ((), ())),
            preferred_element_type=jnp.float32)
        s = s * sm_scale
        if kpm_ref is not None:
            s += kpm_ref[0, 0, pl.ds(c * block, block)][None, :]
        if has_partials or dropout_rate > 0.0:
            q_idx, k_idx = _tile_idx(j * block, c * block, block, block)
        if has_partials:
            s = jnp.where(_partial_keep(kind, q_idx, k_idx, band), s,
                          NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        m_safe = jnp.where(m_new <= VALID_THRESH, 0.0, m_new)
        alpha = jnp.exp(m - m_new)
        # exact-zero probability for structurally masked cells; rows
        # with no valid entry keep l == 0 and fall out as zero output
        p = jnp.where(s > VALID_THRESH, jnp.exp(s - m_safe[:, None]), 0.0)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        if dropout_rate > 0.0:
            keep = dropout_keep_mask(seed_ref[0], i, q_idx, k_idx,
                                     seq_k, dropout_rate)
            p = jnp.where(keep, p, 0.0)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (1 if stream else 0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((block,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block,), jnp.float32)
    acc0 = jnp.zeros((block, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n, body, (m0, l0, acc0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = acc / l_safe[:, None]
    if dropout_rate > 0.0:
        out = out * (1.0 / (1.0 - dropout_rate))
    o_ref[0] = out.astype(o_ref.dtype)
    lse_ref[0, :, 0] = jnp.where(l == 0.0, NEG_INF,
                                 jnp.where(m <= VALID_THRESH, 0.0, m)
                                 + jnp.log(l_safe))


def _mf_dq_kernel(offs_ref, cnts_ref, cols_ref, kinds_ref, seed_ref,
                  q_ref, k_ref, v_ref, kpm_ref, do_ref, lse_ref,
                  delta_ref, dq_ref, *scratch, sm_scale, block, H, Hkv,
                  Hm, nq, seq_k, band, has_partials, dropout_rate,
                  stream):
    if stream:
        kbuf, vbuf, ksem, vsem = scratch
    i = pl.program_id(0)
    j = pl.program_id(1)
    h = jax.lax.rem(i, H)
    row = jax.lax.rem(h, Hm) * nq + j
    n = cnts_ref[row]
    base = offs_ref[row]
    kv_row = (i // H) * Hkv + h // (H // Hkv)
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0, :, 0]
    delta = delta_ref[0, :, 0]
    d = q.shape[-1]

    if stream:
        @pl.when(n > 0)
        def _prologue():
            c0 = cols_ref[base]
            _dma(k_ref, kv_row, c0, kbuf, 0, ksem).start()
            _dma(v_ref, kv_row, c0, vbuf, 0, vsem).start()

    def body(t, dq):
        c = cols_ref[base + t]
        kind = kinds_ref[base + t]
        if stream:
            @pl.when(t + 1 < n)
            def _prefetch_next():
                cn = cols_ref[base + t + 1]
                slot = jax.lax.rem(t + 1, 2)
                _dma(k_ref, kv_row, cn, kbuf, slot, ksem).start()
                _dma(v_ref, kv_row, cn, vbuf, slot, vsem).start()
            slot = jax.lax.rem(t, 2)
            _dma(k_ref, kv_row, c, kbuf, slot, ksem).wait()
            _dma(v_ref, kv_row, c, vbuf, slot, vsem).wait()
            k, v = kbuf[slot], vbuf[slot]      # transposed: (D, block)
        else:
            k = k_ref[0, pl.ds(c * block, block), :]
            v = v_ref[0, pl.ds(c * block, block), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (0 if stream else 1,)), ((), ())),
            preferred_element_type=jnp.float32)
        s = s * sm_scale
        if kpm_ref is not None:
            s += kpm_ref[0, 0, pl.ds(c * block, block)][None, :]
        if has_partials or dropout_rate > 0.0:
            q_idx, k_idx = _tile_idx(j * block, c * block, block, block)
        if has_partials:
            s = jnp.where(_partial_keep(kind, q_idx, k_idx, band), s,
                          NEG_INF)
        p = jnp.where(s > VALID_THRESH, jnp.exp(s - lse[:, None]), 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (0 if stream else 1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            keep = dropout_keep_mask(seed_ref[0], i, q_idx, k_idx,
                                     seq_k, dropout_rate)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_rate)), 0.0)
        ds = p * (dp - delta[:, None])
        return dq + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (1 if stream else 0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, n, body, jnp.zeros((block, d), jnp.float32))
    dq_ref[0] = (dq * sm_scale).astype(dq_ref.dtype)


def _mf_dkv_kernel(coffs_ref, ccnts_ref, crows_ref, ckinds_ref, seed_ref,
                   q_ref, k_ref, v_ref, kpm_ref, do_ref, lse_ref,
                   delta_ref, dk_ref, dv_ref, *scratch, sm_scale, block,
                   H, Hm, nk, seq_k, band, has_partials, dropout_rate,
                   stream):
    if stream:
        qbuf, dobuf, qsem, dosem = scratch
    i = pl.program_id(0)                       # b * H + h (q heads)
    jb = pl.program_id(1)                      # k block
    h = jax.lax.rem(i, H)
    col = jax.lax.rem(h, Hm) * nk + jb
    n = ccnts_ref[col]
    base = coffs_ref[col]
    k = k_ref[0]                               # (block, D)
    v = v_ref[0]
    d = k.shape[-1]
    kpm_row = (kpm_ref[0, 0, pl.ds(jb * block, block)]
               if kpm_ref is not None else None)

    if stream:
        @pl.when(n > 0)
        def _prologue():
            r0 = crows_ref[base]
            _dma(q_ref, i, r0, qbuf, 0, qsem).start()
            _dma(do_ref, i, r0, dobuf, 0, dosem).start()

    def body(t, carry):
        dk, dv = carry
        rq = crows_ref[base + t]
        kind = ckinds_ref[base + t]
        if stream:
            @pl.when(t + 1 < n)
            def _prefetch_next():
                rn = crows_ref[base + t + 1]
                slot = jax.lax.rem(t + 1, 2)
                _dma(q_ref, i, rn, qbuf, slot, qsem).start()
                _dma(do_ref, i, rn, dobuf, slot, dosem).start()
            slot = jax.lax.rem(t, 2)
            _dma(q_ref, i, rq, qbuf, slot, qsem).wait()
            _dma(do_ref, i, rq, dobuf, slot, dosem).wait()
            q, do = qbuf[slot], dobuf[slot]    # transposed: (D, block)
        else:
            q = q_ref[0, pl.ds(rq * block, block), :]
            do = do_ref[0, pl.ds(rq * block, block), :]
        lse = lse_ref[0, 0, pl.ds(rq * block, block)]
        delta = delta_ref[0, 0, pl.ds(rq * block, block)]
        s = jax.lax.dot_general(
            q, k, (((0 if stream else 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # (bq, bk)
        s = s * sm_scale
        if kpm_row is not None:
            s += kpm_row[None, :]
        if has_partials or dropout_rate > 0.0:
            q_idx, k_idx = _tile_idx(rq * block, jb * block, block, block)
        if has_partials:
            s = jnp.where(_partial_keep(kind, q_idx, k_idx, band), s,
                          NEG_INF)
        p = jnp.where(s > VALID_THRESH, jnp.exp(s - lse[:, None]), 0.0)
        dp = jax.lax.dot_general(
            do, v, (((0 if stream else 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # (bq, bk)
        if dropout_rate > 0.0:
            keep = dropout_keep_mask(seed_ref[0], i, q_idx, k_idx,
                                     seq_k, dropout_rate)
            inv_kp = 1.0 / (1.0 - dropout_rate)
            pd = jnp.where(keep, p * inv_kp, 0.0)
            dp = jnp.where(keep, dp * inv_kp, 0.0)
        else:
            pd = p
        dv_new = dv + jax.lax.dot_general(
            pd.astype(do.dtype), do,
            (((0,), (1 if stream else 0,)), ((), ())),
            preferred_element_type=jnp.float32)            # (bk, D)
        ds = p * (dp - delta[:, None])
        dk_new = dk + jax.lax.dot_general(
            ds.astype(q.dtype), q,
            (((0,), (1 if stream else 0,)), ((), ())),
            preferred_element_type=jnp.float32)            # (bk, D)
        return dk_new, dv_new

    z = jnp.zeros((block, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, n, body, (z, z))
    dk_ref[0] = (dk * sm_scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# --------------------------------------------------------------------- #
# pallas_call wrappers
# --------------------------------------------------------------------- #
def _use_stream(mask: BlockMask, interpret: bool) -> bool:
    if _FORCE_STREAM is not None:
        return _FORCE_STREAM
    if max(mask.seq_q, mask.seq_k) < STREAM_THRESHOLD:
        return False
    if mask.block % 128 != 0 and not interpret:
        # the streamed tile's lane dim is the block width, which Mosaic
        # requires 128-aligned; long irregular-block masks stay resident
        _flash.log_once(
            ("masked-stream", mask.block, mask.seq_q, mask.seq_k),
            f"masked_flash: block {mask.block} at seq "
            f"({mask.seq_q}, {mask.seq_k}) cannot DMA-stream (lane "
            "alignment); K/V stay VMEM-resident — expect VMEM pressure "
            "at this length. Use 128-multiple blocks.", warn=True)
        return False
    return True


def _kernel_statics(mask: BlockMask, H, Hkv, sm_scale, rate, stream):
    return dict(sm_scale=sm_scale, block=mask.block, H=H, Hkv=Hkv,
                Hm=mask.heads, nq=mask.nq, seq_k=mask.seq_k,
                band=mask.band, has_partials=mask.has_partials,
                dropout_rate=rate, stream=stream)


def _stream_scratch(d, block, dt_a, dt_b):
    return [pltpu.VMEM((2, d, block), dt_a),
            pltpu.VMEM((2, d, block), dt_b),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,))]


def _masked_fwd(q, k, v, kpm, seed, mask, sm_scale, interpret, rate,
                has_kpm=True):
    b, h, sq, d = q.shape
    hkv = k.shape[1]
    sk = k.shape[2]
    blk = mask.block
    stream = _use_stream(mask, interpret)
    G = h // hkv

    qr = q.reshape(b * h, sq, d)
    kr = k.reshape(b * hkv, sk, d)
    vr = v.reshape(b * hkv, sk, d)

    kernel = functools.partial(
        _mf_fwd_kernel, **_kernel_statics(mask, h, hkv, sm_scale, rate,
                                          stream))
    if not has_kpm:
        kernel = _drop_kpm(kernel, 8)       # 5 scalars + q, k, v
    if stream:
        kv_spec = pl.BlockSpec(memory_space=pltpu.HBM)
        kr = _stream_layout(kr, blk)
        vr = _stream_layout(vr, blk)
    else:
        kv_spec = pl.BlockSpec(
            (1, sk, d),
            lambda i, j, *_: ((i // h) * hkv + (i % h) // G, 0, 0))
    in_specs = [
        pl.BlockSpec((1, blk, d), lambda i, j, *_: (i, j, 0)),   # q
        kv_spec, kv_spec,
    ]
    args = [qr, kr, vr]
    if has_kpm:
        in_specs.append(
            pl.BlockSpec((1, 1, sk), lambda i, j, *_: (i // h, 0, 0)))
        args.append(kpm.reshape(b, 1, sk))
    offs, cnts, cols, kinds = mask.csr()
    scalars = [jnp.asarray(offs), jnp.asarray(cnts), jnp.asarray(cols),
               jnp.asarray(kinds), seed.reshape(1).astype(jnp.int32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(scalars),
        grid=(b * h, mask.nq),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, blk, d), lambda i, j, *_: (i, j, 0)),
            pl.BlockSpec((1, blk, 1), lambda i, j, *_: (i, j, 0)),
        ],
        scratch_shapes=_stream_scratch(d, blk, k.dtype, v.dtype)
        if stream else [])
    o, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq, 1), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=_flash._compiler_params(interpret, stream),
    )(*scalars, *args)
    return o.reshape(b, h, sq, d), lse


def _masked_bwd(res, g, mask, sm_scale, interpret, rate,
                has_kpm=True):
    q, k, v, kpm, seed, o, lse = res
    b, h, sq, d = q.shape
    hkv = k.shape[1]
    G = h // hkv
    sk = k.shape[2]
    blk = mask.block
    stream = _use_stream(mask, interpret)
    do = g
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                               # (b,h,sq)

    qr = q.reshape(b * h, sq, d)
    kr = k.reshape(b * hkv, sk, d)
    vr = v.reshape(b * hkv, sk, d)
    dor = do.reshape(b * h, sq, d)
    kpm_args = [kpm.reshape(b, 1, sk)] if has_kpm else []
    lser = lse.reshape(b * h, sq, 1)
    deltar = delta.reshape(b * h, sq, 1)
    compiler_params = _flash._compiler_params(interpret, stream)

    # ---- dq (CSR row walk) ----
    kernel = functools.partial(
        _mf_dq_kernel, **_kernel_statics(mask, h, hkv, sm_scale, rate,
                                         stream))
    if not has_kpm:
        kernel = _drop_kpm(kernel, 8)       # 5 scalars + q, k, v
    if stream:
        kv_spec = pl.BlockSpec(memory_space=pltpu.HBM)
        k_arg, v_arg = _stream_layout(kr, blk), _stream_layout(vr, blk)
    else:
        kv_spec = pl.BlockSpec(
            (1, sk, d),
            lambda i, j, *_: ((i // h) * hkv + (i % h) // G, 0, 0))
        k_arg, v_arg = kr, vr
    row_spec = pl.BlockSpec((1, blk, d), lambda i, j, *_: (i, j, 0))
    row_vec = pl.BlockSpec((1, blk, 1), lambda i, j, *_: (i, j, 0))
    offs, cnts, cols, kinds = mask.csr()
    scalars = [jnp.asarray(offs), jnp.asarray(cnts), jnp.asarray(cols),
               jnp.asarray(kinds), seed.reshape(1).astype(jnp.int32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(scalars),
        grid=(b * h, mask.nq),
        in_specs=[row_spec, kv_spec, kv_spec] + ([
            pl.BlockSpec((1, 1, sk), lambda i, j, *_: (i // h, 0, 0))]
            if has_kpm else []) + [row_spec, row_vec, row_vec],
        out_specs=row_spec,
        scratch_shapes=_stream_scratch(d, blk, k.dtype, v.dtype)
        if stream else [])
    dq = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        interpret=interpret,
        compiler_params=compiler_params,
    )(*scalars, qr, k_arg, v_arg, *kpm_args, dor, lser, deltar)

    # ---- dk, dv (CSC column walk, per-q-head partials) ----
    kernel = functools.partial(
        _mf_dkv_kernel, sm_scale=sm_scale, block=blk, H=h, Hm=mask.heads,
        nk=mask.nk, seq_k=sk, band=mask.band,
        has_partials=mask.has_partials, dropout_rate=rate, stream=stream)
    if not has_kpm:
        kernel = _drop_kpm(kernel, 8)       # 5 scalars + q, k, v
    if stream:
        q_spec = pl.BlockSpec(memory_space=pltpu.HBM)
        q_arg, do_arg = _stream_layout(qr, blk), _stream_layout(dor, blk)
    else:
        q_spec = pl.BlockSpec((1, sq, d), lambda i, j, *_: (i, 0, 0))
        q_arg, do_arg = qr, dor
    col_spec = pl.BlockSpec(
        (1, blk, d),
        lambda i, j, *_: ((i // h) * hkv + (i % h) // G, j, 0))
    coffs, ccnts, crows, ckinds = mask.csc()
    scalars = [jnp.asarray(coffs), jnp.asarray(ccnts), jnp.asarray(crows),
               jnp.asarray(ckinds), seed.reshape(1).astype(jnp.int32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(scalars),
        grid=(b * h, mask.nk),
        in_specs=[
            q_spec,                                          # q (full)
            col_spec, col_spec,                              # k, v tiles
        ] + ([pl.BlockSpec((1, 1, sk), lambda i, j, *_: (i // h, 0, 0))]
             if has_kpm else []) + [
            q_spec,                                          # do (full)
            pl.BlockSpec((1, 1, sq), lambda i, j, *_: (i, 0, 0)),  # lse
            pl.BlockSpec((1, 1, sq), lambda i, j, *_: (i, 0, 0)),  # delta
        ],
        out_specs=[
            pl.BlockSpec((1, blk, d), lambda i, j, *_: (i, j, 0)),
            pl.BlockSpec((1, blk, d), lambda i, j, *_: (i, j, 0)),
        ],
        scratch_shapes=_stream_scratch(d, blk, q.dtype, do.dtype)
        if stream else [])
    dk, dv = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            # GQA: fp32 per-q-head partials so the group sum really
            # accumulates at fp32 (flash.py's scheme)
            jax.ShapeDtypeStruct((b * h, sk, d),
                                 jnp.float32 if G > 1 else k.dtype),
            jax.ShapeDtypeStruct((b * h, sk, d),
                                 jnp.float32 if G > 1 else v.dtype),
        ],
        interpret=interpret,
        compiler_params=compiler_params,
    )(*scalars, q_arg, kr, vr, *kpm_args, do_arg,
      lser.reshape(b * h, 1, sq), deltar.reshape(b * h, 1, sq))

    dq = dq.reshape(b, h, sq, d)
    if G > 1:
        dk = dk.reshape(b, hkv, G, sk, d).sum(2).astype(k.dtype)
        dv = dv.reshape(b, hkv, G, sk, d).sum(2).astype(v.dtype)
    else:
        dk = dk.reshape(b, hkv, sk, d)
        dv = dv.reshape(b, hkv, sk, d)
    return dq, dk, dv


# --------------------------------------------------------------------- #
# custom vjp + public API
# --------------------------------------------------------------------- #
# seed rides as a traced int32 array (a per-step dropout seed must not
# recompile); its cotangent is None. The BlockMask is a hashable static.
@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def masked_flash_call(q, k, v, kpm, seed, mask, sm_scale, interpret,
                      rate, has_kpm=True):
    """Low-level entry (all operands explicit — what
    ``parallel/pallas_shard.sharded_masked_flash`` wraps in shard_map).
    Prefer :func:`masked_flash_attention`. With ``has_kpm=False`` the
    (then-unused, dummy-shaped) ``kpm`` operand never reaches the
    kernels — the dense/causal hot path pays no all-zero mask add."""
    o, _ = _masked_fwd(q, k, v, kpm, seed, mask, sm_scale, interpret,
                       rate, has_kpm=has_kpm)
    return o


def _mf_vjp_fwd(q, k, v, kpm, seed, mask, sm_scale, interpret, rate,
                has_kpm=True):
    o, lse = _masked_fwd(q, k, v, kpm, seed, mask, sm_scale, interpret,
                         rate, has_kpm=has_kpm)
    return o, (q, k, v, kpm, seed, o, lse)


def _mf_vjp_bwd(mask, sm_scale, interpret, rate, has_kpm, res, g):
    q, k, v, kpm, seed, o, lse = res
    dq, dk, dv = _masked_bwd((q, k, v, kpm, seed, o, lse), g, mask,
                             sm_scale, interpret, rate, has_kpm=has_kpm)
    return dq, dk, dv, jnp.zeros_like(kpm), None


masked_flash_call.defvjp(_mf_vjp_fwd, _mf_vjp_bwd)


def masked_flash_attention(q, k, v, mask: BlockMask, key_mask=None,
                           sm_scale: Optional[float] = None,
                           dropout_rate: float = 0.0,
                           dropout_rng=None,
                           interpret: Optional[bool] = None):
    """Blocked flash attention under a static :class:`BlockMask`.

    q: (B, H, Sq, D); k, v: (B, kv_heads, Sk, D) with
    ``H % kv_heads == 0`` (GQA served natively). ``mask.heads`` must be
    1 (head-uniform) or H. ``key_mask``: optional *additive* key mask,
    (B, Sk) or BERT-style (B, 1, 1, Sk). O(S) memory, O(nonzero
    blocks) compute/bytes; fwd + custom-vjp bwd; in-kernel hash
    dropout (requires ``dropout_rng``).
    """
    b, h, sq, d = q.shape
    hkv = k.shape[1]
    sk = k.shape[2]
    assert h % hkv == 0 and k.shape == v.shape, (q.shape, k.shape,
                                                 v.shape)
    assert mask.seq_q == sq and mask.seq_k == sk, (
        f"mask geometry ({mask.seq_q}, {mask.seq_k}) vs inputs "
        f"({sq}, {sk})")
    assert mask.heads in (1, h), (
        f"mask heads {mask.heads} must be 1 (uniform) or {h}")
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(d)
    if interpret is None:
        interpret = not _flash._use_pallas()
    dropout_rate = float(dropout_rate)
    if dropout_rate > 0.0:
        assert dropout_rng is not None, \
            "masked_flash_attention: dropout_rate > 0 requires dropout_rng"
        assert dropout_rate < 1.0, dropout_rate
        seed = dropout_seed_from_rng(dropout_rng)
    else:
        seed = jnp.zeros((1, 1), jnp.int32)
    if key_mask is None:
        # dummy operand: has_kpm=False keeps it out of the kernels
        kpm = jnp.zeros((b, 1), jnp.float32)
    else:
        kpm = key_mask.reshape(b, sk).astype(jnp.float32)
    return masked_flash_call(q, k, v, kpm, seed, mask, float(sm_scale),
                             bool(interpret), dropout_rate,
                             key_mask is not None)
