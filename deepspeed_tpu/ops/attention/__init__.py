from deepspeed_tpu.ops.attention.flash import (attention_reference,
                                               flash_attention,
                                               get_attention_options,
                                               set_attention_options)
from deepspeed_tpu.ops.attention.masked_flash import (BlockMask,
                                                      masked_flash_attention,
                                                      masked_flash_cost)
from deepspeed_tpu.ops.attention.paged import (paged_decode_attention,
                                               paged_decode_supported)
from deepspeed_tpu.ops.attention.ring import ring_attention

__all__ = ["attention_reference", "flash_attention", "ring_attention",
           "paged_decode_attention", "paged_decode_supported",
           "BlockMask", "masked_flash_attention", "masked_flash_cost",
           "get_attention_options", "set_attention_options"]
