from deepspeed_tpu.ops.attention.flash import (attention_reference,
                                               flash_attention)
from deepspeed_tpu.ops.attention.paged import (paged_decode_attention,
                                               paged_decode_supported)
from deepspeed_tpu.ops.attention.ring import ring_attention

__all__ = ["attention_reference", "flash_attention", "ring_attention",
           "paged_decode_attention", "paged_decode_supported"]
