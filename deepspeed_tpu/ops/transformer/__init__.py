from deepspeed_tpu.ops.transformer.transformer import (
    DeepSpeedTransformerConfig, DeepSpeedTransformerLayer,
    init_transformer_params, transformer_layer_forward)

__all__ = ["DeepSpeedTransformerConfig", "DeepSpeedTransformerLayer",
           "init_transformer_params", "transformer_layer_forward"]
