"""The DeepSpeed transformer layer — TPU edition.

Mirrors the reference's fused-kernel layer API
(``deepspeed/ops/transformer/transformer.py``: DeepSpeedTransformerConfig :37,
DeepSpeedTransformerLayer :399 with params qkvw/qkvb/ow/ob/attn_nw/attn_nb/
inter_w/inter_b/output_w/output_b/norm_w/norm_b :424-443) while replacing the
5.8 kLoC CUDA pipeline (csrc/transformer/: QKV GEMM → transpose → QK^T →
softmax → dropout → PV → out GEMM → bias+residual+LayerNorm → GELU FF) with:

- Pallas flash attention (ops/attention/flash.py) for the softmax core;
- XLA fusion for the elementwise chains (bias+GELU, bias+dropout+residual+LN
  fuse into their surrounding GEMMs on TPU — measured, not assumed; the CUDA
  hand-fusions exist because nvcc wouldn't do it for them);
- recompute knobs (normalize_invertible, gelu_checkpoint,
  attn_dropout_checkpoint) map onto ``jax.checkpoint`` policies at the model
  level rather than buffer-juggling.

The layer is a pure function over a params dict — `init_transformer_params`
builds the dict with the reference's initializer_range semantics.
"""

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.attention.flash import (
    attention_reference, flash_attention)


class DeepSpeedTransformerConfig:
    """(reference transformer.py:37). Unused CUDA-only knobs are accepted for
    config compatibility and noted where their TPU meaning differs."""

    def __init__(self,
                 batch_size: int = -1,
                 max_seq_length: int = -1,
                 hidden_size: int = -1,
                 intermediate_size: int = -1,
                 heads: int = -1,
                 attn_dropout_ratio: float = -1,
                 hidden_dropout_ratio: float = -1,
                 num_hidden_layers: int = -1,
                 initializer_range: float = -1,
                 local_rank: int = -1,
                 seed: int = -1,
                 fp16: bool = False,
                 bf16: bool = True,
                 pre_layer_norm: bool = True,
                 normalize_invertible: bool = False,
                 gelu_checkpoint: bool = False,
                 adjust_init_range: bool = True,
                 attn_dropout_checkpoint: bool = False,
                 stochastic_mode: bool = False,
                 huggingface: bool = False,
                 training: bool = True):
        self.batch_size = batch_size
        self.max_seq_length = max_seq_length
        self.hidden_size = hidden_size
        self.intermediate_size = (intermediate_size if intermediate_size > 0
                                  else 4 * hidden_size)
        self.heads = heads
        self.attn_dropout_ratio = max(attn_dropout_ratio, 0.0)
        self.hidden_dropout_ratio = max(hidden_dropout_ratio, 0.0)
        self.num_hidden_layers = num_hidden_layers
        self.initializer_range = (initializer_range if initializer_range > 0
                                  else 0.02)
        self.local_rank = local_rank
        self.seed = seed
        self.fp16 = fp16
        self.bf16 = bf16 and not fp16
        self.pre_layer_norm = pre_layer_norm
        # recompute knobs: consumed by model-level jax.checkpoint policy
        self.normalize_invertible = normalize_invertible
        self.gelu_checkpoint = gelu_checkpoint
        self.attn_dropout_checkpoint = attn_dropout_checkpoint
        self.adjust_init_range = adjust_init_range
        # In the reference, stochastic_mode selects the __STOCHASTIC_MODE__
        # kernel build (stochastic-rounding fp16 ops, ~2% faster, run-to-run
        # nondeterministic). Here rounding mode is an optimizer-boundary
        # concern, not a kernel build flag: the engine-level
        # ``bf16: {"master_weights": false, "stochastic_rounding": true}``
        # config (docs/config.md) is the TPU-native equivalent.
        self.stochastic_mode = stochastic_mode
        self.huggingface = huggingface
        self.training = training

    @property
    def compute_dtype(self):
        if self.fp16:
            return jnp.float16
        if self.bf16:
            return jnp.bfloat16
        return jnp.float32

    @classmethod
    def from_dict(cls, json_object):
        config = cls()
        for key, value in json_object.items():
            setattr(config, key, value)
        if config.intermediate_size <= 0:
            config.intermediate_size = 4 * config.hidden_size
        return config


def init_transformer_params(config: DeepSpeedTransformerConfig, key,
                            layer_id: int = 0) -> Dict[str, Any]:
    """Param dict matching the reference layer's parameter list
    (transformer.py:424-443). output_w init is scaled by 1/sqrt(2L) when
    adjust_init_range is set (reference :419-422 'output layers scaled
    initialization')."""
    h = config.hidden_size
    inter = config.intermediate_size
    rng = config.initializer_range
    out_rng = rng
    if config.adjust_init_range and config.num_hidden_layers > 0:
        out_rng = rng / np.sqrt(2.0 * config.num_hidden_layers)
    ks = jax.random.split(key, 4)
    return {
        "qkvw": jax.random.normal(ks[0], (h, 3 * h), jnp.float32) * rng,
        "qkvb": jnp.zeros((3 * h,), jnp.float32),
        "ow": jax.random.normal(ks[1], (h, h), jnp.float32) * out_rng,
        "ob": jnp.zeros((h,), jnp.float32),
        "attn_nw": jnp.ones((h,), jnp.float32),
        "attn_nb": jnp.zeros((h,), jnp.float32),
        "inter_w": jax.random.normal(ks[2], (h, inter), jnp.float32) * rng,
        "inter_b": jnp.zeros((inter,), jnp.float32),
        "output_w": jax.random.normal(ks[3], (inter, h), jnp.float32) * out_rng,
        "output_b": jnp.zeros((h,), jnp.float32),
        "norm_w": jnp.ones((h,), jnp.float32),
        "norm_b": jnp.zeros((h,), jnp.float32),
    }


from deepspeed_tpu.ops.functional import dropout as _dropout
from deepspeed_tpu.ops.functional import layer_norm as _layer_norm

_WARNED_NO_ATTN_DROPOUT = False


def _warn_no_attn_dropout():
    """Custom attention_fn paths (block-sparse) carry no attention dropout
    — same as the reference's sparse swap, but say so once instead of
    silently changing regularization."""
    global _WARNED_NO_ATTN_DROPOUT
    if not _WARNED_NO_ATTN_DROPOUT:
        _WARNED_NO_ATTN_DROPOUT = True
        from deepspeed_tpu.utils.logging import logger
        logger.warning(
            "attention_fn override active with attn_dropout > 0: custom "
            "core attention (e.g. block-sparse) applies NO attention "
            "dropout; hidden-dropout still applies")


def transformer_layer_forward(params: Dict[str, Any],
                              config: DeepSpeedTransformerConfig,
                              hidden_states,
                              attention_mask=None,
                              rng=None,
                              deterministic: Optional[bool] = None,
                              use_flash: bool = True,
                              attention_fn=None):
    """One encoder/decoder layer (reference BertTransformerLayer::Forward,
    ds_transformer_cuda.cpp:153).

    hidden_states: (B, S, H); attention_mask: additive (B, 1, 1, S) or None.
    ``attention_fn``: optional core-attention override with signature
    ``(q, k, v, additive_mask) -> ctx`` on (B, heads, S, hd) tensors — the
    hook SparseAttentionUtils uses to swap in block-sparse attention
    (reference swaps the whole BertSelfAttention module instead,
    sparse_attention_utils.py:123).
    Returns (B, S, H).
    """
    if deterministic is None:
        deterministic = not config.training
    dtype = config.compute_dtype
    x = hidden_states.astype(dtype)
    h = config.hidden_size
    heads = config.heads
    assert heads > 0 and h % heads == 0, (
        f"hidden_size {h} must be divisible by heads {heads}")
    hd = h // heads
    B, S, _ = x.shape

    if rng is not None:
        r_attn, r_h1, r_h2 = jax.random.split(rng, 3)
    else:
        r_attn = r_h1 = r_h2 = None

    def attn_block(x_in):
        qkv = x_in @ params["qkvw"].astype(dtype) + params["qkvb"].astype(dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        # (B, S, H) -> (B, heads, S, hd): the reference's transform_0213 kernel
        q = q.reshape(B, S, heads, hd).transpose(0, 2, 1, 3)
        k = k.reshape(B, S, heads, hd).transpose(0, 2, 1, 3)
        v = v.reshape(B, S, heads, hd).transpose(0, 2, 1, 3)
        attn_drop = (config.attn_dropout_ratio
                     if (config.attn_dropout_ratio > 0 and not deterministic
                         and r_attn is not None) else 0.0)
        if attention_fn is not None:
            if attn_drop > 0:
                _warn_no_attn_dropout()
            ctx = attention_fn(q, k, v, attention_mask)
        elif not use_flash:
            sm_scale = 1.0 / np.sqrt(hd)
            s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                           k.astype(jnp.float32)) * sm_scale
            if attention_mask is not None:
                s = s + attention_mask.astype(jnp.float32)
            p = jax.nn.softmax(s, axis=-1).astype(dtype)
            p = _dropout(p, config.attn_dropout_ratio, r_attn, deterministic)
            ctx = jnp.einsum("bhqk,bhkd->bhqd", p, v)
        else:
            # in-kernel attention dropout (reference: fused softmax-dropout
            # kernels); mask regenerates in bwd from the same hash counter
            ctx = flash_attention(q, k, v, mask=attention_mask,
                                  dropout_rate=attn_drop,
                                  dropout_rng=r_attn)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(B, S, h)
        out = ctx @ params["ow"].astype(dtype) + params["ob"].astype(dtype)
        return _dropout(out, config.hidden_dropout_ratio, r_h1, deterministic)

    def ff_block(x_in):
        inter = x_in @ params["inter_w"].astype(dtype) + \
            params["inter_b"].astype(dtype)
        inter = jax.nn.gelu(inter, approximate=False)
        out = inter @ params["output_w"].astype(dtype) + \
            params["output_b"].astype(dtype)
        return _dropout(out, config.hidden_dropout_ratio, r_h2, deterministic)

    # recompute knobs (reference compile-time variants,
    # ds_transformer_cuda.cpp ctor flags): each maps to jax.checkpoint on
    # the corresponding segment — its intermediates are recomputed in
    # backward instead of saved. attn_dropout_checkpoint drops the
    # attention block's saved activations (the reference re-runs
    # softmax-dropout); gelu_checkpoint drops the FF intermediate (the
    # reference re-runs bias-GELU); normalize_invertible avoids saving
    # LayerNorm outputs (the reference reconstructs the input from the
    # output; recompute-from-input is the same memory class).
    attn = (jax.checkpoint(attn_block)
            if config.attn_dropout_checkpoint else attn_block)
    ff = jax.checkpoint(ff_block) if config.gelu_checkpoint else ff_block
    ln = (jax.checkpoint(_layer_norm)
          if config.normalize_invertible else _layer_norm)

    if config.pre_layer_norm:
        x = x + attn(ln(x, params["attn_nw"], params["attn_nb"]))
        x = x + ff(ln(x, params["norm_w"], params["norm_b"]))
    else:  # post-LN (original BERT)
        x = ln(x + attn(x), params["attn_nw"], params["attn_nb"])
        x = ln(x + ff(x), params["norm_w"], params["norm_b"])
    return x


class DeepSpeedTransformerLayer:
    """Object facade over the pure function, mirroring the reference class
    (transformer.py:399). Holds (config, params); call like a module."""

    layer_id_counter = 0

    def __init__(self, config: DeepSpeedTransformerConfig, key=None,
                 initial_params: Optional[Dict[str, Any]] = None):
        self.config = config
        self.layer_id = DeepSpeedTransformerLayer.layer_id_counter
        DeepSpeedTransformerLayer.layer_id_counter += 1
        if initial_params is not None:
            self.params = initial_params
        else:
            if key is None:
                key = jax.random.PRNGKey(
                    config.seed if config.seed >= 0 else 0)
            self.params = init_transformer_params(config, key, self.layer_id)

    def __call__(self, hidden_states, attention_mask=None, rng=None,
                 params: Optional[Dict[str, Any]] = None,
                 deterministic: Optional[bool] = None):
        return transformer_layer_forward(
            params if params is not None else self.params, self.config,
            hidden_states, attention_mask=attention_mask, rng=rng,
            deterministic=deterministic)
