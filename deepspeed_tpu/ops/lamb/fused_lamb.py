"""Reference import-path alias (deepspeed/ops/lamb/fused_lamb.py:12):
``from deepspeed_tpu.ops.lamb.fused_lamb import FusedLamb``. The
implementation is the XLA-fused Lamb in ops/optimizers.py (single
jitted update; norms and trust ratios fuse — no CUDA kernel needed)."""

from deepspeed_tpu.ops.optimizers import FusedLamb, Lamb  # noqa
