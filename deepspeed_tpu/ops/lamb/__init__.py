"""``deepspeed_tpu.ops.lamb`` (reference deepspeed/ops/lamb/): the LAMB
implementation lives in ops/optimizers.py as an XLA-fused update; this
package keeps the reference import paths working."""

from deepspeed_tpu.ops.lamb.fused_lamb import FusedLamb  # noqa
