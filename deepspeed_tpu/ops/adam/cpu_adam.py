"""ZeRO-Offload CPU Adam, Python side.

Reference: ``deepspeed/ops/adam/cpu_adam.py:8`` ``DeepSpeedCPUAdam``
(``create_adam`` on import ``:33``, ``step(fp16_param_groups=...)`` writing
device params via a fused copy ``:67-74``). The native kernel is
``csrc/adam/cpu_adam.cpp`` (AVX2+FMA+OpenMP), loaded via ctypes; if the
shared library is missing it is built on demand with ``make -C csrc``, and a
numpy fallback keeps the API functional on hosts without a toolchain.

TPU integration: the optimizer owns host-resident fp32 master params +
moments (numpy); ``step(grads)`` runs the SIMD update and returns the
updated params as **bfloat16 bytes** ready for a single ``jax.device_put``
H2D transfer — the analogue of the reference's overlapped fp16 copy-back
(``csrc/adam/custom_cuda_kernel.cu``).
"""

import ctypes
import os
import subprocess
import threading
from typing import Any, Optional

import numpy as np

__all__ = ["DeepSpeedCPUAdam", "load_library"]

_LIB = None
_LIB_LOCK = threading.Lock()
_LIB_NAME = "libdstpu_adam.so"


def _csrc_dir() -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.normpath(os.path.join(here, "..", "..", "..", "csrc"))


def load_library():
    """Load the native Adam library, (re)building via make first — a no-op
    when the .so is newer than the source. Returns None when neither a
    prebuilt .so nor a toolchain is available."""
    global _LIB
    with _LIB_LOCK:
        if _LIB is not None:
            return _LIB
        so_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               _LIB_NAME)
        # rebuild only when the .so is missing or older than the source, and
        # serialize concurrent builders (multi-host launcher / parallel
        # pytest on a shared filesystem) with an exclusive lock file so no
        # process ever dlopens a half-written binary
        src_path = os.path.join(_csrc_dir(), "adam", "cpu_adam.cpp")
        stale = (not os.path.exists(so_path) or
                 (os.path.exists(src_path) and
                  os.path.getmtime(src_path) > os.path.getmtime(so_path)))
        if stale:
            lock_path = so_path + ".buildlock"
            try:
                import fcntl
                with open(lock_path, "w") as lockf:
                    fcntl.flock(lockf, fcntl.LOCK_EX)
                    # another process may have finished while we waited
                    if (not os.path.exists(so_path) or
                            (os.path.exists(src_path) and
                             os.path.getmtime(src_path) >
                             os.path.getmtime(so_path))):
                        subprocess.run(["make", "-C", _csrc_dir()],
                                       check=True, capture_output=True)
            except Exception:
                if not os.path.exists(so_path):
                    return None  # no toolchain and no prebuilt library
        try:
            lib = ctypes.CDLL(so_path)
        except OSError:
            return None
        lib.ds_adam_create.argtypes = [
            ctypes.c_int, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_float, ctypes.c_int, ctypes.c_int]
        lib.ds_adam_step.argtypes = [
            ctypes.c_int, ctypes.c_longlong, ctypes.c_float,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_longlong, ctypes.c_void_p]
        lib.ds_adam_step.restype = ctypes.c_int
        lib.ds_adam_simd_width.restype = ctypes.c_int
        lib.ds_adam_destroy.argtypes = [ctypes.c_int]
        lib.ds_adam_destroy.restype = ctypes.c_int
        _LIB = lib
        return _LIB


def _fptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class DeepSpeedCPUAdam:
    """Host-side Adam over flat fp32 numpy leaves (reference
    ``cpu_adam.py:8``). Functional contract: construct with the parameter
    pytree (host copies are made), call :meth:`step` with the grad pytree
    (numpy or JAX arrays), read back :attr:`master_params` or the bf16
    output of ``step``.
    """

    _next_id = 0

    def __init__(self, model_params: Any, lr: float = 1e-3,
                 betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, bias_correction: bool = True,
                 adamw_mode: bool = True, amsgrad: bool = False):
        assert not amsgrad, "amsgrad not supported (reference cpu_adam.py:29)"
        import jax  # local import: keep module importable without jax

        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.bias_correction = bias_correction
        self.adamw_mode = adamw_mode

        leaves, self._treedef = jax.tree_util.tree_flatten(model_params)
        self._shapes = [np.shape(x) for x in leaves]
        # explicit .copy(): np.asarray on a jax.Array aliases the device
        # buffer read-only, and the native kernel writes through raw
        # pointers — it must own its memory
        self.master_params = [
            np.array(x, dtype=np.float32, copy=True).ravel()
            for x in leaves]
        self.exp_avg = [np.zeros_like(p) for p in self.master_params]
        self.exp_avg_sq = [np.zeros_like(p) for p in self.master_params]
        self.step_count = 0

        self.opt_id = DeepSpeedCPUAdam._next_id
        DeepSpeedCPUAdam._next_id += 1
        self._lib = load_library()
        if self._lib is not None:
            self._lib.ds_adam_create(
                self.opt_id, ctypes.c_float(lr), ctypes.c_float(betas[0]),
                ctypes.c_float(betas[1]), ctypes.c_float(eps),
                ctypes.c_float(weight_decay), int(adamw_mode),
                int(bias_correction))
            # free the native registry entry when this optimizer dies
            # (one config leaked per instance otherwise)
            import weakref
            weakref.finalize(self, self._lib.ds_adam_destroy, self.opt_id)

    @property
    def uses_native_kernel(self) -> bool:
        return self._lib is not None

    def _step_numpy(self, i: int, g: np.ndarray, lr: float):
        """Fallback mirror of the C++ kernel (also its test oracle)."""
        b1, b2 = self.betas
        p, m, v = self.master_params[i], self.exp_avg[i], self.exp_avg_sq[i]
        if self.weight_decay > 0 and not self.adamw_mode:
            g = g + self.weight_decay * p
        np.multiply(m, b1, out=m)
        m += (1 - b1) * g
        np.multiply(v, b2, out=v)
        v += (1 - b2) * g * g
        if self.bias_correction:
            bc1 = 1 - b1 ** self.step_count
            inv_sqrt_bc2 = 1.0 / np.sqrt(1 - b2 ** self.step_count)
        else:
            bc1, inv_sqrt_bc2 = 1.0, 1.0
        denom = np.sqrt(v) * inv_sqrt_bc2 + self.eps
        if self.weight_decay > 0 and self.adamw_mode:
            p -= lr * self.weight_decay * p
        p -= (lr / bc1) * (m / denom)

    def step(self, grads: Any, lr: Optional[float] = None,
             bf16_out: bool = False, beta1: Optional[float] = None):
        """One Adam step over every leaf. Returns the updated parameter
        pytree — bf16 numpy arrays when ``bf16_out`` (the H2D payload),
        else fp32 views of the master copy.

        ``beta1``: scheduled momentum override (OneCycle cycle_momentum).
        The native side keeps only an AdamConfig (all state lives in the
        numpy arrays here), so re-registering the config with the new
        beta1 is a cheap, safe way to retune it mid-training."""
        import jax
        lr = self.lr if lr is None else float(lr)
        if beta1 is not None and float(beta1) != self.betas[0]:
            self.betas = (float(beta1), self.betas[1])
            if self._lib is not None:
                self._lib.ds_adam_create(
                    self.opt_id, ctypes.c_float(self.lr),
                    ctypes.c_float(self.betas[0]),
                    ctypes.c_float(self.betas[1]),
                    ctypes.c_float(self.eps),
                    ctypes.c_float(self.weight_decay),
                    int(self.adamw_mode), int(self.bias_correction))
        self.step_count += 1
        g_leaves = self._treedef.flatten_up_to(grads)
        outs = []
        for i, g in enumerate(g_leaves):
            g = np.ascontiguousarray(
                np.asarray(g, dtype=np.float32).ravel())
            n = self.master_params[i].size
            assert g.size == n, f"grad leaf {i}: {g.size} != {n}"
            out16 = np.empty(n, np.uint16) if bf16_out else None
            if self._lib is not None:
                rc = self._lib.ds_adam_step(
                    self.opt_id, self.step_count, ctypes.c_float(lr),
                    _fptr(self.master_params[i]), _fptr(g),
                    _fptr(self.exp_avg[i]), _fptr(self.exp_avg_sq[i]),
                    n,
                    out16.ctypes.data_as(ctypes.c_void_p)
                    if out16 is not None else None)
                assert rc == 0, f"native adam step failed rc={rc}"
            else:
                self._step_numpy(i, g, lr)
                if out16 is not None:
                    import ml_dtypes
                    out16[:] = self.master_params[i].astype(
                        ml_dtypes.bfloat16).view(np.uint16)  # RNE, like C++
            if out16 is not None:
                import ml_dtypes  # ships with jax
                outs.append(out16.view(ml_dtypes.bfloat16)
                            .reshape(self._shapes[i]))
            else:
                outs.append(self.master_params[i].reshape(self._shapes[i]))
        return self._treedef.unflatten(outs)

    # -- state I/O for checkpointing ------------------------------------ #
    def state_dict(self):
        return {"step": self.step_count,
                "master_params": [p.copy() for p in self.master_params],
                "exp_avg": [m.copy() for m in self.exp_avg],
                "exp_avg_sq": [v.copy() for v in self.exp_avg_sq]}

    def load_state_dict(self, sd):
        self.step_count = int(sd["step"])
        for dst, src in zip(self.master_params, sd["master_params"]):
            np.copyto(dst, np.asarray(src).ravel())
        for dst, src in zip(self.exp_avg, sd["exp_avg"]):
            np.copyto(dst, np.asarray(src).ravel())
        for dst, src in zip(self.exp_avg_sq, sd["exp_avg_sq"]):
            np.copyto(dst, np.asarray(src).ravel())
