from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam
