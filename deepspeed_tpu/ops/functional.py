"""Shared elementwise building blocks (LayerNorm, dropout).

Single home for the fp32-upcast LayerNorm and inverted dropout used by the
transformer layer and the model families — the TPU analog of the reference's
normalize_kernels.cu / dropout_kernels.cu, except XLA fuses these into the
surrounding GEMMs so the "kernel" is just the math.
"""

import jax
import jax.numpy as jnp


def layer_norm(x, w, b, eps: float = 1e-12):
    """LayerNorm in fp32 regardless of input dtype (matches the reference
    kernels' fp32 statistics), output in input dtype."""
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    out = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (out * w.astype(jnp.float32) + b.astype(jnp.float32)).astype(x.dtype)


def dropout(x, rate: float, rng, deterministic: bool):
    """Inverted dropout; identity when deterministic/rate==0/rng is None."""
    if deterministic or rate == 0.0 or rng is None:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))


def matmul_bf16_accum_fp32(x, w_t):
    """x @ w_t.T with bf16-cast operands and fp32 accumulation — the MXU
    fast path for vocab-size projections. w_t: (vocab, hidden)."""
    dtype = x.dtype if x.dtype in (jnp.bfloat16, jnp.float16) else jnp.bfloat16
    return jax.lax.dot_general(
        x.astype(dtype), w_t.astype(dtype),
        (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
