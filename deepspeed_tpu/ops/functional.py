"""Shared elementwise building blocks (LayerNorm, dropout).

Single home for the fp32-upcast LayerNorm and inverted dropout used by the
transformer layer and the model families — the TPU analog of the reference's
normalize_kernels.cu / dropout_kernels.cu, except XLA fuses these into the
surrounding GEMMs so the "kernel" is just the math.
"""

import jax
import jax.numpy as jnp
import numpy as np


def layer_norm(x, w, b, eps: float = 1e-12):
    """LayerNorm in fp32 regardless of input dtype (matches the reference
    kernels' fp32 statistics), output in input dtype."""
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    out = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (out * w.astype(jnp.float32) + b.astype(jnp.float32)).astype(x.dtype)


def rms_norm(x, w, eps: float = 1e-6):
    """RMSNorm in fp32 statistics (no mean subtraction, no bias), output
    in input dtype — the pre-norm used by the llama model family."""
    x32 = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(ms + eps) *
            w.astype(jnp.float32)).astype(x.dtype)


def _hash_keep_mask(seed32, n, rate: float):
    """lowbias32-style counter hash -> boolean keep mask of n elements.

    One integer hash per element instead of a threefry invocation per
    block: a GPT-2 345M step with the reference's 0.1-dropout config
    draws ~50 full-activation masks; threefry is the expensive part of
    that, not the masking (the attention kernels already use this hash
    for the same reason — ops/attention/flash.dropout_keep_mask)."""
    idx = jax.lax.iota(jnp.uint32, n)
    x = idx ^ seed32
    x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
    x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    keep_thresh = min(int(round((1.0 - rate) * 2.0**32)), 2**32 - 1)
    return x < jnp.uint32(keep_thresh)


def dropout(x, rate: float, rng, deterministic: bool):
    """Inverted dropout; identity when deterministic/rate==0/rng is None.

    The mask comes from a counter-based integer hash seeded by the jax
    key (one cheap 32-bit fold of the key, then one hash per element) —
    same statistical contract as ``jax.random.bernoulli`` for dropout
    purposes at a fraction of the TPU cost."""
    if deterministic or rate == 0.0 or rng is None:
        return x
    keep = 1.0 - rate
    data = jax.random.key_data(rng).astype(jnp.uint32)
    seed32 = (data[-1] ^ (data[-2] * jnp.uint32(0x9E3779B9))
              if data.shape[-1] >= 2 else data[-1])
    mask = _hash_keep_mask(seed32, int(np.prod(x.shape)),
                           rate).reshape(x.shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))


@jax.custom_vjp
def matmul_bf16_accum_fp32(x, w_t):
    """x @ w_t.T with bf16-cast operands and fp32 accumulation — the MXU
    fast path for vocab-size projections. w_t: (vocab, hidden).

    Custom VJP: autodiff's transposed dots would otherwise inherit bf16
    OUTPUTS (each partial dw rounded to bf16 before accumulation), making
    head gradients ~0.4% grouping-dependent — observed as a
    sequence-parallel vs dense mismatch. The backward dots here keep bf16
    operands but fp32 accumulation and fp32 results.
    """
    dtype = x.dtype if x.dtype in (jnp.bfloat16, jnp.float16) else jnp.bfloat16
    return jax.lax.dot_general(
        x.astype(dtype), w_t.astype(dtype),
        (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)


def _mm_bf16_fwd(x, w_t):
    return matmul_bf16_accum_fp32(x, w_t), (x, w_t)


def _mm_bf16_bwd(res, g):
    x, w_t = res
    dtype = x.dtype if x.dtype in (jnp.bfloat16, jnp.float16) else jnp.bfloat16
    gb = g.astype(dtype)
    # dx = g @ w_t  (contract vocab), fp32 accumulation
    dx = jax.lax.dot_general(
        gb, w_t.astype(dtype), (((g.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(x.dtype)
    # dw_t = g^T @ x (contract tokens), fp32 accumulation
    xb = x.astype(dtype).reshape(-1, x.shape[-1])
    gf = gb.reshape(-1, g.shape[-1])
    dw = jax.lax.dot_general(
        gf, xb, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(w_t.dtype)
    return dx, dw


matmul_bf16_accum_fp32.defvjp(_mm_bf16_fwd, _mm_bf16_bwd)


def stochastic_round_bf16(x, key):
    """fp32 -> bf16 cast with stochastic rounding.

    TPU-native realization of the reference's ``__STOCHASTIC_MODE__``
    build variant (csrc stochastic-rounding kernels, setup.py:211-242 in
    the reference): rounding direction is random with probability equal
    to the remainder, so E[sr(x)] == x and sub-ulp optimizer updates
    accumulate in expectation instead of being RNE-truncated to zero.
    This is what makes master-weight-free bf16 training track fp32-master
    quality (``bf16: {"master_weights": false}`` in the engine config).

    Mechanics: bitcast fp32 to uint32, add a uniform 16-bit integer to
    the low (truncated) mantissa bits, then keep the high 16 bits as the
    bf16 pattern. The carry out of the low half implements round-up with
    exactly remainder/2^16 probability; truncation otherwise rounds
    down. Finite values above bf16's max finite may stochastically round
    up to inf (their high half is at most 0x7F7F, so the +1 carry stops
    at 0x7F80 = inf, never NaN-space); only non-finite inputs bypass SR
    and take the plain RNE cast.
    """
    x = x.astype(jnp.float32)
    bits = jax.lax.bitcast_convert_type(x, jnp.uint32)
    noise = jax.random.bits(key, x.shape, jnp.uint32) & jnp.uint32(0xFFFF)
    rounded = jax.lax.shift_right_logical(
        bits + noise, jnp.uint32(16)).astype(jnp.uint16)
    sr = jax.lax.bitcast_convert_type(rounded, jnp.bfloat16)
    return jnp.where(jnp.isfinite(x), sr, x.astype(jnp.bfloat16))
