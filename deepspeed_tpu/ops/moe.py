"""Mixture-of-Experts layer with expert parallelism (TPU-native).

Beyond-reference extension (the DeepSpeed v0.3.0 snapshot has no MoE —
SURVEY.md §2.3 "No MoE/expert parallelism"): completes the ep member of
the tp/pp/dp/sp/ep parallelism family on the same named-mesh design as
the rest of the framework.

Design (GShard/Switch-style, XLA-first):
- Static shapes end to end: top-k routing is expressed as one-hot
  dispatch/combine tensors (T, E, C) — no dynamic gathers, no
  data-dependent shapes, so the whole layer jits and shards cleanly.
- Capacity: each expert owns C = ceil(top_k * T * capacity_factor / E)
  slots; tokens beyond an expert's capacity are dropped for that expert
  (their gate mass is simply lost, GShard semantics). Positions are
  assigned in token order via cumsum — second choices queue behind all
  first choices (GShard's priority rule).
- Expert parallelism = GSPMD: the (E, C, H) expert tensors carry a
  sharding constraint over the ``expert`` mesh axis; XLA inserts the
  all_to_all between the token-sharded and expert-sharded layouts —
  no hand-written collective, which is the named-axis analog of the
  reference's NCCL groups.
- Aux losses ride with the output: Switch load-balance loss
  (E * sum_e f_e * p_e) and router z-loss (mean logsumexp^2), both fp32.
"""

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class MoEConfig:
    hidden_size: int
    intermediate_size: int
    num_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    load_balance_coef: float = 1e-2
    router_z_coef: float = 1e-3

    def __post_init__(self):
        assert self.top_k >= 1, self.top_k
        assert self.num_experts >= self.top_k, (self.num_experts,
                                                self.top_k)


def init_moe_params(config: MoEConfig, key, dtype=jnp.float32):
    """{"router": (H, E), "wi": (E, H, F), "wo": (E, F, H)}."""
    kr, ki, ko = jax.random.split(key, 3)
    h, f, e = (config.hidden_size, config.intermediate_size,
               config.num_experts)
    return {
        "router": (jax.random.normal(kr, (h, e)) * 0.02).astype(dtype),
        "wi": (jax.random.normal(ki, (e, h, f)) * 0.02).astype(dtype),
        "wo": (jax.random.normal(ko, (e, f, h)) * 0.02).astype(dtype),
    }


def expert_capacity(config: MoEConfig, num_tokens: int) -> int:
    c = int(np.ceil(config.top_k * num_tokens * config.capacity_factor
                    / config.num_experts))
    return max(c, 1)


def _one_hot_positions(mask, capacity, start_counts):
    """Slot positions for one routing choice: mask (T, E) 0/1; tokens take
    slots in token order, starting after ``start_counts`` (E,) already-used
    slots. Returns (pos (T, E) int32, kept (T, E) bool, counts (E,))."""
    pos = jnp.cumsum(mask, axis=0) - 1 + start_counts[None, :]
    kept = jnp.logical_and(mask > 0, pos < capacity)
    counts = start_counts + jnp.sum(mask, axis=0)
    return pos.astype(jnp.int32), kept, counts


def moe_router(params, config: MoEConfig, x_tokens):
    """Routing: x_tokens (T, H) -> (dispatch (T, E, C) f32 0/1,
    combine (T, E, C) f32, aux_loss f32 scalar).

    fp32 router math (softmax over expert logits is tiny and
    precision-sensitive; reference-free design choice matching public
    MoE practice)."""
    t = x_tokens.shape[0]
    e = config.num_experts
    c = expert_capacity(config, t)

    logits = jnp.einsum("th,he->te", x_tokens.astype(jnp.float32),
                        params["router"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)               # (T, E)

    # --- top-k choices (static unroll over k): each round takes the
    # argmax of the remaining probs; earlier rounds claim capacity slots
    # first on ties (GShard priority — round r's choices take slots
    # before any round r+1 choice)
    remaining = probs
    counts = jnp.zeros((e,), jnp.int32)
    choices = []                                          # (mask, gate, pos, kept)
    for _ in range(config.top_k):
        idx = jnp.argmax(remaining, axis=-1)              # (T,)
        mask = jax.nn.one_hot(idx, e, dtype=jnp.float32)  # (T, E)
        gate = jnp.sum(probs * mask, axis=-1)             # (T,)
        pos, kept, counts = _one_hot_positions(mask, c, counts)
        choices.append((mask, gate, pos, kept))
        remaining = remaining * (1.0 - mask)
    if config.top_k > 1:
        # renormalize over the selected gates (GShard)
        denom = jnp.maximum(sum(g for _, g, _, _ in choices), 1e-9)
    else:
        denom = 1.0

    def scatter(kept, pos, gate):
        # (T, E, C): one-hot over the capacity slot, weighted by the gate
        slot = jax.nn.one_hot(pos, c, dtype=jnp.float32)  # (T, E, C)
        d = slot * kept[..., None].astype(jnp.float32)
        return d, d * gate[:, None, None]

    dispatch = jnp.zeros((t, e, c), jnp.float32)
    combine = jnp.zeros((t, e, c), jnp.float32)
    for mask, gate, pos, kept in choices:
        d_r, w_r = scatter(kept, pos, gate / denom)
        dispatch = dispatch + d_r
        combine = combine + w_r

    # Switch load-balance loss: fraction of tokens routed (first choice)
    # vs mean router probability, per expert
    f_e = jnp.mean(choices[0][0], axis=0)
    p_e = jnp.mean(probs, axis=0)
    lb = config.load_balance_coef * e * jnp.sum(f_e * p_e)
    z = config.router_z_coef * jnp.mean(
        jax.nn.logsumexp(logits, axis=-1) ** 2)
    return dispatch, combine, lb + z


# The expert math shared by both dispatch forms (moe_layer injects a
# GSPMD sharding constraint around the (E, C, H) slot tensors; the
# sharded form injects the all_to_all pair) — one implementation, so the
# two forms cannot drift.
def _dispatch_slots(dispatch, xt, dtype):
    return jnp.einsum("tec,th->ech", dispatch.astype(dtype),
                      xt.astype(dtype))


def _expert_ffn(slots, wi, wo, dtype):
    hdn = jax.nn.gelu(jnp.einsum("ech,ehf->ecf", slots, wi.astype(dtype)))
    return jnp.einsum("ecf,efh->ech", hdn, wo.astype(dtype))


def _combine_tokens(combine, out, dtype):
    return jnp.einsum("tec,ech->th", combine.astype(dtype), out)


def moe_layer(params, config: MoEConfig, x, *,
              expert_axis: Optional[str] = None, mesh=None,
              dtype=jnp.bfloat16):
    """MoE FFN: x (B, S, H) -> (y (B, S, H), aux_loss scalar fp32).

    ``expert_axis``: mesh axis name to shard experts over (expert
    parallelism); None = fully replicated experts. The constraint is all
    GSPMD needs — it inserts the token<->expert all_to_all pair. Pass
    ``mesh`` when calling outside a ``with mesh:`` context (e.g. from
    the engine's compiled step, which jits with explicit shardings).

    Scale note: routing is formulated over the GLOBAL token set (T =
    B*S), so expert buffers are (E, C_global, H) — exact and simple, and
    what the tests pin, but the dispatch collective grows with the data
    degree, AND the one-hot dispatch/combine tensors are (T, E, C) with
    E*C ~= top_k*capacity_factor*T, i.e. ~2.5*T^2 elements per MoE layer
    — at T=16k global tokens that is ~2.6GB fp32 of HBM per layer,
    which OOMs before the collective-growth concern bites. Above a few
    thousand global tokens use :func:`moe_layer_sharded` (per-shard
    dispatch under shard_map: local capacity, explicit all_to_all);
    the kernel math here is unchanged by that wrapping."""
    b, s, h = x.shape
    xt = x.reshape(b * s, h)
    dispatch, combine, aux = moe_router(params, config, xt)

    def constrain(v):
        if expert_axis is None:
            return v
        from jax.lax import with_sharding_constraint as wsc
        from jax.sharding import NamedSharding, PartitionSpec as P
        spec = P(expert_axis, None, None)
        if mesh is not None:
            return wsc(v, NamedSharding(mesh, spec))
        return wsc(v, spec)

    slots = constrain(_dispatch_slots(dispatch, xt, dtype))
    out = constrain(_expert_ffn(slots, params["wi"], params["wo"], dtype))
    y = _combine_tokens(combine, out, dtype)
    return y.reshape(b, s, h).astype(x.dtype), aux


def moe_layer_reference(params, config: MoEConfig, x):
    """Token-loop numpy oracle with identical routing/capacity/priority
    semantics — the test ground truth."""
    b, s, h = x.shape
    xt = np.asarray(x, np.float32).reshape(b * s, h)
    router = np.asarray(params["router"], np.float32)
    wi = np.asarray(params["wi"], np.float32)
    wo = np.asarray(params["wo"], np.float32)
    e = config.num_experts
    c = expert_capacity(config, xt.shape[0])

    logits = xt @ router
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)

    arange = np.arange(len(xt))
    idxs, gates = [], []
    p = probs.copy()
    for _ in range(config.top_k):
        idx = p.argmax(-1)
        gates.append(probs[arange, idx])
        idxs.append(idx)
        p[arange, idx] = 0.0
    if config.top_k > 1:
        denom = np.maximum(sum(gates), 1e-9)
        gates = [g / denom for g in gates]
    choices = [(r, ti, idxs[r][ti], gates[r][ti])
               for r in range(config.top_k) for ti in range(len(xt))]

    used = np.zeros(e, np.int32)
    y = np.zeros_like(xt)
    # first choices take slots before any second choice (GShard priority)
    for _, ti, ei, g in sorted(choices, key=lambda t: t[0]):
        if used[ei] < c:
            used[ei] += 1
            hdn = _np_gelu(xt[ti] @ wi[ei])
            y[ti] += g * (hdn @ wo[ei])
    return y.reshape(b, s, h)


def _np_gelu(x):
    return 0.5 * x * (1.0 + np.tanh(np.sqrt(2.0 / np.pi) *
                                    (x + 0.044715 * x ** 3)))


def moe_layer_sharded(params, config: MoEConfig, x, mesh,
                      expert_axis: str = "expert", dtype=jnp.bfloat16):
    """Per-shard MoE dispatch under ``shard_map`` — the scalable form of
    :func:`moe_layer` for large meshes.

    Tokens AND experts shard over ``expert_axis`` (the classic
    single-axis MoE layout): each of the P devices routes its local
    T/P tokens with local capacity C_l = ceil(top_k * T_l * cf / E),
    then one explicit ``all_to_all`` pair swaps the (E, C_l, H) slot tensors
    so every device holds its E/P experts' slots from all peers —
    collective payload per device is capacity-bound (E * C_l * H),
    independent of the data degree, where the GSPMD global formulation
    grows with it. Semantics match moe_layer except capacity/priority
    are per shard (identical when nothing overflows).

    x: (B, S, H) with B divisible by the axis size; params as
    init_moe_params (router replicated; wi/wo sharded over experts).
    Returns (y, aux) like moe_layer (aux is the mean over shards).
    """
    from jax.sharding import PartitionSpec as P

    p_size = mesh.shape[expert_axis]
    e = config.num_experts
    assert e % p_size == 0, (e, p_size)
    b = x.shape[0]
    assert b % p_size == 0, (x.shape, p_size)

    def shard_fn(router, wi, vo, xs):
        bs, ss, h = xs.shape
        xt = xs.reshape(bs * ss, h)
        dispatch, combine, aux = moe_router(
            {"router": router}, config, xt)
        # (T_l, E, C_l) x (T_l, H) -> (E, C_l, H) local slots
        slots = jnp.einsum("tec,th->ech", dispatch.astype(dtype),
                           xt.astype(dtype))
        # swap: split experts across peers, gather peers' slots for ours
        slots = jax.lax.all_to_all(slots, expert_axis, split_axis=0,
                                   concat_axis=1, tiled=True)
        hdn = jax.nn.gelu(jnp.einsum("ech,ehf->ecf", slots,
                                     wi.astype(dtype)))
        out = jnp.einsum("ecf,efh->ech", hdn, vo.astype(dtype))
        # swap back: return each peer its tokens' outputs
        out = jax.lax.all_to_all(out, expert_axis, split_axis=1,
                                 concat_axis=0, tiled=True)
        y = jnp.einsum("tec,ech->th", combine.astype(dtype), out)
        aux = jax.lax.pmean(aux, expert_axis)
        return y.reshape(bs, ss, h).astype(xs.dtype), aux

    fn = jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(), P(expert_axis, None, None),
                  P(expert_axis, None, None), P(expert_axis, None, None)),
        out_specs=(P(expert_axis, None, None), P()),
        check_vma=False)
    return fn(params["router"], params["wi"], params["wo"], x)
