"""Multi-host bootstrap — the torch.distributed.init_process_group analog.

The reference engine called ``dist.init_process_group('nccl')``
(engine.py:139) with env-var rendezvous set up by its launcher
(launch.py:106-116) and an optional MPI bootstrap (engine.py:198 _mpi_check).
On TPU the same role is played by ``jax.distributed.initialize``: one process
per host, chips auto-discovered, XLA collectives ride ICI/DCN.
"""

import os
from typing import Optional

from deepspeed_tpu.utils.logging import logger

_initialized = False


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Initialize multi-host JAX if launched by ``dstpu`` (or explicitly).

    Reads DSTPU_COORDINATOR / DSTPU_NUM_PROCESSES / DSTPU_PROCESS_ID set by
    the launcher; falls back to TPU-pod auto-detection via
    ``jax.distributed.initialize()`` no-arg form when JAX can discover the
    topology itself; no-op for single-process runs.
    """
    global _initialized
    if _initialized:
        return

    import jax

    coordinator = coordinator_address or os.environ.get("DSTPU_COORDINATOR")
    nprocs = num_processes if num_processes is not None else \
        int(os.environ.get("DSTPU_NUM_PROCESSES", "0") or 0)
    pid = process_id if process_id is not None else \
        int(os.environ.get("DSTPU_PROCESS_ID", "-1") or -1)
    if pid < 0 and os.environ.get("DSTPU_PROCESS_ID_FROM_MPI"):
        # OpenMPIRunner path: identity comes from the MPI rank env
        # (reference bootstraps ranks from mpi4py, engine.py:198 _mpi_check)
        pid = int(os.environ.get("OMPI_COMM_WORLD_RANK", "-1") or -1)

    if coordinator and nprocs > 1 and pid >= 0:
        logger.info(f"jax.distributed.initialize(coordinator={coordinator}, "
                    f"num_processes={nprocs}, process_id={pid})")
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=nprocs,
                                   process_id=pid)
        _initialized = True
    elif os.environ.get("TPU_WORKER_HOSTNAMES", "").count(",") > 0:
        # Multi-worker TPU pod slice: jax can self-discover.
        logger.info("jax.distributed.initialize() [TPU pod auto-detect]")
        jax.distributed.initialize()
        _initialized = True
    # else: single process, nothing to do.


def is_initialized() -> bool:
    return _initialized
