"""DeepSpeed-TPU: a TPU-native training framework.

Re-implements the capabilities of the reference DeepSpeed snapshot
(``deepspeed/__init__.py``; initialize at :52, add_config_arguments at :195)
on JAX/XLA/Pallas: ZeRO via GSPMD sharding, pipeline + 3D parallelism over a
named device mesh, fused transformer kernels in Pallas, bf16-first mixed
precision, block-sparse attention, and a multi-host launcher.
"""

# version shims first: the runtime below (and user scripts) use the
# modern jax.shard_map spelling, which older jax lacks
from deepspeed_tpu.utils.jax_compat import install as _install_jax_compat
_install_jax_compat()

from deepspeed_tpu.runtime.engine import DeepSpeedEngine, TrainState
from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu.runtime.pipe import (
    LayerSpec, PipelineModule, PipelineSpec, TiedLayerSpec)
from deepspeed_tpu.runtime.pipe.engine import PipelineEngine
from deepspeed_tpu.runtime.lr_schedules import (
    WarmupLR, OneCycle, LRRangeTest, add_tuning_arguments)
from deepspeed_tpu.utils.logging import log_dist
from deepspeed_tpu.runtime.dataloader import (
    DeepSpeedDataLoader, PrefetchLoader, RepeatingLoader)
from deepspeed_tpu.parallel.topology import (
    ProcessTopology, PipeDataParallelTopology, PipeModelDataParallelTopology,
    ParallelGrid)
from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.ops.optimizers import (
    Adam, FusedAdam, Lamb, FusedLamb, SGD)
# reference exports the fused layer at top level (__init__.py:15)
from deepspeed_tpu.ops.transformer import (
    DeepSpeedTransformerLayer, DeepSpeedTransformerConfig)
# reference exports `deepspeed.checkpointing` (__init__.py:16)
from deepspeed_tpu.runtime.activation_checkpointing import checkpointing
# explicit multi-host bootstrap for user scripts (engine.py calls it
# automatically at initialize(); exported for the standalone-use parity
# of deepspeed.init_distributed)
from deepspeed_tpu.distributed import init_distributed
# serving (TPU-native extension: the reference snapshot is
# training-only; docs/inference.md)
from deepspeed_tpu.inference import InferenceEngine

__version__ = "0.1.0"


def _git_info():
    """Best-effort (hash, branch) — the reference bakes these at install
    (setup.py writes git_version_info consumed by basic_install_test.py);
    here they read from the working tree and fall back to 'unknown'."""
    import os
    head = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".git", "HEAD")
    try:
        with open(head) as f:
            ref = f.read().strip()
        if ref.startswith("ref:"):
            refname = ref.split()[1]
            branch = refname.split("/")[-1]
            try:
                with open(os.path.join(os.path.dirname(head),
                                       *refname.split("/"))) as f:
                    return f.read().strip()[:9], branch
            except OSError:
                # after git gc/pack-refs the loose ref file is gone —
                # the hash lives in .git/packed-refs (ADVICE r3 #1)
                try:
                    with open(os.path.join(os.path.dirname(head),
                                           "packed-refs")) as f:
                        for line in f:
                            parts = line.strip().split(" ", 1)
                            if len(parts) == 2 and parts[1] == refname:
                                return parts[0][:9], branch
                except OSError:
                    pass
                return "unknown", branch
        return ref[:9], "detached"
    except OSError:
        return "unknown", "unknown"


__git_hash__, __git_branch__ = _git_info()


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               mpu=None,
               param_specs=None,
               dist_init_required=None,
               collate_fn=None,
               config=None,
               config_params=None):
    """Initialize the DeepSpeed-TPU engine (reference __init__.py:52).

    Returns the same 4-tuple as the reference:
    ``(engine, optimizer, training_dataloader, lr_scheduler)``.

    Model contract (TPU-native): ``model`` is a pure loss function
    ``loss_fn(params, batch[, rng]) -> loss | (loss, aux)`` and
    ``model_parameters`` is the initial parameter pytree. Use
    :func:`flax_loss_fn` to adapt a flax module + criterion.
    """
    if isinstance(model, (PipelineModule, PipelineSpec)):
        # (reference __init__.py:111-133 dispatches on PipelineModule)
        assert mpu is None, "mpu is owned by the PipelineModule's topology"
        assert param_specs is None, \
            "pipeline models carry their own shardings (PipelineSpec " \
            "pre/stage/post_specs); param_specs is not consumed here"
        engine = PipelineEngine(model=model,
                                args=args,
                                optimizer=optimizer,
                                model_parameters=model_parameters,
                                training_data=training_data,
                                lr_scheduler=lr_scheduler,
                                collate_fn=collate_fn,
                                config=config,
                                config_params=config_params)
    else:
        engine = DeepSpeedEngine(args=args,
                                 model=model,
                                 optimizer=optimizer,
                                 model_parameters=model_parameters,
                                 training_data=training_data,
                                 lr_scheduler=lr_scheduler,
                                 mpu=mpu,
                                 param_specs=param_specs,
                                 collate_fn=collate_fn,
                                 config=config,
                                 config_params=config_params)
    return (engine, engine.optimizer, engine.training_dataloader,
            engine.lr_scheduler)


def flax_loss_fn(module, criterion):
    """Adapt a flax linen Module + criterion to the engine's loss contract.

    ``criterion(outputs, batch) -> loss``; batches are pytrees whose
    structure the criterion understands (e.g. dicts with 'x'/'y').
    """
    def loss_fn(params, batch, rng):
        inputs = batch["x"] if isinstance(batch, dict) else batch[0]
        outputs = module.apply({"params": params}, inputs,
                               rngs={"dropout": rng})
        return criterion(outputs, batch)
    return loss_fn


def add_config_arguments(parser):
    """Add --deepspeed / --deepspeed_config CLI args
    (reference __init__.py:144-192)."""
    group = parser.add_argument_group("DeepSpeed-TPU",
                                      "DeepSpeed-TPU configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed-TPU (helper flag)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="DeepSpeed-TPU json configuration file")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help="Deprecated alias of --deepspeed")
    group.add_argument("--deepscale_config", default=None, type=str,
                       help="Deprecated alias of --deepspeed_config")
    return parser
