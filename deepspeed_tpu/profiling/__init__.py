"""Unified profiling & telemetry layer.

One opt-in config section (``observability: {}``) wires four probes
through the engine:

- **FLOPs/MFU profiler** (:mod:`.flops`): cost-analysis of the compiled
  micro-step → model FLOPs, bytes accessed, per-step MFU against a
  peak-FLOPs device registry.
- **Recompile tracking** (:mod:`.recompile`): every compiled entry
  point is wrapped; compile counts/wall-times are recorded and
  steady-state recompiles (the silent TPU perf killer) warn loudly.
- **HBM watermarks** (:mod:`.memory`): structured
  ``device.memory_stats()`` samples at step boundaries, with per-phase
  deltas and a run peak (host-RSS fallback on backends without
  allocator stats).
- **Trace spans** (:mod:`.spans`): ``trace_span("forward")`` shows up in
  captured XLA traces *and* in a standalone Chrome-trace JSON.

Everything lands as ``(tag, value, step)`` scalars on the monitor AND
in a crash-safe JSONL event log (``events.jsonl``) that
``tools/obs_report.py`` renders into a run summary. The x-axis is
cumulative samples, matching the reference's tensorboard convention.

:class:`Observer` is the engine-facing facade; the probe modules are
importable standalone.
"""

import os
from typing import Any, Dict, Optional, Tuple

from deepspeed_tpu.profiling.flops import (
    FlopsProfile, compute_mfu, format_profile, peak_flops_per_device,
    profile_jit_fn)
from deepspeed_tpu.profiling.memory import MemoryWatermark, memory_snapshot
from deepspeed_tpu.profiling.recompile import (CompileEvent, CompileTracker,
                                               TrackedFunction)
from deepspeed_tpu.profiling.spans import (ChromeTraceRecorder,
                                           get_default_recorder,
                                           set_default_recorder, trace_span)
from deepspeed_tpu.utils.logging import log_dist, logger

__all__ = [
    "Observer", "FlopsProfile", "CompileTracker", "CompileEvent",
    "TrackedFunction", "MemoryWatermark", "memory_snapshot",
    "ChromeTraceRecorder", "trace_span", "set_default_recorder",
    "get_default_recorder", "compute_mfu", "peak_flops_per_device",
    "profile_jit_fn",
]

# scalar tags (pinned by tests/unit/test_observability.py and consumed
# by tools/obs_report.py — change both together)
TAG_FLOPS = "Observability/flops_per_step"
TAG_BYTES = "Observability/bytes_accessed"
TAG_MFU = "Observability/mfu"
TAG_RECOMPILES = "Observability/recompiles"
TAG_COMPILE_MS = "Observability/compile_ms_total"
TAG_MEM_IN_USE = "Memory/bytes_in_use"
TAG_MEM_PEAK = "Memory/peak_bytes_in_use"
TAG_MEM_DELTA = "Memory/step_delta_bytes"
# async-pipeline host-overhead counters (docs/performance.md "Async
# step pipeline"; rendered by tools/obs_report.py)
TAG_DISPATCHES = "Observability/dispatches"       # cumulative jit calls
TAG_HOST_SYNCS = "Observability/host_syncs"       # cumulative forced syncs
TAG_HOST_GAP = "Observability/host_gap_ms"        # per-step host gap time
# serving telemetry tags, re-exported into this registry from their
# canonical home (utils/monitor.py write_serving_metrics, which writes
# them; stdlib-only tools/obs_report.py mirrors the strings and the
# pair is pinned by tests/unit/test_inference.py)
from deepspeed_tpu.utils.monitor import (  # noqa: E402,F401
    TAG_SERVE_CHUNK_DISPATCHES, TAG_SERVE_DECODE_ATTN,
    TAG_SERVE_FLEET_QDEPTH, TAG_SERVE_GOODPUT,
    TAG_SERVE_HANDOFF, TAG_SERVE_KV_PAGES, TAG_SERVE_KV_POOL_BPT,
    TAG_SERVE_MIGRATIONS, TAG_SERVE_OCCUPANCY, TAG_SERVE_PREFIX_HIT,
    TAG_SERVE_QUANT_LOGIT_ERR, TAG_SERVE_QUEUE_DEPTH,
    TAG_SERVE_QUEUE_WAIT, TAG_SERVE_REPLICA_RESTARTS,
    TAG_SERVE_SHED_RATE, TAG_SERVE_SLO, TAG_SERVE_SPEC_ACCEPT,
    TAG_SERVE_TBT, TAG_SERVE_TBT_MAX, TAG_SERVE_TOKEN_LATENCY,
    TAG_SERVE_TOKENS_IN_FLIGHT, TAG_SERVE_TPS, TAG_SERVE_TTFT,
    TAG_SERVE_WEIGHT_VERSION)
# elastic / async-checkpoint plane (ISSUE 10), same canonical-home
# arrangement (utils/monitor.py write_elastic_metrics writes them;
# obs_report mirrors; pinned by tests/unit/test_elastic.py)
from deepspeed_tpu.utils.monitor import (  # noqa: E402,F401
    TAG_CKPT_PENDING, TAG_CKPT_RESTARTS, TAG_CKPT_SNAPSHOT_MS,
    TAG_CKPT_WRITE_MS)
# health plane (ISSUE 15), same canonical-home arrangement (utils/
# health.py writes it via the monitor; obs_report mirrors; pinned by
# tests/unit/test_health.py)
from deepspeed_tpu.utils.monitor import (  # noqa: E402,F401
    TAG_HEALTH_ALERTS)


class Observer:
    """Engine-facing facade over the probes.

    Construction is cheap and always succeeds; when ``enabled`` is
    False (config off, or non-zero rank — telemetry is rank-0 like the
    monitor) every method is a no-op/passthrough, so the engine wires
    it unconditionally. Instrumentation failures degrade to warnings:
    observability must never take down a training step.
    """

    def __init__(self, cfg: Dict[str, Any], monitor=None, rank: int = 0,
                 device=None, num_devices: Optional[int] = None):
        self.cfg = cfg
        self.monitor = monitor
        self.enabled = bool(cfg.get("enabled")) and rank == 0
        self._device = device
        self._num_devices = num_devices
        self._log = None
        self.compile_tracker: Optional[CompileTracker] = None
        self.memory: Optional[MemoryWatermark] = None
        self.recorder: Optional[ChromeTraceRecorder] = None
        self.flops_profiles: Dict[str, FlopsProfile] = {}
        self._step_provider = lambda: 0
        self._closed = False
        if not self.enabled:
            return

        events_dir = cfg.get("events_dir") or "/tmp/deepspeed_tpu_obs"
        try:
            from deepspeed_tpu.utils.monitor import _JsonlWriter
            self._log = _JsonlWriter(
                events_dir, max_mb=cfg.get("events_max_mb", 0) or 0)
        except Exception as e:
            logger.warning(f"observability: event log unavailable "
                           f"({e}); scalars go to the monitor only")
        # route every monitor scalar (loss, lr, step time, comm bytes,
        # checkpoint events) into the event log too, so obs_report sees
        # one complete record even when tensorboard is off
        if self.monitor is not None and self._log is not None:
            self.monitor.mirror = self._log

        self.compile_tracker = CompileTracker(
            step_provider=lambda: self._step_provider(),
            warn_after=int(cfg.get("recompile_warn_after", 1)),
            on_event=self._on_compile_event)
        if cfg.get("memory_watermarks", True):
            self.memory = MemoryWatermark(device)
        self.recorder = ChromeTraceRecorder()
        self._chrome_path = cfg.get("chrome_trace_path") or None
        self._chrome_last_dump = 0.0  # monotonic secs; 0 = never dumped
        # the engine has no shutdown hook; close() (idempotent) seals
        # the compile summary + final chrome trace at interpreter exit
        import atexit
        atexit.register(self.close)
        log_dist(f"observability: enabled (events -> "
                 f"{os.path.join(events_dir, 'events.jsonl')})", ranks=[0])

    # ------------------------------------------------------------ sinks
    def set_step_provider(self, fn) -> None:
        """Host-step source for compile-event attribution (the engine's
        ``_host_global_step`` mirror — no device sync)."""
        self._step_provider = fn

    def scalar(self, tag: str, value, step: int) -> None:
        """One (tag, value, step) record to monitor + event log."""
        if not self.enabled:
            return
        if self.monitor is not None:
            self.monitor.write_scalar(tag, value, step)
        elif self._log is not None:
            self._log.add_scalar(tag, value, step)

    def event(self, kind: str, **fields) -> None:
        """One structured (non-scalar) event row in the JSONL log."""
        if self._log is not None:
            self._log.add_event(kind, **fields)

    def _on_compile_event(self, ev: CompileEvent) -> None:
        self.event("compile", fn=ev.fn_name, count=ev.count,
                   wall_ms=round(ev.wall_ms, 3), step=ev.step)

    def record_comm_plan(self, **plan_fields) -> None:
        """One ``comm_plan`` event row: the collective autotuner's
        decision (algo/block/hierarchy), its cost-model evidence, and
        any calibration result (runtime/comm_autotune.py) — rendered by
        tools/obs_report.py next to the per-step comm bytes so a run's
        wire numbers carry the WHY of the exchange that produced them."""
        self.event("comm_plan", **plan_fields)

    # ------------------------------------------------------------ probes
    def wrap_jit(self, fn, name: str):
        """Wrap a jit-compiled callable for compile tracking; identity
        when disabled (existing code sees the raw jit function)."""
        if not self.enabled or self.compile_tracker is None:
            return fn
        return self.compile_tracker.wrap(fn, name)

    def span(self, name: str, **extra):
        """Phase span: XLA TraceAnnotation always (near-free, shows in
        captured traces even with observability off), Chrome-trace event
        when enabled. trace_span itself never raises from
        instrumentation (annotation enter/exit are guarded in-body)."""
        return trace_span(name, recorder=self.recorder, **extra)

    def wants_flops_profile(self, name: str) -> bool:
        return (self.enabled and bool(self.cfg.get("flops_profiler", True))
                and name not in self.flops_profiles)

    def maybe_profile_flops(self, name: str, fn, args: Tuple,
                            samples: int = 0) -> Optional[FlopsProfile]:
        """One-time cost-analysis of a compiled entry point (an AOT
        re-compile — opt-in cost, absorbed by the persistent compile
        cache on re-runs). Writes the FLOPs/bytes scalars and logs the
        reference-style profile block."""
        if not self.wants_flops_profile(name):
            return self.flops_profiles.get(name)
        try:
            prof = profile_jit_fn(fn, args, name=name, device=self._device,
                                  num_devices=self._num_devices)
        except Exception as e:
            logger.warning(f"observability: cost analysis of {name!r} "
                           f"failed ({e!r}); MFU will not be reported")
            # sentinel so we don't retry (and re-fail) every step
            prof = FlopsProfile(name=name, flops=0.0, bytes_accessed=0.0,
                                peak_flops_per_device=0.0, device_kind="?",
                                num_devices=0)
            self.flops_profiles[name] = prof
            return prof
        self.flops_profiles[name] = prof
        self.scalar(TAG_FLOPS, prof.flops, samples)
        self.scalar(TAG_BYTES, prof.bytes_accessed, samples)
        self.event("flops_profile", fn=name, flops=prof.flops,
                   bytes_accessed=prof.bytes_accessed,
                   peak_flops_per_device=prof.peak_flops_per_device,
                   device_kind=prof.device_kind,
                   num_devices=prof.num_devices,
                   compile_ms=round(prof.compile_ms or 0.0, 3))
        log_dist(format_profile(prof), ranks=[0])
        return prof

    # --------------------------------------------------------- per step
    def mfu(self, step_time_ms: Optional[float],
            micro_steps_per_step: int = 1,
            program: str = "micro_step") -> Optional[float]:
        """Model FLOPs utilization for one step time, from the profiled
        program, or None when either is missing. cost_analysis flops
        are PER-DEVICE (FlopsProfile docstring) so the denominator is
        the per-device peak — the ratio equals global-flops /
        all-device-peak. The engine calls this at telemetry-flush
        barriers with the window-averaged step time (per-dispatch wall
        clock is not device time once the host runs ahead of an async
        device)."""
        if not self.enabled or not step_time_ms:
            return None
        prof = (self.flops_profiles.get(program)
                or self.flops_profiles.get("micro_step"))
        if prof is None or prof.flops <= 0:
            return None
        return compute_mfu(prof.flops * max(micro_steps_per_step, 1),
                           step_time_ms / 1e3,
                           prof.peak_flops_per_device)

    def write_mfu(self, step_time_ms: Optional[float], samples: int,
                  micro_steps_per_step: int = 1,
                  program: str = "micro_step") -> Optional[float]:
        """Compute AND emit the MFU scalar for one honest step time —
        the single emission path (the engine calls it at telemetry
        flush barriers with the window-averaged time)."""
        mfu = self.mfu(step_time_ms, micro_steps_per_step, program)
        if mfu is not None:
            self.scalar(TAG_MFU, mfu, samples)
        return mfu

    def on_step(self, samples: int, step_time_ms: Optional[float],
                micro_steps_per_step: int = 1,
                program: str = "micro_step",
                host_gap_ms: Optional[float] = None,
                host_syncs: Optional[int] = None) -> None:
        """Step-boundary emission: MFU, recompile + dispatch counters,
        memory watermarks; Chrome trace refreshed on disk.
        ``micro_steps_per_step`` scales the profiled program's FLOPs up
        to the full optimizer step (gradient accumulation runs the
        compiled micro-step N times per reported step time; the fused
        ``batch_step`` program already covers the window, so its caller
        passes 1). ``program`` names the profiled entry point.
        ``host_gap_ms``/``host_syncs`` are the async-pipeline host
        overhead counters (time the host spent outside the dispatch,
        cumulative forced device syncs)."""
        if not self.enabled:
            return
        self.write_mfu(step_time_ms, samples, micro_steps_per_step,
                       program)
        if self.compile_tracker is not None:
            self.scalar(TAG_RECOMPILES, self.compile_tracker.total_compiles,
                        samples)
            self.scalar(TAG_COMPILE_MS, self.compile_tracker.total_compile_ms,
                        samples)
            self.scalar(TAG_DISPATCHES,
                        self.compile_tracker.total_dispatches, samples)
        if host_gap_ms is not None:
            self.scalar(TAG_HOST_GAP, host_gap_ms, samples)
        if host_syncs is not None:
            self.scalar(TAG_HOST_SYNCS, host_syncs, samples)
        if self.memory is not None:
            snap = self.memory.sample("step")
            if snap is not None:
                self.scalar(TAG_MEM_IN_USE, snap["bytes_in_use"], samples)
                self.scalar(TAG_MEM_PEAK, self.memory.peak_bytes, samples)
                self.scalar(TAG_MEM_DELTA, snap["delta_bytes"], samples)
        if self._chrome_path and self.recorder is not None:
            # throttled: rewriting the whole trace JSON is O(buffered
            # events) — once early (so the file exists mid-run), then at
            # most every few seconds; close() writes the final state
            import time as _time
            now = _time.monotonic()
            if self._chrome_last_dump == 0.0 or \
                    now - self._chrome_last_dump > 5.0:
                try:
                    self.recorder.dump(self._chrome_path)
                    self._chrome_last_dump = now
                except Exception:
                    pass
        if self._log is not None:
            self._log.flush()

    def close(self) -> None:
        if self._closed or not self.enabled:
            return
        self._closed = True
        # drop the atexit pin: without this, the registry (via the
        # step_provider closure) would keep the engine — and its
        # on-device state — alive for the whole process lifetime
        import atexit
        try:
            atexit.unregister(self.close)
        except Exception:
            pass
        self._step_provider = lambda: 0
        if self._chrome_path and self.recorder is not None:
            try:
                self.recorder.dump(self._chrome_path)
            except Exception:
                pass
        if self.compile_tracker is not None:
            self.event("compile_summary", **self.compile_tracker.summary())
        if self.monitor is not None and \
                getattr(self.monitor, "mirror", None) is self._log:
            self.monitor.mirror = None
        if self._log is not None:
            self._log.close()
            self._log = None
