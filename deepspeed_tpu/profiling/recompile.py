"""Recompilation tracking for the engine's jit-compiled step functions.

Silent steady-state recompiles are the classic TPU perf killer: a shape
or dtype drift (last short batch, a python float promoted differently,
a debug flag flipping a static arg) quietly re-pays tens of seconds of
XLA compile inside what looks like a training step. The reference's
eager runtime cannot have this failure mode, so it has no analog — here
every compiled entry point is wrapped in a :class:`CompileTracker` that
counts compiles, records compile wall time, and WARNS when a function
compiles again after the run reached steady state.

Detection is exact, not heuristic: jax's jit functions expose
``_cache_size()`` (the C++ dispatch cache population); a call that grows
it compiled. A signature-set fallback covers jax builds without it.
"""

import time
from typing import Callable, Dict, List, NamedTuple, Optional

from deepspeed_tpu.utils.logging import logger

__all__ = ["CompileEvent", "CompileTracker", "TrackedFunction"]


class CompileEvent(NamedTuple):
    fn_name: str
    count: int          # 1 for the function's first compile, 2, 3, ...
    wall_ms: float      # wall time of the call that compiled (compile
                        # + first dispatch; the actionable number)
    step: int           # engine step at which it happened


def _arg_signature(args, kwargs):
    """Shape/dtype fingerprint of a call — the fallback compile detector
    when ``_cache_size`` is unavailable. Read BEFORE dispatch (donated
    buffers are gone after)."""
    import numpy as np

    def leaf_sig(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return (np.shape(x), str(x.dtype))
        return (type(x).__name__, repr(x)[:32])
    import jax
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    return (treedef, tuple(leaf_sig(x) for x in leaves))


class TrackedFunction:
    """Transparent wrapper over a jit-compiled callable: calls pass
    through unchanged; compiles are observed and reported to the owning
    tracker. ``lower``/other attributes forward to the wrapped function
    (the HLO-audit tests call ``.lower()`` on engine step functions)."""

    def __init__(self, fn: Callable, name: str, tracker: "CompileTracker"):
        self._fn = fn
        self._name = name
        self._tracker = tracker
        self._seen_signatures = set()
        self._has_cache_size = hasattr(fn, "_cache_size")

    def _cache_size(self) -> Optional[int]:
        if not self._has_cache_size:
            return None
        try:
            return self._fn._cache_size()
        except Exception:
            self._has_cache_size = False
            return None

    def __call__(self, *args, **kwargs):
        before = self._cache_size()
        sig = None
        if before is None:
            sig = _arg_signature(args, kwargs)
            compiled_guess = sig not in self._seen_signatures
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        wall_ms = (time.perf_counter() - t0) * 1e3
        if before is not None:
            after = self._cache_size()
            compiled = after is not None and after > before
        else:
            compiled = compiled_guess
            self._seen_signatures.add(sig)
        self._tracker._record_dispatch(self._name)
        if compiled:
            self._tracker._record(self._name, wall_ms)
        return out

    def lower(self, *args, **kwargs):
        return self._fn.lower(*args, **kwargs)

    def __getattr__(self, item):
        return getattr(self._fn, item)


class CompileTracker:
    """Per-engine compile accounting.

    ``step_provider`` supplies the current host step for event
    attribution; ``warn_after`` is the step past which any re-compile of
    an already-compiled function is treated as steady-state (warned
    loudly, once per function). ``on_event`` (optional) receives each
    CompileEvent — the engine's Observer appends them to the run's
    event log.
    """

    def __init__(self, step_provider: Optional[Callable[[], int]] = None,
                 warn_after: int = 1,
                 on_event: Optional[Callable[[CompileEvent], None]] = None):
        self._step_provider = step_provider or (lambda: 0)
        self.warn_after = int(warn_after)
        self.on_event = on_event
        self.counts: Dict[str, int] = {}
        self.compile_ms: Dict[str, float] = {}
        # every CALL of a wrapped function, compiled or cached — the
        # host-dispatch accounting the async-pipeline bench row and
        # dispatch-count tests pin (one batch_step dispatch per
        # train_batch on the fused path)
        self.dispatch_counts: Dict[str, int] = {}
        self.events: List[CompileEvent] = []
        self._warned_fns = set()

    def wrap(self, fn: Callable, name: str) -> TrackedFunction:
        return TrackedFunction(fn, name, self)

    @property
    def total_compiles(self) -> int:
        return sum(self.counts.values())

    @property
    def total_dispatches(self) -> int:
        return sum(self.dispatch_counts.values())

    def _record_dispatch(self, name: str) -> None:
        self.dispatch_counts[name] = self.dispatch_counts.get(name, 0) + 1

    @property
    def total_compile_ms(self) -> float:
        return sum(self.compile_ms.values())

    def _record(self, name: str, wall_ms: float) -> None:
        self.counts[name] = self.counts.get(name, 0) + 1
        self.compile_ms[name] = self.compile_ms.get(name, 0.0) + wall_ms
        step = int(self._step_provider())
        ev = CompileEvent(fn_name=name, count=self.counts[name],
                          wall_ms=wall_ms, step=step)
        self.events.append(ev)
        if self.counts[name] > 1 and step > self.warn_after and \
                name not in self._warned_fns:
            self._warned_fns.add(name)
            logger.warning(
                f"steady-state recompile: {name!r} compiled again at step "
                f"{step} (compile #{self.counts[name]}, "
                f"{wall_ms:.0f} ms call). A shape/dtype changed between "
                "steps — on TPU this silently re-pays full XLA "
                "compilation per occurrence; pin batch shapes (drop the "
                "last short batch) or pad to a fixed bucket.")
        if self.on_event is not None:
            try:
                self.on_event(ev)
            except Exception:
                pass  # telemetry must never break the step

    def summary(self) -> dict:
        return {
            "total_compiles": self.total_compiles,
            "total_compile_ms": round(self.total_compile_ms, 3),
            "total_dispatches": self.total_dispatches,
            "per_fn": {n: {"count": c,
                           "wall_ms": round(self.compile_ms.get(n, 0.0), 3),
                           "dispatches": self.dispatch_counts.get(n, 0)}
                       for n, c in sorted(self.counts.items())},
        }
