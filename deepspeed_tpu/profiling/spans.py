"""Structured trace spans: one context manager, two sinks.

``trace_span("forward")`` emits
- a ``jax.profiler.TraceAnnotation`` — the span shows up inside captured
  XLA traces (the ``profiler``/``observability.trace`` window), nested
  under the device timeline exactly where it ran; and
- a Chrome-trace JSON "complete" event into a
  :class:`ChromeTraceRecorder` — loadable in ``chrome://tracing`` /
  Perfetto without capturing a full XLA trace.

The recorder is deliberately tiny (host wall-clock only, no device
sync): spans measure *dispatch-side* phase structure. Device-honest
timing stays with SynchronizedWallClockTimer / the XLA trace.
"""

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import List, Optional

__all__ = ["ChromeTraceRecorder", "trace_span", "set_default_recorder",
           "get_default_recorder"]


class ChromeTraceRecorder:
    """Accumulates Chrome-trace 'X' (complete) events; ``dump(path)``
    writes the standard ``{"traceEvents": [...]}`` container.

    The buffer is bounded (``max_events``, oldest dropped first, with a
    count of what was shed) so a multi-day run cannot grow host memory
    without limit; the viewers care about the recent window anyway."""

    def __init__(self, max_events: int = 100_000):
        self.events: List[dict] = []
        self.max_events = int(max_events)
        self.dropped = 0
        self._lock = threading.Lock()
        self._origin = time.perf_counter()
        self._lanes: set = set()

    def _append(self, *evs: dict) -> None:
        """Append under the lock, then shed past ``max_events`` (oldest
        first, count kept in ``dropped``) — the one shedding policy for
        both the thread-span and lane paths."""
        with self._lock:
            self.events.extend(evs)
            if len(self.events) > self.max_events:
                shed = len(self.events) - self.max_events
                del self.events[:shed]
                self.dropped += shed

    def add(self, name: str, t0: float, t1: float, **extra) -> None:
        ev = {"name": name, "ph": "X", "cat": "deepspeed_tpu",
              "ts": (t0 - self._origin) * 1e6,       # chrome wants µs
              "dur": max(t1 - t0, 0.0) * 1e6,
              "pid": os.getpid(), "tid": threading.get_ident()}
        if extra:
            ev["args"] = extra
        self._append(ev)

    # the lane-id memo only suppresses duplicate thread_name metadata
    # rows; past this many distinct lanes it resets (a re-emitted
    # metadata row is harmless, an unbounded per-request set is a leak
    # on a long-running serving daemon)
    _LANES_CAP = 10_000

    def add_lane(self, lane: int, lane_name: str, name: str,
                 t0: float, t1: float, **extra) -> None:
        """A complete event on a NAMED virtual lane (``tid = lane``)
        instead of the calling thread — the serving tracer draws each
        request's queue_wait/prefill/decode phases on its own
        per-request lane (``lane`` = request uid, ``lane_name`` =
        "req <uid>"). The first event on a lane also emits the
        ``thread_name`` metadata row so Perfetto labels it; if the
        bounded buffer later sheds that row, the lane falls back to
        its numeric tid — cosmetic only."""
        lane = int(lane)
        ev = {"name": name, "ph": "X", "cat": "deepspeed_tpu/serve",
              "ts": (t0 - self._origin) * 1e6,
              "dur": max(t1 - t0, 0.0) * 1e6,
              "pid": os.getpid(), "tid": lane}
        if extra:
            ev["args"] = extra
        if lane not in self._lanes:
            if len(self._lanes) >= self._LANES_CAP:
                self._lanes.clear()
            self._lanes.add(lane)
            self._append(
                {"name": "thread_name", "ph": "M",
                 "pid": os.getpid(), "tid": lane,
                 "args": {"name": lane_name}}, ev)
        else:
            self._append(ev)

    def dump(self, path: str) -> str:
        with self._lock:
            payload = {"traceEvents": list(self.events),
                       "displayTimeUnit": "ms"}
            if self.dropped:
                payload["otherData"] = {
                    "dropped_events": self.dropped,
                    "note": "oldest events shed by the bounded buffer"}
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)  # readable mid-run, never half-written
        return path


_default_recorder: Optional[ChromeTraceRecorder] = None


def set_default_recorder(rec: Optional[ChromeTraceRecorder]) -> None:
    global _default_recorder
    _default_recorder = rec


def get_default_recorder() -> Optional[ChromeTraceRecorder]:
    return _default_recorder


@contextmanager
def trace_span(name: str, recorder: Optional[ChromeTraceRecorder] = None,
               **extra):
    """Context manager wrapping a phase in both sinks. Never raises from
    instrumentation: a missing/odd jax profiler degrades to timing-only."""
    rec = recorder if recorder is not None else _default_recorder
    try:
        import jax.profiler as _jp
        annotation = _jp.TraceAnnotation(name)
    except Exception:
        annotation = None
    t0 = time.perf_counter()
    if annotation is not None:
        try:
            annotation.__enter__()
        except Exception:
            annotation = None  # profiler refused to start: timing-only
    try:
        yield
    finally:
        if annotation is not None:
            try:
                annotation.__exit__(None, None, None)
            except Exception:
                pass
        if rec is not None:
            rec.add(name, t0, time.perf_counter(), **extra)
