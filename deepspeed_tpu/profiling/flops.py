"""FLOPs / MFU profiler over XLA's compiled-program cost model.

TPU-native analog of the reference's ``flops_profiler`` (which walks
nn.Module hooks counting matmul shapes): here the compiled program *is*
the model, so the authoritative count comes from
``jit(fn).lower(...).compile().cost_analysis()`` — the same numbers the
XLA scheduler itself uses. That makes the profile exact for whatever
actually runs (fused backward, remat re-computation, quantized
collectives included), not an eager-mode estimate.

MFU is reported against a small peak-FLOPs device registry (bf16 MXU
peaks for the TPU generations this repo targets, plus a nominal CPU
fallback so CPU smoke runs still produce a well-defined fraction).
"""

import time
from typing import Any, NamedTuple, Optional

from deepspeed_tpu.utils.logging import log_dist

__all__ = [
    "FlopsProfile", "PEAK_FLOPS_REGISTRY", "peak_flops_per_device",
    "normalize_cost_analysis", "profile_compiled", "profile_jit_fn",
    "compute_mfu", "format_profile",
]

# Peak dense bf16 FLOP/s per chip. Sources: TPU v4 275 TFLOP/s,
# v5e 197 TFLOP/s, v5p 459 TFLOP/s (cloud TPU system docs; v5e matches
# the number bench.py's hardware MFU row already uses). Matching is by
# substring on ``device.device_kind`` lowercased, most specific first.
PEAK_FLOPS_REGISTRY = (
    ("tpu v5p", 459e12),
    ("tpu v5 lite", 197e12),   # v5e reports device_kind "TPU v5 lite"
    ("tpu v5e", 197e12),
    ("tpu v5", 459e12),
    ("tpu v4", 275e12),
)
# Nominal placeholder so MFU stays a well-defined positive fraction in
# CPU smoke runs (tests, forced-CPU bench children). Deliberately NOT a
# measured CPU peak: CPU MFU values are only meaningful relative to
# each other within one run.
CPU_FALLBACK_PEAK_FLOPS = 1e11


class FlopsProfile(NamedTuple):
    """One compiled program's cost-model record.

    NB: for a GSPMD-partitioned program, XLA's ``cost_analysis()``
    reports the **per-device** partition's cost (verified on the
    8-device mesh: a data-sharded matmul reports 2m^3/8), so ``flops``
    and ``bytes_accessed`` here are per-device per invocation. MFU must
    therefore be computed against the per-device peak; multiply by
    ``num_devices`` for cluster-wide totals."""
    name: str
    flops: float               # per-DEVICE FLOPs per invocation
    bytes_accessed: float      # per-DEVICE HBM bytes per invocation
    peak_flops_per_device: float
    device_kind: str
    num_devices: int
    compile_ms: Optional[float] = None

    @property
    def flops_total(self) -> float:
        """Cluster-wide FLOPs per invocation."""
        return self.flops * max(self.num_devices, 1)

    @property
    def arithmetic_intensity(self) -> float:
        """FLOPs per byte of memory traffic (roofline x-coordinate)."""
        return self.flops / self.bytes_accessed if self.bytes_accessed else 0.0


def peak_flops_per_device(device=None):
    """``(peak_flops, label)`` for a jax device (first local device when
    None). Unknown accelerators fall back to the CPU placeholder with a
    ``+nominal-peak`` label so reports can't silently claim real MFU."""
    if device is None:
        import jax
        device = jax.local_devices()[0]
    kind = str(getattr(device, "device_kind", "cpu"))
    low = kind.lower()
    for needle, peak in PEAK_FLOPS_REGISTRY:
        if needle in low:
            return peak, kind
    return CPU_FALLBACK_PEAK_FLOPS, f"{kind}+nominal-peak"


def normalize_cost_analysis(cost: Any) -> dict:
    """``compiled.cost_analysis()`` returns a list of per-module dicts on
    jax 0.4.x and a plain dict on newer jax; normalize to
    ``{"flops": float, "bytes_accessed": float}`` (0.0 when the backend
    reports nothing — cost analysis is best-effort on some platforms)."""
    if cost is None:
        cost = {}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    flops = float(cost.get("flops", 0.0) or 0.0)
    nbytes = float(cost.get("bytes accessed", 0.0) or 0.0)
    return {"flops": max(flops, 0.0), "bytes_accessed": max(nbytes, 0.0)}


def _shape_specs(args):
    """Pytree of ShapeDtypeStructs mirroring ``args`` — lowering needs
    only avals, and spec'ing avoids touching possibly-donated buffers.
    Shardings are carried over when present: without them the AOT
    compile would produce a REPLICATED program whose FLOPs/bytes differ
    from the partitioned step that actually runs on a multi-device
    mesh (and whose compile can be far more expensive)."""
    import jax
    import numpy as np
    from jax.sharding import Sharding

    def spec(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            shd = getattr(x, "sharding", None)
            if isinstance(shd, Sharding):
                try:
                    return jax.ShapeDtypeStruct(np.shape(x), x.dtype,
                                                sharding=shd)
                except TypeError:
                    pass  # older jax: positional-only struct
            return jax.ShapeDtypeStruct(np.shape(x), x.dtype)
        return x
    return jax.tree_util.tree_map(spec, args)


def profile_compiled(compiled, name: str, device=None,
                     num_devices: Optional[int] = None,
                     compile_ms: Optional[float] = None) -> FlopsProfile:
    """Cost-model record of an already-compiled jax stages.Compiled."""
    import jax
    cost = normalize_cost_analysis(compiled.cost_analysis())
    peak, kind = peak_flops_per_device(device)
    if num_devices is None:
        num_devices = len(jax.devices())
    return FlopsProfile(name=name, flops=cost["flops"],
                        bytes_accessed=cost["bytes_accessed"],
                        peak_flops_per_device=peak, device_kind=kind,
                        num_devices=num_devices, compile_ms=compile_ms)


def profile_jit_fn(fn, args, name: str = "step", device=None,
                   num_devices: Optional[int] = None) -> FlopsProfile:
    """Lower + compile ``fn`` at ``args``' shapes and return its cost
    record. ``fn`` is any jit-wrapped callable exposing ``.lower``; args
    may be live arrays OR already-donated ones (only shapes are read).

    NB: this performs an AOT compile — jax does not share the dispatch
    cache with ``lower().compile()`` — so callers should treat it as a
    one-time, opt-in cost (the persistent compile cache absorbs it on
    re-runs)."""
    specs = _shape_specs(args)
    t0 = time.perf_counter()
    compiled = fn.lower(*specs).compile()
    dt_ms = (time.perf_counter() - t0) * 1e3
    return profile_compiled(compiled, name, device=device,
                            num_devices=num_devices, compile_ms=dt_ms)


def compute_mfu(flops_per_step: float, step_time_s: float,
                peak_flops: float) -> float:
    """Model FLOPs utilization: achieved FLOP/s over peak. Pass
    matching scopes — per-device flops (what ``cost_analysis`` reports
    for partitioned programs) against the per-device peak, or global
    flops against the all-device peak; the ratio is the same."""
    if step_time_s <= 0 or peak_flops <= 0:
        return 0.0
    return flops_per_step / step_time_s / peak_flops


def format_profile(profile: FlopsProfile,
                   step_time_ms: Optional[float] = None) -> str:
    """Reference-flops_profiler-style block, logged once per program."""
    lines = [
        f"flops profiler: {profile.name}",
        f"  device               : {profile.device_kind} "
        f"x{profile.num_devices} "
        f"(peak {profile.peak_flops_per_device / 1e12:.1f} TFLOP/s/dev)",
        f"  flops per step/dev   : {profile.flops / 1e9:.3f} GFLOP",
        f"  bytes accessed/dev   : {profile.bytes_accessed / 2**20:.2f} MiB",
        f"  arithmetic intensity : "
        f"{profile.arithmetic_intensity:.2f} FLOP/byte",
    ]
    if profile.compile_ms is not None:
        lines.append(f"  cost-model compile   : {profile.compile_ms:.0f} ms")
    if step_time_ms:
        mfu = compute_mfu(profile.flops, step_time_ms / 1e3,
                          profile.peak_flops_per_device)
        lines.append(f"  step time            : {step_time_ms:.2f} ms")
        lines.append(f"  MFU                  : {mfu * 100:.2f}%")
    return "\n".join(lines)


def log_profile(profile: FlopsProfile,
                step_time_ms: Optional[float] = None) -> None:
    log_dist(format_profile(profile, step_time_ms), ranks=[0])
