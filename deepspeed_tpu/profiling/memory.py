"""HBM watermark sampling.

Replaces the one-line ``SynchronizedWallClockTimer.memory_usage()``
string with structured samples: ``device.memory_stats()`` where the
backend provides it (TPU), a host-RSS fallback where it does not (the
CPU backend returns None — tests and forced-CPU smoke runs still get
well-defined watermark scalars, labeled ``source: "host"``).

Sampling is a cheap host call (no device sync), so the engine can take
a watermark at every step boundary; :class:`MemoryWatermark` keeps the
run peak and per-phase deltas on top of the raw samples.
"""

import os
from typing import Dict, Optional

__all__ = ["memory_snapshot", "MemoryWatermark"]

_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def _host_rss_bytes() -> Optional[int]:
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _PAGE
    except Exception:
        return None


def _host_peak_rss_bytes() -> Optional[int]:
    try:
        import resource
        ru = resource.getrusage(resource.RUSAGE_SELF)
        return int(ru.ru_maxrss) * 1024  # linux reports KiB
    except Exception:
        return None


def memory_snapshot(device=None) -> Optional[Dict]:
    """``{"bytes_in_use", "peak_bytes_in_use", "source"}`` for one
    device, host-RSS fallback when the backend has no allocator stats.
    None only when neither source is readable."""
    stats = None
    if device is None:
        try:
            import jax
            device = jax.local_devices()[0]
        except Exception:
            device = None
    if device is not None:
        try:
            stats = device.memory_stats()
        except Exception:
            stats = None
    if stats:
        return {"bytes_in_use": int(stats.get("bytes_in_use", 0)),
                "peak_bytes_in_use": int(
                    stats.get("peak_bytes_in_use",
                              stats.get("bytes_in_use", 0))),
                "source": "device"}
    rss = _host_rss_bytes()
    peak = _host_peak_rss_bytes()
    if rss is None and peak is None:
        return None
    return {"bytes_in_use": int(rss or peak or 0),
            "peak_bytes_in_use": int(peak or rss or 0),
            "source": "host"}


class MemoryWatermark:
    """Stateful watermark tracking over :func:`memory_snapshot`.

    ``sample(phase)`` returns the snapshot extended with
    ``delta_bytes`` (bytes_in_use change since the previous sample, any
    phase) and maintains ``peak_bytes`` across the run — the number an
    OOM post-mortem wants even if the fatal step never reported."""

    def __init__(self, device=None):
        self._device = device
        self.last: Optional[Dict] = None
        self.peak_bytes: int = 0
        self.samples_by_phase: Dict[str, Dict] = {}

    def sample(self, phase: str = "step") -> Optional[Dict]:
        snap = memory_snapshot(self._device)
        if snap is None:
            return None
        prev = self.last
        snap = dict(snap)
        snap["phase"] = phase
        snap["delta_bytes"] = (snap["bytes_in_use"] - prev["bytes_in_use"]
                               if prev else 0)
        self.peak_bytes = max(self.peak_bytes, snap["peak_bytes_in_use"],
                              snap["bytes_in_use"])
        self.last = snap
        self.samples_by_phase[phase] = snap
        return snap
