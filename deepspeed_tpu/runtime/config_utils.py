"""Config helpers (reference ``deepspeed/runtime/config_utils.py``)."""

import collections


def get_scalar_param(param_dict, param_name, param_default_value):
    """Fetch a scalar config value with a default (reference config_utils.py:12)."""
    if param_dict is None:
        return param_default_value
    return param_dict.get(param_name, param_default_value)


def get_list_param(param_dict, param_name, param_default_value):
    if param_dict is None:
        return param_default_value
    return param_dict.get(param_name, param_default_value)


def get_dict_param(param_dict, param_name, param_default_value):
    if param_dict is None:
        return param_default_value
    return param_dict.get(param_name, param_default_value)


def dict_raise_error_on_duplicate_keys(ordered_pairs):
    """json object_pairs_hook that rejects duplicate keys
    (reference config_utils.py:16)."""
    d = dict((k, v) for k, v in ordered_pairs)
    if len(d) != len(ordered_pairs):
        counter = collections.Counter(k for k, _ in ordered_pairs)
        keys = [k for k, v in counter.items() if v > 1]
        raise ValueError("Duplicate keys in DeepSpeed-TPU config: {}".format(keys))
    return d
