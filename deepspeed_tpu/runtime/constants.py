"""Config keys and defaults.

Key names intentionally match the reference JSON schema
(``deepspeed/runtime/constants.py``) so a reference user's ds_config.json works
unchanged; defaults re-tuned for TPU where noted (bf16 on by default is new).
"""

import os

#############################################
# Routes
#############################################
ROUTE_TRAIN = "train"
ROUTE_EVAL = "eval"
ROUTE_PREDICT = "predict"
ROUTE_ENCODE = "encode"

#############################################
# Batch size (reference constants.py:24-40; triangle invariant config.py:557)
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_BATCH_SIZE_DEFAULT = None

TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None
# TPU spelling; both accepted.
TRAIN_MICRO_BATCH_SIZE_PER_CHIP = "train_micro_batch_size_per_chip"

GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"
GRADIENT_ACCUMULATION_STEPS_DEFAULT = None

SPARSE_GRADIENTS = "sparse_gradients"
SPARSE_GRADIENTS_DEFAULT = False
# explicit opt-in list of embedding leaf paths (or path substrings) for
# the CSR grad exchange; when set, the name-regex heuristic is bypassed
SPARSE_GRADIENTS_PARAMS = "sparse_gradients_params"
SPARSE_GRADIENTS_PARAMS_DEFAULT = None

#############################################
# Optimizer / scheduler
#############################################
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE_DEFAULT = None
OPTIMIZER_PARAMS = "params"
TYPE = "type"
LEGACY_FUSION = "legacy_fusion"
LEGACY_FUSION_DEFAULT = False

SCHEDULER = "scheduler"
SCHEDULER_TYPE_DEFAULT = None
SCHEDULER_PARAMS = "params"

MAX_GRAD_NORM = "max_grad_norm"

ADAM_OPTIMIZER = "adam"
LAMB_OPTIMIZER = "lamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
DEEPSPEED_ADAM = "deepspeed_adam"  # reference name for CPU (offload) adam
SGD_OPTIMIZER = "sgd"
ADAMW_OPTIMIZER = "adamw"
DEEPSPEED_OPTIMIZERS = [
    ADAM_OPTIMIZER, ADAMW_OPTIMIZER, LAMB_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER,
    DEEPSPEED_ADAM, SGD_OPTIMIZER,
]

#############################################
# Steps
#############################################
STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10

#############################################
# Training options
#############################################
PRESCALE_GRADIENTS = "prescale_gradients"
PRESCALE_GRADIENTS_DEFAULT = False

GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0

DISABLE_ALLGATHER = "disable_allgather"
DISABLE_ALLGATHER_DEFAULT = False

DUMP_STATE = "dump_state"
DUMP_STATE_DEFAULT = False

VOCABULARY_SIZE = "vocabulary_size"
VOCABULARY_SIZE_DEFAULT = None

#############################################
# FP16 (reference constants.py:131-154). On TPU fp16 maps to bf16 by default
# unless fp16.force_fp16 is set (bf16 needs no loss scaling).
#############################################
FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_ENABLED_DEFAULT = False

FP16_LOSS_SCALE = "loss_scale"
FP16_LOSS_SCALE_DEFAULT = 0

FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_INITIAL_SCALE_POWER_DEFAULT = 32

FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_LOSS_SCALE_WINDOW_DEFAULT = 1000

FP16_HYSTERESIS = "hysteresis"
FP16_HYSTERESIS_DEFAULT = 2

FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_MIN_LOSS_SCALE_DEFAULT = 1

#############################################
# BF16 (TPU-native extension; not in the reference snapshot)
#############################################
BF16 = "bf16"
BF16_ENABLED = "enabled"
BF16_ENABLED_DEFAULT = False
# Master-weight-free bf16: params held in bf16 end-to-end (no fp32
# master copy — saves 4 bytes/param of HBM); requires stochastic
# rounding so sub-ulp updates accumulate in expectation. The TPU-native
# analog of the reference's __STOCHASTIC_MODE__ kernel build variant
# (reference setup.py:211-242, transformer.py stochastic_mode flag).
BF16_MASTER_WEIGHTS = "master_weights"
BF16_MASTER_WEIGHTS_DEFAULT = True
BF16_STOCHASTIC_ROUNDING = "stochastic_rounding"
BF16_STOCHASTIC_ROUNDING_DEFAULT = False
BF16_SR_SEED = "sr_seed"
BF16_SR_SEED_DEFAULT = 0

#############################################
# Gradient clipping
#############################################
GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0

#############################################
# ZeRO stages
#############################################
ZERO_OPTIMIZATION = "zero_optimization"

#############################################
# Logging / tensorboard
#############################################
WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
WALL_CLOCK_BREAKDOWN_DEFAULT = False

MEMORY_BREAKDOWN = "memory_breakdown"
MEMORY_BREAKDOWN_DEFAULT = False

#############################################
# Quantized (int8) gradient allreduce — TPU-native extension
# (ZeRO++-style comm compression; see runtime/quantized_collectives.py)
#
# "compressed_allreduce": {"enabled": false, "block": 256}
#############################################
COMPRESSED_ALLREDUCE = "compressed_allreduce"
COMPRESSED_ALLREDUCE_ENABLED = "enabled"
COMPRESSED_ALLREDUCE_ENABLED_DEFAULT = False
COMPRESSED_ALLREDUCE_BLOCK = "block"
COMPRESSED_ALLREDUCE_BLOCK_DEFAULT = 256

#############################################
# Hierarchical quantized collectives — TPU-native extension
# (ZeRO++ qgZ/qwZ/hpZ shapes; see runtime/quantized_collectives.py).
# Supersedes "compressed_allreduce" (still accepted as a legacy alias
# for {enabled, block}).
#
# "quantized_comm": {
#   "enabled": false,
#   "algo": "twohop",           # qgZ two-hop | "allgather" (legacy, dp=2)
#   "block": 256,               # quantization block size
#   "hierarchical": 0,          # intra-slice size (>=2 splits the data
#                               # axis into data_inter x data_intra)
#   "quantize_weights": false,  # qwZ: int8 ZeRO param all-gather
#   "secondary_partition": false# hpZ: intra-sharded compute-dtype copy
# }
#############################################
QUANTIZED_COMM = "quantized_comm"
QUANTIZED_COMM_ENABLED = "enabled"
QUANTIZED_COMM_ENABLED_DEFAULT = False
QUANTIZED_COMM_ALGO = "algo"
QUANTIZED_COMM_ALGO_DEFAULT = "twohop"
QUANTIZED_COMM_BLOCK = "block"
QUANTIZED_COMM_BLOCK_DEFAULT = 256
QUANTIZED_COMM_HIERARCHICAL = "hierarchical"
QUANTIZED_COMM_HIERARCHICAL_DEFAULT = 0
QUANTIZED_COMM_QUANTIZE_WEIGHTS = "quantize_weights"
QUANTIZED_COMM_QUANTIZE_WEIGHTS_DEFAULT = False
QUANTIZED_COMM_SECONDARY_PARTITION = "secondary_partition"
QUANTIZED_COMM_SECONDARY_PARTITION_DEFAULT = False

#############################################
# Topology-aware collective autotuner + compute/comm overlap
# (runtime/comm_autotune.py; docs/performance.md "Collective
# autotuner"). Picks the quantized_comm exchange (algo / block /
# hierarchy split) per mesh topology and gradient-size histogram from
# a per-hop latency+bandwidth cost model, and overlaps the gradient
# exchange of micro-step i with micro-step i+1's compute inside the
# fused scan. Explicit quantized_comm.{algo,block,hierarchical} keys
# act as overrides.
#
# "comm_autotune": {
#   "enabled": false,
#   "overlap": "auto",          # true | false | "auto" (on when the
#                               # fused quantized exchange is active)
#   "calibrate": false,         # verify wire model vs compiled HLO at
#                               # init (best-effort probe)
#   "intra_size": 0,            # fast-wire extent of the data axis
#                               # (0 = infer: devices per process)
#   "intra_gbps": 75.0,         # fast (ICI) per-direction bandwidth
#   "inter_gbps": 12.5,         # slow (DCN/inter-slice) bandwidth
#   "intra_latency_us": 1.0,
#   "inter_latency_us": 10.0,
#   "block_candidates": [64, 128, 256]
# }
#############################################
COMM_AUTOTUNE = "comm_autotune"
COMM_AUTOTUNE_ENABLED = "enabled"
COMM_AUTOTUNE_ENABLED_DEFAULT = False
COMM_AUTOTUNE_OVERLAP = "overlap"
COMM_AUTOTUNE_OVERLAP_DEFAULT = "auto"
COMM_AUTOTUNE_CALIBRATE = "calibrate"
COMM_AUTOTUNE_CALIBRATE_DEFAULT = False
COMM_AUTOTUNE_INTRA_SIZE = "intra_size"
COMM_AUTOTUNE_INTRA_SIZE_DEFAULT = 0
COMM_AUTOTUNE_INTRA_GBPS = "intra_gbps"
COMM_AUTOTUNE_INTER_GBPS = "inter_gbps"
COMM_AUTOTUNE_INTRA_LATENCY_US = "intra_latency_us"
COMM_AUTOTUNE_INTER_LATENCY_US = "inter_latency_us"
COMM_AUTOTUNE_BLOCK_CANDIDATES = "block_candidates"

#############################################
# Profiler (TPU-native: jax.profiler trace capture; SURVEY.md §5 —
# the reference's wall_clock_breakdown/timers ladder, plus XLA traces)
#
# "profiler": {
#   "enabled": false,
#   "output_path": "/tmp/jax-trace",
#   "start_step": 2,        # skip compile steps
#   "num_steps": 3
# }
#############################################
PROFILER = "profiler"
PROFILER_ENABLED = "enabled"
PROFILER_ENABLED_DEFAULT = False
PROFILER_OUTPUT_PATH = "output_path"
PROFILER_OUTPUT_PATH_DEFAULT = "/tmp/deepspeed_tpu_trace"
PROFILER_START_STEP = "start_step"
PROFILER_START_STEP_DEFAULT = 2
PROFILER_NUM_STEPS = "num_steps"
PROFILER_NUM_STEPS_DEFAULT = 3

#############################################
# Unified observability (deepspeed_tpu/profiling/): FLOPs/MFU cost
# profiler, recompile tracking, HBM watermarks, trace spans, and the
# crash-safe JSONL event log that tools/obs_report.py renders. The
# legacy top-level "profiler" section above is aliased as
# observability.trace (its keys seed the defaults; explicit
# observability.trace keys win), mirroring the
# compressed_allreduce -> quantized_comm aliasing.
#
# "observability": {
#   "enabled": false,
#   "events_dir": "/tmp/deepspeed_tpu_obs",  # events.jsonl location
#   "flops_profiler": true,      # cost-analysis FLOPs/MFU record
#   "memory_watermarks": true,   # structured memory_stats() scalars
#   "recompile_warn_after": 1,   # warn on recompiles past this step
#   "chrome_trace_path": "",     # span timeline JSON ("" disables)
#   "trace": {                   # jax.profiler window (legacy "profiler")
#     "enabled": false, "output_path": "/tmp/deepspeed_tpu_trace",
#     "start_step": 2, "num_steps": 3
#   }
# }
#############################################
OBSERVABILITY = "observability"
OBS_ENABLED = "enabled"
OBS_ENABLED_DEFAULT = False
OBS_EVENTS_DIR = "events_dir"
OBS_EVENTS_DIR_DEFAULT = "/tmp/deepspeed_tpu_obs"
OBS_FLOPS_PROFILER = "flops_profiler"
OBS_FLOPS_PROFILER_DEFAULT = True
OBS_MEMORY_WATERMARKS = "memory_watermarks"
OBS_MEMORY_WATERMARKS_DEFAULT = True
OBS_RECOMPILE_WARN_AFTER = "recompile_warn_after"
OBS_RECOMPILE_WARN_AFTER_DEFAULT = 1
OBS_CHROME_TRACE_PATH = "chrome_trace_path"
OBS_CHROME_TRACE_PATH_DEFAULT = ""
# size-based events.jsonl rotation (0 = off): the live file atomically
# rolls to events.jsonl.<n> when it exceeds this many MiB, so a
# long-running (serving) job's event log is bounded per segment;
# tools/obs_report.py reads rotated segments back in order
OBS_EVENTS_MAX_MB = "events_max_mb"
OBS_EVENTS_MAX_MB_DEFAULT = 0
OBS_TRACE = "trace"
# request-granular serving observability (inference/tracing.py): the
# lifecycle event trail, latency-decomposition histograms, and the
# SLO/goodput split. Host-side and sync-free — on by default (the
# serving engine emits nothing anyway unless inference.events_dir or a
# monitor is wired).
OBS_SERVE = "serve"
OBS_SERVE_ENABLED = "enabled"
OBS_SERVE_ENABLED_DEFAULT = True
OBS_SERVE_SLO = "slo"
OBS_SERVE_SLO_TTFT_MS = "ttft_ms"
OBS_SERVE_SLO_TTFT_MS_DEFAULT = 2000.0    # time to first token budget
OBS_SERVE_SLO_TBT_MS = "tbt_ms"
OBS_SERVE_SLO_TBT_MS_DEFAULT = 200.0      # mean time-between-tokens budget
# serve_decode_window sampling: one window row per request every
# round(1/rate) tokens (deterministic stride, not RNG; 0 disables)
OBS_SERVE_SAMPLE_RATE = "sample_rate"
OBS_SERVE_SAMPLE_RATE_DEFAULT = 0.0625
# per-section override of the rotation cap for the SERVING events log
# (None = inherit the top-level observability.events_max_mb)
OBS_SERVE_EVENTS_MAX_MB = "events_max_mb"
OBS_SERVE_EVENTS_MAX_MB_DEFAULT = None
# fleet identity: which replica this engine serves as. Stamped onto
# every serve-tracer event row (``replica_id``) so the offline fleet
# merger (tools/obs_report.py --fleet) can attribute rows across
# process boundaries. None (the default) omits the field — a
# standalone engine's trail is unchanged.
OBS_SERVE_REPLICA_ID = "replica_id"
OBS_SERVE_REPLICA_ID_DEFAULT = None
# postmortem health plane (deepspeed_tpu/utils/health.py): flight
# recorder ring, stall watchdog, numeric anomaly detectors. Entirely
# host-side; enabling it is pinned to leave losses/params/outputs
# bitwise identical (tests/unit/test_health.py).
#
# "health": {
#   "enabled": false,
#   "ring_events": 256,        # flight-ring rows kept in memory
#   "stall_timeout_s": 0.0,    # 0 disables the watchdog thread
#   "on_stall": "warn",        # or "exit" (code 87, see health.py)
#   "flight_path": "",         # "" = <events_dir>/flight.json
#   "detectors": {
#     "enabled": true,
#     "nonfinite_streak": 3,        # NaN/inf losses in a row -> alert
#     "spike_zscore": 6.0,          # rolling z-score spike threshold
#     "spike_window": 32,           # rolling window (steps)
#     "grad_norm_max": 1.0e4,       # grad-norm explosion ceiling
#     "scale_collapse_below": 2.0,  # dynamic loss-scale floor
#     "recompile_storm_count": 3,   # compiles within ...
#     "recompile_storm_window": 16  # ... this many steps -> alert
#   }
# }
OBS_HEALTH = "health"
OBS_HEALTH_ENABLED = "enabled"
OBS_HEALTH_ENABLED_DEFAULT = False
OBS_HEALTH_RING_EVENTS = "ring_events"
OBS_HEALTH_RING_EVENTS_DEFAULT = 256
OBS_HEALTH_STALL_TIMEOUT_S = "stall_timeout_s"
OBS_HEALTH_STALL_TIMEOUT_S_DEFAULT = 0.0
OBS_HEALTH_ON_STALL = "on_stall"
OBS_HEALTH_ON_STALL_DEFAULT = "warn"
OBS_HEALTH_FLIGHT_PATH = "flight_path"
OBS_HEALTH_FLIGHT_PATH_DEFAULT = ""
OBS_HEALTH_DETECTORS = "detectors"
OBS_HEALTH_DET_ENABLED = "enabled"
OBS_HEALTH_DET_ENABLED_DEFAULT = True
OBS_HEALTH_DET_NONFINITE_STREAK = "nonfinite_streak"
OBS_HEALTH_DET_NONFINITE_STREAK_DEFAULT = 3
OBS_HEALTH_DET_SPIKE_ZSCORE = "spike_zscore"
OBS_HEALTH_DET_SPIKE_ZSCORE_DEFAULT = 6.0
OBS_HEALTH_DET_SPIKE_WINDOW = "spike_window"
OBS_HEALTH_DET_SPIKE_WINDOW_DEFAULT = 32
OBS_HEALTH_DET_GRAD_NORM_MAX = "grad_norm_max"
OBS_HEALTH_DET_GRAD_NORM_MAX_DEFAULT = 1.0e4
OBS_HEALTH_DET_SCALE_COLLAPSE_BELOW = "scale_collapse_below"
OBS_HEALTH_DET_SCALE_COLLAPSE_BELOW_DEFAULT = 2.0
OBS_HEALTH_DET_RECOMPILE_STORM_COUNT = "recompile_storm_count"
OBS_HEALTH_DET_RECOMPILE_STORM_COUNT_DEFAULT = 3
OBS_HEALTH_DET_RECOMPILE_STORM_WINDOW = "recompile_storm_window"
OBS_HEALTH_DET_RECOMPILE_STORM_WINDOW_DEFAULT = 16

#############################################
# Async step pipeline (TPU-native: the host must never sit between two
# device steps. One scan-fused compiled program per global batch, a
# background prefetch stage that overlaps H2D with compute, and
# deferred loss telemetry so steady-state steps enqueue work and
# return without a device round-trip; see docs/performance.md
# "Async step pipeline".)
#
# "async_pipeline": {
#   "fused_accumulation": true,   # lax.scan over the gas micro batches
#                                 # inside ONE jit (auto-falls back to
#                                 # the per-micro loop for offload/
#                                 # 1-bit/sparse-grad configs)
#   "prefetch_depth": 2,          # batches in flight in the background
#                                 # prefetch thread; 0 disables it
#   "sync_loss_every_step": false # true restores the old per-step
#                                 # float(loss) device sync
# }
#############################################
ASYNC_PIPELINE = "async_pipeline"
ASYNC_FUSED_ACCUMULATION = "fused_accumulation"
ASYNC_FUSED_ACCUMULATION_DEFAULT = True
ASYNC_PREFETCH_DEPTH = "prefetch_depth"
ASYNC_PREFETCH_DEPTH_DEFAULT = 2
ASYNC_SYNC_LOSS_EVERY_STEP = "sync_loss_every_step"
ASYNC_SYNC_LOSS_EVERY_STEP_DEFAULT = False

#############################################
# Persistent XLA compilation cache (TPU-native: first jit of a large
# model costs tens of seconds — and minutes through a remote-compile
# tunnel; caching the compiled executable on disk makes re-runs,
# bench children, and resumed jobs start hot. No reference analog:
# CUDA kernels there are AOT-built at install time via DS_BUILD_*
# env flags, setup.py:47-68 — this knob is the JIT-world equivalent.)
#
# "compile_cache": {
#   "enabled": true,
#   "dir": "~/.cache/deepspeed_tpu/xla_cache",   # the computed default
#   "min_compile_secs": 1.0    # don't cache trivial programs
# }
#############################################
COMPILE_CACHE = "compile_cache"
COMPILE_CACHE_ENABLED = "enabled"
COMPILE_CACHE_ENABLED_DEFAULT = True
COMPILE_CACHE_DIR = "dir"
# per-user default (a world-shared /tmp path would let another local
# user pre-own the dir — permission collisions at best)
COMPILE_CACHE_DIR_DEFAULT = os.path.join(
    os.path.expanduser("~"), ".cache", "deepspeed_tpu", "xla_cache")
COMPILE_CACHE_MIN_COMPILE_SECS = "min_compile_secs"
COMPILE_CACHE_MIN_COMPILE_SECS_DEFAULT = 1.0

#############################################
# Fault-tolerant checkpointing (TPU-native: preemption mid-save is the
# expected failure mode on TPU pods — every save is atomically
# committed, every load verified, recovery automatic; see
# runtime/checkpoint.py and docs/checkpointing.md)
#
# "checkpoint": {
#   "verify_checksums": true,   # CRC32-verify files against COMMITTED
#   "keep_n": 0,                # retention: 0 keeps all committed tags
#   "io_retries": 3,            # transient-OSError retries per file op
#   "io_retry_backoff": 0.05,   # base seconds, doubles per attempt
#   "async_save": false,        # snapshot at the boundary, commit in a
#                               # background writer (docs/checkpointing.md
#                               # "Async snapshot saves")
#   "drain_on_preemption": false, # SIGTERM/SIGINT -> finish window,
#                               # commit preempt tag, exit resumable (85)
#   "save_dir": null,           # where the preemption drain commits
#                               # (default: last save/load dir used)
#   "supervisor": {             # launcher relaunch-on-preemption policy
#     "max_restarts": 3,        # give up after this many resumable exits
#     "backoff": 1.0            # base seconds, doubles per restart
#   }
# }
#############################################
CHECKPOINT = "checkpoint"
CHECKPOINT_VERIFY_CHECKSUMS = "verify_checksums"
CHECKPOINT_VERIFY_CHECKSUMS_DEFAULT = True
CHECKPOINT_KEEP_N = "keep_n"
CHECKPOINT_KEEP_N_DEFAULT = 0
CHECKPOINT_IO_RETRIES = "io_retries"
CHECKPOINT_IO_RETRIES_DEFAULT = 3
CHECKPOINT_IO_RETRY_BACKOFF = "io_retry_backoff"
CHECKPOINT_IO_RETRY_BACKOFF_DEFAULT = 0.05
CHECKPOINT_ASYNC_SAVE = "async_save"
CHECKPOINT_ASYNC_SAVE_DEFAULT = False
CHECKPOINT_DRAIN_ON_PREEMPTION = "drain_on_preemption"
CHECKPOINT_DRAIN_ON_PREEMPTION_DEFAULT = False
CHECKPOINT_SAVE_DIR = "save_dir"
CHECKPOINT_SAVE_DIR_DEFAULT = None
CHECKPOINT_SUPERVISOR = "supervisor"
CHECKPOINT_SUPERVISOR_MAX_RESTARTS = "max_restarts"
CHECKPOINT_SUPERVISOR_MAX_RESTARTS_DEFAULT = 3
CHECKPOINT_SUPERVISOR_BACKOFF = "backoff"
CHECKPOINT_SUPERVISOR_BACKOFF_DEFAULT = 1.0

#############################################
# Inference serving engine (TPU-native extension: the reference
# snapshot is training-only. Bucketed prefill/decode over a
# preallocated donated KV cache + continuous-batching scheduler;
# see deepspeed_tpu/inference/ and docs/inference.md.)
#
# "inference": {
#   "max_batch_size": 8,          # concurrent decode slots
#   "prompt_buckets": [64, 256],  # prompt pad lengths (ascending)
#   "batch_buckets": [1, 8],      # prefill batch pad sizes (ascending)
#   "max_seq_len": 1024,          # KV-cache length (prompt + generated)
#   "max_new_tokens": 128,        # per-request default
#   "temperature": 0.0,           # 0 = greedy (per-request overridable)
#   "top_k": 0,                   # engine-global (compiled-in) filter
#   "eos_token_id": null,         # default stop token
#   "events_dir": "",             # serving events.jsonl ("" disables)
#   "quantize_weights": false,    # qwZ int8 block weight shipping:
#                                 # false | "bf16" (wire-only, eager
#                                 # dequant; true is an alias) | "int8"
#                                 # (int8-RESIDENT weights — compiled
#                                 # programs dequant per block at each
#                                 # matmul, ~2x less weight HBM)
#   "quantize_block": 256,        # qwZ block size
#   "admit_lookahead": 4,         # HOL fix: queue entries scanned for a
#                                 # head that fits (0 = strict FIFO)
#   "paged_kv": {                 # paged/block KV cache (default path;
#                                 # occupancy ~ tokens in flight, not
#                                 # slots x max_len)
#     "enabled": true,            # false = dense slot x max_len cache
#     "page_size": 16,            # tokens per page
#     "num_pages": 0,             # pool size incl. null page; 0 = auto
#                                 # (dense-equivalent worst case)
#     "prefix_cache": true,       # hash-dedup shared prompt prefixes
#     "attn_kernel": "pallas",    # decode attention: fused Pallas
#                                 # paged kernel (O(live tokens) pool
#                                 # reads) | "gather" (stripe oracle);
#                                 # unsupported geometries auto-fall
#                                 # back to gather with a one-line log
#     "decode_page_buckets": [],  # table-width buckets (pages) for the
#                                 # decode dispatch; [] = one program
#                                 # at full pages_per_seq width. More
#                                 # buckets = one decode program per
#                                 # width at warmup; gather fallback
#                                 # bandwidth then scales with the
#                                 # batch's LIVE pages, not max_len
#     "kv_dtype": null,           # pool payload dtype: null = the
#                                 # engine dtype; "int8" = quantized
#                                 # pool (per-token-row fp32 scales
#                                 # ride alongside, dequant in-kernel)
#     "kv_quant_block": 0         # int8 pool scale block over
#                                 # head_dim; 0 = one scale per token
#                                 # row (must divide head_dim)
#   },
#   "mesh": {                     # serving mesh (GSPMD NamedShardings)
#     "axes": {}                  # e.g. {"model": 4}: tensor-parallel
#                                 # prefill/decode over ICI
#   },
#   "chunked_prefill": {          # long-prompt chunked prefill
#     "enabled": false,           # requires paged_kv.enabled; prompts
#                                 # whose suffix exceeds the largest
#                                 # prompt bucket prefill chunk-by-
#                                 # chunk, interleaved with decode
#                                 # (at most one chunk dispatch/step)
#     "chunk_tokens": 256,        # tokens per chunk dispatch (one
#                                 # compiled chunk program per batch
#                                 # bucket — no prompt-bucket ladder)
#     "cp_threshold_tokens": 0    # prompts at least this long run
#                                 # their chunks context-parallel
#                                 # (ring attention over the serving
#                                 # mesh); 0 = off
#   },
#   "spec_decode": {              # speculative multi-token decoding
#     "enabled": false,           # requires paged_kv.enabled
#     "k": 4,                     # max draft tokens proposed/dispatch
#     "method": "ngram",          # "ngram" (prompt-lookup; host-side,
#                                 # no second model) | "callable"
#                                 # (engine-injected small draft model)
#     "ngram_min": 1,             # shortest suffix match tried
#     "ngram_max": 3,             # longest suffix match tried first
#     "verify_widths": []         # compiled verify seq widths;
#                                 # [] = one program at k + 1
#   },
#   "disagg": {                   # disaggregated prefill/decode workers
#     "enabled": false,           # requires paged_kv.enabled
#     "separate_pools": null,     # null = auto (true iff decode_mesh
#                                 # axes set); true forces a prefill
#                                 # pool + priced page handoff
#     "prefill_pages": 0,         # prefill pool size; 0 = auto
#     "decode_mesh": {            # decode worker's own mesh (else the
#       "axes": {}                # decode loop shares inference.mesh)
#     }
#   },
#   "fleet": {                    # multi-replica router (inference/
#                                 # fleet.py FleetRouter)
#     "replicas": 1,              # in-process engine replicas fronted
#     "routing": "least_loaded",  # | "prefix_affinity" (route to the
#                                 # replica whose prefix cache covers
#                                 # the most prompt tokens)
#     "slo_shed": {               # SLO-driven admission (goodput > raw
#                                 # throughput)
#       "enabled": false,
#       "ttft_budget_ms": null,   # p95 TTFT budget; null = the
#                                 # observability.serve.slo.ttft_ms SLO
#       "min_samples": 8,         # TTFTs before the ladder may engage
#       "shed_below_priority": 1, # rung 1: reject requests with
#                                 # priority < this while p95 breaches
#       "degrade_factor": 2.0,    # rung 2 at budget x factor: cap
#                                 # max_new + switch speculation off
#       "degrade_max_new": 32     # the rung-2 max_new cap (0 = no cap)
#     },
#     "swap": {                   # live weight swap (engine.swap_params)
#       "verify_integrity": true  # CRC-verify the tag before pushing
#     }
#   }
# }
#############################################
INFERENCE = "inference"
INF_MAX_BATCH_SIZE = "max_batch_size"
INF_MAX_BATCH_SIZE_DEFAULT = 8
INF_PROMPT_BUCKETS = "prompt_buckets"
INF_PROMPT_BUCKETS_DEFAULT = (64, 256)
INF_BATCH_BUCKETS = "batch_buckets"
INF_BATCH_BUCKETS_DEFAULT = (1, 8)
INF_MAX_SEQ_LEN = "max_seq_len"
INF_MAX_SEQ_LEN_DEFAULT = 1024
INF_MAX_NEW_TOKENS = "max_new_tokens"
INF_MAX_NEW_TOKENS_DEFAULT = 128
INF_TEMPERATURE = "temperature"
INF_TEMPERATURE_DEFAULT = 0.0
INF_TOP_K = "top_k"
INF_TOP_K_DEFAULT = 0
INF_EOS_TOKEN_ID = "eos_token_id"
INF_EOS_TOKEN_ID_DEFAULT = None
INF_EVENTS_DIR = "events_dir"
INF_EVENTS_DIR_DEFAULT = ""
INF_QUANTIZE_WEIGHTS = "quantize_weights"
INF_QUANTIZE_WEIGHTS_DEFAULT = False
INF_QUANTIZE_BLOCK = "quantize_block"
INF_QUANTIZE_BLOCK_DEFAULT = 256
INF_ADMIT_LOOKAHEAD = "admit_lookahead"
INF_ADMIT_LOOKAHEAD_DEFAULT = 4
INF_PAGED_KV = "paged_kv"
INF_PAGED_ENABLED = "enabled"
INF_PAGED_ENABLED_DEFAULT = True
INF_PAGED_PAGE_SIZE = "page_size"
INF_PAGED_PAGE_SIZE_DEFAULT = 16
INF_PAGED_NUM_PAGES = "num_pages"
INF_PAGED_NUM_PAGES_DEFAULT = 0     # 0 = auto (dense-equivalent pool)
INF_PAGED_PREFIX_CACHE = "prefix_cache"
INF_PAGED_PREFIX_CACHE_DEFAULT = True
INF_PAGED_ATTN_KERNEL = "attn_kernel"
INF_PAGED_ATTN_KERNEL_DEFAULT = "pallas"   # "gather" = stripe fallback
INF_PAGED_DECODE_PAGE_BUCKETS = "decode_page_buckets"
INF_PAGED_DECODE_PAGE_BUCKETS_DEFAULT = ()  # () = one full-width program
INF_PAGED_KV_DTYPE = "kv_dtype"
INF_PAGED_KV_DTYPE_DEFAULT = None   # None = follow the engine dtype
INF_PAGED_KV_QUANT_BLOCK = "kv_quant_block"
INF_PAGED_KV_QUANT_BLOCK_DEFAULT = 0  # 0 = one scale per token row
INF_MESH = "mesh"
INF_MESH_AXES = "axes"
# chunked prefill (long prompts): split prefill into fixed
# chunk_tokens-sized dispatches interleaved with decode steps — TBT
# stays bounded under long prompts, ONE compiled chunk program per
# batch bucket replaces the prompt-bucket ladder for chunked requests,
# and prompts past the largest bucket (up to max_seq_len) serve
# instead of rejecting. cp_threshold_tokens >= chunk-size routes
# chunks of prompts at least that long through the context-parallel
# (ring attention) prefill program over the serving mesh (0 = off).
INF_CHUNKED_PREFILL = "chunked_prefill"
INF_CHUNK_ENABLED = "enabled"
INF_CHUNK_ENABLED_DEFAULT = False
INF_CHUNK_TOKENS = "chunk_tokens"
INF_CHUNK_TOKENS_DEFAULT = 256
INF_CHUNK_CP_THRESHOLD = "cp_threshold_tokens"
INF_CHUNK_CP_THRESHOLD_DEFAULT = 0   # 0 = context-parallel off
INF_SPEC_DECODE = "spec_decode"
INF_SPEC_ENABLED = "enabled"
INF_SPEC_ENABLED_DEFAULT = False
INF_SPEC_K = "k"
INF_SPEC_K_DEFAULT = 4
INF_SPEC_METHOD = "method"
INF_SPEC_METHOD_DEFAULT = "ngram"
INF_SPEC_NGRAM_MIN = "ngram_min"
INF_SPEC_NGRAM_MIN_DEFAULT = 1
INF_SPEC_NGRAM_MAX = "ngram_max"
INF_SPEC_NGRAM_MAX_DEFAULT = 3
INF_SPEC_VERIFY_WIDTHS = "verify_widths"
INF_SPEC_VERIFY_WIDTHS_DEFAULT = ()  # () = one program at k + 1
INF_DISAGG = "disagg"
INF_DISAGG_ENABLED = "enabled"
INF_DISAGG_ENABLED_DEFAULT = False
INF_DISAGG_SEPARATE_POOLS = "separate_pools"
INF_DISAGG_SEPARATE_POOLS_DEFAULT = None  # auto: decode_mesh axes set
INF_DISAGG_PREFILL_PAGES = "prefill_pages"
INF_DISAGG_PREFILL_PAGES_DEFAULT = 0     # 0 = auto
INF_DISAGG_DECODE_MESH = "decode_mesh"
INF_FLEET = "fleet"
INF_FLEET_REPLICAS = "replicas"
INF_FLEET_REPLICAS_DEFAULT = 1
INF_FLEET_ROUTING = "routing"
INF_FLEET_ROUTING_DEFAULT = "least_loaded"
INF_FLEET_ROUTING_CHOICES = ("least_loaded", "prefix_affinity")
INF_FLEET_SLO_SHED = "slo_shed"
INF_FLEET_SHED_ENABLED = "enabled"
INF_FLEET_SHED_ENABLED_DEFAULT = False
INF_FLEET_SHED_TTFT_BUDGET_MS = "ttft_budget_ms"
INF_FLEET_SHED_TTFT_BUDGET_MS_DEFAULT = None  # None = serve SLO ttft_ms
INF_FLEET_SHED_MIN_SAMPLES = "min_samples"
INF_FLEET_SHED_MIN_SAMPLES_DEFAULT = 8
INF_FLEET_SHED_BELOW_PRIORITY = "shed_below_priority"
INF_FLEET_SHED_BELOW_PRIORITY_DEFAULT = 1
INF_FLEET_SHED_DEGRADE_FACTOR = "degrade_factor"
INF_FLEET_SHED_DEGRADE_FACTOR_DEFAULT = 2.0
INF_FLEET_SHED_DEGRADE_MAX_NEW = "degrade_max_new"
INF_FLEET_SHED_DEGRADE_MAX_NEW_DEFAULT = 32  # 0 = no cap
INF_FLEET_SWAP = "swap"
INF_FLEET_SWAP_VERIFY_INTEGRITY = "verify_integrity"
INF_FLEET_SWAP_VERIFY_INTEGRITY_DEFAULT = True
# process-isolated fleet (ISSUE 16): one engine per child process,
# fronted over the inference/rpc.py channel
INF_FLEET_PROCESS_MODE = "process_mode"
INF_FLEET_PM_ENABLED = "enabled"
INF_FLEET_PM_ENABLED_DEFAULT = False
INF_FLEET_PM_RPC_TIMEOUT_S = "rpc_timeout_s"
INF_FLEET_PM_RPC_TIMEOUT_S_DEFAULT = 120.0
INF_FLEET_PM_RPC_RETRIES = "rpc_retries"
INF_FLEET_PM_RPC_RETRIES_DEFAULT = 2
INF_FLEET_PM_RPC_BACKOFF_S = "rpc_backoff_s"
INF_FLEET_PM_RPC_BACKOFF_S_DEFAULT = 0.05
INF_FLEET_PM_MAX_RESTARTS = "max_restarts"
INF_FLEET_PM_MAX_RESTARTS_DEFAULT = 1
INF_FLEET_PM_RESTART_BACKOFF_S = "restart_backoff_s"
INF_FLEET_PM_RESTART_BACKOFF_S_DEFAULT = 0.5
INF_FLEET_PM_READY_TIMEOUT_S = "ready_timeout_s"
INF_FLEET_PM_READY_TIMEOUT_S_DEFAULT = 300.0
# goodput-driven autoscale (ISSUE 16): spawn on sustained rung-1
# shedding, retire (drain-via-migration) on sustained idleness
INF_FLEET_AUTOSCALE = "autoscale"
INF_FLEET_AS_ENABLED = "enabled"
INF_FLEET_AS_ENABLED_DEFAULT = False
INF_FLEET_AS_MIN_REPLICAS = "min_replicas"
INF_FLEET_AS_MIN_REPLICAS_DEFAULT = 1
INF_FLEET_AS_MAX_REPLICAS = "max_replicas"
INF_FLEET_AS_MAX_REPLICAS_DEFAULT = 4
INF_FLEET_AS_UP_PATIENCE = "scale_up_patience"
INF_FLEET_AS_UP_PATIENCE_DEFAULT = 4
INF_FLEET_AS_DOWN_PATIENCE = "scale_down_patience"
INF_FLEET_AS_DOWN_PATIENCE_DEFAULT = 64
INF_FLEET_AS_COOLDOWN_STEPS = "cooldown_steps"
INF_FLEET_AS_COOLDOWN_STEPS_DEFAULT = 16

TENSORBOARD = "tensorboard"
TENSORBOARD_ENABLED = "enabled"
TENSORBOARD_ENABLED_DEFAULT = False
TENSORBOARD_OUTPUT_PATH = "output_path"
TENSORBOARD_OUTPUT_PATH_DEFAULT = ""
TENSORBOARD_JOB_NAME = "job_name"
TENSORBOARD_JOB_NAME_DEFAULT = "DeepSpeedTPUJobName"

#############################################
# Sparse attention (reference config.py:156-317)
#############################################
SPARSE_ATTENTION = "sparse_attention"
SPARSE_DENSE_MODE = "dense"
SPARSE_FIXED_MODE = "fixed"
SPARSE_VARIABLE_MODE = "variable"
SPARSE_BIGBIRD_MODE = "bigbird"
SPARSE_BSLONGFORMER_MODE = "bslongformer"
SPARSE_MODE = "mode"
SPARSE_MODE_DEFAULT = SPARSE_FIXED_MODE
SPARSE_BLOCK = "block"
SPARSE_BLOCK_DEFAULT = 16
SPARSE_DIFFERENT_LAYOUT_PER_HEAD = "different_layout_per_head"
SPARSE_DIFFERENT_LAYOUT_PER_HEAD_DEFAULT = False
SPARSE_NUM_LOCAL_BLOCKS = "num_local_blocks"
SPARSE_NUM_LOCAL_BLOCKS_DEFAULT = 4
SPARSE_NUM_GLOBAL_BLOCKS = "num_global_blocks"
SPARSE_NUM_GLOBAL_BLOCKS_DEFAULT = 1
SPARSE_ATTENTION_TYPE = "attention"
SPARSE_ATTENTION_TYPE_DEFAULT = "bidirectional"
SPARSE_HORIZONTAL_GLOBAL_ATTENTION = "horizontal_global_attention"
SPARSE_HORIZONTAL_GLOBAL_ATTENTION_DEFAULT = False
SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS = "num_different_global_patterns"
SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS_DEFAULT = 1
SPARSE_NUM_RANDOM_BLOCKS = "num_random_blocks"
SPARSE_NUM_RANDOM_BLOCKS_DEFAULT = 0
SPARSE_LOCAL_WINDOW_BLOCKS = "local_window_blocks"
SPARSE_LOCAL_WINDOW_BLOCKS_DEFAULT = [4]
SPARSE_GLOBAL_BLOCK_INDICES = "global_block_indices"
SPARSE_GLOBAL_BLOCK_INDICES_DEFAULT = [0]
SPARSE_GLOBAL_BLOCK_END_INDICES = "global_block_end_indices"
SPARSE_GLOBAL_BLOCK_END_INDICES_DEFAULT = None
SPARSE_NUM_SLIDING_WINDOW_BLOCKS = "num_sliding_window_blocks"
SPARSE_NUM_SLIDING_WINDOW_BLOCKS_DEFAULT = 3

#############################################
# Pipeline (reference config.py:327)
#############################################
PIPELINE = "pipeline"
PIPELINE_STAGES = "stages"
PIPELINE_STAGES_DEFAULT = None
PIPELINE_PARTITION = "partition"
PIPELINE_PARTITION_DEFAULT = "best"
PIPELINE_SEED_LAYERS = "seed_layers"
PIPELINE_SEED_LAYERS_DEFAULT = False
PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL = "activation_checkpoint_interval"
PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL_DEFAULT = 0

#############################################
# Activation checkpointing (reference activation_checkpointing/config.py:59)
#############################################
ACTIVATION_CHECKPOINTING = "activation_checkpointing"
ACT_CKPT_PARTITION_ACTIVATIONS = "partition_activations"
ACT_CKPT_PARTITION_ACTIVATIONS_DEFAULT = False
ACT_CKPT_NUMBER_CHECKPOINTS = "number_checkpoints"
ACT_CKPT_NUMBER_CHECKPOINTS_DEFAULT = None
ACT_CKPT_CONTIGUOUS_MEMORY_OPTIMIZATION = "contiguous_memory_optimization"
ACT_CKPT_CONTIGUOUS_MEMORY_OPTIMIZATION_DEFAULT = False
ACT_CKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY = "synchronize_checkpoint_boundary"
ACT_CKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY_DEFAULT = False
ACT_CKPT_CPU_CHECKPOINTING = "cpu_checkpointing"
ACT_CKPT_CPU_CHECKPOINTING_DEFAULT = False
ACT_CKPT_PROFILE = "profile"
ACT_CKPT_PROFILE_DEFAULT = False

#############################################
# Mesh (TPU-native extension: named-axis device mesh)
#############################################
MESH = "mesh"
MESH_AXES = "axes"  # e.g. {"data": 8, "model": 1, "pipe": 1}
